"""Peer health scoring, quarantine, candidate ordering, negative
discovery TTL, and the stale-pooled-socket retry.

These pin the resilience semantics the chaos matrix exercises
end-to-end: strikes accumulate across connect failures / IO errors /
corruption attributions, K strikes quarantine with a decaying re-admit,
candidates order by observed latency, and one dead DHT round can't
blank discovery for a full TTL.
"""

import threading
import time

import pytest

import zest_tpu.transfer.swarm as swarm_mod
from zest_tpu.config import Config
from zest_tpu.p2p.health import HealthRegistry
from zest_tpu.transfer.swarm import SwarmDownloader


# ── HealthRegistry unit behavior (fake clock) ──


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture
def clock():
    return Clock()


@pytest.fixture
def reg(clock):
    return HealthRegistry(strikes_to_quarantine=3, quarantine_base_s=10.0,
                          time_fn=clock)


A, B, C = ("a", 1), ("b", 2), ("c", 3)


def test_strikes_trip_quarantine(reg):
    assert not reg.record_failure(A)
    assert not reg.record_failure(A)
    assert reg.record_failure(A)  # third strike trips the breaker
    assert reg.is_quarantined(A)
    assert reg.summary()["quarantine_events"] == 1


def test_success_resets_strikes(reg):
    reg.record_failure(A)
    reg.record_failure(A)
    reg.record_success(A, rtt_s=0.05)
    assert not reg.record_failure(A)  # back to strike 1 of 3
    assert not reg.is_quarantined(A)


def test_readmit_on_probation_with_doubled_window(reg, clock):
    for _ in range(3):
        reg.record_failure(A)
    assert reg.is_quarantined(A)
    clock.t += 10.1  # base window expires
    assert not reg.is_quarantined(A)
    # Probation: ONE more strike re-quarantines, window doubled.
    assert reg.record_failure(A)
    assert reg.is_quarantined(A)
    clock.t += 10.1
    assert reg.is_quarantined(A), "second window must be longer than base"
    clock.t += 10.0
    assert not reg.is_quarantined(A)


def test_partition_orders_by_latency_and_drops_quarantined(reg):
    reg.record_success(A, rtt_s=0.5)    # known slow
    reg.record_success(B, rtt_s=0.01)   # known fast
    for _ in range(3):
        reg.record_failure(C)           # quarantined
    healthy, shunned = reg.partition([A, B, C])
    assert healthy == [B, A]
    assert shunned == [C]
    # Unknown peers slot between known-fast and known-slow.
    D = ("d", 4)
    healthy, _ = reg.partition([A, B, D])
    assert healthy == [B, D, A]


def test_partition_is_stable_for_unknowns(reg):
    healthy, _ = reg.partition([A, B, C])
    assert healthy == [A, B, C]  # caller priority preserved on ties


def test_adaptive_timeouts_track_ewma(reg):
    assert reg.connect_timeout(A) == 3.0      # tight default, no history
    assert reg.io_timeout(A) == 20.0
    reg.record_success(A, rtt_s=0.02, connect_s=0.01)
    assert reg.connect_timeout(A) == 0.75     # 4x ewma clamped to floor
    assert reg.io_timeout(A) == 2.0           # 8x ewma clamped to floor
    reg.record_success(B, rtt_s=30.0, connect_s=30.0)
    assert reg.connect_timeout(B) == 5.0      # never past legacy ceiling
    assert reg.io_timeout(B) == 60.0


# ── Swarm-level behavior with scripted peers ──


class FakePeer:
    def __init__(self, behavior):
        self.behavior = behavior
        self.lock = threading.Lock()
        self.closed = False
        self.io_timeouts = []

    def request_chunk(self, chunk_hash, start, end, io_timeout=None):
        self.io_timeouts.append(io_timeout)
        return self.behavior(chunk_hash, start, end)

    def close(self):
        self.closed = True


class ScriptedPool:
    """lease() serves a pre-pooled peer once (reused=True), then pops
    scripted connect outcomes (a FakePeer, or an exception to raise)."""

    def __init__(self):
        self.pooled: dict[tuple, FakePeer] = {}
        self.scripts: dict[tuple, list] = {}
        self.leases: list[tuple] = []

    def lease(self, host, port, info_hash, peer_id, listen_port=None,
              connect_timeout=None, io_timeout=None):
        addr = (host, port)
        self.leases.append(addr)
        peer = self.pooled.get(addr)
        if peer is not None:
            return peer, True
        outcome = self.scripts.get(addr, [ConnectionRefusedError("no route")])
        step = outcome.pop(0) if len(outcome) > 1 else outcome[0]
        if isinstance(step, BaseException):
            raise step
        self.pooled[addr] = step
        return step, False

    def remove(self, host, port):
        peer = self.pooled.pop((host, port), None)
        if peer is not None:
            peer.close()

    def close_all(self):
        for addr in list(self.pooled):
            self.remove(*addr)


def _result(data=b"blob", offset=0):
    class R:
        pass

    r = R()
    r.data = data
    r.chunk_offset = offset
    return r


def _swarm(tmp_path, pool, clock=None, strikes=3):
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    health = HealthRegistry(strikes_to_quarantine=strikes,
                            quarantine_base_s=10.0,
                            time_fn=clock or time.monotonic)
    return SwarmDownloader(cfg, peer_sources=[], pool=pool, health=health)


XH = b"x" * 32


def test_dead_peer_quarantined_and_skipped(tmp_path, clock):
    pool = ScriptedPool()
    pool.scripts[("dead", 1)] = [ConnectionRefusedError("refused")]
    swarm = _swarm(tmp_path, pool, clock=clock, strikes=2)
    swarm.add_direct_peer("dead", 1)

    for _ in range(2):
        assert swarm.try_peer_download(XH, "aa", 0, 1) is None
    assert swarm.stats.peers_quarantined == 1
    attempts_before = swarm.stats.peer_attempts
    # Quarantined: the candidate is skipped outright, no new attempts.
    assert swarm.try_peer_download(XH, "aa", 0, 1) is None
    assert swarm.stats.peer_attempts == attempts_before
    summary = swarm.summary()
    assert summary["health"]["quarantined_now"] == 1


def test_corruption_reports_strike_toward_quarantine(tmp_path, clock):
    pool = ScriptedPool()
    swarm = _swarm(tmp_path, pool, clock=clock, strikes=2)
    addr = ("corrupt", 9)
    swarm.report_corrupt(addr)
    assert not swarm.health.is_quarantined(addr)
    swarm.report_corrupt(addr)
    assert swarm.health.is_quarantined(addr)
    assert swarm.stats.corrupt_from_peer == 2
    assert swarm.stats.peers_quarantined == 1
    assert swarm.summary()["health"]["corrupt_strikes"] == 2


def test_stale_pooled_socket_gets_one_reconnect_retry(tmp_path):
    """The PeerPool eviction race / server idle-close contract: an IO
    failure on a REUSED pooled connection surfaces as exactly one
    retried request on a fresh connection — never a failed download,
    never a strike against the innocent peer."""
    pool = ScriptedPool()
    addr = ("peer", 7)

    def stale(*a):
        raise ConnectionResetError("socket closed under us (evicted)")

    pool.pooled[addr] = FakePeer(stale)
    pool.scripts[addr] = [FakePeer(lambda *a: _result(b"payload"))]
    swarm = _swarm(tmp_path, pool, strikes=1)
    swarm.add_direct_peer(*addr)

    got = swarm.try_peer_download(XH, "aa", 0, 1)
    assert got is not None and got.data == b"payload"
    assert got.addr == addr
    assert swarm.stats.peer_retries == 1
    assert swarm.stats.peer_failures == 1
    # With strikes_to_quarantine=1 ANY strike would quarantine: the
    # stale socket must not have been blamed on the peer.
    assert not swarm.health.is_quarantined(addr)
    assert swarm.health._peers[addr].successes == 1


def test_fresh_connection_failure_strikes_without_retry(tmp_path):
    pool = ScriptedPool()
    pool.scripts[("down", 3)] = [ConnectionRefusedError("refused")]
    swarm = _swarm(tmp_path, pool, strikes=1)
    swarm.add_direct_peer("down", 3)
    assert swarm.try_peer_download(XH, "aa", 0, 1) is None
    assert swarm.stats.peer_retries == 0
    assert swarm.health.is_quarantined(("down", 3))


def test_candidates_ordered_by_observed_health(tmp_path):
    pool = ScriptedPool()
    fast, slow = ("fast", 1), ("slow", 2)
    pool.scripts[fast] = [FakePeer(lambda *a: _result(b"f"))]
    pool.scripts[slow] = [FakePeer(lambda *a: _result(b"s"))]
    swarm = _swarm(tmp_path, pool)
    swarm.add_direct_peer(*slow)  # direct order: slow first
    swarm.add_direct_peer(*fast)
    swarm.health.record_success(slow, rtt_s=0.8)
    swarm.health.record_success(fast, rtt_s=0.01)

    got = swarm.try_peer_download(XH, "aa", 0, 1)
    assert got is not None and got.data == b"f"
    assert pool.leases[0] == fast  # health ordering beat direct order


def test_deadline_starved_timeout_does_not_strike(tmp_path):
    """A connect/IO timeout the deadline squeezed below the health-
    derived budget is the BUDGET's failure, not the peer's: no strike,
    or healthy peers would start the next pull quarantined."""
    from zest_tpu.resilience import Deadline

    pool = ScriptedPool()
    pool.scripts[("p", 1)] = [ConnectionRefusedError("budget ran out")]
    swarm = _swarm(tmp_path, pool, strikes=1)
    swarm.add_direct_peer("p", 1)
    tight = Deadline(0.5)  # remaining << default 3s connect budget
    assert swarm.try_peer_download(XH, "aa", 0, 1, deadline=tight) is None
    assert swarm.stats.peer_failures == 1
    assert not swarm.health.is_quarantined(("p", 1))


def test_deadline_abandons_peer_tier(tmp_path):
    from zest_tpu.resilience import Deadline

    pool = ScriptedPool()
    pool.scripts[("p", 1)] = [FakePeer(lambda *a: _result())]
    swarm = _swarm(tmp_path, pool)
    swarm.add_direct_peer("p", 1)
    expired = Deadline(0.0)
    assert swarm.try_peer_download(XH, "aa", 0, 1, deadline=expired) is None
    assert swarm.stats.peer_attempts == 0


# ── Discovery TTLs ──


class CountingSource:
    def __init__(self, results):
        self.results = results  # list of lists, popped per call
        self.calls = 0

    def find_peers(self, info_hash):
        self.calls += 1
        return self.results.pop(0) if len(self.results) > 1 \
            else self.results[0]

    def announce(self, info_hash, port):
        pass


def test_empty_discovery_uses_short_negative_ttl(tmp_path, monkeypatch):
    monkeypatch.setattr(swarm_mod, "NEGATIVE_DISCOVERY_TTL_S", 0.05)
    source = CountingSource([[], [("peer", 1)]])
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    swarm = SwarmDownloader(cfg, peer_sources=[source], pool=ScriptedPool())

    assert swarm.discover_peers(b"i" * 20) == []
    assert swarm.discover_peers(b"i" * 20) == []  # within negative TTL
    assert source.calls == 1
    time.sleep(0.06)
    assert swarm.discover_peers(b"i" * 20) == [("peer", 1)]
    assert source.calls == 2


def test_successful_discovery_keeps_full_ttl(tmp_path):
    source = CountingSource([[("peer", 1)]])
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    swarm = SwarmDownloader(cfg, peer_sources=[source], pool=ScriptedPool())
    for _ in range(3):
        assert swarm.discover_peers(b"i" * 20) == [("peer", 1)]
    assert source.calls == 1


# ── Reciprocity book, strike kinds, transition events (ISSUE 12) ──


def test_served_bytes_accumulates_and_decays(reg, clock):
    reg.record_success(A, nbytes=1_000_000)
    assert reg.served_bytes(A) == pytest.approx(1_000_000)
    reg.record_success(A, nbytes=500_000)
    assert reg.served_bytes(A) == pytest.approx(1_500_000, rel=1e-3)
    clock.t += 120.0  # one reciprocity tau: ~1/e remains
    assert reg.served_bytes(A) == pytest.approx(1_500_000 / 2.718, rel=0.01)
    assert reg.served_bytes(B) == 0.0  # stranger


def test_strike_kinds_visible_in_detail(reg):
    reg.record_failure(A, kind="seed_stall")
    reg.record_failure(A, kind="corrupt")
    reg.record_failure(B, kind="io")
    rows = {r["peer"]: r for r in reg.detail()}
    assert rows["a:1"]["strike_kinds"] == {"corrupt": 1, "seed_stall": 1}
    assert rows["b:2"]["strike_kinds"] == {"io": 1}


def test_transition_events_quarantine_then_probation(reg, clock):
    events = []
    reg.subscribe(lambda ev, addr: events.append((ev, addr)))
    for _ in range(3):
        reg.record_failure(A)
    assert events == [("quarantined", A)]
    # The window expires; the FIRST observation (a partition or
    # is_quarantined query) flips the peer to probation — once.
    clock.t += 10.1
    reg.partition([A, B])
    reg.partition([A])
    assert events == [("quarantined", A), ("probation", A)]
    # Probation re-admit semantics: one more strike re-quarantines.
    assert reg.record_failure(A)
    assert events[-1] == ("quarantined", A)


def test_probation_success_clears_to_full_strikes(reg, clock):
    for _ in range(3):
        reg.record_failure(A)
    clock.t += 10.1
    assert not reg.is_quarantined(A)       # re-admitted on probation
    reg.record_success(A, rtt_s=0.01)      # good behavior clears it
    assert not reg.record_failure(A)       # 1 of 3 again, no trip
    assert not reg.record_failure(A)
    assert reg.record_failure(A)           # full K strikes needed anew


def test_listener_exception_does_not_break_recording(reg):
    reg.subscribe(lambda ev, addr: (_ for _ in ()).throw(RuntimeError()))
    for _ in range(3):
        reg.record_failure(A)              # must not raise
    assert reg.is_quarantined(A)


class RecordingSource:
    def __init__(self):
        self.announces = []

    def find_peers(self, info_hash):
        return []

    def announce(self, info_hash, port):
        self.announces.append((info_hash, port))


def _eventually(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_quarantine_transition_triggers_reannounce(tmp_path, clock):
    """Quarantine-aware announce: a breaker trip (and the later
    probation re-admit) replays the announce for every swarm this host
    registered with. The sweep is asynchronous — the observing thread
    (a pull worker, a serve loop) must never block on tracker HTTP —
    so the assertions poll."""
    source = RecordingSource()
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    health = HealthRegistry(strikes_to_quarantine=2,
                            quarantine_base_s=10.0, time_fn=clock)
    swarm = SwarmDownloader(cfg, peer_sources=[source],
                            pool=ScriptedPool(), health=health)
    swarm.announce_available(XH, "aa")
    base = len(source.announces)
    assert base == 1

    for _ in range(2):
        health.record_failure(("bad", 9))
    assert _eventually(                        # quarantine re-announce
        lambda: len(source.announces) >= base + 1
        and swarm.stats.reannounces == 1), (
        source.announces, swarm.stats.reannounces)

    clock.t += 10.1
    health.partition([("bad", 9)])             # probation observation
    # Within the coalescing window the transition still fires, but the
    # per-swarm dedup skips the tracker round trip — a quarantine
    # storm re-registers each swarm once per window, not once per
    # transition (ISSUE 16 satellite).
    time.sleep(0.2)
    assert len(source.announces) == base + 1
    assert swarm.stats.reannounces == 1

    clock.t += swarm_mod.REANNOUNCE_WINDOW_S + 0.1
    for _ in range(2):
        health.record_failure(("bad", 9))      # re-trip past the window
    assert _eventually(
        lambda: len(source.announces) >= base + 2
        and swarm.stats.reannounces == 2), (
        source.announces, swarm.stats.reannounces)


def test_reannounce_without_prior_announce_is_noop(tmp_path, clock):
    source = RecordingSource()
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    health = HealthRegistry(strikes_to_quarantine=2,
                            quarantine_base_s=10.0, time_fn=clock)
    swarm = SwarmDownloader(cfg, peer_sources=[source],
                            pool=ScriptedPool(), health=health)
    for _ in range(2):
        health.record_failure(("bad", 9))
    time.sleep(0.1)  # nothing async should have been spawned either
    assert source.announces == []
    assert swarm.stats.reannounces == 0


def test_io_timeout_after_lease_attributed_as_seed_stall(tmp_path):
    """A peer that leases fine but times out mid-request stalled AS A
    SEEDER — struck with the distinct seed_stall kind (health.detail()
    separates 'serves, slowly-to-death' from 'unreachable'). A connect
    failure stays kind 'error'."""
    pool = ScriptedPool()
    stall_peer = FakePeer(lambda *a: (_ for _ in ()).throw(
        TimeoutError("stalled serving us")))
    pool.scripts[("stall", 1)] = [stall_peer]
    pool.scripts[("dead", 2)] = [ConnectionRefusedError("refused")]
    swarm = _swarm(tmp_path, pool)
    swarm.add_direct_peer("stall", 1)
    assert swarm.try_peer_download(XH, "aa", 0, 1) is None
    swarm.add_direct_peer("dead", 2)
    assert swarm.try_peer_download(XH, "aa", 0, 1) is None
    rows = {r["peer"]: r for r in swarm.health.detail()}
    assert rows["stall:1"]["strike_kinds"].get("seed_stall", 0) >= 1
    assert "error" not in rows["stall:1"]["strike_kinds"]
    assert rows["dead:2"]["strike_kinds"] == {"error": 1}


def test_close_unsubscribes_from_shared_registry(tmp_path, clock):
    """A closed swarm must not keep re-announcing on a shared
    registry's later transitions (zombie announces for a listen_port
    nobody serves)."""
    source = RecordingSource()
    cfg = Config(hf_home=tmp_path / "hf", cache_dir=tmp_path / "zest")
    health = HealthRegistry(strikes_to_quarantine=2,
                            quarantine_base_s=10.0, time_fn=clock)
    swarm = SwarmDownloader(cfg, peer_sources=[source],
                            pool=ScriptedPool(), health=health)
    swarm.announce_available(XH, "aa")
    swarm.close()
    for _ in range(2):
        health.record_failure(("bad", 9))
    time.sleep(0.1)  # an async sweep would have landed by now
    assert len(source.announces) == 1  # only the original announce
    assert swarm.stats.reannounces == 0
