"""Pallas BLAKE3 kernel vs the pure reference (bit-exactness).

Runs in interpreter mode on the CPU test mesh; the same kernel lowers to
Mosaic on TPU (verified on hardware by bench.py's correctness gate). The
grouped-grid design (leaf groups sequential, CVs in scratch) must be
bit-exact across group boundaries, so sizes straddle the 16-leaf group
width as well as all the tree shapes the XLA test covers.
"""

import numpy as np
import pytest

from zest_tpu.cas import blake3 as ref
from zest_tpu.ops.blake3_pallas import _LEAVES_PER_GROUP, PallasHasher

_RNG = np.random.default_rng(7)
_GROUP_BYTES = _LEAVES_PER_GROUP * 1024
_SIZES = [
    0, 1, 63, 64, 65, 1023, 1024, 1025, 3000,           # leaf shapes
    _GROUP_BYTES - 1, _GROUP_BYTES, _GROUP_BYTES + 1,   # group boundary
    2 * _GROUP_BYTES + 7, 40_000,                       # multi-group
]
# (the 64–128 KiB shapes run on hardware via bench.py's correctness gate;
# in the interpreter they cost minutes for no extra tree coverage)


@pytest.fixture(scope="module")
def hasher():
    return PallasHasher(interpret=True)


@pytest.mark.slow
def test_plain_matches_reference(hasher):
    """All tree shapes plus a mixed-length tail in ONE kernel call —
    interpret-mode execution is lane-parallel, so batching every case
    into a single 128-lane invocation costs the same ~60 s as one case.
    The tail models the gathered-pool shape (fixed capacity, variable
    fill per row)."""
    mixed = (5, 33_000, 1024, 0, 17_000, 7, 99, 512, 2048, 4097,
             9000, 12_345, 20_000, 31_999)
    chunks = [_RNG.bytes(n) for n in (*_SIZES, *mixed)]
    got = hasher.hash_batch(chunks)
    for c, g in zip(chunks, got):
        assert g == ref.blake3(c), f"mismatch at len {len(c)}"


@pytest.mark.slow
def test_keyed_matches_reference():
    # Small capacity on purpose: the key only changes per-compress flags,
    # orthogonal to tree shape, and each new capacity is a fresh ~60 s
    # interpret compile.
    key = bytes(range(32))
    hasher = PallasHasher(key=key, interpret=True)
    chunks = [_RNG.bytes(n) for n in (0, 100, 1024, 2000)]
    got = hasher.hash_batch(chunks)
    for c, g in zip(chunks, got):
        assert g == ref.blake3_keyed(key, c), f"mismatch at len {len(c)}"


@pytest.mark.slow
def test_batch_not_a_tile_multiple(hasher):
    # B=5 forces lane padding to 128; padded rows must not leak out
    chunks = [_RNG.bytes(100 + i) for i in range(5)]
    got = hasher.hash_batch(chunks)
    assert len(got) == 5
    for c, g in zip(chunks, got):
        assert g == ref.blake3(c)


def test_capacity_validation(hasher):
    import jax.numpy as jnp

    with pytest.raises(ValueError, match="1 KiB multiple"):
        hasher.hash_device(
            jnp.zeros((1, 100), jnp.uint32), jnp.zeros((1,), jnp.int32)
        )
    with pytest.raises(ValueError, match="128 KiB"):
        hasher.hash_device(
            jnp.zeros((1, 129 * 256), jnp.uint32),
            jnp.zeros((1,), jnp.int32),
        )


def test_bad_key_length():
    with pytest.raises(ValueError, match="32 bytes"):
        PallasHasher(key=b"short")


def test_empty_batch(hasher):
    assert hasher.hash_batch([]) == []
