"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has no model math, so it has no long-context story at all —
SURVEY.md §5 records "Long-context / sequence parallelism: Absent" and maps
its only structural analog (range-aware partial transfer,
src/bep_xet.zig:66-74) onto this build's sharding plane. The TPU build makes
long context first-class: sequences shard over a ``seq`` mesh axis, and
attention runs as a *ring* — K/V blocks rotate around the axis via
``jax.lax.ppermute`` while each device's resident Q block folds every
incoming block into a numerically stable streaming softmax (the blockwise /
flash recurrence). Peak memory per device is O(T/P · T/P) for scores instead
of O(T²), and each step's transfer overlaps the previous step's compute in
XLA's schedule, so ICI time hides behind the MXU.

Written shard_map-first: :func:`ring_self_attention` is the per-device
program (callable only inside ``shard_map``/``vmap`` with a bound axis
name); :func:`ring_attention` wraps it for globally sharded arrays. The
recurrence is a ``lax.scan`` over ring steps — static trip count, no Python
control flow under jit, reverse-differentiable (the ppermute transposes to
the reverse rotation, giving the ring-backward of Liu et al. for free).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from zest_tpu.parallel.spmd import pvary_over

SEQ_AXIS = "seq"

_NEG_INF = float("-inf")


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Exact (optionally causal) attention for one sequence shard.

    Must run under ``shard_map`` with ``axis_name`` bound to the mesh axis
    the sequence dimension is sharded over. Shapes are per-device:

    - ``q``: (B, Tq, H, D) — this device's query block
    - ``k``/``v``: (B, Tk, Hkv, D) — this device's key/value block; GQA is
      supported (H must be a multiple of Hkv)

    Returns (B, Tq, H, D) in ``q``'s dtype. Score/softmax math is float32
    (matching the dense paths in models/gpt2.py and models/moe.py); the
    P(=axis size) ring steps each do one ppermute of (k, v) to the next
    device and one blockwise accumulate, so every device sees every K/V
    block exactly once. Causality is enforced with global positions
    (block index × block length + offset), masking whole future blocks to
    -inf — they contribute exp(-inf)=0 to the running sums, keeping every
    shape static for XLA.
    """
    B, Tq, H, D = q.shape
    _, Tk, Hkv, _ = k.shape
    if H % Hkv:
        raise ValueError(f"query heads {H} not a multiple of kv heads {Hkv}")
    ring = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(D) if scale is None else scale

    qf = q.astype(jnp.float32) * scale
    perm = [(j, (j + 1) % ring) for j in range(ring)]
    qpos = idx * Tq + jnp.arange(Tq)

    def accumulate(acc, kb, vb, s):
        """Fold the K/V block held after ``s`` rotations into the running
        softmax. After s forward rotations this device holds the block
        that started on device (idx - s) mod ring."""
        m, l, o = acc
        owner = (idx - s) % ring
        kk = kb.astype(jnp.float32)
        if Hkv != H:  # GQA: broadcast each kv head across its query group
            kk = jnp.repeat(kk, H // Hkv, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kk)
        if causal:
            kpos = owner * Tk + jnp.arange(Tk)
            mask = kpos[None, :] <= qpos[:, None]
            scores = jnp.where(mask, scores, _NEG_INF)
        block_max = jnp.max(scores, axis=-1)                    # (B, H, Tq)
        new_m = jnp.maximum(m, block_max)
        # Fully masked so far → new_m = -inf; subtract 0 instead so the
        # exps stay NaN-free (scores are -inf there, giving p = 0).
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(scores - safe_m[..., None])                 # (B,H,Tq,Tk)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        vv = vb.astype(jnp.float32)
        if Hkv != H:
            vv = jnp.repeat(vv, H // Hkv, axis=2)
        upd = jnp.einsum("bhqk,bkhd->bhqd", p, vv)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + upd
        return new_m, l, o

    def step(carry, s):
        m, l, o, kb, vb = carry
        acc = accumulate((m, l, o), kb, vb, s)
        kb, vb = jax.lax.ppermute((kb, vb), axis_name, perm)
        return (*acc, kb, vb), None

    acc0 = (jnp.full((B, H, Tq), _NEG_INF, jnp.float32),
            jnp.zeros((B, H, Tq), jnp.float32),
            jnp.zeros((B, H, Tq, D), jnp.float32))
    m0, l0, o0 = pvary_over(acc0, (axis_name,), q, k, v)
    # Scan the first ring-1 accumulate-then-rotate steps, then fold the
    # final block in WITHOUT rotating — the last ppermute's output would
    # be discarded, and the scan carry would stop XLA from DCE'ing that
    # wasted K/V transfer (1/ring extra ICI bandwidth per layer).
    (m, l, o, kl, vl), _ = jax.lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(ring - 1)
    )
    m, l, o = accumulate((m, l, o), kl, vl, ring - 1)
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Ring attention over globally (B, T, H, D) arrays sharded on T.

    Convenience wrapper: shard_maps :func:`ring_self_attention` over
    ``mesh``'s ``seq_axis``. T must divide evenly by the axis size. All
    other mesh axes see the arrays as replicated; for combined data+seq
    sharding call ``ring_self_attention`` from your own shard_map (as
    models/llama.py's context-parallel step does).
    """
    spec = P(None, seq_axis)
    fn = jax.shard_map(
        functools.partial(
            ring_self_attention, axis_name=seq_axis,
            causal=causal, scale=scale,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
