"""Peer discovery via the JAX coordinator — the pod-native DHT replacement.

The reference finds peers with a Kademlia DHT + HTTP trackers
(src/dht.zig, src/bt_tracker.zig). Inside a pod/cluster every process
already shares a coordination service — the ``jax.distributed`` KV store —
so discovery is a key prefix, not a routing table:

    zest/avail/{info_hash_hex}/{process_id} -> "host:port"

``announce`` writes this process' DCN endpoint under each xorb it can
serve; ``find_peers`` lists the prefix. Both satisfy the
``SwarmDownloader.PeerSource`` protocol (zest_tpu.transfer.swarm), so the
waterfall code cannot tell coordinator discovery from DHT discovery.

An in-memory registry with the same surface backs single-process runs and
tests (the reference's analog: direct ``--peer`` flags, main.zig:180-187).
"""

from __future__ import annotations

import threading


class InMemoryRegistry:
    """Process-local PeerSource; also the fake for loopback swarm tests."""

    def __init__(self) -> None:
        self._avail: dict[bytes, dict[str, tuple[str, int]]] = {}
        self._lock = threading.Lock()
        self.self_addr: tuple[str, int] | None = None

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        with self._lock:
            items = list(self._avail.get(info_hash, {}).items())
        # Never hand back our own announce ("self" key), whatever self_addr
        # says — dialing ourselves would fake P2P stats.
        return [
            addr for key, addr in items
            if key != "self" and addr != self.self_addr
        ]

    def announce(self, info_hash: bytes, port: int) -> None:
        host = self.self_addr[0] if self.self_addr else "127.0.0.1"
        with self._lock:
            self._avail.setdefault(info_hash, {})["self"] = (host, port)

    def add(self, info_hash: bytes, host: str, port: int,
            peer_key: str | None = None) -> None:
        # Key defaults to the address so adding two peers never clobbers.
        key = peer_key if peer_key is not None else f"{host}:{port}"
        with self._lock:
            self._avail.setdefault(info_hash, {})[key] = (host, port)


def _kv_client():
    """The distributed-runtime KV client, or None when not initialized."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:
        return None


class CoordinatorRegistry:
    """PeerSource over the jax.distributed KV store.

    Requires ``jax.distributed.initialize`` (every multi-host TPU job has
    it). Announces are idempotent puts; lookups list the per-xorb prefix.
    """

    PREFIX = "zest/avail"

    def __init__(self, advertise_host: str, process_id: int | None = None):
        self.advertise_host = advertise_host
        self.process_id = process_id
        self._client = _kv_client()
        if self._client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; use InMemoryRegistry "
                "or call jax.distributed.initialize() first"
            )
        if self.process_id is None:
            import jax

            self.process_id = jax.process_index()

    def _prefix(self, info_hash: bytes) -> str:
        return f"{self.PREFIX}/{info_hash.hex()}"

    def announce(self, info_hash: bytes, port: int) -> None:
        self._client.key_value_set(
            f"{self._prefix(info_hash)}/{self.process_id}",
            f"{self.advertise_host}:{port}",
            allow_overwrite=True,
        )

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        try:
            entries = self._client.key_value_dir_get(self._prefix(info_hash))
        except Exception:
            return []
        out: list[tuple[str, int]] = []
        for key, value in entries:
            if key.rsplit("/", 1)[-1] == str(self.process_id):
                continue  # never hand back ourselves
            host, _, port = value.rpartition(":")
            if host and port.isdigit():
                out.append((host, int(port)))
        return out

    def barrier(self, name: str, timeout_s: float = 60.0) -> None:
        """Coordination-service barrier — staged rounds (seed-then-leech,
        per-wave sync) without inventing a side channel. Every process
        must call with the same ``name``."""
        self._client.wait_at_barrier(name, int(timeout_s * 1000))
