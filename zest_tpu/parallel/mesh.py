"""Mesh construction: the pod topology every distribution step runs over.

The reference has no mesh — its "topology" is whatever peers the DHT finds
(src/dht.zig). A TPU pod's membership is static per job, so topology here is
explicit: a ``jax.sharding.Mesh`` built from config, with one canonical 1-D
``pod`` axis for byte distribution (every device participates in the xorb
all-gather) and arbitrary N-D logical axes for landing checkpoints into a
pjit-sharded model (zest_tpu.models.loader).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.config import MeshConfig

POD_AXIS = "pod"


def pod_mesh(devices=None) -> Mesh:
    """1-D mesh over all devices: the byte-distribution plane.

    Bulk xorb movement is an all-gather along this axis; ICI carries it
    in-pod, DCN between pods (slice ordering puts same-host devices
    adjacent, so XLA's all-gather rides ICI hops first).
    """
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (POD_AXIS,))


def model_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """N-D logical mesh from ``MeshConfig.mesh_axes`` (e.g. data=2,model=4).

    Axis order is significant: earlier axes get the slower (DCN-adjacent)
    dimension, the last axis stays ICI-contiguous — the layout that keeps
    tensor-parallel collectives on ICI (SURVEY.md §5 "distributed backend").
    """
    devices = jax.devices() if devices is None else devices
    if not axes:
        return pod_mesh(devices)
    sizes = list(axes.values())
    n = math.prod(sizes)
    if n != len(devices):
        raise ValueError(
            f"mesh axes {axes} need {n} devices, have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(axes))


def mesh_from_config(mesh_cfg: MeshConfig, devices=None) -> Mesh:
    return model_mesh(mesh_cfg.mesh_axes or None, devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str = POD_AXIS) -> NamedSharding:
    """First-dimension sharding over ``axis`` — the pool layout."""
    return NamedSharding(mesh, P(axis))


def num_slots(mesh: Mesh, axis: str = POD_AXIS) -> int:
    """Pod slots along ``axis`` — the ``num_hosts`` a DistributionPlan must
    be built with to drive ``PodDistributor(mesh)`` (one slot per device on
    the axis; a multi-device process fetches for all its slots)."""
    return int(mesh.shape[axis])


def host_index() -> int:
    return jax.process_index()
