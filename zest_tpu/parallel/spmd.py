"""Small shared helpers for shard_map SPMD programs."""

from __future__ import annotations

import jax


def pvary_over(tree, axis_names, *operands):
    """Mark ``tree``'s leaves device-varying for shard_map VMA typing.

    A ``lax.scan`` carry initialized from constants starts *unvarying*,
    but the loop body mixes it with ``axis_index`` and the mapped
    operands, so its output is varying — a carry-type mismatch. This
    marks the initializers varying over ``axis_names`` **plus every
    manual axis the given operands vary over**, so the same program
    works inside single- and multi-axis shard_maps (e.g. a ring under
    ``{data, seq}``, a pipeline under ``{data, pipe}``).
    """
    vary = set(axis_names)
    for arr in operands:
        vary |= set(getattr(jax.typeof(arr), "vma", ()) or ())
    axes = tuple(sorted(vary))
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(tree, axes, to="varying")
    return jax.lax.pvary(tree, axes)  # pre-0.9 spelling
