"""TPU-native distribution plane: mesh, ownership plan, HBM tier, collectives.

This package is what makes the build TPU-first rather than a port
(SURVEY.md §2.4): the reference's dynamic peer swarm becomes a static pod
mesh (``mesh``), DHT lookup becomes a pure rendezvous-hash ownership
function (``plan``), the on-disk xorb cache gains a device-resident tier
(``hbm``), TCP peer wire becomes one jitted all-gather over ICI
(``collectives``), and tracker/DHT discovery becomes the jax.distributed
KV store (``coordinator``). The training plane's sharding modes live here
too: ring attention for sequence/context parallelism (``ring``) and the
GPipe SPMD schedule for pipeline parallelism (``pipeline``); tensor/
data/expert parallelism are PartitionSpec-driven in zest_tpu.models.
"""

from zest_tpu.parallel.collectives import (  # noqa: F401
    GatheredPool,
    PodDistributor,
    PoolLayout,
    all_gather_throughput,
    pack_rows,
    split_waves,
)
from zest_tpu.parallel.coordinator import (  # noqa: F401
    CoordinatorRegistry,
    InMemoryRegistry,
)
from zest_tpu.parallel.expert import (  # noqa: F401
    ExpertPlacement,
    ExpertRoutedPlan,
    classify_file,
)
from zest_tpu.parallel.hbm import HbmStagingCache, TieredCache  # noqa: F401
from zest_tpu.parallel.hierarchy import (  # noqa: F401
    HierarchicalDistributor,
    HierarchicalPlan,
    hier_mesh,
    owner_pod_host,
)
from zest_tpu.parallel.mesh import (  # noqa: F401
    POD_AXIS,
    mesh_from_config,
    model_mesh,
    num_slots,
    pod_mesh,
)
from zest_tpu.parallel.pipeline import (  # noqa: F401
    PIPE_AXIS,
    microbatch,
    pipeline_blocks,
    unmicrobatch,
)
from zest_tpu.parallel.plan import (  # noqa: F401
    DistributionPlan,
    FetchAssignment,
    owner_host,
)
from zest_tpu.parallel.ring import (  # noqa: F401
    SEQ_AXIS,
    ring_attention,
    ring_self_attention,
)
