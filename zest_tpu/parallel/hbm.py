"""HBM staging tier: device-resident xorb cache above the disk cache.

The reference's storage is two disk tiers (src/swarm.zig:57-148,
src/storage.zig:102-143). The TPU build adds tier 0: fetched xorb blobs
staged as ``jax.Array``s in HBM so (a) repeated extraction never re-uploads,
(b) blobs are already device-resident for the ICI all-gather
(zest_tpu.parallel.collectives), and (c) on-device BLAKE3
(zest_tpu.ops.blake3) can verify without a host round-trip.

Same range-aware ``get_with_range``/``put``/``put_partial`` contract as
:class:`zest_tpu.storage.XorbCache`, so the waterfall is tier-agnostic.
LRU eviction bounds occupancy to ``Config.hbm_staging_bytes``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

import jax
import numpy as np

from zest_tpu import telemetry
from zest_tpu.storage import CacheResult

_M_EVENTS = telemetry.counter(
    "zest_hbm_cache_events_total",
    "HBM staging-cache events (hit/miss/eviction)", ("event",))


@dataclass
class HbmEntry:
    array: jax.Array          # uint8[length], device-resident
    chunk_offset: int

    @property
    def nbytes(self) -> int:
        return int(self.array.size)


class HbmStagingCache:
    """LRU cache of xorb blobs in device memory.

    Keys follow the disk tier: ``{hash_hex}`` for full xorbs,
    ``{hash_hex}.{range_start}`` for partials (reference: swarm.zig:100-105).
    """

    def __init__(self, budget_bytes: int, device=None):
        self.budget_bytes = int(budget_bytes)
        self.device = device
        self._entries: OrderedDict[str, HbmEntry] = OrderedDict()
        self._used = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ── Core ops ──

    def _device_put(self, data: bytes) -> jax.Array:
        # np.frombuffer is a zero-copy view of the blob; hand it straight
        # to device_put so the only copy is host→device. (The old
        # jnp.asarray(...) materialized a committed default-device array
        # FIRST, then device_put copied it again — a full extra
        # traversal of every staged byte.)
        return jax.device_put(np.frombuffer(data, dtype=np.uint8),
                              self.device)

    def _insert(self, key: str, data: bytes, chunk_offset: int) -> None:
        if len(data) > self.budget_bytes:
            return  # larger than the whole tier: skip, disk tier has it
        arr = self._device_put(data)
        with self._lock:
            prev = self._entries.pop(key, None)
            if prev is not None:
                self._used -= prev.nbytes
            while self._used + len(data) > self.budget_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._used -= evicted.nbytes
                self.evictions += 1
                _M_EVENTS.inc(event="eviction")
            self._entries[key] = HbmEntry(arr, chunk_offset)
            self._used += len(data)

    def put(self, hash_hex: str, data: bytes) -> None:
        self._insert(hash_hex, data, 0)

    def put_partial(self, hash_hex: str, range_start: int, data: bytes) -> None:
        self._insert(f"{hash_hex}.{range_start}", data, range_start)

    def _lookup(self, hash_hex: str,
                range_start: int | None = None) -> HbmEntry | None:
        """One locked critical section per logical get: full-key probe,
        optional partial-key probe, LRU touch AND the hit/miss counter
        bump all happen under the same lock acquisition — concurrent
        pipeline workers can't interleave a probe with someone else's
        count, so hits+misses always equals the number of gets."""
        with self._lock:
            key = hash_hex
            entry = self._entries.get(key)
            if entry is None and range_start is not None:
                key = f"{hash_hex}.{range_start}"
                entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        _M_EVENTS.inc(event="hit" if entry is not None else "miss")
        return entry

    def get_device(self, hash_hex: str, range_start: int = 0) -> HbmEntry | None:
        """Device-resident lookup — the input to collectives/ops paths."""
        return self._lookup(hash_hex, range_start if range_start else None)

    def get_with_range(self, hash_hex: str, range_start: int,
                       covers=None) -> CacheResult | None:
        """Waterfall-compatible lookup: full entry first, then the partial
        keyed by ``range_start`` — bytes come back to host for extraction.
        ``covers`` follows the XorbCache fall-through contract: a
        non-covering full entry falls through to the partial instead of
        shadowing it (storage.XorbCache.get_with_range)."""
        if covers is None:
            entry = self._lookup(hash_hex, range_start)
            if entry is None:
                return None
            return CacheResult(bytes(np.asarray(entry.array)),
                               entry.chunk_offset)
        for key in (hash_hex, f"{hash_hex}.{range_start}"):
            entry = self._lookup(key, None)
            if entry is not None:
                result = CacheResult(bytes(np.asarray(entry.array)),
                                     entry.chunk_offset)
                if covers(result):
                    return result
        return None

    def has(self, hash_hex: str) -> bool:
        with self._lock:
            return hash_hex in self._entries

    # ── Introspection ──

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def summary(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "used_bytes": self._used,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


class TieredCache:
    """HBM tier over the disk tier with waterfall-identical semantics.

    Reads hit HBM first; disk hits are promoted into HBM. Writes go to both
    (disk is durable truth for seeding across restarts; HBM is the fast
    tier). Drop-in for XorbCache anywhere in the transfer pipeline.
    """

    def __init__(self, disk, hbm: HbmStagingCache):
        self.disk = disk
        self.hbm = hbm

    def has(self, hash_hex: str) -> bool:
        return self.hbm.has(hash_hex) or self.disk.has(hash_hex)

    def get_with_range(self, hash_hex: str, range_start: int,
                       covers=None) -> CacheResult | None:
        res = self.hbm.get_with_range(hash_hex, range_start,
                                      covers=covers)
        if res is not None:
            return res
        res = self.disk.get_with_range(hash_hex, range_start,
                                       covers=covers)
        if res is not None:
            if res.chunk_offset == 0:
                self.hbm.put(hash_hex, res.data)
            else:
                self.hbm.put_partial(hash_hex, res.chunk_offset, res.data)
        return res

    def put(self, hash_hex: str, data: bytes) -> None:
        self.disk.put(hash_hex, data)
        self.hbm.put(hash_hex, data)

    def put_partial(self, hash_hex: str, range_start: int, data: bytes) -> None:
        self.disk.put_partial(hash_hex, range_start, data)
        self.hbm.put_partial(hash_hex, range_start, data)
