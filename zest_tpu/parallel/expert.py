"""Expert-sharded distribution: route each expert's xorbs to its host.

BASELINE config #4 ("Mixtral-8x7B expert-sharded"): under expert
parallelism each host holds only n_experts / n_hosts experts, so
replicating every checkpoint byte to every host — the plain
PodDistributor all-gather — wastes (X-1)/X of the ICI traffic and HBM for
the expert weights (≈27B of Mixtral's 47B params). This planner splits a
pull into:

  - **shared units** — xorb ranges feeding dense tensors (attention,
    norms, router, embeddings) every host needs: distributed by the normal
    rendezvous plan + ICI all-gather (zest_tpu.parallel.collectives).
  - **expert units** — ranges feeding exactly one expert's tensors: owned
    and fetched *only* by that expert's host, never gathered. A range
    touching several experts' tensors (chunk straddles a boundary) is
    routed to one of them and served to the rest over the peer waterfall.

The reference has no analog — its swarm replicates whole files to whoever
asks (src/swarm.zig:279-314); expert routing is the TPU-native counterpart
of "only fetch what you'll serve" (SURVEY.md §2.4 "per-expert xorb→device
routing").

Coordinate chain: safetensors header → tensor byte ranges
(models/safetensors_io.parse_header_prefix) → reconstruction term spans
(prefix sums of unpacked_length) → fetch-info units → owner host.
"""

from __future__ import annotations

from bisect import bisect_right
from dataclasses import dataclass, field

from zest_tpu.cas import hashing
from zest_tpu.cas.reconstruction import Reconstruction, Term
from zest_tpu.parallel.plan import (
    DistributionPlan,
    FetchAssignment,
    owner_host,
)


@dataclass(frozen=True)
class ExpertPlacement:
    """Static expert → host map, matching ``P(EXPERT_AXIS)`` sharding.

    Contiguous blocks: expert e lives on host ``e * num_hosts //
    n_experts`` — the same slicing GSPMD gives a stacked [X, ...] array
    sharded over an ``expert`` mesh axis of size ``num_hosts``, so bytes
    routed here land on the host whose shard consumes them.
    """

    n_experts: int
    num_hosts: int

    def __post_init__(self):
        if self.n_experts <= 0 or self.num_hosts <= 0:
            raise ValueError("n_experts and num_hosts must be positive")

    def host_of_expert(self, expert: int) -> int:
        if not 0 <= expert < self.n_experts:
            raise ValueError(f"expert {expert} out of range")
        return expert * self.num_hosts // self.n_experts

    def experts_of_host(self, host: int) -> list[int]:
        return [x for x in range(self.n_experts)
                if self.host_of_expert(x) == host]


@dataclass(frozen=True)
class FileTensorMap:
    """One file's routing inputs: its reconstruction + tensor byte ranges.

    ``tensor_experts`` maps absolute file byte ranges to the expert index
    owning those bytes (None = dense/shared) — built by ``classify_file``
    from a safetensors header and an ``expert_of(name)`` function such as
    models/moe.expert_of_tensor.
    """

    rec: Reconstruction
    # sorted, non-overlapping: (file_start, file_end, expert | None)
    tensor_experts: tuple[tuple[int, int, int | None], ...]


def classify_file(
    rec: Reconstruction,
    header,
    expert_of,
) -> FileTensorMap:
    """Build a FileTensorMap from a parsed safetensors header.

    Bytes not covered by any tensor (the header itself, padding) are
    shared — every host parses headers during reassembly.
    """
    spans = sorted(
        (*info.file_range(header.data_start), expert_of(name))
        for name, info in header.tensors.items()
        if info.nbytes
    )
    return FileTensorMap(rec, tuple(spans))


def _term_spans(rec: Reconstruction) -> list[tuple[int, int, Term]]:
    """Absolute file byte span of each term (prefix sums)."""
    spans, off = [], 0
    for t in rec.terms:
        spans.append((off, off + t.unpacked_length, t))
        off += t.unpacked_length
    return spans


def _experts_touching(
    span: tuple[int, int],
    tensor_experts: tuple[tuple[int, int, int | None], ...],
    starts: list[int],
) -> tuple[set[int], bool]:
    """(expert indices, any_shared_bytes) for a file byte span.

    ``shared`` is True when the span holds any byte outside expert
    tensors — dense-tensor bytes, the header, or inter-tensor padding —
    because every host needs those bytes to reassemble the file.
    """
    lo, hi = span
    experts: set[int] = set()
    shared = False
    covered = lo
    i = max(bisect_right(starts, lo) - 1, 0)
    while i < len(tensor_experts) and tensor_experts[i][0] < hi:
        t_lo, t_hi, expert = tensor_experts[i]
        if t_hi > lo:
            if expert is None:
                shared = True
            else:
                experts.add(expert)
            if t_lo > covered:
                shared = True  # uncovered gap before this tensor
            covered = max(covered, t_hi)
        i += 1
    if covered < hi:
        shared = True
    return experts, shared


@dataclass
class ExpertRoutedPlan:
    """A pull split into the all-gather plan and per-host expert fetches."""

    placement: ExpertPlacement
    shared: DistributionPlan
    # host -> the expert units it (and only it) fetches
    expert_units: dict[int, list[FetchAssignment]] = field(
        default_factory=dict
    )

    @staticmethod
    def build(
        files: list[FileTensorMap],
        placement: ExpertPlacement,
    ) -> "ExpertRoutedPlan":
        num_hosts = placement.num_hosts
        # unit key -> (fetch_info, expert owners seen, shared?)
        units: dict[tuple[str, int], list] = {}
        for fm in files:
            spans = _term_spans(fm.rec)
            starts = [s for s, _, _ in fm.tensor_experts]
            for t_lo, t_hi, term in spans:
                fi = fm.rec.find_fetch_info(term)
                if fi is None:
                    # A term no fetch_info covers can never be fetched;
                    # dropping it would produce a complete-looking plan
                    # that fails only at reassembly time.
                    raise ValueError(
                        f"no fetch_info covers term {term.hash_hex}"
                        f"[{term.range.start},{term.range.end})"
                    )
                key = (term.hash_hex, fi.range.start)
                experts, shared = _experts_touching(
                    (t_lo, t_hi), fm.tensor_experts, starts
                )
                entry = units.setdefault(key, [fi, set(), False])
                if fi.range.end > entry[0].range.end:
                    entry[0] = fi
                entry[1] |= experts
                entry[2] |= shared
        shared_plan = DistributionPlan(num_hosts, [])
        expert_units: dict[int, list[FetchAssignment]] = {}
        for (hh, start), (fi, experts, shared) in sorted(units.items()):
            if shared or not experts:
                shared_plan.assignments.append(FetchAssignment(
                    hash_hex=hh, fetch_info=fi,
                    owner=owner_host(
                        hashing.hex_to_hash(hh), start, num_hosts
                    ),
                ))
            else:
                # Unit feeds only expert tensors. Route to the host owning
                # the (deterministically) first expert; a straddling unit's
                # other experts read it via the peer waterfall.
                host = placement.host_of_expert(min(experts))
                expert_units.setdefault(host, []).append(FetchAssignment(
                    hash_hex=hh, fetch_info=fi, owner=host,
                ))
        return ExpertRoutedPlan(placement, shared_plan, expert_units)

    def units_for_host(self, host: int) -> list[FetchAssignment]:
        """Everything this host fetches from CDN/disk: its rendezvous share
        of the shared plan plus its experts' private units."""
        return self.shared.for_host(host) + self.expert_units.get(host, [])

    @property
    def expert_bytes(self) -> int:
        return sum(
            a.est_bytes for units in self.expert_units.values()
            for a in units
        )

    def summary(self) -> dict:
        shared = self.shared.summary()
        per_host = [0] * self.placement.num_hosts
        for host, units in self.expert_units.items():
            per_host[host] += sum(a.est_bytes for a in units)
        total = self.expert_bytes
        n = self.placement.num_hosts
        return {
            "shared": shared,
            "expert_units": sum(len(u) for u in self.expert_units.values()),
            "expert_bytes": total,
            "expert_bytes_per_host": per_host,
            # ICI bytes the split avoids: an all-gather would move each
            # expert byte to the other n-1 hosts.
            "ici_bytes_saved": total * (n - 1),
        }
