"""Pipeline parallelism: GPipe-style microbatch pipelining as one SPMD
program over a ``pipe`` mesh axis.

The reference has no model execution at all (SURVEY.md §2.4: "none of
DP/TP/PP/..."); the TPU build's training plane carries the full sharding
set, and this module supplies PP. Design is the idiomatic-XLA formulation
rather than a multi-program schedule: every device runs the *same* traced
program (shard_map over the ``pipe`` axis), stage identity comes from
``axis_index``, activations move stage-to-stage with ``ppermute``, and the
schedule is a single ``lax.scan`` over ``M + S - 1`` ticks (M microbatches
through S stages — the GPipe bubble). Data selection is masked (`where` on
stage id), never branched, so shapes stay static and XLA overlaps each
tick's ppermute with the next tick's layer compute.

Composition contract: the model's per-layer params are *stacked* on a
leading layer axis (the convention every model in zest_tpu.models already
follows for ``lax.scan``), so sharding that axis over ``pipe`` — spec
``P('pipe', ...)`` — gives each stage a contiguous block of layers with no
reshuffling. Reverse-mode differentiates through ppermute/scan into the
standard backward pipeline schedule automatically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from zest_tpu.parallel.spmd import pvary_over

PIPE_AXIS = "pipe"


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...). Batch must divide evenly."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} not divisible into {n_microbatches} microbatches"
        )
    return x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """(M, mb, ...) → (M*mb, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def pipeline_spmd(
    block_fn: Callable,
    local_params,
    xs: jax.Array,
    axis_name: str = PIPE_AXIS,
):
    """The per-device pipeline program (call inside ``shard_map``).

    - ``block_fn(carry, layer_params) -> (carry, None)``: one layer, the
      exact signature ``lax.scan`` bodies already use in zest_tpu.models.
    - ``local_params``: this stage's stacked layer slice (L/S leading dim).
    - ``xs``: (M, mb, ...) — the full microbatched input, replicated; only
      stage 0 reads it.

    Returns (M, mb, ...) — valid on the LAST stage (other stages hold
    zeros; the wrapper selects the last stage's copy).

    Tick ``t``: stage ``s`` works on microbatch ``t - s``. A stage whose
    microbatch index is out of [0, M) computes on masked (zero) data —
    the pipeline bubble costs compute but keeps one uniform program.
    """
    S = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    M = xs.shape[0]
    mb_shape = xs.shape[1:]

    def run_stage(act):
        out, _ = jax.lax.scan(block_fn, act, local_params)
        return out

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 injects microbatch t (clamped; masked when t >= M),
        # other stages consume what the previous stage sent last tick.
        inj = xs[jnp.clip(t, 0, M - 1)]
        act = jnp.where(s == 0, inj, recv)
        act = run_stage(act)
        # Last stage banks microbatch t - (S-1) once it's real.
        out_idx = t - (S - 1)
        bank = (s == S - 1) & (out_idx >= 0)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(bank, act, outputs[jnp.clip(out_idx, 0, M - 1)]),
            jnp.clip(out_idx, 0, M - 1), 0,
        )
        # Shift stage s → s+1. Non-circular: stage 0 receives zeros
        # (immediately overwritten by its injection next tick).
        sent = jax.lax.ppermute(
            act, axis_name, [(i, i + 1) for i in range(S - 1)]
        )
        return (sent, outputs), None

    zeros = jnp.zeros(mb_shape, xs.dtype)
    outputs0 = jnp.zeros((M, *mb_shape), xs.dtype)
    zeros, outputs0 = pvary_over(
        (zeros, outputs0), (axis_name,),
        xs, *jax.tree.leaves(local_params),
    )
    (_, outputs), _ = jax.lax.scan(
        tick, (zeros, outputs0), jnp.arange(M + S - 1)
    )
    # Only the last stage's bank is real; zero the rest so the caller can
    # sum-select across the pipe axis without a gather.
    return jnp.where(s == S - 1, outputs, 0)


def pipeline_blocks(
    block_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    n_microbatches: int,
    axis_name: str = PIPE_AXIS,
    param_specs=None,
) -> jax.Array:
    """Run stacked layers over ``x`` (B, ...) through the pipeline.

    ``stacked_params``: pytree with leading layer dim L on every leaf
    (L divisible by the pipe-axis size); ``param_specs`` optionally maps
    each leaf to its spec — defaults to ``P(axis_name)`` (layer-sharded,
    everything else replicated). Returns (B, ...) with the same meaning as
    ``lax.scan(block_fn, x, stacked_params)`` run unsharded.
    """
    if param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    xs = microbatch(x, n_microbatches)

    # out_specs P() needs a device-invariant value: non-last stages hold
    # zeros, so a psum over the pipe axis reconstructs the last stage's
    # bank everywhere (one small all-reduce of the final activations).
    def mapped(params, xs):
        out = pipeline_spmd(block_fn, params, xs, axis_name)
        return jax.lax.psum(out, axis_name)

    fn = jax.shard_map(
        mapped, mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
    )
    return unmicrobatch(fn(stacked_params, xs))
