"""Distribution plan: deterministic xorb → owner-host assignment.

The reference answers "who has this xorb?" dynamically (DHT lookup,
src/dht.zig:400-446). A pod inverts the question: membership is static, so
*ownership is a pure function* — every host computes the same plan with no
coordination, via rendezvous (highest-random-weight) hashing of
(xorb hash, range start, host). Owners fetch their xorbs from CDN/disk;
everyone else receives the bytes over ICI/DCN (zest_tpu.parallel.collectives)
or pulls them from the owner via chunk RPC. HRW keeps assignment balanced
and stable: a host joining/leaving remaps only its own share — the TPU
equivalent of the reference's per-xorb swarm identity (src/peer_id.zig:28-33).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from zest_tpu.cas import hashing
from zest_tpu.cas.reconstruction import FetchInfo, Reconstruction


def owner_host(xorb_hash: bytes, range_start: int, num_hosts: int) -> int:
    """Rendezvous-hash owner of one fetch unit among ``num_hosts``."""
    if num_hosts <= 0:
        raise ValueError("num_hosts must be positive")
    if num_hosts == 1:
        return 0
    tail = struct.pack("<Q", range_start)
    best_host, best_score = 0, b""
    for h in range(num_hosts):
        score = hashing.blake3_hash(
            xorb_hash + tail + struct.pack("<Q", h)
        )
        if score > best_score:
            best_host, best_score = h, score
    return best_host


@dataclass(frozen=True)
class FetchAssignment:
    """One fetch unit: a xorb's fetch_info range, owned by ``owner``."""

    hash_hex: str
    fetch_info: FetchInfo
    owner: int

    @property
    def est_bytes(self) -> int:
        """Compressed transfer size — the load-balance weight."""
        return self.fetch_info.url_range_end - self.fetch_info.url_range_start


def collect_units(
    recs: list[Reconstruction],
) -> list[tuple[tuple[str, int], FetchInfo]]:
    """Deduplicated, sorted fetch units for a set of reconstructions.

    Chunk-level dedup: a xorb range shared across files (or repeated
    terms) is fetched exactly once. Keeps the widest entry per start — a
    narrower duplicate would leave later readers short of chunks. Shared
    by every planner (flat, hierarchical, expert-routed) so ownership
    policies differ without re-collecting.
    """
    units: dict[tuple[str, int], FetchInfo] = {}
    for rec in recs:
        for hash_hex, entries in rec.fetch_info.items():
            for fi in entries:
                key = (hash_hex, fi.range.start)
                prev = units.get(key)
                if prev is None or fi.range.end > prev.range.end:
                    units[key] = fi
    return sorted(units.items())


@dataclass
class DistributionPlan:
    """The pod-wide fetch schedule for a set of files.

    Built identically on every host from the same reconstructions (order-
    independent: units are sorted by key before assignment), so no plan
    needs to be exchanged — the TPU analog of the reference's emergent
    per-peer scheduling (src/swarm.zig:279-314).
    """

    num_hosts: int
    assignments: list[FetchAssignment] = field(default_factory=list)
    _by_owner: dict[int, list[FetchAssignment]] | None = field(
        default=None, repr=False, compare=False
    )

    @staticmethod
    def build(recs: list[Reconstruction], num_hosts: int) -> "DistributionPlan":
        assignments = [
            FetchAssignment(
                hash_hex=hh,
                fetch_info=fi,
                owner=owner_host(
                    hashing.hex_to_hash(hh), start, num_hosts
                ),
            )
            for (hh, start), fi in collect_units(recs)
        ]
        return DistributionPlan(num_hosts, assignments)

    def by_owner(self) -> dict[int, list[FetchAssignment]]:
        """Assignments grouped by owner — built once, O(units)."""
        if self._by_owner is None:
            grouped: dict[int, list[FetchAssignment]] = {}
            for a in self.assignments:
                grouped.setdefault(a.owner, []).append(a)
            self._by_owner = grouped
        return self._by_owner

    def for_host(self, host: int) -> list[FetchAssignment]:
        """The fetch units this host must source from CDN/disk."""
        return self.by_owner().get(host, [])

    def bytes_per_host(self) -> list[int]:
        out = [0] * self.num_hosts
        for a in self.assignments:
            out[a.owner] += a.est_bytes
        return out

    @property
    def total_bytes(self) -> int:
        return sum(a.est_bytes for a in self.assignments)

    def summary(self) -> dict:
        per_host = self.bytes_per_host()
        peak = max(per_host) if per_host else 0
        mean = self.total_bytes / self.num_hosts if self.num_hosts else 0
        return {
            "units": len(self.assignments),
            "hosts": self.num_hosts,
            "total_bytes": self.total_bytes,
            "bytes_per_host": per_host,
            # 1.0 = perfectly balanced CDN ingress (design target for
            # BASELINE config #5's hierarchical scheduling).
            "balance": round(mean / peak, 4) if peak else 1.0,
        }
