"""ICI distribution: bulk xorb movement as XLA collectives.

The reference moves bulk bytes peer-to-peer over TCP (src/bt_wire.zig,
src/bt_peer.zig). In-pod, the wire is the mesh: each host stages the blobs
it owns (per the rendezvous plan) into rows of a pool array sharded over the
``pod`` axis, and one jitted resharding — sharded → replicated — makes XLA
emit the all-gather that carries every row to every device over ICI. No
framing, no handshakes, no per-peer state machines; "seeding" is
participating in the collective (SURVEY.md §2.1 row 15).

Row protocol: each fetch unit gets one fixed-capacity row shaped
``[u32le length][blob bytes][zero padding]``. Capacity is computed from the
plan (identical on every host, no negotiation), rows are grouped by owner so
shard *h* of the pool is exactly host *h*'s contribution.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from zest_tpu.parallel.mesh import POD_AXIS, replicated, row_sharded
from zest_tpu.parallel.plan import DistributionPlan, FetchAssignment

_LEN_HEADER = 4
_ROW_ALIGN = 128  # TPU lane width: keep the trailing dim MXU/VPU-friendly


def _round_up(n: int, align: int) -> int:
    return (n + align - 1) // align * align


@dataclass(frozen=True)
class PoolLayout:
    """Deterministic row layout for a plan — computed identically everywhere.

    ``rows_per_host`` rows per pod slot (padded to the max so shards are
    equal); unit *i* of host *h* lives at row ``h * rows_per_host + i``.
    """

    num_hosts: int
    rows_per_host: int
    row_len: int
    # (hash_hex, fetch range start) -> (row, chunk_offset)
    index: dict[tuple[str, int], tuple[int, int]]
    # per host: its unit keys in row order (so packing is O(own units))
    host_keys: tuple[tuple[tuple[str, int], ...], ...]
    # hashes whose single unit at start 0 is provably the whole xorb
    full_xorbs: frozenset[str]

    @property
    def total_rows(self) -> int:
        return self.num_hosts * self.rows_per_host

    @property
    def pool_bytes(self) -> int:
        return self.total_rows * self.row_len

    @staticmethod
    def from_plan(plan: DistributionPlan) -> "PoolLayout":
        by_owner = plan.by_owner()
        per_host: list[list[FetchAssignment]] = [
            by_owner.get(h, []) for h in range(plan.num_hosts)
        ]
        rows_per_host = max((len(units) for units in per_host), default=0)
        max_blob = max(
            (a.est_bytes for a in plan.assignments), default=0
        )
        row_len = _round_up(_LEN_HEADER + max_blob, _ROW_ALIGN)
        index: dict[tuple[str, int], tuple[int, int]] = {}
        host_keys: list[tuple[tuple[str, int], ...]] = []
        starts_by_hash: dict[str, list[int]] = {}
        for h, units in enumerate(per_host):
            keys = []
            for i, a in enumerate(units):
                key = (a.hash_hex, a.fetch_info.range.start)
                index[key] = (h * rows_per_host + i, a.fetch_info.range.start)
                keys.append(key)
                starts_by_hash.setdefault(a.hash_hex, []).append(
                    a.fetch_info.range.start
                )
            host_keys.append(tuple(keys))
        # Same evidence rule as XetBridge._cache_fetched: a blob is the
        # whole xorb only when its hash has exactly one planned range and
        # that range starts at chunk 0.
        full = frozenset(
            hh for hh, starts in starts_by_hash.items()
            if starts == [0]
        )
        return PoolLayout(
            plan.num_hosts, rows_per_host, row_len, index,
            tuple(host_keys), full,
        )


def split_waves(
    plan: DistributionPlan,
    budget_bytes: int,
    pad_factor: int = 4,
) -> list[DistributionPlan]:
    """Split a plan into waves whose staged pool fits an HBM budget.

    The reference bounds in-flight memory by batching 128 terms at a time
    (src/parallel_download.zig:117-131); the collective analog is bounding
    each all-gather's pool. Two concerns, one mechanism: units are sorted
    by descending size, so each wave's ``row_len`` is set by its first
    unit and (a) the wave is closed before ``pool_bytes`` would exceed
    ``budget_bytes``, (b) a unit needing less than ``row_len/pad_factor``
    opens a fresh wave instead of paying >pad_factor× row padding (one
    64 MiB xorb among thousands of 100 KB ranges would otherwise inflate
    the pool ~600×). Deterministic: every host computes the same split
    from the same plan, no negotiation. A single unit larger than the
    budget still gets its own wave — it cannot be subdivided here.

    ``budget_bytes <= 0`` disables windowing (one wave).
    """
    if budget_bytes <= 0 or len(plan.assignments) <= 1:
        return [plan]
    units = sorted(
        plan.assignments,
        key=lambda a: (-a.est_bytes, a.hash_hex, a.fetch_info.range.start),
    )
    waves: list[DistributionPlan] = []
    cur: list[FetchAssignment] = []
    counts: dict[int, int] = {}
    rows_per_host = 0
    row_len = 0
    for a in units:
        need = _round_up(_LEN_HEADER + a.est_bytes, _ROW_ALIGN)
        if cur:
            new_rows = max(rows_per_host, counts.get(a.owner, 0) + 1)
            if (plan.num_hosts * new_rows * row_len > budget_bytes
                    or need * pad_factor < row_len):
                waves.append(DistributionPlan(plan.num_hosts, cur))
                cur, counts, rows_per_host = [], {}, 0
        if not cur:
            row_len = need
        cur.append(a)
        counts[a.owner] = counts.get(a.owner, 0) + 1
        rows_per_host = max(rows_per_host, counts[a.owner])
    if cur:
        waves.append(DistributionPlan(plan.num_hosts, cur))
    return waves


def pack_rows(
    layout: PoolLayout,
    blobs: dict[tuple[str, int], bytes],
    host: int,
) -> np.ndarray:
    """Host ``host``'s shard of the pool: its owned blobs in row order."""
    out = np.zeros((layout.rows_per_host, layout.row_len), dtype=np.uint8)
    base = host * layout.rows_per_host
    for key in layout.host_keys[host]:
        row, _off = layout.index[key]
        blob = blobs.get(key)
        if blob is None or _LEN_HEADER + len(blob) > layout.row_len:
            # Missing or over-capacity blob: leave a zero row so readers
            # fall through the waterfall to CDN — one bad unit must never
            # abort the whole round (or strand a multi-host collective).
            continue
        r = row - base
        out[r, :_LEN_HEADER] = np.frombuffer(
            len(blob).to_bytes(_LEN_HEADER, "little"), dtype=np.uint8
        )
        out[r, _LEN_HEADER : _LEN_HEADER + len(blob)] = np.frombuffer(
            blob, dtype=np.uint8
        )
    return out


FETCH_WORKERS = 16  # matches the reference's 16-way concurrent fetcher
                    # (default_max_concurrent_downloads, config.zig:13)


def fetch_owned_blobs(
    plan: DistributionPlan, fetch_fn, slot: int,
    workers: int = FETCH_WORKERS,
) -> dict[tuple[str, int], bytes]:
    """Fetch every unit ``slot`` owns, ``workers``-way concurrent (the
    units are CDN/disk reads — I/O bound). A failed fetch leaves its key
    out (→ zero row → CDN fallback downstream): one bad unit must never
    abort a round or strand a multi-host collective."""
    from concurrent.futures import ThreadPoolExecutor

    owned = plan.for_host(slot)
    blobs: dict[tuple[str, int], bytes] = {}
    if not owned:
        return blobs

    def one(a):
        try:
            return (a.hash_hex, a.fetch_info.range.start), fetch_fn(a)
        except Exception:
            return None

    if len(owned) == 1 or workers <= 1:
        results = map(one, owned)
    else:
        with ThreadPoolExecutor(min(workers, len(owned))) as pool:
            results = list(pool.map(one, owned))
    for r in results:
        if r is not None:
            blobs[r[0]] = r[1]
    return blobs


def pack_global_rows(
    layout: PoolLayout,
    plan: DistributionPlan,
    fetch_fn,
    slot: int | None,
    local_shards: dict[int, dict[tuple[str, int], bytes]] | None = None,
) -> np.ndarray:
    """Single-process pool assembly, shared by the flat and hierarchical
    distributors.

    ``slot=None`` means this process is the sole controller of every mesh
    slot (one host driving N chips) and fetches every slot's band itself.
    An explicit ``slot`` simulates one host of a multi-host pod: only that
    band is fetched, other slots come from ``local_shards`` (tests) or
    stay zero (→ waterfall fallback downstream)."""
    bands = []
    for h in range(plan.num_hosts):
        if local_shards and h in local_shards:
            bands.append(pack_rows(layout, local_shards[h], h))
        elif slot is None or h == slot:
            bands.append(
                pack_rows(layout, fetch_owned_blobs(plan, fetch_fn, h), h)
            )
        else:
            bands.append(
                np.zeros((layout.rows_per_host, layout.row_len), np.uint8)
            )
    return np.concatenate(bands, axis=0)


@partial(jax.jit, static_argnames=("mesh",))
def _replicate_jit(mesh: Mesh, pool: jax.Array) -> jax.Array:
    """sharded-over-pod → replicated: XLA lowers this to an ICI all-gather."""
    return jax.lax.with_sharding_constraint(pool, replicated(mesh))


def _replicate(mesh: Mesh, pool: jax.Array) -> jax.Array:
    out = _replicate_jit(mesh, pool)
    if not out.sharding.is_fully_replicated:
        # Older jax (observed on 0.4.37 CPU) drops the output constraint
        # and returns the input sharding; an explicit resharding
        # device_put restores the replication contract. No-op (never
        # taken) on versions where the jitted constraint holds.
        out = jax.device_put(out, replicated(mesh))
    return out


class GatheredPool:
    """The post-all-gather pool: every device holds every row."""

    def __init__(self, layout: PoolLayout, pool: jax.Array):
        self.layout = layout
        self.pool = pool
        self._host_view: np.ndarray | None = None

    def _rows(self) -> np.ndarray:
        if self._host_view is None:
            self._host_view = np.asarray(self.pool)
        return self._host_view

    def blob(self, hash_hex: str, range_start: int) -> tuple[bytes, int] | None:
        """(blob bytes, chunk_offset) for a fetch unit, or None."""
        loc = self.layout.index.get((hash_hex, range_start))
        if loc is None:
            return None
        row, chunk_offset = loc
        raw = self._rows()[row]
        n = int.from_bytes(raw[:_LEN_HEADER].tobytes(), "little")
        if n == 0 or _LEN_HEADER + n > self.layout.row_len:
            return None
        return raw[_LEN_HEADER : _LEN_HEADER + n].tobytes(), chunk_offset

    def fill_cache(self, cache, verify=None) -> tuple[int, int]:
        """Seed a range-aware cache (disk/HBM/tiered) with every gathered
        blob — after this, the waterfall's tier-1 lookup hits locally and
        the P2P byte ratio goes to 1.0 for planned units.

        ``verify(hash_hex, data)`` optionally gates *full-xorb* writes
        (partial blobs carry per-chunk hashes in their frames, checked at
        extraction). Returns (filled, rejected).
        """
        filled = rejected = 0
        for (hash_hex, range_start) in self.layout.index:
            got = self.blob(hash_hex, range_start)
            if got is None:
                continue
            data, chunk_offset = got
            # Full-key writes need proof the blob is the whole xorb
            # (layout.full_xorbs); an offset-0 slice cached as full would
            # poison later range reads (same rule as bridge._cache_fetched).
            if chunk_offset == 0 and hash_hex in self.layout.full_xorbs:
                if verify is not None and not verify(hash_hex, data):
                    rejected += 1
                    continue
                cache.put(hash_hex, data)
            else:
                cache.put_partial(hash_hex, chunk_offset, data)
            filled += 1
        return filled, rejected


class PodDistributor:
    """Orchestrates one distribution round: stage → all-gather → index.

    ``fetch_fn(assignment) -> bytes`` is called only for units this host
    owns; the returned blob must cover exactly the assignment's fetch-info
    chunk range (owners with a full xorb on disk slice it first). Missing
    units (fetch_fn raised) leave a zero-length row — readers fall through
    the waterfall to CDN, preserving the reference's degradation semantics
    (SURVEY.md §5 "failure detection").
    """

    def __init__(self, mesh: Mesh, axis: str = POD_AXIS):
        self.mesh = mesh
        self.axis = axis

    def _mesh_slots(self) -> int:
        return int(self.mesh.shape[self.axis])

    def local_slots(self) -> list[int]:
        """Pod-axis slots backed by a device this process addresses."""
        k = list(self.mesh.axis_names).index(self.axis)
        by_slot = np.moveaxis(np.asarray(self.mesh.devices), k, 0)
        by_slot = by_slot.reshape(by_slot.shape[0], -1)  # 1-axis mesh safe
        pid = jax.process_index()
        return [
            i for i in range(by_slot.shape[0])
            if any(d.process_index == pid for d in by_slot[i])
        ]

    def distribute(
        self,
        plan: DistributionPlan,
        fetch_fn,
        host: int | None = None,
        local_shards: dict[int, dict[tuple[str, int], bytes]] | None = None,
    ) -> GatheredPool:
        """Run the round.

        Single-process: ``host=None`` (default) fetches every slot's band
        — the sole-controller case (one host, N chips); an explicit
        ``host`` simulates one host of a multi-host pod, with
        ``local_shards`` optionally pre-supplying other slots (tests).
        Multi-process: each process packs only the bands of slots whose
        devices it addresses.
        """
        if plan.num_hosts != self._mesh_slots():
            raise ValueError(
                f"plan built for {plan.num_hosts} hosts, mesh axis "
                f"{self.axis!r} has {self._mesh_slots()} slots"
            )
        layout = PoolLayout.from_plan(plan)
        if layout.total_rows == 0:
            return GatheredPool(
                layout,
                jnp.zeros((0, layout.row_len or _ROW_ALIGN), jnp.uint8),
            )

        if jax.process_count() == 1:
            global_rows = pack_global_rows(
                layout, plan, fetch_fn,
                host, local_shards,
            )
            sharded = jax.device_put(
                global_rows, row_sharded(self.mesh, self.axis)
            )
        else:
            # Multi-process: a "plan host" is a pod *slot* (one device along
            # the axis). This process fetches for every slot whose device it
            # addresses and contributes the concatenated bands as its local
            # shard data.
            bands = [
                pack_rows(
                    layout, fetch_owned_blobs(plan, fetch_fn, slot), slot
                )
                for slot in self.local_slots()
            ]
            local_band = np.concatenate(bands, axis=0)
            sharded = jax.make_array_from_process_local_data(
                row_sharded(self.mesh, self.axis),
                local_band,
                (layout.total_rows, layout.row_len),
            )

        gathered = _replicate(self.mesh, sharded)
        gathered.block_until_ready()
        return GatheredPool(layout, gathered)


# ── Raw all-gather microbench primitive (bench.py: ici_all_gather) ──


def all_gather_throughput(
    mesh: Mesh, mbytes_per_device: int = 64, iters: int = 5
) -> float:
    """GB/s of a pod-axis all-gather — the ICI wire-speed analog of the
    reference's bt_wire_frame bench (src/bench.zig:167-255)."""
    import time

    n = int(mesh.shape[POD_AXIS])
    per_dev = mbytes_per_device * 1024 * 1024
    x = jax.device_put(
        jnp.zeros((n, per_dev // _ROW_ALIGN, _ROW_ALIGN), jnp.uint8),
        row_sharded(mesh),
    )
    _replicate(mesh, x).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        _replicate(mesh, x).block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    moved = per_dev * (n - 1) * n  # bytes crossing links per gather
    return moved / dt / 1e9
