"""Hierarchical DCN+ICI distribution for multi-pod pulls.

BASELINE config #5 ("Llama-405B v5p-256 hierarchical DCN+ICI"): at
multi-pod scale the network is two-tier — fast ICI inside each pod, slower
DCN between pods — and a flat rendezvous plan (zest_tpu.parallel.plan)
wastes the tiering: it balances CDN ingress over *global* hosts but says
nothing about how bytes cross DCN. This module adds the two-level story
(SURVEY.md §7 "hard parts" #3):

  - **two-level ownership**: a fetch unit is HRW-hashed first to an owning
    *pod* (balances CDN/DCN ingress per pod), then to an owning *host
    within that pod* (balances intra-pod fetch work). Every process
    computes the same (pod, host) pair with no coordination.
  - **two-stage gather**: the pool array lives on a 2-D ``(pods, hosts)``
    mesh. Stage 1 un-shards the ``pods`` axis — XLA emits the cross-pod
    all-gather that rides DCN, moving each unit (n_pods - 1)× across the
    slow tier, exactly once per destination pod. Stage 2 un-shards the
    ``hosts`` axis — the in-pod ICI all-gather. Staging them as two
    jitted reshardings (instead of one replicate) gives the per-stage
    DCN/ICI timing the BASELINE metrics require; fused or staged, the
    bytes moved are identical.

The reference's closest analog is "100 WAN peers" (DESIGN.md:563-574):
its WAN/LAN split is emergent from peer RTTs; ours is explicit in the
mesh axes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.cas import hashing
from zest_tpu.cas.reconstruction import Reconstruction
from zest_tpu.parallel.collectives import (
    GatheredPool,
    PoolLayout,
    fetch_owned_blobs,
    pack_global_rows,
    pack_rows,
)
from zest_tpu.parallel.plan import (
    DistributionPlan,
    FetchAssignment,
    collect_units,
    owner_host,
)

PODS_AXIS = "pods"
HOSTS_AXIS = "hosts"

# Domain-separation salts so pod-level and host-level rendezvous draws are
# independent (same convention as hashing's keyed domains).
_POD_SALT = b"zest-hier-pod"
_HOST_SALT = b"zest-hier-host"


def hier_mesh(n_pods: int, hosts_per_pod: int, devices=None) -> Mesh:
    """2-D ``(pods, hosts)`` mesh. Device order matters: consecutive
    devices share a pod (the ICI-contiguous trailing axis), so the
    ``hosts`` all-gather stays on ICI and only the leading axis crosses
    DCN — the layout rule from zest_tpu.parallel.mesh.model_mesh."""
    devices = jax.devices() if devices is None else devices
    if n_pods * hosts_per_pod != len(devices):
        raise ValueError(
            f"{n_pods}×{hosts_per_pod} mesh needs {n_pods * hosts_per_pod} "
            f"devices, have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(n_pods, hosts_per_pod)
    return Mesh(arr, (PODS_AXIS, HOSTS_AXIS))


def owner_pod_host(
    xorb_hash: bytes, range_start: int, n_pods: int, hosts_per_pod: int
) -> tuple[int, int]:
    """Two independent rendezvous draws: owning pod, then host in pod."""
    pod = owner_host(_POD_SALT + xorb_hash, range_start, n_pods)
    host = owner_host(_HOST_SALT + xorb_hash, range_start, hosts_per_pod)
    return pod, host


@dataclass
class HierarchicalPlan:
    """A DistributionPlan whose owner slots encode (pod, host) pod-major.

    ``flat`` is a plain DistributionPlan over n_pods × hosts_per_pod
    global slots (slot = pod * hosts_per_pod + host), so the pool layout,
    packing, and registry machinery from collectives/plan are reused
    unchanged — only the owner assignment differs.
    """

    n_pods: int
    hosts_per_pod: int
    flat: DistributionPlan

    @staticmethod
    def build(
        recs: list[Reconstruction], n_pods: int, hosts_per_pod: int
    ) -> "HierarchicalPlan":
        assignments = []
        for (hh, start), fi in collect_units(recs):
            pod, host = owner_pod_host(
                hashing.hex_to_hash(hh), start, n_pods, hosts_per_pod
            )
            assignments.append(FetchAssignment(
                hash_hex=hh, fetch_info=fi,
                owner=pod * hosts_per_pod + host,
            ))
        return HierarchicalPlan(
            n_pods, hosts_per_pod,
            DistributionPlan(n_pods * hosts_per_pod, assignments),
        )

    def bytes_per_pod(self) -> list[int]:
        """CDN/DCN ingress per pod — the balance target of level 1."""
        out = [0] * self.n_pods
        for a in self.flat.assignments:
            out[a.owner // self.hosts_per_pod] += a.est_bytes
        return out

    def summary(self) -> dict:
        per_pod = self.bytes_per_pod()
        peak = max(per_pod) if per_pod else 0
        mean = sum(per_pod) / self.n_pods if self.n_pods else 0
        s = self.flat.summary()
        s["pods"] = self.n_pods
        s["bytes_per_pod"] = per_pod
        s["pod_balance"] = round(mean / peak, 4) if peak else 1.0
        return s


def _stage_shardings(mesh: Mesh):
    """Shardings over the 3-D pool view [pods, hosts·rows_per_host, len].

    The pool is kept 3-D (pod dim explicit) so each stage is a single-axis
    resharding: owner → after_dcn un-shards only ``pods`` (an all-gather
    between same-host-index devices of different pods — the DCN tier);
    after_dcn → replicated un-shards ``hosts`` (in-pod ICI). A flat 2-D
    pool sharded P((pods, hosts)) would NOT decompose this way — its
    contiguous blocks interleave host indices, so the "DCN" stage would
    move bytes between in-pod hosts too.
    """
    owner = NamedSharding(mesh, P(PODS_AXIS, HOSTS_AXIS, None))
    after_dcn = NamedSharding(mesh, P(None, HOSTS_AXIS, None))
    replicated = NamedSharding(mesh, P())
    return owner, after_dcn, replicated


@partial(jax.jit, static_argnums=(0,))
def _to(sharding: NamedSharding, pool: jax.Array) -> jax.Array:
    return jax.lax.with_sharding_constraint(pool, sharding)


class HierarchicalDistributor:
    """One multi-pod distribution round: pack → DCN gather → ICI gather.

    Single-process simulates the full topology (the driver's virtual-mesh
    dryrun, with ``local_shards`` pre-supplying other slots' blobs);
    multi-process, each process fetches for every (pod, host) slot whose
    device it addresses and contributes those bands as per-device shards.
    """

    def __init__(self, mesh: Mesh):
        if tuple(mesh.axis_names) != (PODS_AXIS, HOSTS_AXIS):
            raise ValueError(
                f"expected a (pods, hosts) mesh, got {mesh.axis_names}"
            )
        self.mesh = mesh
        self.n_pods = int(mesh.shape[PODS_AXIS])
        self.hosts_per_pod = int(mesh.shape[HOSTS_AXIS])
        # Filled by distribute(): wall-clock of the two collective stages
        # and the pool layout they moved.
        self.stage_seconds: dict[str, float] = {}
        self._layout: PoolLayout | None = None

    def distribute(
        self,
        plan: HierarchicalPlan,
        fetch_fn,
        slot: int | None = None,
        local_shards: dict[int, dict[tuple[str, int], bytes]] | None = None,
    ) -> GatheredPool:
        if (plan.n_pods, plan.hosts_per_pod) != (
            self.n_pods, self.hosts_per_pod
        ):
            raise ValueError(
                f"plan is {plan.n_pods}×{plan.hosts_per_pod}, mesh is "
                f"{self.n_pods}×{self.hosts_per_pod}"
            )
        flat = plan.flat
        layout = PoolLayout.from_plan(flat)
        self._layout = layout
        if layout.total_rows == 0:
            return GatheredPool(
                layout, jnp.zeros((0, layout.row_len or 128), jnp.uint8)
            )

        owner_sh, after_dcn_sh, repl_sh = _stage_shardings(self.mesh)
        pool_shape = (
            self.n_pods,
            self.hosts_per_pod * layout.rows_per_host,
            layout.row_len,
        )
        if jax.process_count() == 1:
            global_rows = pack_global_rows(
                layout, flat, fetch_fn, slot,
                local_shards,
            )
            # 3-D pod-major view: slot s = pod·H + host, so the reshape
            # keeps every band in place.
            pool = jax.device_put(global_rows.reshape(pool_shape), owner_sh)
        else:
            # Multi-process: device (p, h)'s shard of the owner-sharded
            # pool is exactly slot (p·H + h)'s band — build each
            # addressable device's shard locally, no global assembly.
            R = layout.rows_per_host
            mesh_devs = np.asarray(self.mesh.devices)
            shards = []
            for p in range(self.n_pods):
                for h in range(self.hosts_per_pod):
                    dev = mesh_devs[p, h]
                    if dev.process_index != jax.process_index():
                        continue
                    s = p * self.hosts_per_pod + h
                    band = pack_rows(
                        layout, fetch_owned_blobs(flat, fetch_fn, s), s
                    )
                    shards.append(jax.device_put(band[None], dev))
            pool = jax.make_array_from_single_device_arrays(
                pool_shape, owner_sh, shards
            )
        pool.block_until_ready()

        t0 = time.perf_counter()
        pool = _to(after_dcn_sh, pool)   # stage 1: cross-pod (DCN)
        pool.block_until_ready()
        t1 = time.perf_counter()
        pool = _to(repl_sh, pool)        # stage 2: in-pod (ICI)
        pool.block_until_ready()
        t2 = time.perf_counter()
        self.stage_seconds = {"dcn": t1 - t0, "ici": t2 - t1}
        return GatheredPool(
            layout, pool.reshape(layout.total_rows, layout.row_len)
        )

    def stage_stats(self) -> dict:
        """Bytes each stage moved + measured wall-clock (per-stage timing,
        SURVEY.md §5 'tracing/profiling' requirement).

        The basis is ``layout.pool_bytes`` — what the collectives actually
        carry (fixed-capacity rows, padded), not the plan's compressed
        est_bytes sum. Per device the owner shard is pool/(P·H); stage 1
        delivers it to the other P-1 pods, stage 2 fans each pod's
        pool/H band out to its other H-1 hosts.
        """
        if self._layout is None:
            raise RuntimeError("stage_stats before distribute()")
        pool = self._layout.pool_bytes
        # Totals are bytes *received* summed over devices: stage 1 — each
        # of P·H devices receives (P-1) owner shards of pool/(P·H); stage
        # 2 — each receives (H-1) bands of pool/H.
        out = {
            "pool_bytes": pool,
            "dcn_bytes": pool * (self.n_pods - 1),
            "ici_bytes": pool * self.n_pods * (self.hosts_per_pod - 1),
        }
        for name, secs in self.stage_seconds.items():
            out[f"{name}_seconds"] = round(secs, 6)
            moved = out[f"{name}_bytes"]
            out[f"{name}_gbps"] = (
                round(moved / secs / 1e9, 3) if secs > 0 else 0.0
            )
        return out
