"""HBM as a managed multi-model serving cache (ISSUE 18).

The loader lands layer-by-layer (PR 8), swaps revisions in place
(PR 9), and the daemon decodes via ``/v1/generate`` — but HBM was a
one-model scratch buffer: a request against a cold model paid a full
pull wall before its first token. This module lifts the PR-12
PinBook/CacheEvictor pattern from the disk tier to HBM:

* A process-wide :class:`HbmPool` holds multiple resident model trees
  as **flat HF name → jax.Array dicts** (exactly what the streaming
  landing commits, so ``loader.params_digest`` is directly comparable
  between a cold pull and a pool re-land), byte-accounted against the
  ``ZEST_HBM_POOL_BYTES`` watermark.
* **Pinning** protects the actively-decoding model; LRU eviction drops
  cold trees back to the xorb/snapshot cache (arrays deleted, bytes
  stay on disk) — never a pinned one.
* **Scale-to-zero re-landing**: a generate against an evicted model
  re-lands from the local snapshot in layer-priority order
  (``registry.order_names``), and decode starts at *first-layer
  commit* — the gated decoders below run each forward layer as soon as
  its tensors are resident, overlapping prefill with the landing tail
  behind per-layer gates instead of waiting for the whole checkpoint.
* **Lazy MoE expert paging** (the creative stretch): a Mixtral entry
  lands only its dense core; expert tensors are pulled on demand per
  routed token through :class:`ExpertPager`, a small expert LRU inside
  the pool's budget, each page-in BLAKE3-verified against the digest
  pinned at first read — the same byte-identity boundary any peer/CDN
  byte crosses (the snapshot itself is the product of merkle-verified
  chunks; the pager guards the disk → HBM re-read).

Observability is wired from day one: ``zest_hbm_pool_bytes{state}``,
``zest_hbm_pool_evictions_total{reason}``, ``zest_ttft_seconds{temp}``,
timeline series (occupancy, gate stalls, evictions) and remediation
targets (``pool_land`` rush for stalled gates, ``pool_shed`` for
thrash) so PRs 10/14/17 cover the new hot path.

``ZEST_HBM_POOL=0`` removes the pool entirely (:func:`pool` returns
None) — the daemon then serves exactly the pre-pool single-model path.
"""

from __future__ import annotations

import functools
import json
import math
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from zest_tpu import telemetry
from zest_tpu.telemetry import remediate, timeline

# Families the gated decoders below cover. gpt2 (and unknown types)
# fall back to the classic single-model path in api.http_api — the
# pool never claims a model it cannot gate-decode.
POOL_FAMILIES = ("llama", "mistral", "qwen2", "mixtral")

# Re-land commit group: tensors accumulate to ~this many bytes before
# one batched commit_tensors (cut only at layer-priority boundaries so
# a gate never opens on half a layer). The remediation "rush" flips to
# per-layer flushes.
DEFAULT_GROUP_BYTES = 64 << 20

# Expert LRU budget as a fraction of the checkpoint's full expert
# bytes — 0.375 keeps worst-case residency safely under the 50%
# acceptance bound while still absorbing router locality.
EXPERT_BUDGET_FRACTION = 0.375

_M_POOL_BYTES = telemetry.gauge(
    "zest_hbm_pool_bytes",
    "HBM bytes held by the serving pool, by pin state", ("state",))
_M_POOL_EVICTIONS = telemetry.counter(
    "zest_hbm_pool_evictions_total",
    "Model trees evicted from the HBM pool", ("reason",))
_M_TTFT = telemetry.histogram(
    "zest_ttft_seconds",
    "Time from /v1/generate arrival to first generated token",
    ("temp",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0))
_M_EXPERT_PAGES = telemetry.counter(
    "zest_hbm_pool_expert_pages_total",
    "Expert-tensor page events in the MoE pager", ("outcome",))
# Same (name, labels) as transfer.pull's counter — the registry
# returns the shared instance, so serving TTFT breaches land in the
# same series as pull-side SLO breaches.
_M_SLO_BREACHES = telemetry.counter(
    "zest_slo_breaches_total",
    "Pulls that breached an armed SLO budget (ZEST_SLO_TTHBM_S / "
    "ZEST_SLO_TTFL_S)", ("slo",))


# ── Checkpoint topology helpers ──


def _snapshot_cfg(snapshot_dir: str | Path) -> dict:
    return json.loads((Path(snapshot_dir) / "config.json").read_text())


def snapshot_meta(snapshot_dir: str | Path) -> tuple[str | None, tuple]:
    """(model_type, eos_ids) from a snapshot's config.json — what the
    serving layer needs to route a request (pool vs classic path)
    before touching the pool. ``(None, ())`` when the snapshot has no
    readable config."""
    try:
        cfg_json = _snapshot_cfg(snapshot_dir)
    except (OSError, json.JSONDecodeError):
        return None, ()
    from zest_tpu.models.generate import _eos_token_ids

    return cfg_json.get("model_type"), _eos_token_ids(cfg_json)


def _is_expert_name(name: str) -> bool:
    from zest_tpu.models.moe import expert_of_tensor

    return expert_of_tensor(name) is not None


def _llama_layer_names(i: int, present: frozenset[str]) -> list[str]:
    pre = f"model.layers.{i}."
    names = [
        pre + "input_layernorm.weight",
        pre + "self_attn.q_proj.weight",
        pre + "self_attn.k_proj.weight",
        pre + "self_attn.v_proj.weight",
        pre + "self_attn.o_proj.weight",
        pre + "post_attention_layernorm.weight",
        pre + "mlp.gate_proj.weight",
        pre + "mlp.up_proj.weight",
        pre + "mlp.down_proj.weight",
    ]
    # Optional bias leaves (Qwen2 q/k/v, attention_bias o): gate on
    # what the checkpoint actually ships, or the gate would wait on a
    # tensor that never lands.
    for opt in ("self_attn.q_proj.bias", "self_attn.k_proj.bias",
                "self_attn.v_proj.bias", "self_attn.o_proj.bias"):
        if pre + opt in present:
            names.append(pre + opt)
    missing = [n for n in names if n not in present]
    if missing:
        raise ValueError(f"checkpoint missing {missing[:3]}")
    return names


def _moe_layer_names(i: int, present: frozenset[str]) -> list[str]:
    pre = f"model.layers.{i}."
    names = [
        pre + "input_layernorm.weight",
        pre + "self_attn.q_proj.weight",
        pre + "self_attn.k_proj.weight",
        pre + "self_attn.v_proj.weight",
        pre + "self_attn.o_proj.weight",
        pre + "post_attention_layernorm.weight",
        pre + "block_sparse_moe.gate.weight",
    ]
    missing = [n for n in names if n not in present]
    if missing:
        raise ValueError(f"checkpoint missing {missing[:3]}")
    return names


# ── Gated flat decoders ──
#
# The family modules decode over STACKED trees (params_from_hf piles
# per-layer tensors into [L, ...] leaves) — useless mid-landing, when
# layer 7 exists but layer 8 is still on the wire. These decoders run
# the identical math directly over the flat HF-orientation dict the
# landing commits, one jitted step shared by every layer (identical
# shapes → one compile), with a Python layer loop that waits on the
# entry's committed-tensor frontier. HF stores Linear weights
# [out, in]; the family modules materialize the transpose at load —
# here the transpose folds into the jitted matmul (``x @ W.T``), which
# XLA canonicalizes to the same dot, so logits (and greedy tokens)
# match the family path bit-for-bit on the same checkpoint.


@functools.lru_cache(maxsize=16)
def _llama_layer_step(cfg):
    from zest_tpu.models.llama import _rms_norm, _rope

    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim

    def step(lp, x, ck, cv, pos):
        B, S, _ = x.shape
        h = _rms_norm(x, lp["ln1"], cfg.rms_eps)

        def proj(w, b):
            y = h @ lp[w].T
            if b in lp:
                y = y + lp[b]
            return y.reshape(B, S, -1, D)

        q = _rope(proj("q_w", "q_b"), cfg, pos)
        k = _rope(proj("k_w", "k_b"), cfg, pos)
        v = proj("v_w", "v_b")
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, pos, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, pos, axis=1)
        kk, vv = ck, cv
        if KV != H:
            kk = jnp.repeat(kk, H // KV, axis=2)
            vv = jnp.repeat(vv, H // KV, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / math.sqrt(D)
        valid = (jnp.arange(ck.shape[1])[None, :]
                 <= pos + jnp.arange(S)[:, None])
        scores = jnp.where(valid[None, None, :, :], scores,
                           jnp.finfo(scores.dtype).min)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", att.astype(x.dtype), vv)
        out = out.reshape(B, S, H * D) @ lp["o_w"].T
        if "o_b" in lp:
            out = out + lp["o_b"]
        x = x + out
        h = _rms_norm(x, lp["ln2"], cfg.rms_eps)
        mlp = (jax.nn.silu(h @ lp["gate_w"].T)
               * (h @ lp["up_w"].T)) @ lp["down_w"].T
        return x + mlp, ck, cv

    return jax.jit(step)


@functools.lru_cache(maxsize=16)
def _llama_head(cfg):
    from zest_tpu.models.llama import _rms_norm

    def head(x_last, norm_g, head_w):
        # HF lm_head and wte are both [vocab, E], so tied and untied
        # checkpoints share this one projection (x @ W.T).
        return _rms_norm(x_last, norm_g, cfg.rms_eps) @ head_w.T

    return jax.jit(head)


@functools.lru_cache(maxsize=16)
def _moe_attn_step(cfg):
    from zest_tpu.models.moe import _rms_norm, _rope

    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim

    def step(lp, x, ck, cv, pos):
        B, S, _ = x.shape
        h = _rms_norm(x, lp["ln1"], cfg.rms_eps)
        q = (h @ lp["q_w"].T).reshape(B, S, H, D)
        k = (h @ lp["k_w"].T).reshape(B, S, KV, D)
        v = (h @ lp["v_w"].T).reshape(B, S, KV, D)
        q = _rope(q, cfg.rope_theta, pos)
        k = _rope(k, cfg.rope_theta, pos)
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, pos, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, pos, axis=1)
        kk, vv = ck, cv
        if KV != H:
            kk = jnp.repeat(kk, H // KV, axis=2)
            vv = jnp.repeat(vv, H // KV, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / math.sqrt(D)
        valid = (jnp.arange(ck.shape[1])[None, :]
                 <= pos + jnp.arange(S)[:, None])
        scores = jnp.where(valid[None, None, :, :], scores,
                           jnp.finfo(scores.dtype).min)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", att.astype(x.dtype), vv)
        x = x + out.reshape(B, S, cfg.n_embd) @ lp["o_w"].T
        h2 = _rms_norm(x, lp["ln2"], cfg.rms_eps)
        return x, h2, ck, cv

    return jax.jit(step)


@functools.lru_cache(maxsize=16)
def _moe_router(cfg):
    def route(flat, gate_w):
        # Mirrors moe._moe_block's routing exactly: f32 logits →
        # softmax → top-k → renormalize by the selected mass.
        logits = (flat @ gate_w.T).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, cfg.top_k)
        gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)
        return gate_vals, gate_idx

    return jax.jit(route)


@jax.jit
def _expert_ffn(h, w1, w3, w2):
    """Per-expert SwiGLU over (N, E) tokens, HF [out, in] weights."""
    return (jax.nn.silu(h @ w1.T) * (h @ w3.T)) @ w2.T


@functools.lru_cache(maxsize=16)
def _moe_head(cfg):
    from zest_tpu.models.moe import _rms_norm

    def head(x_last, norm_g, head_w):
        return _rms_norm(x_last, norm_g, cfg.rms_eps) @ head_w.T

    return jax.jit(head)


# ── Expert pager ──


class ExpertPager:
    """Lazy (layer, expert) → HBM pager with an LRU inside the pool
    budget.

    Expert tensors stay on disk until a router actually selects the
    expert; a page-in mmap-reads the three SwiGLU tensors, verifies
    each against the BLAKE3 digest pinned at first read (the HBM-side
    extension of the merkle boundary every pulled byte already
    crossed — a disk flip between page-ins is caught, not served), and
    device-puts them. The LRU evicts whole expert groups, never one
    the current token still needs.
    """

    def __init__(self, reader, budget_bytes: int):
        self._reader = reader          # name → np view (mmap-backed)
        self.budget_bytes = int(budget_bytes)
        self._lru: dict[tuple[int, int], dict] = {}  # insertion = LRU
        self._sizes: dict[tuple[int, int], int] = {}
        self._digests: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self.bytes = 0
        self.peak_bytes = 0
        self.total_expert_bytes = 0
        self.page_ins = 0
        self.hits = 0
        self.evictions = 0
        self.verified = 0

    def _names(self, layer: int, expert: int) -> dict[str, str]:
        pre = f"model.layers.{layer}.block_sparse_moe.experts.{expert}."
        return {leaf: pre + leaf + ".weight"
                for leaf in ("w1", "w3", "w2")}

    def get(self, layer: int, expert: int) -> dict:
        key = (layer, expert)
        with self._lock:
            grp = self._lru.get(key)
            if grp is not None:
                # Move to MRU position (dict preserves insertion order).
                self._lru[key] = self._lru.pop(key)
                self.hits += 1
                _M_EXPERT_PAGES.inc(outcome="hit")
                return grp
        # Page-in outside the lock: mmap read + verify + device_put can
        # overlap across layers; a duplicate race costs one redundant
        # read, never a wrong result.
        from zest_tpu.cas import hashing

        grp, size = {}, 0
        for leaf, name in self._names(layer, expert).items():
            view = self._reader(name)
            digest = hashing.blake3_hash(view.tobytes())
            with self._lock:
                pinned = self._digests.setdefault(name, digest)
            if digest != pinned:
                _M_EXPERT_PAGES.inc(outcome="corrupt")
                raise RuntimeError(
                    f"expert tensor {name} changed on disk since its "
                    "digest was pinned — refusing to serve it")
            self.verified += 1
            grp[leaf] = jnp.asarray(view)
            size += int(view.nbytes)
        jax.block_until_ready(list(grp.values()))
        with self._lock:
            raced = self._lru.get(key)
            if raced is not None:
                for arr in grp.values():
                    arr.delete()
                return raced
            # Make room BEFORE admitting, oldest first; the group being
            # admitted is exempt (a single over-budget expert still
            # serves — residency honesty over refusal).
            while self._lru and self.bytes + size > self.budget_bytes:
                old_key = next(iter(self._lru))
                for arr in self._lru.pop(old_key).values():
                    arr.delete()
                self.bytes -= self._sizes.pop(old_key)
                self.evictions += 1
                _M_EXPERT_PAGES.inc(outcome="evict")
            self._lru[key] = grp
            self._sizes[key] = size
            self.bytes += size
            self.peak_bytes = max(self.peak_bytes, self.bytes)
            self.page_ins += 1
            _M_EXPERT_PAGES.inc(outcome="miss")
        return grp

    def clear(self) -> None:
        with self._lock:
            for grp in self._lru.values():
                for arr in grp.values():
                    arr.delete()
            self._lru.clear()
            self._sizes.clear()
            self.bytes = 0

    def stats(self) -> dict:
        return {
            "budget_bytes": self.budget_bytes,
            "bytes": self.bytes,
            "peak_bytes": self.peak_bytes,
            "total_expert_bytes": self.total_expert_bytes,
            "residency": (self.peak_bytes / self.total_expert_bytes
                          if self.total_expert_bytes else 0.0),
            "page_ins": self.page_ins,
            "hits": self.hits,
            "evictions": self.evictions,
            "verified": self.verified,
        }


# ── Pool entries ──


class PoolEntry:
    """One model tree in the pool. ``params``/``committed`` mutate in
    place (the gated decoder closures capture the entry, so an evict →
    re-land cycle is visible through the same objects)."""

    def __init__(self, key: str, repo: str, model_type: str,
                 cfg_json: dict):
        self.key = key
        self.repo = repo
        self.model_type = model_type
        self.cfg_json = cfg_json
        self.state = "new"          # new|landing|resident|evicted|error
        self.params: dict[str, jax.Array] = {}
        self.committed: set[str] = set()
        self.cond = threading.Condition()
        self.bytes = 0              # committed dense-core bytes
        self.reserved = 0           # expected full dense-core bytes
        self.pins = 0
        self.last_use = time.monotonic()
        self.expected: frozenset[str] = frozenset()
        self.first_layer: frozenset[str] = frozenset()
        self.where: dict[str, Path] = {}   # tensor name → home shard
        self.land_error: Exception | None = None
        self.pager: ExpertPager | None = None
        self.generate = None        # built once, survives evictions
        self.lands = 0
        self.gate_stall_s = 0.0
        self.t_land_start: float | None = None
        self.t_first_layer: float | None = None
        self.t_land_end: float | None = None
        self.t_decode_start: float | None = None

    @property
    def hbm_bytes(self) -> int:
        pager = self.pager.bytes if self.pager is not None else 0
        if self.state in ("landing", "resident"):
            # A landing entry accounts its full reservation so
            # admission pressure is computed against where the land is
            # headed, not a mid-flight snapshot.
            return max(self.bytes, self.reserved) + pager
        return pager

    def wait_for(self, names) -> float:
        """Block until every name is committed; returns stalled
        seconds. The committed set only grows during a land, so a
        satisfied gate is lock-free on re-check."""
        need = set(names)
        if need <= self.committed:
            return 0.0
        t0 = time.perf_counter()
        with self.cond:
            while not need <= self.committed:
                if self.state == "error":
                    raise RuntimeError(
                        f"landing {self.repo} failed"
                    ) from self.land_error
                if self.state == "evicted":
                    raise RuntimeError(
                        f"{self.repo} was evicted mid-decode — the "
                        "pin that should prevent this is missing")
                self.cond.wait(timeout=0.5)
        stall = time.perf_counter() - t0
        with self.cond:
            self.gate_stall_s += stall
        return stall

    def summary_row(self) -> dict:
        row = {
            "repo": self.repo,
            "model_type": self.model_type,
            "state": self.state,
            "bytes": self.hbm_bytes,
            "pins": self.pins,
            "lands": self.lands,
            "gate_stall_s": round(self.gate_stall_s, 3),
            "idle_s": round(time.monotonic() - self.last_use, 1),
        }
        if self.pager is not None:
            row["experts"] = self.pager.stats()
        return row


# ── The pool ──


class HbmPool:
    """Process-wide managed HBM pool; construct via :func:`pool`."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.budget = int(getattr(cfg, "hbm_pool_bytes", 0))
        self.group_bytes = DEFAULT_GROUP_BYTES
        self.land_delay_s = 0.0     # test hook: sleep between flushes
        self._lock = threading.RLock()
        self._entries: dict[str, PoolEntry] = {}
        self._rush = threading.Event()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.pinned_survivals = 0
        self._register_hooks()

    # ── wiring ──

    def _register_hooks(self) -> None:
        timeline.register_probe("hbm_pool.resident_bytes",
                                lambda: float(self.used_bytes()))
        timeline.register_probe("hbm_pool.pinned_bytes",
                                lambda: float(self.pinned_bytes()))
        timeline.register_probe("hbm_pool.models",
                                lambda: float(len(self.resident())))
        timeline.register_probe("hbm_pool.gate_stall_s",
                                lambda: self._total_stall_s())
        timeline.register_probe("hbm_pool.evictions",
                                lambda: float(self.evictions))
        timeline.register_probe("hbm_pool.landing",
                                lambda: float(self._landing_count()))
        remediate.register_target("pool_shed", self._shed_cmd)
        remediate.register_target("pool_land", self._land_cmd)

    def _unregister_hooks(self) -> None:
        for name in ("hbm_pool.resident_bytes", "hbm_pool.pinned_bytes",
                     "hbm_pool.models", "hbm_pool.gate_stall_s",
                     "hbm_pool.evictions", "hbm_pool.landing"):
            timeline.unregister_probe(name)
        remediate.unregister_target("pool_shed")
        remediate.unregister_target("pool_land")

    def _shed_cmd(self, cmd: str) -> bool:
        """Remediation target: pool thrash → drop the coldest unpinned
        resident tree back to disk, freeing headroom."""
        return self.shed_coldest(reason="shed") is not None

    def _land_cmd(self, cmd: str) -> bool:
        """Remediation target: a stalled land gate arms rush mode —
        every layer boundary flushes immediately instead of batching
        to ``group_bytes``, trading commit batching for gate latency.
        Reversible: cleared when no land is in flight."""
        if cmd == "rush":
            self._rush.set()
            return True
        return False

    # ── accounting ──

    def used_bytes(self) -> int:
        with self._lock:
            return sum(e.hbm_bytes for e in self._entries.values())

    def pinned_bytes(self) -> int:
        with self._lock:
            return sum(e.hbm_bytes for e in self._entries.values()
                       if e.pins > 0)

    def _total_stall_s(self) -> float:
        with self._lock:
            return sum(e.gate_stall_s for e in self._entries.values())

    def _landing_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if e.state == "landing")

    def _update_gauges(self) -> None:
        pinned = self.pinned_bytes()
        _M_POOL_BYTES.set(float(pinned), state="pinned")
        _M_POOL_BYTES.set(float(self.used_bytes() - pinned),
                          state="resident")

    # ── admission / eviction ──

    @staticmethod
    def supports(model_type: str | None) -> bool:
        return (model_type or "") in POOL_FAMILIES

    def acquire(self, snapshot_dir: str | Path,
                repo: str | None = None) -> tuple[PoolEntry, bool]:
        """Pin (and if needed admit/re-land) the model at
        ``snapshot_dir``. Returns ``(entry, hot)`` — ``hot`` is True
        iff the tree was fully resident before this call. The caller
        MUST :meth:`release` the entry when its decode finishes."""
        key = str(Path(snapshot_dir).resolve())
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                cfg_json = _snapshot_cfg(key)
                model_type = cfg_json.get("model_type") or ""
                if not self.supports(model_type):
                    raise ValueError(
                        f"model_type {model_type!r} is not pool-served "
                        f"(families: {', '.join(POOL_FAMILIES)})")
                entry = PoolEntry(key, repo or Path(key).name,
                                  model_type, cfg_json)
                self._entries[key] = entry
            hot = entry.state == "resident"
            if hot:
                self.hits += 1
            else:
                self.misses += 1
            entry.pins += 1
            entry.last_use = time.monotonic()
            if entry.state in ("new", "evicted", "error"):
                try:
                    self._start_land(entry)
                except Exception:
                    entry.pins -= 1
                    raise
            self._update_gauges()
        return entry, hot

    def release(self, entry: PoolEntry) -> None:
        with self._lock:
            entry.pins = max(0, entry.pins - 1)
            entry.last_use = time.monotonic()
            self._update_gauges()

    def shed_coldest(self, reason: str = "shed") -> str | None:
        """Evict the least-recently-used unpinned resident tree;
        returns its repo name or None when nothing is evictable."""
        with self._lock:
            victims = [e for e in self._entries.values()
                       if e.state == "resident" and e.pins == 0]
            if not victims:
                return None
            victim = min(victims, key=lambda e: e.last_use)
            self._evict_entry(victim, reason)
            return victim.repo

    def _evict_for(self, need: int) -> None:
        """LRU-evict unpinned resident trees until ``need`` more bytes
        fit under the watermark. Pinned (or landing) trees survive —
        by design even if the pool stays over budget."""
        if not self.budget:
            return
        while self.used_bytes() + need > self.budget:
            victims = [e for e in self._entries.values()
                       if e.state == "resident" and e.pins == 0]
            if not victims:
                if any(e.pins > 0 for e in self._entries.values()
                       if e.state in ("resident", "landing")):
                    self.pinned_survivals += 1
                break
            self._evict_entry(min(victims, key=lambda e: e.last_use),
                              "pressure")

    def _evict_entry(self, entry: PoolEntry, reason: str) -> None:
        with entry.cond:
            for arr in entry.params.values():
                try:
                    arr.delete()
                except Exception:  # noqa: BLE001 - already deleted
                    pass
            entry.params.clear()
            entry.committed.clear()
            entry.bytes = 0
            entry.state = "evicted"
            entry.cond.notify_all()
        if entry.pager is not None:
            entry.pager.clear()
        self.evictions += 1
        _M_POOL_EVICTIONS.inc(reason=reason)
        telemetry.record("pool_evict", repo=entry.repo, reason=reason)
        self._update_gauges()

    def swap_to(self, old_snapshot_dir: str | Path | None,
                new_snapshot_dir: str | Path,
                repo: str | None = None,
                wait: bool = True) -> tuple[PoolEntry, float]:
        """Continuous fan-out hot-swap (ISSUE 19): land the NEW
        revision's snapshot pinned (the same pin discipline that keeps
        an in-flight decode's tree unevictable keeps the in-flight
        REVISION unevictable here), wait until it is resident, then
        evict the OLD revision's tree. Ordered land-then-evict so the
        pool never holds zero revisions of the repo mid-swap: a decode
        admitted while the swap runs serves whichever revision is
        resident, never a gap. Returns ``(entry, swap_s)``; the entry
        stays pinned — the caller :meth:`release`\\ s it when its
        serving generation moves on."""
        t0 = time.perf_counter()
        entry, hot = self.acquire(new_snapshot_dir, repo)
        if wait and not hot:
            with entry.cond:
                while entry.state == "landing":
                    entry.cond.wait(timeout=0.5)
            if entry.state == "error":
                self.release(entry)
                raise RuntimeError(
                    f"landing {entry.repo} failed") from entry.land_error
        if old_snapshot_dir is not None:
            old_key = str(Path(old_snapshot_dir).resolve())
            if old_key != entry.key:
                # Best-effort: a pinned old tree survives (a decode is
                # still reading it); the next swap or pressure pass
                # collects it once the pin drops.
                self.evict(old_key, reason="superseded")
        swap_s = time.perf_counter() - t0
        telemetry.record("pool_swap", repo=entry.repo,
                         swap_s=round(swap_s, 4))
        return entry, swap_s

    def evict(self, snapshot_dir: str | Path,
              reason: str = "manual") -> bool:
        key = str(Path(snapshot_dir).resolve())
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.state != "resident":
                return False
            if entry.pins > 0:
                self.pinned_survivals += 1
                return False
            self._evict_entry(entry, reason)
            return True

    # ── landing ──

    def _start_land(self, entry: PoolEntry) -> None:
        """Begin a streaming re-land (caller holds the pool lock)."""
        from zest_tpu.models.loader import snapshot_files
        from zest_tpu.models.safetensors_io import SafetensorsFile

        entry.state = "landing"
        entry.land_error = None
        entry.t_land_start = time.perf_counter()
        entry.t_first_layer = None
        entry.t_land_end = None
        entry.lands += 1

        files = snapshot_files(entry.key)
        if not files:
            entry.state = "error"
            entry.land_error = FileNotFoundError(
                f"no .safetensors under {entry.key}")
            raise entry.land_error

        # Header-only pass: name → home file, sizes, and the expert
        # split — no tensor bytes move yet.
        where: dict[str, Path] = {}
        sizes: dict[str, int] = {}
        for path in files:
            with SafetensorsFile(path) as sf:
                for name in sf.names():
                    where[name] = path
                    sizes[name] = sf.info(name).nbytes
        paging = entry.model_type == "mixtral"
        expected = frozenset(
            n for n in where if not (paging and _is_expert_name(n)))
        entry.expected = expected
        entry.where = dict(where)
        entry.reserved = sum(sizes[n] for n in expected)
        from zest_tpu.models import registry
        entry.first_layer = registry.first_layer_names(expected)

        if paging and entry.pager is None:
            def reader(name: str, _where=dict(where)):
                with SafetensorsFile(_where[name]) as sf:
                    return np.array(sf.tensor(name))  # copy: mmap dies

            expert_total = sum(sizes[n] for n in where
                               if n not in expected)
            pager = ExpertPager(
                reader, int(expert_total * EXPERT_BUDGET_FRACTION))
            pager.total_expert_bytes = expert_total
            entry.pager = pager

        # Make room for where this land is headed before bytes fly —
        # the entry is already in "landing" state, so its reservation
        # is part of used_bytes() and pressure is computed against the
        # land's destination, not its mid-flight snapshot.
        self._evict_for(0)
        telemetry.record("pool_land", repo=entry.repo,
                         bytes=entry.reserved, land=entry.lands)
        t = threading.Thread(target=self._land, args=(entry,),
                             name=f"hbm-pool-land-{entry.repo}",
                             daemon=True)
        # The land thread holds its own pin so pressure from a
        # concurrent admission can never evict a tree mid-land.
        entry.pins += 1
        t.start()

    def _land(self, entry: PoolEntry) -> None:
        from zest_tpu.models import registry
        from zest_tpu.models.loader import commit_tensors
        from zest_tpu.models.safetensors_io import SafetensorsFile

        handles: dict[Path, SafetensorsFile] = {}

        def flush(batch: dict) -> None:
            if not batch:
                return
            committed = commit_tensors(batch, coalesce=True)
            jax.block_until_ready(list(committed.values()))
            size = sum(int(a.nbytes) for a in committed.values())
            with entry.cond:
                entry.params.update(committed)
                entry.committed |= set(committed)
                entry.bytes += size
                if (entry.t_first_layer is None
                        and entry.first_layer <= entry.committed):
                    entry.t_first_layer = time.perf_counter()
                entry.cond.notify_all()
            if self.land_delay_s:
                time.sleep(self.land_delay_s)

        try:
            with telemetry.span("hbm_pool.land", repo=entry.repo):
                names = [n for n in registry.order_names(entry.expected)]
                batch: dict[str, np.ndarray] = {}
                batch_bytes = 0
                last_prio: tuple | None = None
                for name in names:
                    prio = registry.layer_priority(name)
                    at_boundary = (last_prio is not None
                                   and prio != last_prio)
                    if batch and at_boundary and (
                            batch_bytes >= self.group_bytes
                            or self._rush.is_set()):
                        flush(batch)
                        batch, batch_bytes = {}, 0
                    last_prio = prio
                    path = entry.where[name]
                    if path not in handles:
                        handles[path] = SafetensorsFile(path)
                    view = handles[path].tensor(name)
                    batch[name] = view
                    batch_bytes += int(view.nbytes)
                flush(batch)
            with entry.cond:
                entry.t_land_end = time.perf_counter()
                entry.state = "resident"
                entry.cond.notify_all()
            telemetry.record(
                "pool_land_done", repo=entry.repo,
                wall_s=round(entry.t_land_end - entry.t_land_start, 3),
                first_layer_s=round(
                    (entry.t_first_layer or entry.t_land_end)
                    - entry.t_land_start, 3))
        except Exception as exc:  # noqa: BLE001 - recorded + re-raised at gates
            # Abort cleanup: release every array this landing already
            # committed (the satellite-1 contract, pool side) — a
            # failed re-land must not strand partial-tree bytes.
            with entry.cond:
                for name in list(entry.params):
                    try:
                        entry.params.pop(name).delete()
                    except Exception:  # noqa: BLE001
                        pass
                entry.committed.clear()
                entry.bytes = 0
                entry.land_error = exc
                entry.state = "error"
                entry.cond.notify_all()
            telemetry.record("pool_land_error", repo=entry.repo,
                             error=str(exc))
        finally:
            for sf in handles.values():
                sf.close()
            with self._lock:
                entry.pins = max(0, entry.pins - 1)
                if self._landing_count() == 0:
                    self._rush.clear()
                self._update_gauges()

    # ── decoding ──

    def generate_for(self, snapshot_dir: str | Path, repo: str,
                     prompt_ids, steps: int, *, temperature: float = 0.0,
                     top_k: int | None = None, top_p: float | None = None,
                     seed: int = 0, stop_at_eos: bool = True,
                     on_token=None):
        """Serve one generate through the pool: pin → (re-)land →
        gated decode starting at first-layer commit → release.
        Returns ``(tokens, info)`` with TTFT/temperature facts."""
        t_req = time.perf_counter()
        entry, hot = self.acquire(snapshot_dir, repo)
        try:
            if entry.generate is None:
                entry.generate = _build_gated_generate(entry)
            first: dict[str, float] = {}

            def tap(pos, tokens):
                if "t" not in first:
                    first["t"] = time.perf_counter()
                if on_token is not None:
                    on_token(pos, tokens)

            out = entry.generate(
                prompt_ids, steps, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                stop_at_eos=stop_at_eos, on_token=tap)
            ttft = first.get("t", time.perf_counter()) - t_req
            temp = "hot" if hot else "cold"
            _M_TTFT.observe(ttft, temp=temp)
            land_end = entry.t_land_end
            info = {
                "temp": temp,
                "ttft_s": round(ttft, 4),
                "gate_stall_s": round(entry.gate_stall_s, 4),
                "decode_start_before_land_end": bool(
                    entry.t_decode_start is not None
                    and (land_end is None
                         or entry.t_decode_start < land_end)),
            }
            if entry.pager is not None:
                info["experts"] = entry.pager.stats()
            self._check_ttft_slo(repo, ttft, temp)
            timeline.post("hbm_pool.ttft_s", ttft)
            return out, info
        finally:
            self.release(entry)

    def _check_ttft_slo(self, repo: str, ttft: float, temp: str) -> None:
        """Mirror of transfer.pull._check_slos for the serving tier:
        ``ZEST_SLO_TTFT_S`` arms a budget on time-to-first-token."""
        budget = getattr(self.cfg, "slo_ttft_s", None)
        if not budget:
            return
        breached = ttft > budget
        telemetry.session.SESSIONS.note_slo("ttft", breached)
        if breached:
            _M_SLO_BREACHES.inc(slo="ttft")
            telemetry.record("slo_breach", slo="ttft", repo=repo,
                             budget_s=budget, actual_s=round(ttft, 4),
                             session=None, blamed_stage=temp)

    # ── introspection ──

    def digest(self, snapshot_dir: str | Path) -> str | None:
        """``loader.params_digest`` over a resident tree (None when not
        resident). O(model bytes) — verification, not the hot path."""
        from zest_tpu.models.loader import params_digest

        key = str(Path(snapshot_dir).resolve())
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.state != "resident":
                return None
            entry.pins += 1
        try:
            return params_digest(entry.params)
        finally:
            self.release(entry)

    def resident(self) -> list[dict]:
        with self._lock:
            return [e.summary_row() for e in self._entries.values()
                    if e.state in ("landing", "resident")]

    def summary(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "budget_bytes": self.budget,
                "used_bytes": self.used_bytes(),
                "pinned_bytes": self.pinned_bytes(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "pinned_survivals": self.pinned_survivals,
                "gate_stall_s": round(self._total_stall_s(), 3),
                "rush": self._rush.is_set(),
                "models": [e.summary_row()
                           for e in self._entries.values()],
            }

    def close(self) -> None:
        with self._lock:
            for entry in list(self._entries.values()):
                if entry.state in ("resident", "landing"):
                    self._evict_entry(entry, "reset")
                # Leaving the pool for good: the disk tree loses its
                # HBM-tree pin and becomes an ordinary disk-eviction
                # candidate again (an *evicted* entry keeps it — the
                # snapshot is what a re-land reads).
                try:
                    from zest_tpu.transfer import tenancy
                    tenancy.release_tree(self.cfg, entry.repo)
                except Exception:  # noqa: BLE001 - advisory cleanup
                    pass
            self._entries.clear()
        self._unregister_hooks()


# ── Gated generate builders ──


def _build_gated_generate(entry: PoolEntry):
    if entry.model_type == "mixtral":
        return _build_moe_generate(entry)
    return _build_llama_generate(entry)


def _sample_row(logits_np, key_row, temperature, top_k, top_p):
    """Batched host-side sampling matching the family key layout:
    greedy is a plain argmax (identical tie-breaking to jnp.argmax);
    temperature sampling reuses sampling.sample_token per row with the
    same per-(position, row) key the cached loop would use."""
    if temperature <= 0.0:
        return np.argmax(logits_np, axis=-1).astype(np.int32)
    from zest_tpu.models.sampling import sample_token

    nxt = jax.vmap(
        lambda l, k: sample_token(l, k, temperature, top_k, top_p)
    )(jnp.asarray(logits_np), key_row)
    return np.asarray(nxt, np.int32)


def _build_llama_generate(entry: PoolEntry):
    from zest_tpu.models.generate import _eos_token_ids, trim_at_eos
    from zest_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.from_hf(entry.cfg_json)
    eos_ids = _eos_token_ids(entry.cfg_json)
    embed_name = "model.embed_tokens.weight"
    norm_name = "model.norm.weight"
    head_name = "lm_head.weight"

    def layer_view(i: int) -> dict:
        pre = f"model.layers.{i}."
        P = entry.params
        lp = {
            "ln1": P[pre + "input_layernorm.weight"],
            "q_w": P[pre + "self_attn.q_proj.weight"],
            "k_w": P[pre + "self_attn.k_proj.weight"],
            "v_w": P[pre + "self_attn.v_proj.weight"],
            "o_w": P[pre + "self_attn.o_proj.weight"],
            "ln2": P[pre + "post_attention_layernorm.weight"],
            "gate_w": P[pre + "mlp.gate_proj.weight"],
            "up_w": P[pre + "mlp.up_proj.weight"],
            "down_w": P[pre + "mlp.down_proj.weight"],
        }
        for leaf, opt in (("q_b", "self_attn.q_proj.bias"),
                          ("k_b", "self_attn.k_proj.bias"),
                          ("v_b", "self_attn.v_proj.bias"),
                          ("o_b", "self_attn.o_proj.bias")):
            if pre + opt in P:
                lp[leaf] = P[pre + opt]
        return lp

    step = _llama_layer_step(cfg)
    head = _llama_head(cfg)

    def generate(prompt_ids, steps, temperature=0.0, top_k=None,
                 top_p=None, seed=0, stop_at_eos=True, on_token=None):
        prompt = np.asarray(prompt_ids, np.int32)
        batched = prompt.ndim == 2
        if not batched:
            prompt = prompt[None, :]
        B, n0 = prompt.shape
        total = n0 + steps
        if total > cfg.n_ctx:
            raise ValueError(
                f"prompt ({n0}) + steps ({steps}) = {total} exceeds "
                f"n_ctx {cfg.n_ctx}")
        eos = eos_ids if stop_at_eos else None

        # First-layer gate: decode officially starts here — embeddings
        # + layer 0 resident, the rest still possibly on the wire.
        entry.wait_for(entry.first_layer)
        if entry.t_decode_start is None:
            entry.t_decode_start = time.perf_counter()
        present = set(entry.expected)
        layer_names = [
            _llama_layer_names(i, frozenset(present))
            for i in range(cfg.n_layer)]
        tied = head_name not in present
        tail = {norm_name} | (set() if tied else {head_name})

        wte = entry.params[embed_name]
        dtype = wte.dtype
        KV, D = cfg.n_kv_head, cfg.head_dim
        ck = [jnp.zeros((B, total, KV, D), dtype)
              for _ in range(cfg.n_layer)]
        cv = [jnp.zeros((B, total, KV, D), dtype)
              for _ in range(cfg.n_layer)]
        buf = np.zeros((B, total), np.int32)
        buf[:, :n0] = prompt
        keys = None
        if temperature > 0.0 and steps > 0:
            keys = jax.random.split(
                jax.random.key(seed), (total - 1) * B
            ).reshape(total - 1, B)
        done = np.zeros(B, bool)

        def forward(tokens_np, pos):
            x = entry.params[embed_name][jnp.asarray(tokens_np)]
            for i in range(cfg.n_layer):
                entry.wait_for(layer_names[i])
                x, ck[i], cv[i] = step(layer_view(i), x, ck[i], cv[i],
                                       pos)
            entry.wait_for(tail)
            hw = (entry.params[embed_name] if tied
                  else entry.params[head_name])
            logits = head(x[:, -1:, :], entry.params[norm_name], hw)
            return np.asarray(logits[:, -1, :], np.float32)

        for j in range(n0, total):
            # Position j's token is sampled from logits of the window
            # ending at j-1 — prefill covers positions 0..n0-1 in one
            # dispatch, then one single-token step per position.
            if j == n0:
                logits = forward(buf[:, :n0], 0)
            else:
                logits = forward(buf[:, j - 1:j], j - 1)
            nxt = _sample_row(logits,
                              keys[j - 1] if keys is not None else None,
                              temperature, top_k, top_p)
            if eos is not None:
                nxt = np.where(done, np.int32(eos[0]), nxt)
                done = done | np.isin(nxt, eos)
            buf[:, j] = nxt
            if on_token is not None:
                on_token(j, buf[:, j].copy())
        out = buf
        if eos is not None and steps > 0:
            out = trim_at_eos(out, n0, eos)
        return out if batched else out[0]

    generate.eos_ids = eos_ids
    return generate


def _build_moe_generate(entry: PoolEntry):
    from zest_tpu.models.generate import _eos_token_ids, trim_at_eos
    from zest_tpu.models.moe import MoEConfig

    cfg = MoEConfig.from_hf(entry.cfg_json)
    eos_ids = _eos_token_ids(entry.cfg_json)
    embed_name = "model.embed_tokens.weight"
    norm_name = "model.norm.weight"
    head_name = "lm_head.weight"

    def layer_view(i: int) -> dict:
        pre = f"model.layers.{i}."
        P = entry.params
        return {
            "ln1": P[pre + "input_layernorm.weight"],
            "q_w": P[pre + "self_attn.q_proj.weight"],
            "k_w": P[pre + "self_attn.k_proj.weight"],
            "v_w": P[pre + "self_attn.v_proj.weight"],
            "o_w": P[pre + "self_attn.o_proj.weight"],
            "ln2": P[pre + "post_attention_layernorm.weight"],
        }

    attn = _moe_attn_step(cfg)
    route = _moe_router(cfg)
    head = _moe_head(cfg)

    def moe_ffn(h2, layer: int):
        """Routed expert FFN over (B, S, E) with lazy paging: host
        top-k routing (the exact moe._moe_block math), then only the
        selected experts page in. Accumulation walks experts in
        ascending index — the same order the dense dispatch einsum
        reduces over — for bit-parity with the family path."""
        B, S, E = h2.shape
        flat = h2.reshape(B * S, E)
        gate_w = entry.params[
            f"model.layers.{layer}.block_sparse_moe.gate.weight"]
        gate_vals, gate_idx = route(flat, gate_w)
        gv = np.asarray(gate_vals)            # (N, k) f32
        gi = np.asarray(gate_idx)             # (N, k)
        out = jnp.zeros_like(flat)
        for e in sorted(set(gi.flatten().tolist())):
            weights = (gv * (gi == e)).sum(axis=-1)      # (N,)
            grp = entry.pager.get(layer, e)
            ffn = _expert_ffn(flat, grp["w1"], grp["w3"], grp["w2"])
            out = out + jnp.asarray(weights).astype(flat.dtype)[:, None] * ffn
        return out.reshape(B, S, E)

    def generate(prompt_ids, steps, temperature=0.0, top_k=None,
                 top_p=None, seed=0, stop_at_eos=True, on_token=None):
        prompt = np.asarray(prompt_ids, np.int32)
        batched = prompt.ndim == 2
        if not batched:
            prompt = prompt[None, :]
        B, n0 = prompt.shape
        total = n0 + steps
        if total > cfg.n_ctx:
            raise ValueError(
                f"prompt ({n0}) + steps ({steps}) = {total} exceeds "
                f"n_ctx {cfg.n_ctx}")
        eos = eos_ids if stop_at_eos else None

        entry.wait_for(entry.first_layer)
        if entry.t_decode_start is None:
            entry.t_decode_start = time.perf_counter()
        present = frozenset(entry.expected)
        layer_names = [_moe_layer_names(i, present)
                       for i in range(cfg.n_layer)]
        tail = {norm_name, head_name}

        dtype = entry.params[embed_name].dtype
        KV, D = cfg.n_kv_head, cfg.head_dim
        ck = [jnp.zeros((B, total, KV, D), dtype)
              for _ in range(cfg.n_layer)]
        cv = [jnp.zeros((B, total, KV, D), dtype)
              for _ in range(cfg.n_layer)]
        buf = np.zeros((B, total), np.int32)
        buf[:, :n0] = prompt
        keys = None
        if temperature > 0.0 and steps > 0:
            keys = jax.random.split(
                jax.random.key(seed), (total - 1) * B
            ).reshape(total - 1, B)
        done = np.zeros(B, bool)

        def forward(tokens_np, pos):
            x = entry.params[embed_name][jnp.asarray(tokens_np)]
            for i in range(cfg.n_layer):
                entry.wait_for(layer_names[i])
                x, h2, ck[i], cv[i] = attn(layer_view(i), x, ck[i],
                                           cv[i], pos)
                x = x + moe_ffn(h2, i)
            entry.wait_for(tail)
            logits = head(x[:, -1:, :], entry.params[norm_name],
                          entry.params[head_name])
            return np.asarray(logits[:, -1, :], np.float32)

        for j in range(n0, total):
            if j == n0:
                logits = forward(buf[:, :n0], 0)
            else:
                logits = forward(buf[:, j - 1:j], j - 1)
            nxt = _sample_row(logits,
                              keys[j - 1] if keys is not None else None,
                              temperature, top_k, top_p)
            if eos is not None:
                nxt = np.where(done, np.int32(eos[0]), nxt)
                done = done | np.isin(nxt, eos)
            buf[:, j] = nxt
            if on_token is not None:
                on_token(j, buf[:, j].copy())
        out = buf
        if eos is not None and steps > 0:
            out = trim_at_eos(out, n0, eos)
        return out if batched else out[0]

    generate.eos_ids = eos_ids
    return generate


# ── Module-level singleton ──

_POOL: HbmPool | None = None
_POOL_LOCK = threading.Lock()


def pool(cfg) -> HbmPool | None:
    """The process pool, or None when ``ZEST_HBM_POOL=0`` — the
    knob-off contract: with no pool, serving takes exactly the classic
    single-model path (schema included)."""
    global _POOL
    if not getattr(cfg, "hbm_pool_enabled", False):
        return None
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = HbmPool(cfg)
        return _POOL


def reset() -> None:
    """Tear down the singleton (tests): evict everything, unregister
    timeline probes and remediation targets."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is not None:
            _POOL.close()
        _POOL = None
