"""Token sampling shared by the family decode paths."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_token(logits, key, temperature: float = 0.0,
                 top_k: int | None = None) -> jax.Array:
    """One token from (vocab,) logits: greedy at ``temperature<=0``,
    otherwise softmax sampling at the given temperature, optionally
    restricted to the ``top_k`` most likely tokens. Static-shape (the
    top-k restriction masks, never gathers); jittable."""
    if temperature <= 0.0:
        return jnp.argmax(logits).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        # k > vocab means "no restriction", not an internal top_k error.
        k = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][-1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)
