"""Token sampling and the shared cached-decode loop for all families."""

from __future__ import annotations

import functools
import itertools
import threading
from typing import Callable

import jax
import jax.numpy as jnp


def sample_token(logits, key, temperature: float = 0.0,
                 top_k: int | None = None,
                 top_p: float | None = None) -> jax.Array:
    """One token from (vocab,) logits: greedy at ``temperature<=0``,
    otherwise softmax sampling at the given temperature, optionally
    restricted to the ``top_k`` most likely tokens and/or the nucleus
    of cumulative probability ``top_p``. Static-shape (both
    restrictions mask, never gather); jittable."""
    if top_p is not None and not 0.0 < top_p <= 1.0:
        # A silently-ignored top_p=0 would turn the most restrictive
        # request into unrestricted sampling (HF raises here too).
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature <= 0.0:
        return jnp.argmax(logits).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        # k > vocab means "no restriction", not an internal top_k error.
        k = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][-1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        # Nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p. ``cum - probs < top_p`` keeps every
        # token whose mass *before* it is under the budget — so the
        # most likely token always survives and the boundary token that
        # crosses the budget is included (HF semantics). The mask is
        # scattered back by sorted *position*, not by logit value, so
        # ties at the boundary don't widen the nucleus (argsort is
        # stable: the earliest-index of equal logits wins, as in HF).
        order = jnp.argsort(-logits)
        probs = jax.nn.softmax(logits[order])
        cum = jnp.cumsum(probs)
        keep_sorted = cum - probs < top_p
        keep = jnp.zeros_like(keep_sorted).at[order].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return jax.random.categorical(key, logits).astype(jnp.int32)


# ── Streaming relay ──
#
# The jitted decode must be cacheable across requests, but each request
# brings its own on_token closure — baking the closure into the jit
# signature would retrace per request. Instead the compiled program
# always calls this stable relay with a *traced* request tag; the relay
# routes to that request's registered (callback, done-event) pair, so
# any number of streaming decodes run concurrently against one compiled
# program. After the last token the program emits a pos=-1 sentinel
# through the same ordered callback; the relay turns it into the
# request's done event — the per-request drain signal (a global
# jax.effects_barrier() here would wait on every OTHER in-flight
# stream's decode too, serializing concurrent requests).

_STREAM_CBS: dict[int, tuple[Callable, threading.Event]] = {}
_STREAM_SEQ = itertools.count(1)


def _stream_relay(tag, pos, tokens):
    entry = _STREAM_CBS.get(int(tag))
    if entry is None:
        return
    cb, done = entry
    if int(pos) < 0:
        done.set()
    else:
        try:
            cb(pos, tokens)
        except Exception:  # noqa: BLE001
            # An exception escaping a host callback is undefined
            # behavior on TPU (can wedge the runtime) and would block
            # every later token of this stream behind the 30 s done
            # timeout — a third-party on_token must not reach either
            # path. Dropped, not re-raised; the stream keeps flowing.
            import traceback

            traceback.print_exc()


def normalize_eos(eos) -> tuple[int, ...] | None:
    """The one EOS-id normalizer: HF's ``eos_token_id`` may be a single
    int or a list of several stop ids — map any of that (or None, or an
    empty list) to a tuple of ints or None. Shared by the decode loop,
    config parsing, and trimming so they accept the identical domain."""
    if eos is None:
        return None
    if isinstance(eos, (tuple, list)):
        return tuple(int(e) for e in eos) or None
    return (int(eos),)


def _any_eos(tokens, eos_ids: tuple[int, ...]):
    """(B,) bool: does each token match ANY of the stop ids? Static
    tuple of comparisons — no gather, no dynamic shapes."""
    hit = tokens == jnp.int32(eos_ids[0])
    for e in eos_ids[1:]:
        hit = hit | (tokens == jnp.int32(e))
    return hit


@functools.lru_cache(maxsize=64)
def _decode_fn(init_kv_cache: Callable, decode_step: Callable,
               prefill_step: Callable | None, cfg, steps: int,
               temperature: float, top_k: int | None, top_p: float | None,
               eos_ids: tuple[int, ...] | None, stream: bool) -> Callable:
    """Build + jit the whole decode once per static signature.

    Eagerly re-running the loop re-traces its scan closures every call
    (measured ~0.7 s/request on a tiny model — pure Python tracing, not
    compute). Caching on (family fns, config, sampling statics) makes
    repeat requests hit the jit cache and run at device speed;
    per-prompt-shape retraces are jit's normal behavior. The cache is
    *bounded* (LRU): steps/temperature/top_k/top_p arrive from HTTP
    requests, and an unbounded cache keyed on user input would let a
    parameter sweep pin compiled executables until the server OOMs.
    """

    def run(params, prompt, key, tag):
        if stream:
            from jax.experimental import io_callback
        B, n0 = prompt.shape
        total = n0 + steps
        cache = init_kv_cache(cfg, B, total, dtype=params["wte"].dtype)
        buf = jnp.zeros((B, total), jnp.int32).at[:, :n0].set(prompt)
        keys = jax.random.split(key, (total - 1) * B).reshape(total - 1, B)

        done0 = jnp.zeros((B,), bool)
        start = 0
        if prefill_step is not None and n0 > 1 and steps > 0:
            # Batched prefill: one windowed dispatch writes K/V for
            # every prompt position and yields the last position's
            # logits, from which the first generated token is sampled —
            # with the same key the sequential path would use
            # (keys[n0-1]).
            logits, cache = prefill_step(params, cache, prompt,
                                         jnp.int32(0), cfg,
                                         last_only=True)
            nxt = jax.vmap(
                lambda l, k: sample_token(l, k, temperature, top_k, top_p)
            )(logits[:, -1, :], keys[n0 - 1])
            if eos_ids is not None:
                done0 = _any_eos(nxt, eos_ids)
            buf = buf.at[:, n0].set(nxt)
            if stream:
                io_callback(_stream_relay, None, tag, jnp.int32(n0), nxt,
                            ordered=True)
            start = n0

        def step(carry, inp):
            pos, keys_b = inp
            buf, cache, done = carry
            logits, cache = decode_step(params, cache, buf[:, pos], pos,
                                        cfg)
            nxt = jax.vmap(
                lambda l, k: sample_token(l, k, temperature, top_k, top_p)
            )(logits, keys_b)
            if eos_ids is not None:
                # Rows that already generated a stop id keep emitting
                # the first one; a row becomes done when a *generated*
                # position produces ANY stop id (HF allows a list, e.g.
                # Llama-3's [128001, 128009]).
                nxt = jnp.where(done, jnp.int32(eos_ids[0]), nxt)
                done = done | ((pos + 1 >= n0) & _any_eos(nxt, eos_ids))
            # Prompt positions keep their token; past it we append.
            buf = jnp.where(
                pos + 1 < n0, buf,
                jax.lax.dynamic_update_slice_in_dim(
                    buf, nxt[:, None], jnp.minimum(pos + 1, total - 1), 1
                ),
            )
            if stream:
                wrote = jnp.minimum(pos + 1, total - 1)
                io_callback(
                    _stream_relay, None, tag, wrote,
                    jax.lax.dynamic_index_in_dim(buf, wrote, 1,
                                                 keepdims=False),
                    ordered=True,
                )
            return (buf, cache, done), None

        (buf, _, _), _ = jax.lax.scan(
            step, (buf, cache, done0),
            (jnp.arange(start, total - 1), keys[start:]),
        )
        if stream:
            # End-of-stream sentinel: rides the SAME ordered-callback
            # chain as the tokens, so when the relay delivers it every
            # token of THIS request has been delivered — the
            # per-request drain signal cached_decode_loop waits on.
            io_callback(_stream_relay, None, tag, jnp.int32(-1),
                        jnp.zeros((B,), jnp.int32), ordered=True)
        return buf

    return jax.jit(run)


def cached_decode_loop(
    init_kv_cache: Callable,
    decode_step: Callable,
    params,
    cfg,
    prompt_ids,
    steps: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng: jax.Array | None = None,
    eos_id: int | tuple[int, ...] | list[int] | None = None,
    on_token: Callable | None = None,
    prefill_step: Callable | None = None,
) -> jax.Array:
    """The one decode driver every family shares: prefill the prompt
    through a static-shape KV cache, then produce ``steps`` new tokens
    via a ``lax.scan`` of single-token cached steps — the whole thing
    one cached jitted program per (family, config, sampling) signature.

    With ``prefill_step`` (the family's ``decode_window``) the whole
    prompt is one batched dispatch — MXU-shaped matmuls over all
    prompt positions at once instead of ``len(prompt)`` sequential
    single-token steps; the scan then only covers generated tokens.
    Without it the prompt replays through ``decode_step`` inside the
    scan. Both paths sample bit-identically (the per-position key
    layout is shared).

    ``prompt_ids`` is (T0,) for one sequence — returns (T0+steps,) —
    or (B, T0) for a batch of equal-length prompts — returns
    (B, T0+steps), each row decoded independently (per-row sample keys).

    ``eos_id`` gives HF stop semantics without dynamic shapes: one id
    or a list/tuple of several (HF's ``eos_token_id`` may be a list,
    e.g. Llama-3's two stop ids). Once a row *generates* any of them
    (prompt occurrences don't count), every later generated token in
    that row is forced to the first id — the scan's trip count never
    changes, callers trim at the first stop id.

    ``on_token(pos, tokens)`` streams generation: an ordered
    ``io_callback`` fires after every step with the 0-based position
    just written and the ``(B,)`` int32 token row. On the prefill path
    only *generated* positions are reported (the prompt lands in one
    dispatch); the sequential path also reports prompt replay
    positions — filter on ``pos >= len(prompt)`` either way. One host
    round-trip per token (serving UX, not a throughput path); the
    compiled program stays request-independent by routing callbacks
    through a traced request tag, so concurrent streams don't
    serialize.

    The family contributes only its ``init_kv_cache(cfg, batch, max_len,
    dtype)`` and ``decode_step(params, cache, token, pos, cfg)``; the
    overflow check, prompt-preservation ``where``, buffer clamping, EOS
    freezing, and key splitting live here exactly once.
    """
    prompt = jnp.asarray(prompt_ids, jnp.int32)
    batched = prompt.ndim == 2
    if not batched:
        prompt = prompt[None, :]
    n0 = prompt.shape[1]
    if n0 + steps > cfg.n_ctx:
        raise ValueError(
            f"prompt ({n0}) + steps ({steps}) = {n0 + steps} exceeds "
            f"n_ctx {cfg.n_ctx}"
        )
    key = jax.random.key(0) if rng is None else rng
    if not jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        # Legacy raw uint32 keys (jax.random.PRNGKey) can't reshape
        # after split — normalize to a typed key first.
        key = jax.random.wrap_key_data(key)

    eos_ids = normalize_eos(eos_id)
    fn = _decode_fn(init_kv_cache, decode_step, prefill_step, cfg,
                    int(steps), float(temperature), top_k, top_p, eos_ids,
                    on_token is not None)
    if on_token is None:
        buf = fn(params, prompt, key, jnp.int32(0))
    else:
        tag = next(_STREAM_SEQ)
        done = threading.Event()
        _STREAM_CBS[tag] = (on_token, done)
        try:
            buf = fn(params, prompt, key, jnp.int32(tag))
            # Callbacks ride a separate host thread; drain THIS
            # request's before unregistering or the stream tail would
            # be dropped. The compiled program ends with a pos=-1
            # sentinel on the same ordered-callback chain, so waiting
            # for it is a per-request drain; block_until_ready first so
            # the wait only covers callback delivery, never compute.
            # (A global jax.effects_barrier() would also wait for every
            # other concurrent stream's decode — the fallback below
            # fires only if sentinel delivery stalls.)
            jax.block_until_ready(buf)
            if not done.wait(timeout=30.0):
                jax.effects_barrier()
        finally:
            _STREAM_CBS.pop(tag, None)
    return buf if batched else buf[0]
