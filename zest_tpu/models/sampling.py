"""Token sampling and the shared cached-decode loop for all families."""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def sample_token(logits, key, temperature: float = 0.0,
                 top_k: int | None = None,
                 top_p: float | None = None) -> jax.Array:
    """One token from (vocab,) logits: greedy at ``temperature<=0``,
    otherwise softmax sampling at the given temperature, optionally
    restricted to the ``top_k`` most likely tokens and/or the nucleus
    of cumulative probability ``top_p``. Static-shape (both
    restrictions mask, never gather); jittable."""
    if top_p is not None and not 0.0 < top_p <= 1.0:
        # A silently-ignored top_p=0 would turn the most restrictive
        # request into unrestricted sampling (HF raises here too).
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature <= 0.0:
        return jnp.argmax(logits).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        # k > vocab means "no restriction", not an internal top_k error.
        k = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][-1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        # Nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p. ``cum - probs < top_p`` keeps every
        # token whose mass *before* it is under the budget — so the
        # most likely token always survives and the boundary token that
        # crosses the budget is included (HF semantics). The mask is
        # scattered back by sorted *position*, not by logit value, so
        # ties at the boundary don't widen the nucleus (argsort is
        # stable: the earliest-index of equal logits wins, as in HF).
        order = jnp.argsort(-logits)
        probs = jax.nn.softmax(logits[order])
        cum = jnp.cumsum(probs)
        keep_sorted = cum - probs < top_p
        keep = jnp.zeros_like(keep_sorted).at[order].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def cached_decode_loop(
    init_kv_cache: Callable,
    decode_step: Callable,
    params,
    cfg,
    prompt_ids,
    steps: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng: jax.Array | None = None,
    eos_id: int | None = None,
    on_token: Callable | None = None,
) -> jax.Array:
    """The one decode driver every family shares: prefill token-by-token
    through a static-shape KV cache, then produce ``steps`` new tokens,
    all inside one jitted ``lax.scan``.

    ``prompt_ids`` is (T0,) for one sequence — returns (T0+steps,) —
    or (B, T0) for a batch of equal-length prompts — returns
    (B, T0+steps), each row decoded independently (per-row sample keys).

    ``eos_id`` gives HF stop semantics without dynamic shapes: once a
    row *generates* ``eos_id`` (prompt occurrences don't count), every
    later generated token in that row is forced to ``eos_id`` — the
    scan's trip count never changes, callers trim at the first EOS.

    ``on_token(pos, tokens)`` streams generation: an ordered
    ``io_callback`` fires from inside the compiled scan after every
    step with the 0-based position just written and the ``(B,)`` int32
    token row (prompt positions included — filter on ``pos >= len(
    prompt)``). One host round-trip per token: serving UX, not a
    throughput path.

    The family contributes only its ``init_kv_cache(cfg, batch, max_len,
    dtype)`` and ``decode_step(params, cache, token, pos, cfg)``; the
    overflow check, prompt-preservation ``where``, buffer clamping, EOS
    freezing, and key splitting live here exactly once.
    """
    prompt = jnp.asarray(prompt_ids, jnp.int32)
    batched = prompt.ndim == 2
    if not batched:
        prompt = prompt[None, :]
    B, n0 = prompt.shape
    total = n0 + steps
    if total > cfg.n_ctx:
        raise ValueError(
            f"prompt ({n0}) + steps ({steps}) = {total} exceeds "
            f"n_ctx {cfg.n_ctx}"
        )
    cache = init_kv_cache(cfg, B, total, dtype=params["wte"].dtype)
    buf = jnp.zeros((B, total), jnp.int32).at[:, :n0].set(prompt)
    key = jax.random.key(0) if rng is None else rng
    if not jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        # Legacy raw uint32 keys (jax.random.PRNGKey) can't reshape
        # after split — normalize to a typed key first.
        key = jax.random.wrap_key_data(key)
    keys = jax.random.split(key, (total - 1) * B).reshape(total - 1, B)

    done0 = jnp.zeros((B,), bool)

    def step(carry, inp):
        pos, keys_b = inp
        buf, cache, done = carry
        logits, cache = decode_step(params, cache, buf[:, pos], pos, cfg)
        nxt = jax.vmap(
            lambda l, k: sample_token(l, k, temperature, top_k, top_p)
        )(logits, keys_b)
        if eos_id is not None:
            # Rows that already generated EOS keep emitting EOS; a row
            # becomes done when a *generated* position produces EOS.
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | ((pos + 1 >= n0) & (nxt == eos_id))
        # Prompt positions keep their token; past the prompt we append.
        buf = jnp.where(
            pos + 1 < n0, buf,
            jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None], jnp.minimum(pos + 1, total - 1), 1
            ),
        )
        if on_token is not None:
            from jax.experimental import io_callback

            wrote = jnp.minimum(pos + 1, total - 1)
            io_callback(
                on_token, None, wrote,
                jax.lax.dynamic_index_in_dim(buf, wrote, 1, keepdims=False),
                ordered=True,
            )
        return (buf, cache, done), None

    (buf, _, _), _ = jax.lax.scan(
        step, (buf, cache, done0), (jnp.arange(total - 1), keys)
    )
    return buf if batched else buf[0]
