"""Flagship model: pure-JAX GPT-2 that consumes pulled HF checkpoints.

The reference's end-to-end proof is "pull gpt2, load with transformers,
generate" (test/local/verify-model.sh:90-147). The TPU build closes the
same loop natively: the pulled safetensors map onto this module's param
tree, the forward runs under jit on the MXU (bf16 matmuls, static shapes,
``lax`` control flow only), and the train step shards over a
``{data, model}`` mesh so the checkpoint landed by zest_tpu.models.loader
is consumed in place.

Design notes (TPU-first, not a torch translation):
- params are a flat pytree of arrays; blocks are stacked along a leading
  layer axis and the transformer body is one ``lax.scan`` over layers —
  one compiled block regardless of depth, the idiomatic XLA layout.
- tensor-parallel sharding follows the Megatron pattern expressed as
  PartitionSpecs: qkv/fc shard the output feature dim, proj shards the
  input feature dim, so each block needs exactly one reduce per sublayer,
  which GSPMD inserts automatically.
"""

from __future__ import annotations

import dataclasses
import math
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_ctx: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_eps: float = 1e-5

    @staticmethod
    def tiny(**over) -> "GPT2Config":
        """Test/dryrun-sized config (divisible by 8-wide model axes)."""
        base = dict(vocab_size=256, n_ctx=64, n_embd=64,
                    n_layer=2, n_head=4)
        base.update(over)
        return GPT2Config(**base)

    @staticmethod
    def from_hf(cfg_json: dict) -> "GPT2Config":
        return GPT2Config(
            vocab_size=cfg_json["vocab_size"],
            n_ctx=cfg_json.get("n_ctx", cfg_json.get("n_positions", 1024)),
            n_embd=cfg_json["n_embd"],
            n_layer=cfg_json["n_layer"],
            n_head=cfg_json["n_head"],
            layer_norm_eps=cfg_json.get("layer_norm_epsilon", 1e-5),
        )


# ── Parameters ──


def init_params(rng: jax.Array, cfg: GPT2Config, dtype=jnp.float32) -> dict:
    """Random-init param tree with stacked per-layer leaves (L leading)."""
    E, L = cfg.n_embd, cfg.n_layer
    k = iter(jax.random.split(rng, 8))

    def dense(key, shape, scale=0.02):
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    return {
        "wte": dense(next(k), (cfg.vocab_size, E)),
        "wpe": dense(next(k), (cfg.n_ctx, E), 0.01),
        "ln_f": {"g": jnp.ones((E,), dtype), "b": jnp.zeros((E,), dtype)},
        "blocks": {
            "ln_1": {"g": jnp.ones((L, E), dtype),
                     "b": jnp.zeros((L, E), dtype)},
            "ln_2": {"g": jnp.ones((L, E), dtype),
                     "b": jnp.zeros((L, E), dtype)},
            "attn": {
                "qkv_w": dense(next(k), (L, E, 3 * E)),
                "qkv_b": jnp.zeros((L, 3 * E), dtype),
                "proj_w": dense(next(k), (L, E, E),
                                0.02 / math.sqrt(2 * L)),
                "proj_b": jnp.zeros((L, E), dtype),
            },
            "mlp": {
                "fc_w": dense(next(k), (L, E, 4 * E)),
                "fc_b": jnp.zeros((L, 4 * E), dtype),
                "proj_w": dense(next(k), (L, 4 * E, E),
                                0.02 / math.sqrt(2 * L)),
                "proj_b": jnp.zeros((L, E), dtype),
            },
        },
    }


_HF_BLOCK = re.compile(r"^h\.(\d+)\.(.+)$")

# HF tensor name (within a block) -> (group, leaf). GPT-2 uses Conv1D, whose
# weight is stored (in_features, out_features) — already the x @ W layout,
# no transpose.
_HF_LEAF = {
    "ln_1.weight": ("ln_1", "g"), "ln_1.bias": ("ln_1", "b"),
    "ln_2.weight": ("ln_2", "g"), "ln_2.bias": ("ln_2", "b"),
    "attn.c_attn.weight": ("attn", "qkv_w"),
    "attn.c_attn.bias": ("attn", "qkv_b"),
    "attn.c_proj.weight": ("attn", "proj_w"),
    "attn.c_proj.bias": ("attn", "proj_b"),
    "mlp.c_fc.weight": ("mlp", "fc_w"), "mlp.c_fc.bias": ("mlp", "fc_b"),
    "mlp.c_proj.weight": ("mlp", "proj_w"),
    "mlp.c_proj.bias": ("mlp", "proj_b"),
}


def params_from_hf(
    tensors: dict[str, np.ndarray], cfg: GPT2Config, dtype=jnp.float32
) -> dict:
    """Map an HF gpt2 checkpoint (flat name→array) onto the param tree.

    Accepts either bare names (``h.0.attn.c_attn.weight``) or the
    ``transformer.``-prefixed variant; skips the tied ``lm_head.weight``
    and the non-parameter causal-mask buffers (``attn.bias``).
    """
    flat: dict[str, np.ndarray] = {}
    for name, arr in tensors.items():
        if name.startswith("transformer."):
            name = name[len("transformer."):]
        flat[name] = np.asarray(arr)

    L = cfg.n_layer
    stacks: dict[tuple[str, str], list] = {
        key: [None] * L for key in set(_HF_LEAF.values())
    }
    out = {
        "wte": jnp.asarray(flat["wte.weight"], dtype),
        "wpe": jnp.asarray(flat["wpe.weight"], dtype),
        "ln_f": {"g": jnp.asarray(flat["ln_f.weight"], dtype),
                 "b": jnp.asarray(flat["ln_f.bias"], dtype)},
    }
    for name, arr in flat.items():
        m = _HF_BLOCK.match(name)
        if not m:
            continue
        layer, leaf = int(m.group(1)), m.group(2)
        if leaf not in _HF_LEAF:
            continue  # attn.bias / attn.masked_bias buffers
        stacks[_HF_LEAF[leaf]][layer] = arr
    blocks: dict[str, dict[str, jax.Array]] = {}
    for (group, leaf), layers in stacks.items():
        missing = [i for i, a in enumerate(layers) if a is None]
        if missing:
            raise ValueError(f"checkpoint missing {group}.{leaf} "
                             f"for layers {missing}")
        blocks.setdefault(group, {})[leaf] = jnp.asarray(
            np.stack(layers), dtype
        )
    out["blocks"] = blocks
    return out


# ── Sharding rules (data+tensor parallel) ──


def param_specs(cfg: GPT2Config) -> dict:
    """PartitionSpec tree matching ``init_params`` (Megatron-style TP)."""
    rep1 = {"g": P(), "b": P()}
    return {
        # wte stays replicated: GPT-2's vocab (50257) divides no mesh axis,
        # and a divisibility-dependent spec would make the tree shape a
        # function of the mesh. Landing raw checkpoints still shards the
        # embedding dim when divisible (checkpoint_shard_rules fallback).
        "wte": P(),
        "wpe": P(),
        "ln_f": dict(rep1),
        "blocks": {
            "ln_1": dict(rep1),
            "ln_2": dict(rep1),
            "attn": {
                "qkv_w": P(None, None, MODEL_AXIS),
                "qkv_b": P(None, MODEL_AXIS),
                "proj_w": P(None, MODEL_AXIS, None),
                "proj_b": P(),
            },
            "mlp": {
                "fc_w": P(None, None, MODEL_AXIS),
                "fc_b": P(None, MODEL_AXIS),
                "proj_w": P(None, MODEL_AXIS, None),
                "proj_b": P(),
            },
        },
    }


def checkpoint_shard_rules() -> list[tuple[str, P]]:
    """Name-pattern rules for landing raw HF gpt2 safetensors via
    zest_tpu.models.loader (same layout as ``param_specs``)."""
    return [
        (r"attn\.c_attn\.weight$", P(None, MODEL_AXIS)),
        (r"attn\.c_attn\.bias$", P(MODEL_AXIS)),
        (r"attn\.c_proj\.weight$", P(MODEL_AXIS, None)),
        (r"mlp\.c_fc\.weight$", P(None, MODEL_AXIS)),
        (r"mlp\.c_fc\.bias$", P(MODEL_AXIS)),
        (r"mlp\.c_proj\.weight$", P(MODEL_AXIS, None)),
        # No rule for wte/wpe/ln: the loader's infer_spec fallback shards
        # only evenly divisible dims (vocab 50257 divides nothing → the
        # embedding dim shards instead) and replicates the rest.
    ]


# ── Forward ──


def _layer_norm(x, g, b, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _block(x, p, cfg: GPT2Config):
    """One transformer block; ``p`` holds this layer's slice of the stack."""
    B, T, E = x.shape
    H = cfg.n_head
    h = _layer_norm(x, p["ln_1"]["g"], p["ln_1"]["b"], cfg.layer_norm_eps)
    qkv = h @ p["attn"]["qkv_w"] + p["attn"]["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, E // H).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, E // H).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, E // H).transpose(0, 2, 1, 3)
    scores = (q @ k.transpose(0, 1, 3, 2)) / math.sqrt(E // H)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    att = jax.nn.softmax(scores, axis=-1)
    out = (att @ v).transpose(0, 2, 1, 3).reshape(B, T, E)
    x = x + out @ p["attn"]["proj_w"] + p["attn"]["proj_b"]

    h = _layer_norm(x, p["ln_2"]["g"], p["ln_2"]["b"], cfg.layer_norm_eps)
    h = jax.nn.gelu(h @ p["mlp"]["fc_w"] + p["mlp"]["fc_b"], approximate=True)
    return x + h @ p["mlp"]["proj_w"] + p["mlp"]["proj_b"]


def forward(params: dict, input_ids: jax.Array, cfg: GPT2Config,
            remat: bool = False) -> jax.Array:
    """(B, T) int32 token ids → (B, T, vocab) logits. Jittable."""
    B, T = input_ids.shape
    x = params["wte"][input_ids] + params["wpe"][:T]

    def body(x, layer_params):
        return _block(x, layer_params, cfg), None

    if remat:
        # Per-layer rematerialization (jax.checkpoint): backward-pass
        # recompute instead of saved activations — O(1) layers resident.
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["blocks"])
    x = _layer_norm(x, params["ln_f"]["g"], params["ln_f"]["b"],
                    cfg.layer_norm_eps)
    return x @ params["wte"].T


def loss_fn(params, batch, cfg: GPT2Config, remat: bool = False):
    """Next-token cross entropy over ``batch`` (B, T+1) ids."""
    inputs, targets = batch[:, :-1], batch[:, 1:]
    logits = forward(params, inputs, cfg, remat=remat).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def train_step(params, batch, cfg: GPT2Config, lr: float = 1e-3,
               remat: bool = False):
    """One SGD step — the full step jitted over the mesh in dryruns.

    Inputs arrive sharded (params per ``param_specs``, batch over the data
    axis); GSPMD propagates the shardings and inserts the TP reduces and
    the DP gradient psum. ``remat=True`` trades backward-pass FLOPs for
    activation memory (per-layer jax.checkpoint).
    """
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, remat)
    params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                          params, grads)
    return params, loss


def init_kv_cache(cfg: GPT2Config, batch: int, max_len: int,
                  dtype=jnp.float32) -> dict:
    """Static-shape per-layer K/V cache: (L, B, max_len, H, head_dim)."""
    H = cfg.n_head
    shape = (cfg.n_layer, batch, max_len, H, cfg.n_embd // H)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def decode_window(params, cache: dict, tokens: jax.Array, pos,
                  cfg: GPT2Config, last_only: bool = False):
    """Cached step over a token window: (B, S) ids occupying positions
    ``pos``..``pos+S-1`` → ((B, S, vocab) logits, updated cache).
    S=1 is one incremental decode step; S=len(prompt) is the batched
    prefill (one MXU-shaped dispatch for the whole prompt — same
    contract as llama.decode_window). Jittable; ``pos`` traced."""
    B, S = tokens.shape
    H, D = cfg.n_head, cfg.n_embd // cfg.n_head
    wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos, S, axis=0)
    x = params["wte"][tokens] + wpe[None, :, :]            # (B, S, E)

    def body(carry, inp):
        x, pos = carry
        lp, ck, cv = inp
        h = _layer_norm(x, lp["ln_1"]["g"], lp["ln_1"]["b"],
                        cfg.layer_norm_eps)
        qkv = h @ lp["attn"]["qkv_w"] + lp["attn"]["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D)
        ck = jax.lax.dynamic_update_slice_in_dim(
            ck, k.reshape(B, S, H, D), pos, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(
            cv, v.reshape(B, S, H, D), pos, axis=1)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck) / math.sqrt(D)
        valid = (jnp.arange(ck.shape[1])[None, :]
                 <= pos + jnp.arange(S)[:, None])
        scores = jnp.where(valid[None, None, :, :], scores,
                           jnp.finfo(scores.dtype).min)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", att.astype(x.dtype), cv)
        out = out.reshape(B, S, cfg.n_embd)
        x = x + out @ lp["attn"]["proj_w"] + lp["attn"]["proj_b"]
        h = _layer_norm(x, lp["ln_2"]["g"], lp["ln_2"]["b"],
                        cfg.layer_norm_eps)
        h = jax.nn.gelu(h @ lp["mlp"]["fc_w"] + lp["mlp"]["fc_b"],
                        approximate=True)
        return (x + h @ lp["mlp"]["proj_w"] + lp["mlp"]["proj_b"], pos), \
            (ck, cv)

    (x, _), (new_k, new_v) = jax.lax.scan(
        body, (x, pos), (params["blocks"], cache["k"], cache["v"])
    )
    x = _layer_norm(x, params["ln_f"]["g"], params["ln_f"]["b"],
                    cfg.layer_norm_eps)
    if last_only:
        # Prefill wants one next-token distribution: skip the (B, S,
        # vocab) unembedding for all but the final position.
        x = x[:, -1:, :]
    return x @ params["wte"].T, {"k": new_k, "v": new_v}


def decode_step(params, cache: dict, token: jax.Array, pos, cfg: GPT2Config):
    """One incremental decode step: (B,) ids at position ``pos`` →
    ((B, vocab) logits, updated cache). O(T) per token via the KV cache
    (same contract as llama.decode_step); the S=1 specialization of
    :func:`decode_window`."""
    logits, cache = decode_window(params, cache, token[:, None], pos, cfg)
    return logits[:, 0, :], cache


def generate_cached(params, cfg: GPT2Config, prompt_ids, steps: int,
                    temperature: float = 0.0, top_k: int | None = None,
                    top_p: float | None = None,
                    rng: jax.Array | None = None,
                    eos_id: int | tuple[int, ...] | None = None,
                    on_token=None):
    """KV-cached decode (O(T) per token; sampling.cached_decode_loop);
    token-identical to ``generate_greedy`` at temperature 0."""
    from zest_tpu.models.sampling import cached_decode_loop

    return cached_decode_loop(
        init_kv_cache, decode_step, params, cfg, prompt_ids, steps,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
        eos_id=eos_id, on_token=on_token,
        prefill_step=decode_window,
    )


def generate_greedy(params, cfg: GPT2Config, prompt_ids, steps: int,
                    temperature: float = 0.0, top_k: int | None = None,
                    top_p: float | None = None,
                    rng: jax.Array | None = None):
    """Decode via ``lax.scan`` over a fixed-size buffer (static shapes;
    no Python loop under jit). Returns (len(prompt)+steps,) ids. Default
    greedy; ``temperature``/``top_k``/``top_p`` switch to sampling (see
    models.sampling.sample_token). EOS stopping and token streaming
    live only on the cached path (``generate_cached``)."""
    from zest_tpu.models.sampling import sample_token

    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    n0 = prompt_ids.shape[0]
    total = n0 + steps
    if total > cfg.n_ctx:
        raise ValueError(
            f"prompt ({n0}) + steps ({steps}) = {total} exceeds "
            f"n_ctx {cfg.n_ctx}"
        )
    buf = jnp.zeros((total,), jnp.int32).at[:n0].set(prompt_ids)
    keys = jax.random.split(
        jax.random.key(0) if rng is None else rng, steps
    )

    def step(carry, key):
        buf, pos = carry
        logits = forward(params, buf[None, :], cfg)[0]
        nxt = sample_token(logits[pos - 1], key, temperature, top_k, top_p)
        buf = buf.at[pos].set(nxt)
        return (buf, pos + 1), nxt

    (buf, _), _ = jax.lax.scan(step, (buf, jnp.int32(n0)), keys)
    return buf
