"""Minimal safetensors reader/writer — the checkpoint byte format.

The reference never parses model files; it reassembles opaque bytes and lets
transformers read them (src/xet_bridge.zig:231-264). The TPU build needs the
format itself, because the north-star path lands tensors *directly* into
sharded device buffers without a disk round-trip: the header maps tensor
names to byte ranges, and those ranges compose with reconstruction terms so
a chunk range can be scattered straight to the tensor slices it feeds.

Self-contained on purpose (no ``safetensors`` dependency): the framework
must know byte offsets, which the upstream package hides.

Format (https spec, stable): ``[u64le header_len][JSON header][data]`` where
header maps ``name -> {"dtype", "shape", "data_offsets": [begin, end)}``
with offsets relative to the end of the header; optional ``__metadata__``.
"""

from __future__ import annotations

import json
import mmap
import struct
from dataclasses import dataclass
from pathlib import Path

import numpy as np

import ml_dtypes

# safetensors dtype tag -> numpy dtype (little-endian where sized)
DTYPES: dict[str, np.dtype] = {
    "F64": np.dtype("<f8"),
    "F32": np.dtype("<f4"),
    "F16": np.dtype("<f2"),
    "BF16": np.dtype(ml_dtypes.bfloat16),
    "I64": np.dtype("<i8"),
    "I32": np.dtype("<i4"),
    "I16": np.dtype("<i2"),
    "I8": np.dtype("i1"),
    "U8": np.dtype("u1"),
    "BOOL": np.dtype("?"),
    "U16": np.dtype("<u2"),
    "U32": np.dtype("<u4"),
    "U64": np.dtype("<u8"),
    "F8_E4M3": np.dtype(ml_dtypes.float8_e4m3fn),
    "F8_E5M2": np.dtype(ml_dtypes.float8_e5m2),
}
_TAGS = {v: k for k, v in DTYPES.items()}

_MAX_HEADER = 100 * 1024 * 1024  # upstream parser's sanity cap


@dataclass(frozen=True)
class TensorInfo:
    name: str
    dtype: str                 # safetensors tag, e.g. "F32"
    shape: tuple[int, ...]
    data_offsets: tuple[int, int]   # relative to data section start

    @property
    def nbytes(self) -> int:
        return self.data_offsets[1] - self.data_offsets[0]

    @property
    def np_dtype(self) -> np.dtype:
        return DTYPES[self.dtype]

    def file_range(self, data_start: int) -> tuple[int, int]:
        """Absolute byte range of this tensor within the file — the hook
        that lets reconstruction terms scatter directly into tensors."""
        return (data_start + self.data_offsets[0],
                data_start + self.data_offsets[1])


@dataclass(frozen=True)
class SafetensorsHeader:
    tensors: dict[str, TensorInfo]
    metadata: dict[str, str]
    data_start: int            # file offset where the data section begins

    def names(self) -> list[str]:
        return list(self.tensors)


def _parse_tensors(
    buf: bytes | memoryview, bounded: bool
) -> SafetensorsHeader:
    """Shared header parse; ``bounded=False`` skips the data-section end
    bound (prefix mode — everything else, including overlap and shape/size
    consistency, is validated in both modes)."""
    if len(buf) < 8:
        raise ValueError("truncated safetensors: missing header length")
    (hlen,) = struct.unpack_from("<Q", buf, 0)
    if hlen > _MAX_HEADER or 8 + hlen > len(buf):
        raise ValueError(
            f"safetensors header length {hlen} out of bounds for "
            f"{len(buf)}-byte buffer"
        )
    data_len = len(buf) - 8 - hlen if bounded else None
    header = json.loads(bytes(buf[8 : 8 + hlen]).decode("utf-8"))
    metadata = header.pop("__metadata__", {})
    tensors: dict[str, TensorInfo] = {}
    for name, spec in header.items():
        if spec["dtype"] not in DTYPES:
            raise ValueError(f"unsupported dtype {spec['dtype']} for {name}")
        begin, end = (int(v) for v in spec["data_offsets"])
        if begin < 0 or end < begin or (
            data_len is not None and end > data_len
        ):
            raise ValueError(
                f"{name}: data_offsets [{begin}, {end}) out of bounds"
            )
        shape = tuple(int(d) for d in spec["shape"])
        info = TensorInfo(name, spec["dtype"], shape, (begin, end))
        expect = int(np.prod(shape, dtype=np.int64)) * info.np_dtype.itemsize
        if info.nbytes != expect:
            raise ValueError(
                f"{name}: data_offsets span {info.nbytes} bytes, "
                f"shape/dtype need {expect}"
            )
        tensors[name] = info
    # Ranges must not overlap — aliased tensors would silently share bytes,
    # defeating byte-level integrity (upstream enforces the same).
    spans = sorted(
        (i.data_offsets for i in tensors.values() if i.nbytes),
    )
    for (b0, e0), (b1, _e1) in zip(spans, spans[1:]):
        if b1 < e0:
            raise ValueError(
                f"overlapping tensor data ranges [{b0},{e0}) and [{b1},…)"
            )
    return SafetensorsHeader(tensors, metadata, 8 + hlen)


def parse_header(buf: bytes | memoryview) -> SafetensorsHeader:
    return _parse_tensors(buf, bounded=True)


def parse_header_prefix(buf: bytes | memoryview) -> SafetensorsHeader:
    """Parse a header from the *head bytes only* (data section absent).

    The expert-routing planner (zest_tpu.parallel.expert) must know tensor
    byte ranges before any data bytes are fetched — it pulls just the file
    head, reads the name→range map, and routes the rest of the file's
    chunks to the hosts that need them. Same validation as
    ``parse_header`` minus the data-section end bound (the data length is
    unknown here; that check reruns on reassembly).
    """
    return _parse_tensors(buf, bounded=False)


class SafetensorsFile:
    """mmap-backed lazy reader: header up front, tensor bytes on demand."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._f = open(self.path, "rb")
        try:
            self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # zero-length file
            self._f.close()
            raise ValueError(f"{path}: empty file is not safetensors")
        try:
            self.header = parse_header(memoryview(self._mm))
        except Exception:
            self.close()
            raise

    def close(self) -> None:
        try:
            self._mm.close()
        except BufferError:
            pass  # zero-copy views still alive; the map unmaps on GC
        self._f.close()

    def __enter__(self) -> "SafetensorsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def names(self) -> list[str]:
        return self.header.names()

    def info(self, name: str) -> TensorInfo:
        return self.header.tensors[name]

    def tensor(self, name: str) -> np.ndarray:
        """Zero-copy view (mmap-backed) of one tensor."""
        info = self.header.tensors[name]
        lo, hi = info.file_range(self.header.data_start)
        count = (hi - lo) // info.np_dtype.itemsize
        return np.frombuffer(
            self._mm, dtype=info.np_dtype, count=count, offset=lo
        ).reshape(info.shape)

    def items(self):
        for name in self.header.tensors:
            yield name, self.tensor(name)


def write_safetensors(
    path: str | Path,
    tensors: dict[str, np.ndarray],
    metadata: dict[str, str] | None = None,
) -> None:
    """Writer — used by tests and by checkpoint re-export."""
    header: dict[str, object] = {}
    if metadata:
        header["__metadata__"] = metadata
    offset = 0
    arrays: list[np.ndarray] = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dt = np.dtype(arr.dtype)
        if dt.byteorder == ">":
            arr = arr.astype(dt.newbyteorder("<"))
            dt = arr.dtype
        tag = _TAGS.get(dt) or _TAGS.get(np.dtype(dt.str.lstrip(">=")))
        if tag is None:
            raise ValueError(f"{name}: dtype {arr.dtype} not representable")
        header[name] = {
            "dtype": tag,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + arr.nbytes],
        }
        offset += arr.nbytes
        arrays.append(arr)
    blob = json.dumps(header, separators=(",", ":")).encode("utf-8")
    # Upstream aligns the data section to 8 bytes by padding the JSON.
    pad = (8 - (8 + len(blob)) % 8) % 8
    blob += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        for arr in arrays:
            f.write(arr.tobytes())
