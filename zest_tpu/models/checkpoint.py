"""Training-state checkpointing (orbax) and HF-format export.

The reference's "checkpoint/resume" is the idempotent xorb cache
(SURVEY.md §5) — resuming a *download*. The training plane needs the
other half: persisting a :class:`zest_tpu.models.training.TrainState`
across job restarts (orbax handles sharded arrays natively — each host
writes its own shards, restore re-lands onto the current mesh) and
exporting trained params back to HF safetensors so anything that speaks
``transformers`` can consume the result.
"""

from __future__ import annotations

from pathlib import Path

import jax


def save_train_state(path: str | Path, state) -> None:
    """Write a TrainState (sharded or not) with orbax StandardCheckpointer.

    ``path`` must not already contain a checkpoint (orbax refuses
    overwrites by design — version your step dirs: ``ckpt/step_000100``).
    """
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(Path(path).resolve(), state)


def restore_train_state(path: str | Path, state_like):
    """Restore a TrainState saved by :func:`save_train_state`.

    ``state_like`` supplies structure, dtypes, and target shardings —
    pass the freshly-built state (``create_state(params, tx)``) whose
    arrays sit where the restored ones should land; abstract shapes via
    ``jax.eval_shape`` work too when paired with real shardings.
    """
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(Path(path).resolve(), abstract)


def export_hf_safetensors(path: str | Path, params, cfg) -> None:
    """Trained Llama-family params → one HF-format safetensors file.

    Pairs with ``llama.params_to_hf``; the output loads with
    ``transformers`` (state_dict-compatible names/orientations).
    """
    from zest_tpu.models import llama
    from zest_tpu.models.safetensors_io import write_safetensors

    write_safetensors(path, llama.params_to_hf(params, cfg))
