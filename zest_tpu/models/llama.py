"""Llama-family flagship: pure-JAX Llama 3.x that consumes pulled checkpoints.

BASELINE.md's north-star configs are Llama models (config #2 Llama-3.1-8B
two-host DCN, #3 Llama-3.1-70B v5p-64 ICI, #5 Llama-405B hierarchical) —
the checkpoints the pull pipeline exists to land. This module is their
consumer, the same role models/gpt2.py plays for config #1's verify loop
(reference: test/local/verify-model.sh:90-147). Architecture: RMSNorm,
rotary embeddings, grouped-query attention, SwiGLU MLP — the Llama 2/3
family (and by extension Mistral/Qwen-dense, which share the layout).

Design notes (TPU-first, matching gpt2.py/moe.py):
- stacked per-layer leaves + one ``lax.scan`` over layers: one compiled
  block regardless of depth.
- tensor parallelism as Megatron PartitionSpecs over the ``model`` axis:
  q/k/v/gate/up shard their output dim, o/down their input dim — exactly
  one GSPMD reduce per sublayer.
- **context parallelism is first-class**: :func:`cp_forward` runs the whole
  forward under ``shard_map`` with the sequence dimension sharded over a
  ``seq`` mesh axis, attention as a ppermute ring
  (zest_tpu.parallel.ring), and RoPE phases offset per shard — long
  sequences scale across devices with O(T/P) activation memory per device.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu.models.sampling import cached_decode_loop
from zest_tpu.parallel.ring import SEQ_AXIS, ring_self_attention

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    # Defaults are Llama-3.1-8B's config.json (BASELINE config #2).
    vocab_size: int = 128256
    n_ctx: int = 131072
    n_embd: int = 4096
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 8
    d_ff: int = 14336
    rms_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_embeddings: bool = False
    # Llama-3.1 "llama3" RoPE frequency scaling (config.json rope_scaling).
    # factor None = unscaled (Llama 2 / 3.0 / Mistral).
    rope_scaling_factor: float | None = 8.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_ctx: int = 8192
    # Some family members decouple head_dim from n_embd/n_head
    # (e.g. Mistral-Nemo: 5120/32 but head_dim=128). None = derived.
    head_dim_override: int | None = None
    # Qwen2-style q/k/v projection biases (Qwen2 hardcodes them on
    # without an attention_bias config key).
    attn_bias: bool = False
    # HF attention_bias=True additionally puts a bias on o_proj
    # (Qwen2 does not), so the two are tracked separately.
    o_bias: bool = False

    @staticmethod
    def tiny(**over) -> "LlamaConfig":
        """Test/dryrun-sized config (divisible by 8-wide mesh axes)."""
        base = dict(vocab_size=256, n_ctx=64, n_embd=64, n_layer=2,
                    n_head=4, n_kv_head=2, d_ff=128,
                    rope_scaling_factor=None)
        base.update(over)
        return LlamaConfig(**base)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()  # defaults

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(n_embd=8192, n_layer=80, n_head=64,
                           n_kv_head=8, d_ff=28672)

    @staticmethod
    def from_hf(cfg_json: dict) -> "LlamaConfig":
        rs = cfg_json.get("rope_scaling") or None
        scaling: dict = {"rope_scaling_factor": None}
        if rs:
            rtype = rs.get("rope_type", rs.get("type", "default"))
            if rtype == "llama3":
                scaling = dict(
                    rope_scaling_factor=float(rs["factor"]),
                    rope_low_freq_factor=float(
                        rs.get("low_freq_factor", 1.0)),
                    rope_high_freq_factor=float(
                        rs.get("high_freq_factor", 4.0)),
                    rope_original_ctx=int(
                        rs.get("original_max_position_embeddings", 8192)),
                )
            elif rtype != "default":
                # Silently dropping a scaling rule would yield wrong
                # positional phases on every token — refuse instead.
                raise ValueError(
                    f"unsupported rope_scaling type {rtype!r} "
                    "(supported: llama3, default)"
                )
        if cfg_json.get("mlp_bias"):
            # The tree has no MLP-bias leaves; loading such a checkpoint
            # would silently drop tensors and compute wrong logits.
            raise ValueError(
                "mlp_bias checkpoints are not supported by this tree"
            )
        # Qwen2 hardcodes q/k/v biases without setting attention_bias;
        # an explicit attention_bias=True (HF LlamaAttention) biases
        # o_proj as well.
        explicit = bool(cfg_json.get("attention_bias", False))
        attn_bias = explicit or cfg_json.get("model_type") == "qwen2"
        # Fallbacks for omitted keys match transformers.LlamaConfig's
        # defaults (an old Llama-2-era config.json omits rope_theta and
        # must get 10000.0, not a 3.1 value).
        return LlamaConfig(
            **scaling,
            vocab_size=cfg_json["vocab_size"],
            n_ctx=cfg_json.get("max_position_embeddings", 2048),
            n_embd=cfg_json["hidden_size"],
            n_layer=cfg_json["num_hidden_layers"],
            n_head=cfg_json["num_attention_heads"],
            n_kv_head=cfg_json.get("num_key_value_heads",
                                   cfg_json["num_attention_heads"]),
            d_ff=cfg_json["intermediate_size"],
            rms_eps=cfg_json.get("rms_norm_eps", 1e-6),
            rope_theta=cfg_json.get("rope_theta", 10000.0),
            tie_embeddings=cfg_json.get("tie_word_embeddings", False),
            head_dim_override=cfg_json.get("head_dim"),
            attn_bias=attn_bias,
            o_bias=explicit,
        )

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.n_embd // self.n_head


# ── Parameters ──


def init_params(rng: jax.Array, cfg: LlamaConfig, dtype=jnp.float32) -> dict:
    """Random-init tree with stacked per-layer leaves (L leading)."""
    E, L, F = cfg.n_embd, cfg.n_layer, cfg.d_ff
    qE = cfg.n_head * cfg.head_dim  # == E unless head_dim_override
    kvE = cfg.n_kv_head * cfg.head_dim
    k = iter(jax.random.split(rng, 10))

    def dense(key, shape, scale=0.02):
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    attn = {
        "q_w": dense(next(k), (L, E, qE)),
        "k_w": dense(next(k), (L, E, kvE)),
        "v_w": dense(next(k), (L, E, kvE)),
        "o_w": dense(next(k), (L, qE, E), 0.02 / math.sqrt(2 * L)),
    }
    if cfg.attn_bias:
        attn.update(q_b=jnp.zeros((L, qE), dtype),
                    k_b=jnp.zeros((L, kvE), dtype),
                    v_b=jnp.zeros((L, kvE), dtype))
    if cfg.o_bias:
        attn["o_b"] = jnp.zeros((L, E), dtype)
    out = {
        "wte": dense(next(k), (cfg.vocab_size, E)),
        "ln_f": {"g": jnp.ones((E,), dtype)},
        "blocks": {
            "ln_attn": {"g": jnp.ones((L, E), dtype)},
            "ln_mlp": {"g": jnp.ones((L, E), dtype)},
            "attn": attn,
            "mlp": {
                "gate_w": dense(next(k), (L, E, F)),
                "up_w": dense(next(k), (L, E, F)),
                "down_w": dense(next(k), (L, F, E), 0.02 / math.sqrt(2 * L)),
            },
        },
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = dense(next(k), (E, cfg.vocab_size))
    return out


_HF_ATTN = {
    "self_attn.q_proj": ("attn", "q_w"),
    "self_attn.k_proj": ("attn", "k_w"),
    "self_attn.v_proj": ("attn", "v_w"),
    "self_attn.o_proj": ("attn", "o_w"),
}
_HF_MLP = {
    "mlp.gate_proj": ("mlp", "gate_w"),
    "mlp.up_proj": ("mlp", "up_w"),
    "mlp.down_proj": ("mlp", "down_w"),
}
_HF_NORM = {
    "input_layernorm": ("ln_attn", "g"),
    "post_attention_layernorm": ("ln_mlp", "g"),
}


def params_from_hf(
    tensors: dict[str, np.ndarray], cfg: LlamaConfig, dtype=jnp.float32
) -> dict:
    """Map an HF Llama-family checkpoint (flat name→array) onto the tree.

    HF ``nn.Linear`` weights are stored [out, in]; all are transposed into
    the x @ W layout. Tied-embedding checkpoints (no ``lm_head.weight``)
    map onto a tree without the ``lm_head`` leaf; ``forward`` then reuses
    ``wte``. Missing tensors raise with their names.
    """

    def take(name):
        arr = tensors.get(name)
        if arr is None:
            raise ValueError(f"checkpoint missing {name}")
        return np.asarray(arr)

    out = {
        "wte": jnp.asarray(take("model.embed_tokens.weight"), dtype),
        "ln_f": {"g": jnp.asarray(take("model.norm.weight"), dtype)},
    }
    # Tied checkpoints may still serialize lm_head.weight (state_dict
    # materializes the tie); the tree follows the config, not the file —
    # and an untied config missing the head is an error like any other
    # missing tensor, not a silent fallback to wte.
    if not cfg.tie_embeddings:
        out["lm_head"] = jnp.asarray(take("lm_head.weight").T, dtype)
    blocks: dict = {
        "ln_attn": {"g": []}, "ln_mlp": {"g": []},
        "attn": {leaf: [] for _, leaf in _HF_ATTN.values()},
        "mlp": {leaf: [] for _, leaf in _HF_MLP.values()},
    }
    if cfg.attn_bias:
        for leaf in ("q_b", "k_b", "v_b"):
            blocks["attn"][leaf] = []
    if cfg.o_bias:
        blocks["attn"]["o_b"] = []
    for layer in range(cfg.n_layer):
        pre = f"model.layers.{layer}."
        for hf, (grp, leaf) in _HF_NORM.items():
            blocks[grp][leaf].append(take(f"{pre}{hf}.weight"))
        for hf, (grp, leaf) in {**_HF_ATTN, **_HF_MLP}.items():
            blocks[grp][leaf].append(take(f"{pre}{hf}.weight").T)
        if cfg.attn_bias:
            for proj, leaf in (("q", "q_b"), ("k", "k_b"), ("v", "v_b")):
                blocks["attn"][leaf].append(
                    take(f"{pre}self_attn.{proj}_proj.bias")
                )
        if cfg.o_bias:
            blocks["attn"]["o_b"].append(
                take(f"{pre}self_attn.o_proj.bias")
            )
    out["blocks"] = jax.tree.map(
        lambda leaves: jnp.asarray(np.stack(leaves), dtype),
        blocks, is_leaf=lambda v: isinstance(v, list),
    )
    return out


def params_to_hf(params: dict, cfg: LlamaConfig) -> dict[str, np.ndarray]:
    """Inverse of :func:`params_from_hf`: the stacked tree back to HF
    tensor names/orientations ([out, in] Linears, per-layer unstacked).

    Enables the full lifecycle: pull → finetune → export →
    ``transformers.from_pretrained`` — write the result with
    ``zest_tpu.models.write_safetensors``.
    """
    out = {
        "model.embed_tokens.weight": np.asarray(params["wte"]),
        "model.norm.weight": np.asarray(params["ln_f"]["g"]),
    }
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["lm_head"]).T
    b = params["blocks"]
    for layer in range(cfg.n_layer):
        pre = f"model.layers.{layer}."
        for hf, (grp, leaf) in _HF_NORM.items():
            out[f"{pre}{hf}.weight"] = np.asarray(b[grp][leaf][layer])
        for hf, (grp, leaf) in {**_HF_ATTN, **_HF_MLP}.items():
            out[f"{pre}{hf}.weight"] = np.asarray(b[grp][leaf][layer]).T
        if cfg.attn_bias:
            for proj, leaf in (("q", "q_b"), ("k", "k_b"), ("v", "v_b")):
                out[f"{pre}self_attn.{proj}_proj.bias"] = \
                    np.asarray(b["attn"][leaf][layer])
        if cfg.o_bias:
            out[f"{pre}self_attn.o_proj.bias"] = \
                np.asarray(b["attn"]["o_b"][layer])
    return out


# ── Sharding rules (data + tensor parallel) ──


def param_specs(cfg: LlamaConfig) -> dict:
    """PartitionSpec tree matching ``init_params`` (Megatron-style TP)."""
    out = {
        # Replicated embedding (same rationale as gpt2.param_specs: spec
        # trees stay mesh-independent; raw-checkpoint landing still shards
        # via checkpoint_shard_rules when dims divide).
        "wte": P(),
        "ln_f": {"g": P()},
        "blocks": {
            "ln_attn": {"g": P()},
            "ln_mlp": {"g": P()},
            "attn": {
                "q_w": P(None, None, MODEL_AXIS),
                "k_w": P(None, None, MODEL_AXIS),
                "v_w": P(None, None, MODEL_AXIS),
                "o_w": P(None, MODEL_AXIS, None),
                **({"q_b": P(None, MODEL_AXIS),
                    "k_b": P(None, MODEL_AXIS),
                    "v_b": P(None, MODEL_AXIS)} if cfg.attn_bias else {}),
                # o_b adds after the row-parallel o_w reduce → replicated.
                **({"o_b": P()} if cfg.o_bias else {}),
            },
            "mlp": {
                "gate_w": P(None, None, MODEL_AXIS),
                "up_w": P(None, None, MODEL_AXIS),
                "down_w": P(None, MODEL_AXIS, None),
            },
        },
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = P(None, MODEL_AXIS)
    return out


def checkpoint_shard_rules() -> list[tuple[str, P]]:
    """Name-pattern rules for landing raw HF Llama safetensors via
    zest_tpu.models.loader (HF [out, in] orientation, so the TP dim is
    axis 0 for column-parallel tensors and axis 1 for row-parallel)."""
    return [
        (r"self_attn\.[qkv]_proj\.weight$", P(MODEL_AXIS, None)),
        (r"self_attn\.[qkv]_proj\.bias$", P(MODEL_AXIS)),
        (r"self_attn\.o_proj\.bias$", P(None)),
        (r"self_attn\.o_proj\.weight$", P(None, MODEL_AXIS)),
        (r"mlp\.(gate|up)_proj\.weight$", P(MODEL_AXIS, None)),
        (r"mlp\.down_proj\.weight$", P(None, MODEL_AXIS)),
        (r"^lm_head\.weight$", P(MODEL_AXIS, None)),
    ]


# ── Forward ──


def _rms_norm(x, g, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * g


@functools.lru_cache(maxsize=None)
def _inv_freq(cfg: LlamaConfig) -> np.ndarray:
    """Per-dimension rotary frequencies, with the Llama-3.1 "llama3"
    scaling rule applied when configured: long-wavelength dims slow by
    ``factor``, short wavelengths stay, the band between interpolates
    (HF ROPE_INIT_FUNCTIONS['llama3']). Config-static → numpy, cached."""
    half = cfg.head_dim // 2
    inv = cfg.rope_theta ** (-np.arange(half, dtype=np.float64) / half)
    if cfg.rope_scaling_factor:
        wavelen = 2.0 * math.pi / inv
        smooth = (
            (cfg.rope_original_ctx / wavelen - cfg.rope_low_freq_factor)
            / (cfg.rope_high_freq_factor - cfg.rope_low_freq_factor)
        )
        smooth = np.clip(smooth, 0.0, 1.0)
        # smooth=0 (wavelen > orig/low): fully scaled; smooth=1
        # (wavelen < orig/high): unscaled; between: linear blend.
        inv = (1.0 - smooth) * inv / cfg.rope_scaling_factor + smooth * inv
    return inv.astype(np.float32)


def _rope(x, cfg: LlamaConfig, pos0=0):
    """Rotary embedding over (B, T, H, D), HF rotate-half convention.

    ``pos0`` offsets the positions — the context-parallel path passes each
    shard's global start so phases match the unsharded computation.
    """
    B, T, H, D = x.shape
    freqs = jnp.asarray(_inv_freq(cfg))
    half = D // 2
    pos = pos0 + jnp.arange(T, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


def _qkv(x, p, cfg: LlamaConfig, pos0=0):
    """Projections + RoPE. Head counts come from the weights (-1), not
    the config, so tensor-parallel shards (H/tp local heads inside a
    shard_map) reuse the same code path."""
    B, T, _ = x.shape
    D = cfg.head_dim

    def proj(w, b):
        h = x @ p[w]
        if b in p:  # Qwen2-style q/k/v biases
            h = h + p[b]
        return h.reshape(B, T, -1, D)

    q, k, v = proj("q_w", "q_b"), proj("k_w", "k_b"), proj("v_w", "v_b")
    return (_rope(q, cfg, pos0), _rope(k, cfg, pos0), v)


def _attention(x, p, cfg: LlamaConfig):
    """Dense causal GQA for the single-shard (no seq axis) path."""
    B, T, E = x.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    q, k, v = _qkv(x, p, cfg)
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    scores = (q @ k.transpose(0, 1, 3, 2)) / math.sqrt(D)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
    out = (att @ v).transpose(0, 2, 1, 3).reshape(B, T, H * D)
    out = out @ p["o_w"]
    return out + p["o_b"] if "o_b" in p else out


def _ring_attention(x, p, cfg: LlamaConfig, seq_axis: str,
                    tp_axis: str | None = None):
    """Ring GQA for the context-parallel path (inside shard_map).

    With ``tp_axis`` the attention weights are Megatron-sharded over that
    mesh axis (local heads); manual mode means the output projection's
    partial sums need an explicit psum (GSPMD inserts it automatically
    only outside shard_map)."""
    B, T, _ = x.shape
    pos0 = jax.lax.axis_index(seq_axis) * T
    q, k, v = _qkv(x, p, cfg, pos0=pos0)
    out = ring_self_attention(q, k, v, seq_axis, causal=True)
    out = out.reshape(B, T, -1) @ p["o_w"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    # o_b is replicated: add after the reduce, not per partial sum.
    return out + p["o_b"] if "o_b" in p else out


def _mlp(x, p, tp_axis: str | None = None):
    h = (jax.nn.silu(x @ p["gate_w"]) * (x @ p["up_w"])) @ p["down_w"]
    return h if tp_axis is None else jax.lax.psum(h, tp_axis)


def _body(params, x, cfg: LlamaConfig, attn_fn, tp_axis: str | None = None,
          remat: bool = False):
    def body(x, lp):
        h = _rms_norm(x, lp["ln_attn"]["g"], cfg.rms_eps)
        x = x + attn_fn(h, lp["attn"])
        h = _rms_norm(x, lp["ln_mlp"]["g"], cfg.rms_eps)
        return x + _mlp(h, lp["mlp"], tp_axis), None

    if remat:
        # Per-layer rematerialization: activations inside a block are
        # recomputed in the backward pass instead of saved — O(1) layers
        # of residuals live at once, the standard HBM-for-FLOPs trade.
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["blocks"])
    x = _rms_norm(x, params["ln_f"]["g"], cfg.rms_eps)
    head = params.get("lm_head")
    return x @ (head if head is not None else params["wte"].T)


def forward(
    params: dict, input_ids: jax.Array, cfg: LlamaConfig,
    remat: bool = False,
) -> jax.Array:
    """(B, T) int32 ids → (B, T, vocab) logits. Jittable."""
    x = params["wte"][input_ids]
    return _body(params, x, cfg, lambda h, p: _attention(h, p, cfg),
                 remat=remat)


def loss_fn(params, batch, cfg: LlamaConfig, remat: bool = False):
    """Next-token cross entropy over ``batch`` (B, T+1) ids."""
    inputs, targets = batch[:, :-1], batch[:, 1:]
    logits = forward(params, inputs, cfg, remat=remat).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def train_step(params, batch, cfg: LlamaConfig, lr: float = 1e-3,
               remat: bool = False):
    """One SGD step; under a {data, model} mesh GSPMD inserts the TP
    reduces and DP gradient psum (same contract as gpt2.train_step).
    ``remat=True`` recomputes per-layer activations in the backward pass
    (jax.checkpoint) — memory O(1) layers instead of O(L)."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, remat)
    params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                          params, grads)
    return params, loss


# ── Context parallelism (sequence sharded, ring attention) ──


def cp_forward(
    params: dict,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    data_axis: str = DATA_AXIS,
    remat: bool = False,
) -> jax.Array:
    """Forward with the sequence dimension sharded over ``seq_axis``.

    The whole transformer body runs under ``shard_map``: token/RoPE work is
    local to each shard (phases offset by the shard's global start),
    attention is the ppermute ring, everything else is elementwise or
    feature-dim matmuls that need no cross-shard communication. The
    seq-axis size must divide T (shard_map needs even T/axis_size shards).

    **TP×CP composition is automatic**: if ``mesh`` also has a
    ``MODEL_AXIS`` axis, params shard per :func:`param_specs` (Megatron
    layout, local heads in the ring) with explicit psums after the o/down
    projections — one 3-axis mesh runs dp+sp+tp in a single jitted step.
    """
    spec = P(data_axis, seq_axis)
    tp = MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None
    pspecs = param_specs(cfg) if tp else jax.tree.map(lambda _: P(), params)
    head_sharded = tp and not cfg.tie_embeddings
    out_spec = P(data_axis, seq_axis, MODEL_AXIS if head_sharded else None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(pspecs, spec), out_specs=out_spec,
    )
    def fwd(params, ids):
        x = params["wte"][ids]
        return _body(
            params, x, cfg,
            lambda h, p: _ring_attention(h, p, cfg, seq_axis, tp),
            tp_axis=tp, remat=remat,
        )

    return fwd(params, input_ids)


def cp_loss_fn(params, inputs, targets, cfg: LlamaConfig, mesh: Mesh,
               seq_axis: str = SEQ_AXIS, data_axis: str = DATA_AXIS,
               remat: bool = False):
    """Cross entropy with ``inputs``/``targets`` (B, T) sharded on T.

    The next-token shift crosses shard boundaries, so callers shift
    *globally* (see :func:`cp_train_step`) and pass aligned arrays; the
    logits stay sharded and GSPMD reduces the mean.
    """
    logits = cp_forward(params, inputs, cfg, mesh, seq_axis, data_axis,
                        remat)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def cp_train_step(params, batch, cfg: LlamaConfig, mesh: Mesh,
                  lr: float = 1e-3, seq_axis: str = SEQ_AXIS,
                  data_axis: str = DATA_AXIS, remat: bool = False):
    """Context-parallel SGD step on ``batch`` (B, T+1) ids.

    The shift happens on the global array — GSPMD turns the one-token halo
    into a neighbor exchange — then forward+backward run through the
    shard_mapped ring (its transpose is the reverse-direction ring).
    ``remat=True`` recomputes per-layer activations in the backward —
    with CP this compounds with the O(T/P) sequence sharding. Remat
    inside shard_map requires the step be jitted (eager ``closed_call``
    under shard_map is unimplemented in JAX).
    """
    inputs, targets = batch[:, :-1], batch[:, 1:]
    sharding = NamedSharding(mesh, P(data_axis, seq_axis))
    inputs = jax.lax.with_sharding_constraint(inputs, sharding)
    targets = jax.lax.with_sharding_constraint(targets, sharding)
    loss, grads = jax.value_and_grad(cp_loss_fn)(
        params, inputs, targets, cfg, mesh, seq_axis, data_axis, remat
    )
    params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                          params, grads)
    return params, loss


# ── Decoding ──


def _attention_cached(x, p, cfg: LlamaConfig, cache_k, cache_v, pos):
    """Window attention against a (B, n_ctx, KV, D) cache.

    ``x``: (B, S, E) activations for tokens occupying positions
    ``pos``..``pos+S-1`` (S=1 is the incremental-decode case; S=n0 is
    the batched prefill). Returns (out, new_k, new_v). The cache has
    static shape — row s attends to entries ``<= pos+s``.
    """
    B, S, _ = x.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    q, k, v = _qkv(x, p, cfg, pos0=pos)
    cache_k = jax.lax.dynamic_update_slice_in_dim(cache_k, k, pos, axis=1)
    cache_v = jax.lax.dynamic_update_slice_in_dim(cache_v, v, pos, axis=1)
    kk, vv = cache_k, cache_v
    if KV != H:
        kk = jnp.repeat(kk, H // KV, axis=2)
        vv = jnp.repeat(vv, H // KV, axis=2)
    # (B, H, S, T) scores over the whole static cache, future masked
    # causally within the window.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / math.sqrt(D)
    valid = (jnp.arange(cache_k.shape[1])[None, :]
             <= pos + jnp.arange(S)[:, None])
    scores = jnp.where(valid[None, None, :, :], scores,
                       jnp.finfo(scores.dtype).min)
    att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", att.astype(x.dtype), vv)
    out = out.reshape(B, S, H * D) @ p["o_w"]
    if "o_b" in p:
        out = out + p["o_b"]
    return out, cache_k, cache_v


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int,
                  dtype=jnp.float32) -> dict:
    """Static-shape per-layer K/V cache: (L, B, max_len, KV, D)."""
    shape = (cfg.n_layer, batch, max_len, cfg.n_kv_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def decode_window(params, cache: dict, tokens: jax.Array, pos,
                  cfg: LlamaConfig, last_only: bool = False):
    """Cached step over a token window: (B, S) ids occupying positions
    ``pos``..``pos+S-1`` → ((B, S, vocab) logits, updated cache).

    S=1 is one incremental decode step; S=len(prompt) is the batched
    prefill — the whole prompt becomes one MXU-shaped dispatch instead
    of S sequential single-token steps (sampling.cached_decode_loop
    uses both). Jittable; ``pos`` is a traced scalar, shapes static.
    """
    x = params["wte"][tokens]                              # (B, S, E)

    def body(carry, inp):
        x, pos = carry
        lp, ck, cv = inp
        h = _rms_norm(x, lp["ln_attn"]["g"], cfg.rms_eps)
        out, ck, cv = _attention_cached(h, lp["attn"], cfg, ck, cv, pos)
        x = x + out
        h = _rms_norm(x, lp["ln_mlp"]["g"], cfg.rms_eps)
        return (x + _mlp(h, lp["mlp"]), pos), (ck, cv)

    (x, _), (new_k, new_v) = jax.lax.scan(
        body, (x, pos), (params["blocks"], cache["k"], cache["v"])
    )
    x = _rms_norm(x, params["ln_f"]["g"], cfg.rms_eps)
    if last_only:
        # Prefill wants one next-token distribution: project only the
        # final hidden state through the (huge) unembedding instead of
        # materializing (B, S, vocab).
        x = x[:, -1:, :]
    head = params.get("lm_head")
    logits = x @ (head if head is not None else params["wte"].T)
    return logits, {"k": new_k, "v": new_v}


def decode_step(params, cache: dict, token: jax.Array, pos, cfg: LlamaConfig):
    """One incremental decode step: (B,) token ids at position ``pos`` →
    ((B, vocab) logits, updated cache). O(T) per token via the KV cache
    instead of generate_greedy's O(T²) full recompute — the serving path.
    The S=1 specialization of :func:`decode_window`.
    """
    logits, cache = decode_window(params, cache, token[:, None], pos, cfg)
    return logits[:, 0, :], cache


def generate_cached(params, cfg: LlamaConfig, prompt_ids, steps: int,
                    temperature: float = 0.0, top_k: int | None = None,
                    top_p: float | None = None,
                    rng: jax.Array | None = None,
                    eos_id: int | tuple[int, ...] | None = None,
                    on_token=None):
    """KV-cached decode (O(T) per token; sampling.cached_decode_loop).
    Default greedy, token-identical to ``generate_greedy``."""
    return cached_decode_loop(
        init_kv_cache, decode_step, params, cfg, prompt_ids, steps,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
        eos_id=eos_id, on_token=on_token,
        prefill_step=decode_window,
    )


def generate_greedy(params, cfg: LlamaConfig, prompt_ids, steps: int):
    """Greedy decode via ``lax.scan`` over a fixed buffer (static shapes)."""
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    n0 = prompt_ids.shape[0]
    total = n0 + steps
    if total > cfg.n_ctx:
        raise ValueError(
            f"prompt ({n0}) + steps ({steps}) = {total} exceeds "
            f"n_ctx {cfg.n_ctx}"
        )
    buf = jnp.zeros((total,), jnp.int32).at[:n0].set(prompt_ids)

    def step(carry, _):
        buf, pos = carry
        logits = forward(params, buf[None, :], cfg)[0]
        nxt = jnp.argmax(logits[pos - 1]).astype(jnp.int32)
        buf = buf.at[pos].set(nxt)
        return (buf, pos + 1), nxt

    (buf, _), _ = jax.lax.scan(step, (buf, jnp.int32(n0)), None, length=steps)
    return buf
