"""Direct-to-HBM landing: cached xorb units → device arrays, no file.

The reference always reassembles files on disk and lets torch read them
back (SURVEY.md §3.1) — a full extra write+read of every checkpoint byte.
The north-star path skips it (SURVEY.md §7 hard part #2): the safetensors
header maps tensor names to file byte ranges, reconstruction terms map
file ranges to cached chunk ranges, so a tensor's bytes can be decoded
straight out of the (gathered, verified) xorb cache into a host buffer
and committed to its pjit layout — the only disk artifacts are the
content-addressed cache entries the host was seeding anyway.

This is also what makes expert-sharded landing (BASELINE config #4) pay
off: a host lands *only* the tensors its shards consume; nothing forces
it to materialize other experts' bytes just to write a complete file.
"""

from __future__ import annotations

from zest_tpu.cas import reconstruction as recon
from zest_tpu.cas.xorb import XorbReader
from zest_tpu.models.safetensors_io import SafetensorsHeader


class DirectLandingError(RuntimeError):
    pass


class CachedFileReader:
    """Random-access byte reads over a file that exists only as cached
    xorb units + a reconstruction.

    Decoded terms are memoized (most tensors span few terms, and adjacent
    tensors share boundary terms — without memoization every boundary
    chunk would be decompressed twice).
    """

    def __init__(self, cache, rec: recon.Reconstruction, bridge=None):
        self.cache = cache
        self.rec = rec
        self.bridge = bridge
        self._spans: list[tuple[int, int, recon.Term]] = []
        off = 0
        for t in rec.terms:
            self._spans.append((off, off + t.unpacked_length, t))
            off += t.unpacked_length
        self.size = off
        self._term_bytes: dict[int, bytes] = {}

    def _locate(self, term):
        """(fi, reader, local_start, local_end) for a cached term, or
        (fi, None, 0, 0) on a cache miss — the one place the fetch-info
        lookup, cache read, and chunk-index rebase live, shared by the
        memoizing and in-place decode paths so their semantics cannot
        drift. Raises DirectLandingError when no fetch_info covers the
        term; decode errors propagate (ValueError family) for the
        callers' self-heal."""
        fi = self.rec.find_fetch_info(term)
        if fi is None:
            raise DirectLandingError(
                f"no fetch_info covers term {term.hash_hex}"
            )
        entry = self.cache.get_with_range(term.hash_hex, fi.range.start)
        if entry is None:
            return fi, None, 0, 0
        return (fi, XorbReader(entry.data),
                term.range.start - entry.chunk_offset,
                term.range.end - entry.chunk_offset)

    def _decode_term(self, i: int) -> bytes:
        data = self._term_bytes.get(i)
        if data is not None:
            return data
        _lo, _hi, term = self._spans[i]
        data = None
        decode_err: ValueError | None = None
        fi, reader, local_start, local_end = self._locate(term)
        if reader is not None:
            try:
                data = reader.extract_chunk_range(local_start, local_end)
            except ValueError as exc:  # XorbFormatError / CompressionError
                # Corrupt/short cached entry: with a bridge it costs one
                # term refetch (which overwrites the bad cache key — the
                # same self-heal as fetch_xorb_for_term), never the whole
                # landing. Without one, fail below.
                data = None
                decode_err = exc
        if data is None:
            if self.bridge is None:
                if decode_err is not None:
                    raise DirectLandingError(
                        f"cached unit {term.hash_hex}"
                        f"[{fi.range.start},{fi.range.end}) failed to "
                        f"decode: {decode_err}"
                    ) from decode_err
                raise DirectLandingError(
                    f"unit {term.hash_hex}[{fi.range.start},{fi.range.end})"
                    " not in cache — run the distribution round first"
                )
            # Unit not cached (no distribution round ran, or it missed
            # this unit): pull the term through the full waterfall —
            # peers, then CDN — which also caches the blob for seeding.
            # Direct landing then works even single-host with a cold
            # cache: bytes stream origin → cache → device, no file.
            data = self.bridge.fetch_term(term, self.rec)
        if len(data) != term.unpacked_length:
            raise DirectLandingError(
                f"term decoded to {len(data)} bytes, expected "
                f"{term.unpacked_length}"
            )
        self._term_bytes[i] = data
        return data

    def _decode_term_into(self, i: int, dest) -> int:
        """Decode term ``i`` straight into ``dest`` (exactly the term's
        unpacked length) — the no-memo fast lane for terms wholly inside
        one tensor's read: frame payloads land in the tensor's own
        buffer (XorbReader.extract_range_into), no per-term bytes object
        or join. Any miss or decode failure falls back to
        :meth:`_decode_term` (waterfall + self-heal) and copies."""
        _lo, _hi, term = self._spans[i]
        try:
            _fi, reader, local_start, local_end = self._locate(term)
            if reader is not None:
                return reader.extract_range_into(local_start, local_end,
                                                 dest)
        except ValueError:
            pass  # corrupt entry: the slow path self-heals
        data = self._decode_term(i)
        dest[:] = data
        return len(data)

    def _check_range(self, lo: int, hi: int) -> None:
        if not 0 <= lo <= hi <= self.size:
            raise DirectLandingError(
                f"read [{lo},{hi}) outside file of {self.size} bytes"
            )

    def read(self, lo: int, hi: int) -> bytes:
        """Bytes [lo, hi) of the reconstructed file."""
        self._check_range(lo, hi)  # before allocating hi-lo bytes
        out = bytearray(hi - lo)
        self.read_into(lo, hi, memoryview(out))
        return bytes(out)

    def read_into(self, lo: int, hi: int, out) -> int:
        """Copy bytes [lo, hi) straight into ``out`` (any writable
        buffer of exactly ``hi - lo`` bytes); returns the count.

        One copy per byte — memoryview slices of the decoded terms land
        in ``out`` directly, where ``read()``'s old slice-then-join
        paid two. land_tensors decodes multi-GB checkpoints through
        here, so the extra traversal of every byte was measurable."""
        self._check_range(lo, hi)
        view = memoryview(out).cast("B")
        if view.nbytes != hi - lo:
            raise DirectLandingError(
                f"out buffer is {view.nbytes} bytes for a "
                f"[{lo},{hi}) read"
            )
        written = 0
        for i, (t_lo, t_hi, _term) in enumerate(self._spans):
            if t_hi <= lo:
                continue
            if t_lo >= hi:
                break
            if lo <= t_lo and t_hi <= hi and i not in self._term_bytes:
                # Term wholly inside the read and not already decoded:
                # land it in place (no memo — a term can be wholly
                # inside at most one tensor, so nothing re-reads it;
                # boundary terms shared by adjacent tensors take the
                # memoized branch below both times).
                written += self._decode_term_into(
                    i, view[written : written + t_hi - t_lo]
                )
                continue
            src = memoryview(self._decode_term(i))  # zero-copy slice
            piece = src[max(lo, t_lo) - t_lo : min(hi, t_hi) - t_lo]
            view[written : written + len(piece)] = piece
            written += len(piece)
        return written

    def drop_memo(self) -> None:
        self._term_bytes.clear()


def land_tensors(
    cache,
    rec: recon.Reconstruction,
    header: SafetensorsHeader,
    predicate=None,
    bridge=None,
):
    """Decode selected tensors of one safetensors file from the cache.

    Returns name → np.ndarray (host buffers, zero file I/O beyond the
    cache). ``predicate(name)`` filters — the expert-sharded landing
    passes "is this tensor shared or one of my experts?". With a
    ``bridge``, units missing from the cache are pulled through the
    waterfall instead of failing. Callers commit the arrays with
    models.loader.land_tensor / jax.device_put.
    """
    import numpy as np

    reader = CachedFileReader(cache, rec, bridge=bridge)
    out: dict[str, np.ndarray] = {}
    for name, info in header.tensors.items():
        if predicate is not None and not predicate(name):
            continue
        lo, hi = info.file_range(header.data_start)
        # Decode straight into the tensor's own buffer (read_into: one
        # copy per byte), then view it at the right dtype/shape.
        buf = np.empty(hi - lo, dtype=np.uint8)
        reader.read_into(lo, hi, memoryview(buf))
        out[name] = buf.view(info.np_dtype).reshape(info.shape)
    reader.drop_memo()
    return out


def land_moe_expert_sharded(
    cache,
    recs_with_headers: list[tuple[recon.Reconstruction, SafetensorsHeader]],
    moe_cfg,
    mesh,
    placement,
    dtype=None,
):
    """Land a Mixtral-family checkpoint expert-sharded into HBM.

    Single-controller form (one process drives the mesh): all tensors are
    decoded from the cache, stacked into the models.moe param tree, and
    committed under ``param_specs`` — GSPMD slices the stacked expert
    leaves over the ``expert`` axis in exactly the blocks
    ``ExpertPlacement`` routed bytes for, so every expert's weights land
    on the host that fetched them. No reassembled file touches disk.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zest_tpu.models import moe as moe_mod

    if placement.n_experts != moe_cfg.n_experts:
        raise DirectLandingError(
            f"placement has {placement.n_experts} experts, "
            f"config has {moe_cfg.n_experts}"
        )
    tensors: dict = {}
    for rec, header in recs_with_headers:
        tensors.update(land_tensors(cache, rec, header))
    params = moe_mod.params_from_hf(
        tensors, moe_cfg, dtype=dtype or jnp.float32
    )
    specs = moe_mod.param_specs(moe_cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda v: isinstance(v, P),
    )
    # One batched device_put for the whole tree (per-leaf puts pay a
    # transfer-setup round trip per unique shape; loader.commit_tensors
    # has the measurement).
    return jax.device_put(params, shardings)
