"""Direct-to-HBM landing: cached xorb units → device arrays, no file.

The reference always reassembles files on disk and lets torch read them
back (SURVEY.md §3.1) — a full extra write+read of every checkpoint byte.
The north-star path skips it (SURVEY.md §7 hard part #2): the safetensors
header maps tensor names to file byte ranges, reconstruction terms map
file ranges to cached chunk ranges, so a tensor's bytes can be decoded
straight out of the (gathered, verified) xorb cache into a host buffer
and committed to its pjit layout — the only disk artifacts are the
content-addressed cache entries the host was seeding anyway.

This is also what makes expert-sharded landing (BASELINE config #4) pay
off: a host lands *only* the tensors its shards consume; nothing forces
it to materialize other experts' bytes just to write a complete file.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

from zest_tpu import telemetry
from zest_tpu.cas import compression, reconstruction as recon
from zest_tpu.cas.xorb import XorbReader, _exclusive_cumsum
from zest_tpu.config import DEFAULT_DECODE_CACHE_BYTES
from zest_tpu.models.safetensors_io import SafetensorsHeader

_M_READER_EVENTS = telemetry.counter(
    "zest_decode_reader_cache_events_total",
    "Parsed-reader LRU events on the landing decode path", ("event",))


class DirectLandingError(RuntimeError):
    pass


_pool_lock = threading.Lock()
_decode_pool: ThreadPoolExecutor | None = None
_decode_pool_width = 0


def resolve_decode_workers(workers: int | None = None) -> int:
    """Term-decode parallelism: explicit value, else ``ZEST_DECODE_WORKERS``,
    else auto. 0 means auto (min(4, cpus)); 1 means serial. The LZ4/BLAKE3
    hot loops run in the native lib with the GIL released, so a small pool
    gets real speedup without oversubscribing the landing's own threads."""
    if workers is None:
        try:
            workers = int(os.environ.get("ZEST_DECODE_WORKERS", "0"))
        except ValueError:
            workers = 0
    if workers <= 0:
        workers = min(4, os.cpu_count() or 1)
    return max(1, workers)


def _shared_decode_pool(workers: int) -> ThreadPoolExecutor | None:
    """One process-wide decode pool shared by every reader — concurrent
    file-pipeline workers must not each spawn their own (width x workers
    threads thrashing two cores). Grows to the widest request seen: a
    later reader asking for more workers than the first caller must not
    silently run at the smaller width. The replaced pool drains its
    in-flight tasks and its idle threads exit on collection."""
    if workers <= 1:
        return None
    global _decode_pool, _decode_pool_width
    with _pool_lock:
        if _decode_pool is None or _decode_pool_width < workers:
            _decode_pool = ThreadPoolExecutor(
                workers, thread_name_prefix="zest-term-decode")
            _decode_pool_width = workers
        return _decode_pool


# preadv batching limits: Linux UIO_MAXIOV is 1024 (POSIX guarantees
# only 16, but every platform with os.preadv ships far more); holes
# between adjacent chunk payloads are the 8-byte frame headers, so a
# page-sized gap cap keeps runs long without reading skipped chunks.
_IOV_MAX = 1024
_PREADV_GAP_MAX = 4096


def _preadv_into(fd, iovecs, offset: int) -> tuple[int, int]:
    """Fill ``iovecs`` (writable memoryviews) from ``fd`` starting at
    file ``offset`` — os.preadv with IOV_MAX splitting and short-read
    resume. Returns ``(bytes_read, syscalls)``; short only when the
    file itself is short (the caller then falls back to the decode
    path, which attributes and heals)."""
    want = sum(v.nbytes for v in iovecs)
    total = 0
    calls = 0
    idx = 0
    sub = 0  # bytes already filled of iovecs[idx]
    while total < want:
        batch = [iovecs[idx][sub:] if sub else iovecs[idx]]
        batch.extend(iovecs[idx + 1:idx + _IOV_MAX])
        got = os.preadv(fd, batch, offset + total)
        calls += 1
        if got <= 0:
            break
        total += got
        while got:
            rem = iovecs[idx].nbytes - sub
            if got >= rem:
                got -= rem
                idx += 1
                sub = 0
            else:
                sub += got
                got = 0
    return total, calls


class CachedFileReader:
    """Random-access byte reads over a file that exists only as cached
    xorb units + a reconstruction.

    Decoded terms are memoized (most tensors span few terms, and adjacent
    tensors share boundary terms — without memoization every boundary
    chunk would be decompressed twice).

    Term decode is parallel across a small shared pool (``workers`` > 1;
    see :func:`resolve_decode_workers`): terms of one read land in
    disjoint slices of the destination, so they decode independently.
    The memo stays the dedup point — two threads racing the same
    boundary term at worst both decode it (identical bytes; last write
    wins), never corrupt it.
    """

    def __init__(self, cache, rec: recon.Reconstruction, bridge=None,
                 workers: int | None = None, allow_lossy: bool = False,
                 use_preadv: bool = True):
        self.cache = cache
        self.rec = rec
        self.bridge = bridge
        # Lossy staging overlay (ISSUE 20): readers on the HBM landing
        # path may consume lossy-admitted exchange containers staged
        # beside the cache. Default OFF — file materialization and
        # serving must stay byte-exact, so only the loader opts in.
        self.allow_lossy = allow_lossy
        self.use_preadv = bool(use_preadv) and hasattr(os, "preadv")
        self.preadv_stats = {"terms": 0, "bytes": 0, "syscalls": 0}
        self._spans: list[tuple[int, int, recon.Term]] = []
        off = 0
        for t in rec.terms:
            self._spans.append((off, off + t.unpacked_length, t))
            off += t.unpacked_length
        self.size = off
        self._term_bytes: dict[int, bytes] = {}
        self._memo_lock = threading.Lock()
        self.workers = resolve_decode_workers(workers)
        # Parsed-reader LRU over cache entries: a ~32 MB unit serves
        # many ~MB terms, and reading + frame-parsing the whole entry
        # file once PER TERM was the landing's hidden O(terms × unit)
        # byte traffic — the single largest cost the GB bench charged
        # to hbm_commit. Bounded by bytes (ZEST_DECODE_CACHE); term
        # locality means even two entries hold most of the win.
        cap = getattr(getattr(cache, "cfg", None), "decode_cache_bytes",
                      None)
        self._reader_cache_cap = (DEFAULT_DECODE_CACHE_BYTES
                                  if cap is None else int(cap))
        self._readers: OrderedDict[tuple[str, int],
                                   tuple[XorbReader, int, int]] = \
            OrderedDict()
        self._readers_bytes = 0
        self._readers_lock = threading.Lock()

    def _entry_reader(self, hash_hex: str, range_start: int):
        """(XorbReader, chunk_offset) for a cache entry, LRU-memoized;
        None on a cache miss."""
        key = (hash_hex, range_start)
        with self._readers_lock:
            hit = self._readers.get(key)
            if hit is not None:
                self._readers.move_to_end(key)
        if hit is not None:
            _M_READER_EVENTS.inc(event="hit")
            return hit[0], hit[1]
        _M_READER_EVENTS.inc(event="miss")
        # mmap-backed entry when the cache offers it: the decoder then
        # consumes page-cache bytes in place — no whole-file read()
        # copy — with readahead hinted ahead of the decode walk.
        entry = None
        mapped = getattr(self.cache, "get_with_range_mapped", None)
        if mapped is not None:
            entry = mapped(hash_hex, range_start)
        if entry is None:
            entry = self.cache.get_with_range(hash_hex, range_start)
        if entry is None:
            got = self._lossy_reader(hash_hex, range_start)
            if got is None:
                return None
            reader, chunk_offset, nbytes = got
        else:
            reader = XorbReader(entry.data)
            chunk_offset = entry.chunk_offset
            nbytes = len(entry.data)
        if self._reader_cache_cap > 0:
            with self._readers_lock:
                if key not in self._readers:
                    self._readers[key] = (reader, chunk_offset,
                                          nbytes)
                    self._readers_bytes += nbytes
                while (self._readers_bytes > self._reader_cache_cap
                       and len(self._readers) > 1):
                    _, (_r, _o, dropped) = self._readers.popitem(last=False)
                    self._readers_bytes -= dropped
        return reader, chunk_offset

    def _lossy_reader(self, hash_hex: str, range_start: int):
        """Reader over a lossy-staged exchange container, or None.

        The collective's lossy tier (transfer.lossy) stages quantized
        cross-slice payloads BESIDE the cache, never in it: their bytes
        cannot match the merkle tree. Only readers constructed with
        ``allow_lossy=True`` — the loader's device-landing path, never
        file materialization or serving — overlay the staging, and only
        after a genuine cache miss, so byte-exact data always wins."""
        if not self.allow_lossy:
            return None
        cache_dir = getattr(getattr(self.cache, "cfg", None),
                            "cache_dir", None)
        if cache_dir is None:
            return None
        from zest_tpu.transfer import lossy

        staged = lossy.staging_for(cache_dir).get_with_range(
            hash_hex, range_start)
        if staged is None:
            return None
        container, chunk_offset = staged
        try:
            data = lossy.dequantize_blob(container)
        except ValueError:
            return None  # malformed container: treat as a cache miss
        _M_READER_EVENTS.inc(event="lossy")
        return XorbReader(data), chunk_offset, len(data)

    def _drop_reader(self, hash_hex: str, range_start: int) -> None:
        """Invalidate a memoized reader whose blob failed to decode: the
        self-heal refetch overwrites the DISK cache key, and a stale
        in-memory reader would keep serving the poisoned bytes to every
        later term sharing the entry."""
        with self._readers_lock:
            hit = self._readers.pop((hash_hex, range_start), None)
            if hit is not None:
                self._readers_bytes -= hit[2]

    def _locate(self, term):
        """(fi, reader, local_start, local_end) for a cached term, or
        (fi, None, 0, 0) on a cache miss — the one place the fetch-info
        lookup, cache read, and chunk-index rebase live, shared by the
        memoizing and in-place decode paths so their semantics cannot
        drift. Raises DirectLandingError when no fetch_info covers the
        term; decode errors propagate (ValueError family) for the
        callers' self-heal."""
        fi = self.rec.find_fetch_info(term)
        if fi is None:
            raise DirectLandingError(
                f"no fetch_info covers term {term.hash_hex}"
            )
        got = self._entry_reader(term.hash_hex, fi.range.start)
        if got is None:
            return fi, None, 0, 0
        reader, chunk_offset = got
        return (fi, reader,
                term.range.start - chunk_offset,
                term.range.end - chunk_offset)

    def _decode_term(self, i: int) -> bytes:
        with self._memo_lock:
            data = self._term_bytes.get(i)
        if data is not None:
            return data
        _lo, _hi, term = self._spans[i]
        data = None
        decode_err: ValueError | None = None
        fi, reader, local_start, local_end = self._locate(term)
        if reader is not None:
            try:
                data = reader.extract_chunk_range(local_start, local_end)
            except ValueError as exc:  # XorbFormatError / CompressionError
                # Corrupt/short cached entry: with a bridge it costs one
                # term refetch (which overwrites the bad cache key — the
                # same self-heal as fetch_xorb_for_term), never the whole
                # landing. Without one, fail below. The memoized reader
                # is dropped either way — the refetch heals the DISK
                # key, and a stale in-memory reader would re-poison it.
                self._drop_reader(term.hash_hex, fi.range.start)
                data = None
                decode_err = exc
        if data is None:
            if self.bridge is None:
                if decode_err is not None:
                    raise DirectLandingError(
                        f"cached unit {term.hash_hex}"
                        f"[{fi.range.start},{fi.range.end}) failed to "
                        f"decode: {decode_err}"
                    ) from decode_err
                raise DirectLandingError(
                    f"unit {term.hash_hex}[{fi.range.start},{fi.range.end})"
                    " not in cache — run the distribution round first"
                )
            # Unit not cached (no distribution round ran, or it missed
            # this unit): pull the term through the full waterfall —
            # peers, then CDN — which also caches the blob for seeding.
            # Direct landing then works even single-host with a cold
            # cache: bytes stream origin → cache → device, no file.
            data = self.bridge.fetch_term(term, self.rec)
        if len(data) != term.unpacked_length:
            raise DirectLandingError(
                f"term decoded to {len(data)} bytes, expected "
                f"{term.unpacked_length}"
            )
        with self._memo_lock:
            self._term_bytes[i] = data
        return data

    def _decode_term_into(self, i: int, dest) -> int:
        """Decode term ``i`` straight into ``dest`` (exactly the term's
        unpacked length) — the no-memo fast lane for terms wholly inside
        one tensor's read: frame payloads land in the tensor's own
        buffer (XorbReader.extract_range_into), no per-term bytes object
        or join. Any miss or decode failure falls back to
        :meth:`_decode_term` (waterfall + self-heal) and copies."""
        _lo, _hi, term = self._spans[i]
        try:
            _fi, reader, local_start, local_end = self._locate(term)
            if reader is not None:
                return reader.extract_range_into(local_start, local_end,
                                                 dest)
        except ValueError:
            pass  # corrupt entry: the slow path self-heals
        data = self._decode_term(i)
        dest[:] = data
        return len(data)

    def _preadv_batch(self, jobs, lo: int, hi: int, view):
        """The stored-chunk syscall lane: a term whose cached entry is
        an on-disk file, carries no footer, and is all stored-scheme in
        range reads its payload bytes STRAIGHT from the entry file into
        the destination — one ``preadv`` per contiguous payload run,
        dest-view slices interleaved with throwaway gap buffers for the
        8-byte frame headers between chunks — instead of materializing
        (or page-faulting across) the whole entry just to memcpy slices
        back out. That was the landing's last full-buffer host pass for
        incompressible tensors (ISSUE 20). Eligibility mirrors
        ``copy_plan``'s trust rule exactly — the lane never skips a
        check the decode lane makes. Returns ``(bytes_written,
        leftover_jobs)``; any failure (short entry, EIO, raced eviction)
        hands the affected jobs back to the decode path, which
        attributes corruption and self-heals as before."""
        import numpy as np

        locate = getattr(self.cache, "locate_with_range", None)
        if locate is None:
            return 0, jobs
        with self._memo_lock:
            memoized = set(self._term_bytes)
        per_path: dict[str, tuple[list, list]] = {}
        leftover = []
        for job in jobs:
            i, d_lo, _d_hi = job
            t_lo, t_hi, term = self._spans[i]
            if not (lo <= t_lo and t_hi <= hi) or i in memoized:
                leftover.append(job)
                continue
            fi = self.rec.find_fetch_info(term)
            if fi is None:
                raise DirectLandingError(
                    f"no fetch_info covers term {term.hash_hex}"
                )
            located = locate(term.hash_hex, fi.range.start)
            got = self._entry_reader(term.hash_hex, fi.range.start)
            if located is None or got is None:
                leftover.append(job)
                continue
            path, path_chunk_offset = located
            reader, chunk_offset = got
            if (path_chunk_offset != chunk_offset
                    or reader.xorb_hash_footer is not None):
                leftover.append(job)
                continue
            local = (term.range.start - chunk_offset,
                     term.range.end - chunk_offset)
            try:
                cols = reader.decode_columns(*local)
            except ValueError:
                self._drop_reader(term.hash_hex, fi.range.start)
                leftover.append(job)
                continue
            if cols is None:
                leftover.append(job)  # footer-hashed: verify per chunk
                continue
            src_offs, src_lens, schemes, dst_lens = cols
            if (schemes.any()  # any compressed chunk needs the decoder
                    or int(dst_lens.sum(dtype=np.uint64))
                    != term.unpacked_length):
                leftover.append(job)
                continue
            triples, pjobs = per_path.setdefault(str(path), ([], []))
            dst = d_lo + _exclusive_cumsum(dst_lens).astype(np.int64)
            triples.extend(zip(src_offs.tolist(), dst.tolist(),
                               dst_lens.tolist()))
            pjobs.append(job)

        written = 0
        gap_buf = bytearray(_PREADV_GAP_MAX)  # contents discarded
        for path, (triples, pjobs) in per_path.items():
            try:
                fd = os.open(path, os.O_RDONLY)
            except OSError:
                leftover.extend(pjobs)  # raced eviction: decode heals
                continue
            try:
                triples.sort()
                ok = True
                k = 0
                while k < len(triples):
                    run_start = pos = triples[k][0]
                    iovecs = []
                    while k < len(triples):
                        src, dst, ln = triples[k]
                        gap = src - pos
                        if gap < 0 or gap > _PREADV_GAP_MAX:
                            break
                        if gap:
                            iovecs.append(
                                memoryview(gap_buf)[:gap])
                        iovecs.append(view[dst:dst + ln])
                        pos = src + ln
                        k += 1
                    got, calls = _preadv_into(fd, iovecs, run_start)
                    self.preadv_stats["syscalls"] += calls
                    if got != sum(v.nbytes for v in iovecs):
                        ok = False  # short entry: decode path heals
                        break
            except OSError:
                ok = False
            finally:
                os.close(fd)
            if not ok:
                leftover.extend(pjobs)
                continue
            payload = sum(t[2] for t in triples)
            written += payload
            self.preadv_stats["terms"] += len(pjobs)
            self.preadv_stats["bytes"] += payload
        return written, leftover

    def _decode_batch(self, jobs, lo: int, hi: int, view):
        """The whole-read batch lane: collect chunk descriptors for every
        batchable job and decode them in one native call. Returns
        ``(bytes_written, leftover_jobs)``; on ANY batch failure every
        batched job is handed back to the per-term path, whose slow lane
        attributes corruption and self-heals the cache key exactly as
        before — the batch is an accelerator, never a new trust model."""
        import numpy as np

        with self._memo_lock:
            memoized = set(self._term_bytes)
        groups, batched, leftover = [], [], []
        for job in jobs:
            i, d_lo, _d_hi = job
            t_lo, t_hi, term = self._spans[i]
            if not (lo <= t_lo and t_hi <= hi) or i in memoized:
                leftover.append(job)
                continue
            fi = self.rec.find_fetch_info(term)
            if fi is None:
                raise DirectLandingError(
                    f"no fetch_info covers term {term.hash_hex}"
                )
            got = self._entry_reader(term.hash_hex, fi.range.start)
            if got is None:
                leftover.append(job)
                continue
            reader, chunk_offset = got
            local = (term.range.start - chunk_offset,
                     term.range.end - chunk_offset)
            try:
                cols = reader.decode_columns(*local)
            except ValueError:
                # Malformed entry: drop the poisoned reader; the slow
                # path refetches and overwrites the cache key.
                self._drop_reader(term.hash_hex, fi.range.start)
                leftover.append(job)
                continue
            if cols is None:
                leftover.append(job)  # footer-hashed: verify per chunk
                continue
            src_offs, src_lens, schemes, dst_lens = cols
            if int(dst_lens.sum(dtype=np.uint64)) != term.unpacked_length:
                leftover.append(job)  # short/mis-sized entry
                continue
            dst_offs = np.uint64(d_lo) + _exclusive_cumsum(dst_lens)
            groups.append((reader._data, src_offs, src_lens, schemes,
                           dst_offs, dst_lens))
            batched.append(job)
        if not groups:
            return 0, leftover
        try:
            written = compression.decode_columns_into(
                groups, view, workers=self.workers)
        except ValueError:
            # Corrupt payload somewhere in the batch: re-run those jobs
            # per term so the failure is attributed to ITS entry (and
            # healed) instead of poisoning the whole read.
            return 0, leftover + batched
        return written, leftover

    def _check_range(self, lo: int, hi: int) -> None:
        if not 0 <= lo <= hi <= self.size:
            raise DirectLandingError(
                f"read [{lo},{hi}) outside file of {self.size} bytes"
            )

    def copy_plan(self, lo: int, hi: int):
        """Zero-copy materialization plan for file bytes [lo, hi).

        Returns ``(copies, leftovers)``:

        - ``copies`` — one ``(entry_path, src_offs, dst_offs, lens)``
          group per copyable term: numpy u64 columns of per-chunk
          payload spans, source offsets into the on-disk cache entry,
          destination offsets relative to ``lo``. A term is copyable iff
          its cached entry is an on-disk file, carries no footer (so the
          decode path wouldn't hash-verify it either — the plan never
          weakens the trust model), and every chunk in range is
          stored-scheme: the payload bytes ARE the file bytes, so the
          kernel can move them without userspace ever touching them.
        - ``leftovers`` — merged ``(d_lo, d_hi)`` byte ranges (relative
          to ``lo``) the caller must materialize through the decode
          path: compressed or footer-hashed chunks, cache misses, and
          terms only partially inside the read.

        Planning never reads payload bytes — only the columnar chunk
        table (already LRU-memoized for the decode path)."""
        self._check_range(lo, hi)
        import numpy as np

        copies, leftovers = [], []

        def leftover(d_lo: int, d_hi: int) -> None:
            if leftovers and leftovers[-1][1] == d_lo:
                leftovers[-1] = (leftovers[-1][0], d_hi)
            else:
                leftovers.append((d_lo, d_hi))

        for t_lo, t_hi, term in self._spans:
            if t_hi <= lo:
                continue
            if t_lo >= hi:
                break
            d_lo, d_hi = max(lo, t_lo) - lo, min(hi, t_hi) - lo
            if not (lo <= t_lo and t_hi <= hi):
                leftover(d_lo, d_hi)  # boundary term: decode path
                continue
            fi = self.rec.find_fetch_info(term)
            if fi is None:
                raise DirectLandingError(
                    f"no fetch_info covers term {term.hash_hex}"
                )
            located = self.cache.locate_with_range(term.hash_hex,
                                                   fi.range.start)
            got = self._entry_reader(term.hash_hex, fi.range.start)
            if located is None or got is None:
                leftover(d_lo, d_hi)
                continue
            path, path_chunk_offset = located
            reader, chunk_offset = got
            if (path_chunk_offset != chunk_offset
                    or reader.xorb_hash_footer is not None):
                # Entry flavor changed under us, or it carries footer
                # hashes the decode path would verify per chunk — the
                # copy lane must not skip a check the decode lane makes.
                leftover(d_lo, d_hi)
                continue
            local = (term.range.start - chunk_offset,
                     term.range.end - chunk_offset)
            try:
                cols = reader.decode_columns(*local)
            except ValueError:
                leftover(d_lo, d_hi)  # malformed entry: slow path heals
                continue
            if cols is None:
                leftover(d_lo, d_hi)
                continue
            src_offs, src_lens, schemes, dst_lens = cols
            if (schemes.any()  # any non-NONE scheme needs real decode
                    or int(dst_lens.sum(dtype=np.uint64))
                    != term.unpacked_length):
                leftover(d_lo, d_hi)
                continue
            dst_offs = np.uint64(d_lo) + _exclusive_cumsum(dst_lens)
            copies.append((path, src_offs, dst_offs, dst_lens))
        return copies, leftovers

    def read(self, lo: int, hi: int) -> bytes:
        """Bytes [lo, hi) of the reconstructed file."""
        self._check_range(lo, hi)  # before allocating hi-lo bytes
        out = bytearray(hi - lo)
        self.read_into(lo, hi, memoryview(out))
        return bytes(out)

    def read_into(self, lo: int, hi: int, out) -> int:
        """Copy bytes [lo, hi) straight into ``out`` (any writable
        buffer of exactly ``hi - lo`` bytes); returns the count.

        One copy per byte — memoryview slices of the decoded terms land
        in ``out`` directly, where ``read()``'s old slice-then-join
        paid two. land_tensors decodes multi-GB checkpoints through
        here, so the extra traversal of every byte was measurable."""
        self._check_range(lo, hi)
        view = memoryview(out).cast("B")
        if view.nbytes != hi - lo:
            raise DirectLandingError(
                f"out buffer is {view.nbytes} bytes for a "
                f"[{lo},{hi}) read"
            )
        # Each overlapping term owns a disjoint slice of the output, so
        # decode order is free — serial on one worker, else fanned over
        # the shared pool (multi-GB tensors span hundreds of terms; the
        # native decompress releases the GIL, so the fan-out is real).
        jobs = []  # (term index, dest offset in view, dest end)
        for i, (t_lo, t_hi, _term) in enumerate(self._spans):
            if t_hi <= lo:
                continue
            if t_lo >= hi:
                break
            jobs.append((i, max(lo, t_lo) - lo, min(hi, t_hi) - lo))

        written = 0
        if jobs and self.use_preadv:
            # Stored-chunk terms with an on-disk entry skip the decode
            # engine entirely: their payload bytes preadv straight from
            # the entry file into ``view`` (no whole-entry buffer, no
            # per-page fault walk). Everything else falls through.
            w, jobs = self._preadv_batch(jobs, lo, hi, view)
            written += w
        if len(jobs) > 1 and compression.native_batch_available():
            # Whole-read descriptor batch: every wholly-contained cached
            # term's chunks submit as ONE native call (GIL released,
            # ``self.workers`` C++ threads) — no per-term futures, no
            # per-chunk Python. Terms the batch can't take (cache miss,
            # memoized, boundary-shared, footer-hashed) fall through to
            # the per-term lanes below.
            w, jobs = self._decode_batch(jobs, lo, hi, view)
            written += w
        if not jobs:
            return written

        def decode_into_view(i: int, d_lo: int, d_hi: int) -> int:
            t_lo, t_hi, _term = self._spans[i]
            if lo <= t_lo and t_hi <= hi and i not in self._term_bytes:
                # Term wholly inside the read and not already decoded:
                # land it in place (no memo — a term can be wholly
                # inside at most one tensor, so nothing re-reads it;
                # boundary terms shared by adjacent tensors take the
                # memoized branch below both times).
                return self._decode_term_into(i, view[d_lo:d_hi])
            src = memoryview(self._decode_term(i))  # zero-copy slice
            piece = src[max(lo, t_lo) - t_lo : min(hi, t_hi) - t_lo]
            view[d_lo:d_hi] = piece
            return len(piece)

        def decode_group(group: list[tuple[int, int, int]]) -> int:
            try:
                return sum(decode_into_view(*j) for j in group)
            except BaseException as exc:
                # Detach worker frames before the exception crosses the
                # future boundary: a pinned frame would hold its view
                # slice (and, via closure cells, the whole destination
                # buffer) until a gc pass.
                raise exc.with_traceback(None) from None

        pool = (_shared_decode_pool(self.workers)
                if len(jobs) > 1 else None)
        if pool is None:
            return written + sum(decode_into_view(*j) for j in jobs)
        # One future per CONTIGUOUS job group, not per term: a multi-GB
        # tensor spans hundreds of terms, and per-term submit/result
        # overhead would eat the fan-out's win. Contiguity keeps each
        # worker streaming through adjacent cache entries.
        n_groups = min(len(jobs), self.workers)
        per = (len(jobs) + n_groups - 1) // n_groups
        groups = [jobs[k : k + per] for k in range(0, len(jobs), per)]
        futures = [pool.submit(decode_group, g) for g in groups]
        first_error: BaseException | None = None
        for f in futures:
            # Wait out EVERY job even after a failure — a still-running
            # decode writing into ``view`` while the caller unwinds (and
            # possibly frees the destination) would be a straight
            # use-after-free.
            try:
                written += f.result()
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                if first_error is None:
                    first_error = exc
        # NOTE: buffer-lifetime discipline. Even with the precautions
        # above, a captured exception forms a tb→frame→exception cycle
        # only gc can break, so a failing parallel read can keep ``out``
        # alive briefly. That is fine for np-buffer callers (the landing
        # path); callers that must deterministically close their buffer
        # (the mmap fast lane in transfer.pull) construct the reader
        # with workers=1 and never enter this branch.
        futures.clear()
        if first_error is not None:
            raise first_error
        return written

    def drop_memo(self) -> None:
        with self._memo_lock:
            self._term_bytes.clear()


def land_tensors(
    cache,
    rec: recon.Reconstruction,
    header: SafetensorsHeader,
    predicate=None,
    bridge=None,
    workers: int | None = None,
    allow_lossy: bool = False,
):
    """Decode selected tensors of one safetensors file from the cache.

    Returns name → np.ndarray (host buffers, zero file I/O beyond the
    cache). ``predicate(name)`` filters — the expert-sharded landing
    passes "is this tensor shared or one of my experts?". With a
    ``bridge``, units missing from the cache are pulled through the
    waterfall instead of failing. ``workers`` sizes the term-decode pool
    (see :func:`resolve_decode_workers`). Callers commit the arrays with
    models.loader.land_tensor / jax.device_put.
    """
    import numpy as np

    with telemetry.span("land.decode", file=rec.file_hash.hex(),
                        tensors=len(header.tensors)) as _sp:
        out = _land_tensors_inner(cache, rec, header, predicate, bridge,
                                  workers, allow_lossy, np)
        _sp.add_bytes(sum(int(a.nbytes) for a in out.values()))
        return out


def _land_tensors_inner(cache, rec, header, predicate, bridge, workers,
                        allow_lossy, np):
    reader = CachedFileReader(cache, rec, bridge=bridge, workers=workers,
                              allow_lossy=allow_lossy)
    out: dict[str, np.ndarray] = {}
    if predicate is None and header.tensors:
        # Whole-shard lane: ONE read spanning every tensor, so the whole
        # data section decodes as whole-shard descriptor batches (one
        # native call per run of cached terms) instead of a read per
        # tensor — no per-tensor setup, and boundary terms shared by
        # adjacent tensors decode once instead of hitting the memo
        # twice. Tensors become zero-copy views into the shard buffer
        # (same host peak as the per-tensor buffers they replace).
        spans = {name: info.file_range(header.data_start)
                 for name, info in header.tensors.items()}
        lo = min(s[0] for s in spans.values())
        hi = max(s[1] for s in spans.values())
        buf = np.empty(hi - lo, dtype=np.uint8)
        reader.read_into(lo, hi, memoryview(buf))
        for name, info in header.tensors.items():
            t_lo, t_hi = spans[name]
            out[name] = (buf[t_lo - lo:t_hi - lo]
                         .view(info.np_dtype).reshape(info.shape))
    else:
        for name, info in header.tensors.items():
            if predicate is not None and not predicate(name):
                continue
            lo, hi = info.file_range(header.data_start)
            # Decode straight into the tensor's own buffer (read_into:
            # one copy per byte), then view it at the right dtype/shape.
            buf = np.empty(hi - lo, dtype=np.uint8)
            reader.read_into(lo, hi, memoryview(buf))
            out[name] = buf.view(info.np_dtype).reshape(info.shape)
    reader.drop_memo()
    return out


class StreamingShardReader:
    """Tensor-at-a-time decode over one shard — the streaming landing's
    front end (ISSUE 8).

    Where :func:`land_tensors` decodes the whole shard into ONE host
    buffer and views tensors out of it, this decodes each tensor
    straight into a caller-owned destination (a ring slot): the decode
    engine's output buffer IS the buffer the device transfer reads, so
    the warm landing loses its per-shard intermediate tensor — one full
    host memory pass.

    Boundary terms shared by adjacent tensors ride the underlying
    reader's memo exactly as before (decoded once, not twice), and the
    corruption attribution + cache self-heal path is the same
    :class:`CachedFileReader` machinery — streaming changes the unit of
    buffering, never the trust model. ``close()`` drops the memo."""

    def __init__(self, cache, rec: recon.Reconstruction,
                 header: SafetensorsHeader, bridge=None,
                 workers: int | None = None, allow_lossy: bool = False):
        self.header = header
        self.reader = CachedFileReader(cache, rec, bridge=bridge,
                                       workers=workers,
                                       allow_lossy=allow_lossy)

    def decode_range_into(self, lo: int, hi: int, dest,
                          label: str = "") -> None:
        """Decode file bytes ``[lo, hi)`` into ``dest`` — the run lane:
        a CONTIGUOUS run of tensors decodes as one read, so terms on
        the boundaries *between* run members stay wholly inside the
        read and ride the native descriptor batch (decoded once, in
        place) instead of the per-term memo (decoded to a bytes object
        and copied twice). Measured at ~25% of the warm landing's
        decode wall when every tensor was its own read."""
        with telemetry.span("land.slice", tensors=label) as _sp:
            self.reader.read_into(lo, hi, dest)
            _sp.add_bytes(hi - lo)

    def close(self) -> None:
        self.reader.drop_memo()


def tensor_unit_keys(rec: recon.Reconstruction,
                     header: SafetensorsHeader) -> dict[str, frozenset]:
    """Per-tensor fetch-unit cover: tensor name → the set of fetch-unit
    keys ``(hash_hex, range_start)`` whose bytes the tensor's file range
    touches — the streaming landing's gate condition ("decode tensor X"
    is admissible once exactly these units are cached). Terms with no
    covering fetch_info are skipped (the per-term waterfall self-serves
    them), so a gap costs overlap, never correctness."""
    import bisect

    starts: list[int] = []
    ends: list[int] = []
    keys: list[tuple[str, int] | None] = []
    off = 0
    for t in rec.terms:
        fi = rec.find_fetch_info(t)
        starts.append(off)
        ends.append(off + t.unpacked_length)
        keys.append((t.hash_hex, fi.range.start) if fi is not None
                    else None)
        off += t.unpacked_length
    out: dict[str, frozenset] = {}
    for name, info in header.tensors.items():
        lo, hi = info.file_range(header.data_start)
        cover = set()
        j = max(0, bisect.bisect_right(starts, lo) - 1)
        while j < len(starts) and starts[j] < hi:
            if ends[j] > lo and keys[j] is not None:
                cover.add(keys[j])
            j += 1
        out[name] = frozenset(cover)
    return out


def unit_layer_priorities(
    recs_with_headers,
) -> dict[tuple[str, int], tuple[int, int]]:
    """Landing priority per fetch unit — the MIN
    :func:`zest_tpu.models.registry.layer_priority` over every tensor
    whose bytes the unit serves, taken across all given ``(rec,
    header)`` pairs (a unit deduped across shards keeps its earliest
    use). Terms inside a file's header prefix rank with the embeddings
    (``(0, 0)``): no tensor decodes before its header parses.

    Pure function of content-addressed metadata, so every host of a
    cooperative pull computes the same order with no coordination —
    the property transfer.coop relies on to ship early layers first
    while keeping the ownership plan (and its fingerprint) untouched.
    Units not in the map (non-safetensors files) sort after everything
    via the caller's ``.get(key, tail)`` default."""
    from zest_tpu.models.registry import layer_priority

    out: dict[tuple[str, int], tuple[int, int]] = {}
    for rec, header in recs_with_headers:
        tspans = sorted(
            info.file_range(header.data_start) + (layer_priority(name),)
            for name, info in header.tensors.items()
        )
        off = 0
        ti = 0
        for t in rec.terms:
            lo, hi = off, off + t.unpacked_length
            off = hi
            fi = rec.find_fetch_info(t)
            if fi is None:
                continue
            key = (t.hash_hex, fi.range.start)
            while ti < len(tspans) and tspans[ti][1] <= lo:
                ti += 1
            prio = None
            j = ti
            while j < len(tspans) and tspans[j][0] < hi:
                if prio is None or tspans[j][2] < prio:
                    prio = tspans[j][2]
                j += 1
            if lo < header.data_start and (prio is None or (0, 0) < prio):
                prio = (0, 0)
            if prio is None:
                prio = (2, 0)
            cur = out.get(key)
            if cur is None or prio < cur:
                out[key] = prio
    return out


def unit_priority_sort_key(priorities):
    """Sort key over ``(hash_hex, FetchInfo)`` unit pairs for a
    :func:`unit_layer_priorities` map: layer priority first (unknown
    units sort last), then ``(hash_hex, range_start)`` for determinism.
    The single definition both the pipelined pull and the coop exchange
    sort with, so every host of a cooperative pull agrees on order."""
    def key(u):
        return (priorities.get((u[0], u[1].range.start), (9, 0)),
                u[0], u[1].range.start)
    return key


def land_moe_expert_sharded(
    cache,
    recs_with_headers: list[tuple[recon.Reconstruction, SafetensorsHeader]],
    moe_cfg,
    mesh,
    placement,
    dtype=None,
):
    """Land a Mixtral-family checkpoint expert-sharded into HBM.

    Single-controller form (one process drives the mesh): all tensors are
    decoded from the cache, stacked into the models.moe param tree, and
    committed under ``param_specs`` — GSPMD slices the stacked expert
    leaves over the ``expert`` axis in exactly the blocks
    ``ExpertPlacement`` routed bytes for, so every expert's weights land
    on the host that fetched them. No reassembled file touches disk.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zest_tpu.models import moe as moe_mod

    if placement.n_experts != moe_cfg.n_experts:
        raise DirectLandingError(
            f"placement has {placement.n_experts} experts, "
            f"config has {moe_cfg.n_experts}"
        )
    tensors: dict = {}
    for rec, header in recs_with_headers:
        tensors.update(land_tensors(cache, rec, header))
    params = moe_mod.params_from_hf(
        tensors, moe_cfg, dtype=dtype or jnp.float32
    )
    specs = moe_mod.param_specs(moe_cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda v: isinstance(v, P),
    )
    # One batched device_put for the whole tree (per-leaf puts pay a
    # transfer-setup round trip per unique shape; loader.commit_tensors
    # has the measurement).
    return jax.device_put(params, shardings)
