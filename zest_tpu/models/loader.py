"""Checkpoint landing: safetensors → (sharded) device arrays in HBM.

The reference stops at the filesystem — reassembled files sit in the HF
cache and torch loads them later (SURVEY.md §3.1). The TPU build's north
star continues one hop: pulled tensors land as ``jax.Array``s laid out for
a pjit mesh, so ``pull --device=tpu`` ends with weights already resident
where the model will run (BASELINE config #3).

Sharding is rule-driven: an ordered list of ``(name_regex, PartitionSpec)``
pairs, first match wins, falling back to sharding the largest evenly
divisible axis over the mesh's last axis (the ICI-contiguous one, see
zest_tpu.parallel.mesh.model_mesh). Tensors indivisible by every axis
replicate.
"""

from __future__ import annotations

import contextlib
import functools
import gc
import re
import threading
import time
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu import telemetry
from zest_tpu.config import (
    DEFAULT_LAND_DECODE_AHEAD,
    DEFAULT_LAND_RING_BYTES,
    DEFAULT_LAND_RING_SLOTS,
    DEFAULT_LAND_STREAM,
)
from zest_tpu.models.safetensors_io import SafetensorsFile

_M_COMMIT_BYTES = telemetry.counter(
    "zest_hbm_commit_bytes_total", "Bytes committed host→HBM")
_M_COMMIT_TENSORS = telemetry.counter(
    "zest_hbm_commit_tensors_total", "Tensors committed host→HBM")
_M_RING_STALLS = telemetry.counter(
    "zest_land_ring_stalls_total",
    "Streaming-landing ring acquisitions that had to wait for capacity")

ShardRules = list[tuple[str, P]]


def infer_spec(
    shape: tuple[int, ...], mesh: Mesh, axis: str
) -> P:
    """Default policy: shard the largest dim divisible by the axis size."""
    n = int(mesh.shape[axis])
    if n <= 1 or not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % n == 0 and shape[i] >= n:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def _spec_fits(shape: tuple[int, ...], mesh: Mesh, spec: P) -> bool:
    """A rule spec is usable iff every named axis exists in the mesh and
    divides its tensor dim. Family rules are written against a family's
    canonical mesh; on a different topology (e.g. Mixtral rules on a
    {data, model} mesh with no 'expert' axis) the landing must degrade
    to infer_spec, not fail the whole HBM commit."""
    if len(spec) > len(shape):
        return False
    for dim, axes in zip(shape, spec):
        if axes is None:
            continue
        for ax in axes if isinstance(axes, tuple) else (axes,):
            if ax not in mesh.shape:
                return False
            if dim % int(mesh.shape[ax]):
                return False
            dim //= int(mesh.shape[ax])
    return True


def spec_for(
    name: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    rules: ShardRules | None = None,
    default_axis: str | None = None,
) -> P:
    for pattern, spec in rules or []:
        if re.search(pattern, name):
            if _spec_fits(shape, mesh, spec):
                return spec
            break  # first match wins; unusable → generic fallback
    axis = default_axis or mesh.axis_names[-1]
    return infer_spec(shape, mesh, axis)


def land_tensor(
    arr: np.ndarray, mesh: Mesh, spec: P
) -> jax.Array:
    """One host-resident tensor → device array under ``spec``.

    ``device_put`` with a NamedSharding splits the host buffer across the
    addressable devices; under multi-process each process must hold the
    full tensor (the pull pipeline guarantees that — every host reassembles
    every file, bytes having arrived over ICI, not N× over DCN).
    """
    return jax.device_put(arr, NamedSharding(mesh, spec))


def snapshot_files(snapshot_dir: str | Path) -> list[Path]:
    return sorted(Path(snapshot_dir).glob("*.safetensors"))


def load_checkpoint(
    snapshot_dir: str | Path,
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    predicate=None,
) -> dict[str, jax.Array]:
    """All tensors of a snapshot as a flat name→array dict on device.

    With no mesh, arrays land on the default device unsharded (single-chip
    path). ``dtype`` optionally casts on the way in (checkpoints are often
    f32; TPU wants bf16). ``predicate(name)`` filters tensors.
    """
    out: dict[str, jax.Array] = {}
    for path in snapshot_files(snapshot_dir):
        host: dict[str, np.ndarray] = {}
        with SafetensorsFile(path) as sf:
            for name in sf.names():
                if predicate is not None and not predicate(name):
                    continue
                host[name] = sf.tensor(name)
            # Commit per file: one batched transfer per shard keeps host
            # peak at ~one safetensors file (the sharding contract) while
            # still amortizing the per-shape transfer setup; casting
            # lives in commit_tensors (one implementation, both paths).
            out.update(commit_tensors(host, mesh, rules, dtype=dtype,
                                      donate=True))
    return out


def resolve_dtype(name: str | None):
    """Landing-dtype names (config/CLI) → jnp dtype, None = keep."""
    if name is None:
        return None
    import jax.numpy as jnp

    table = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
             "f16": jnp.float16, "float16": jnp.float16,
             "f32": jnp.float32, "float32": jnp.float32}
    try:
        return table[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown landing dtype {name!r} "
            f"(supported: {', '.join(sorted(table))})"
        ) from None


# Tensors below this size coalesce into one transfer per dtype (the
# norm/bias vectors: a Llama shard carries dozens of KB-scale 1-D
# weights whose per-buffer transfer setup costs more than their bytes).
_COALESCE_MAX_BYTES = 256 * 1024
# Minimum group size worth the on-device split dispatch.
_COALESCE_MIN_TENSORS = 2


# Bit-pattern carrier for coalesced float groups: XLA is free to
# canonicalize NaN payloads when it touches FLOAT values (measured on
# the CPU backend: non-canonical bf16 NaNs came back as 0x7FC0/0xFFC0
# through the jitted split — a byte-integrity hole params_digest only
# exposed once streaming changed which tensors coalesce). Moving the
# group through a same-itemsize unsigned-integer dtype and bitcasting
# back on device keeps every byte inert. Dtypes without a mapping
# (integers — already inert — and exotic sub-byte types) pass through
# unchanged.
_BITSAFE_CARRIER: dict = {}


def _dtype_bits(dt: np.dtype) -> int:
    """True bit width — sub-byte ml_dtypes (int4, float4_e2m1fn)
    report itemsize 1 but are 4 bits wide; a same-"itemsize" uint8
    carrier can't bitcast back to them (ratio-1 bitcast needs equal
    widths, and jax rejects 8→4). ml_dtypes' finfo/iinfo understand
    both its own types and the standard numpy ones; np.finfo does
    not (bfloat16 raises 'not inexact')."""
    import ml_dtypes

    for info in (ml_dtypes.finfo, ml_dtypes.iinfo):
        try:
            return info(dt).bits
        except (ValueError, TypeError):
            continue
    return dt.itemsize * 8


def _bit_carrier(dt: np.dtype) -> np.dtype | None:
    if dt in _BITSAFE_CARRIER:
        return _BITSAFE_CARRIER[dt]
    carrier = None
    if not (np.issubdtype(dt, np.integer) or dt == np.bool_):
        carrier = {8: np.dtype(np.uint8), 16: np.dtype(np.uint16),
                   32: np.dtype(np.uint32)}.get(_dtype_bits(dt))
        if _dtype_bits(dt) == 64:
            # A uint64 carrier only survives device_put when x64 is
            # enabled; in default (x64-off) mode jax VALUE-casts it to
            # uint32 — the high words vanish and every 8-byte bit
            # pattern lands as zeros/garbage. Without x64 the group
            # must NOT coalesce: un-carried tensors take the plain
            # per-tensor device_put, whose float64→float32 downcast is
            # value-correct (the pre-carrier behavior).
            if jax.config.jax_enable_x64:
                carrier = np.dtype(np.uint64)
    # Cache keyed on (dtype, x64) would be overkill: flipping
    # jax_enable_x64 mid-process is unsupported across jax generally.
    _BITSAFE_CARRIER[dt] = carrier
    return carrier


@functools.lru_cache(maxsize=64)
def _coalesced_split(bounds: tuple[int, ...],
                     shapes: tuple[tuple[int, ...], ...],
                     dtype_str: str | None):
    """Jitted flat-buffer → per-tensor views splitter, cached per layout
    so a repeated commit geometry (every shard of one checkpoint) pays
    one compile and ONE dispatch per group — not a slice round-trip per
    tensor. ``dtype_str`` (a numpy dtype name) is the group's REAL
    dtype when the flat buffer rides a bit-pattern carrier; the split
    bitcasts each piece back (ratio-1 bitcast: same shape, zero value
    semantics — see ``_bit_carrier``)."""
    import ml_dtypes  # noqa: F401 - dtype names resolve through it

    target = None
    if dtype_str is not None:
        try:
            target = np.dtype(dtype_str)
        except TypeError:
            target = np.dtype(getattr(ml_dtypes, dtype_str))

    def split(flat):
        out = []
        for i in range(len(shapes)):
            piece = flat[bounds[i]:bounds[i + 1]]
            if target is not None:
                piece = jax.lax.bitcast_convert_type(piece, target)
            out.append(piece.reshape(shapes[i]))
        return tuple(out)

    return jax.jit(split)


def commit_tensors(
    host: dict[str, np.ndarray],
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    donate: bool = False,
    coalesce: bool = True,
) -> dict[str, jax.Array]:
    """One BATCHED ``device_put`` for a whole tensor dict.

    Committing per tensor costs a transfer-setup round trip per unique
    shape — seconds for a checkpoint of ~dozens of shapes on a remote
    chip (measured ~0.1s/shape vs ~30ms for the whole batched commit);
    a single call lets the runtime pipeline every buffer. ``dtype``
    optionally casts *non-integer* tensors on the host first (f32
    checkpoints land bf16 at half the HBM and half the transfer bytes);
    integer/bool tensors keep their dtype — casting a token-id or
    position buffer would silently corrupt it. The filter excludes
    int/bool rather than matching np.floating because ml_dtypes
    extension types (the bf16 most modern checkpoints ship) are NOT
    np.floating subtypes. ``copy=False`` keeps the matched-dtype case
    free (no doubled host peak).

    Two commit-side optimizations from ISSUE 3:

    - **Small-tensor coalescing**: sub-``_COALESCE_MAX_BYTES`` tensors
      that land replicated (the norm/bias vectors — sharded smalls keep
      their own buffer, a concat would misalign the shard boundaries)
      are concatenated per dtype into ONE transfer and split back on
      device by a single jitted dispatch, so a shard's dozens of tiny
      buffers stop paying per-buffer transfer setup.
    - **Donation** (``donate=True``): callers that promise not to reuse
      the staging buffers let the runtime alias/free inputs eagerly —
      a no-op for host numpy staging, but device-resident inputs
      (re-landing, resharding) release their source HBM immediately
      instead of at the next GC.

    ``coalesce=False`` skips the small-tensor grouping: the jitted
    split is cached *per group layout*, and a caller whose group
    composition varies call to call (the streaming landing — its
    commit groups cut the tensor stream wherever the byte threshold
    lands) would pay an XLA compile per flush for a dispatch meant to
    be amortized. Per-shard commits keep the default: one checkpoint
    repeats one layout.
    """
    # .nbytes, never np.asarray: inputs may be device-resident (the
    # resharding path) and asarray would round-trip them through host.
    nbytes = sum(int(getattr(a, "nbytes", 0)) for a in host.values())
    with telemetry.span("hbm.commit", tensors=len(host), bytes=nbytes):
        out = _commit_tensors(host, mesh, rules, dtype, donate, coalesce)
    _M_COMMIT_BYTES.inc(nbytes)
    _M_COMMIT_TENSORS.inc(len(host))
    return out


def _commit_tensors(
    host: dict[str, np.ndarray],
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    donate: bool = False,
    coalesce: bool = True,
) -> dict[str, jax.Array]:
    if dtype is not None:
        def cast(a):
            a = np.asarray(a)
            if np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
                return a
            return a.astype(dtype, copy=False)

        host = {n: cast(a) for n, a in host.items()}
    names = list(host)
    specs = None
    if mesh is not None:
        specs = {n: spec_for(n, host[n].shape, mesh, rules) for n in names}

    # Group coalescible names per dtype (order-preserving). Keyed by the
    # np.dtype OBJECT, not its .str: ml_dtypes sub-byte types (uint4,
    # float8_e8m0fnu, ...) all stringify as '<V1', and a string key
    # would concat distinct dtypes into one group — DTypePromotionError
    # at best, silently mis-typed split views at worst.
    by_dtype: dict[np.dtype, list[str]] = {}
    for n in names if coalesce else ():
        a = host[n]
        if not 0 < a.nbytes < _COALESCE_MAX_BYTES:
            continue
        if specs is not None and specs[n] != P():
            continue
        by_dtype.setdefault(np.dtype(a.dtype), []).append(n)
    groups = [g for g in by_dtype.values()
              if len(g) >= _COALESCE_MIN_TENSORS]
    grouped = {n for g in groups for n in g}

    payloads, payload_shardings = [], []
    singles = [n for n in names if n not in grouped]
    for n in singles:
        payloads.append(host[n])
        payload_shardings.append(
            None if specs is None else NamedSharding(mesh, specs[n]))
    group_dtypes: list[str | None] = []
    for g in groups:
        dt = np.dtype(host[g[0]].dtype)
        carrier = _bit_carrier(dt)
        flat = np.concatenate([np.ascontiguousarray(host[n]).reshape(-1)
                               for n in g])
        if carrier is not None:
            # Ship float groups as raw bit patterns (see _bit_carrier):
            # the on-device split bitcasts back, so XLA never gets a
            # chance to canonicalize NaN payloads in transit.
            flat = flat.view(carrier)
        group_dtypes.append(dt.name if carrier is not None else None)
        payloads.append(flat)
        payload_shardings.append(
            None if specs is None else NamedSharding(mesh, P()))

    if specs is None:
        arrays = jax.device_put(payloads, donate=donate)
    else:
        arrays = jax.device_put(payloads, payload_shardings, donate=donate)

    out = dict(zip(singles, arrays[:len(singles)]))
    for g, gdt, flat_dev in zip(groups, group_dtypes,
                                arrays[len(singles):]):
        bounds, shapes, off = [0], [], 0
        for n in g:
            off += int(np.prod(host[n].shape, dtype=np.int64))
            bounds.append(off)
            shapes.append(tuple(host[n].shape))
        parts = _coalesced_split(tuple(bounds), tuple(shapes),
                                 gdt)(flat_dev)
        out.update(zip(g, parts))
    return {n: out[n] for n in names}  # caller-visible order preserved


def params_digest(params: dict) -> str:
    """Order-independent BLAKE3 digest of a landed param tree — name,
    dtype, shape, and raw bytes of every tensor, device arrays fetched
    back to host. The byte-identity oracle the cooperative-pull smoke
    (scripts/coop_smoke.py) compares against a solo pull: two landings
    agree iff every tensor's HBM contents agree bit-for-bit. O(model
    bytes) — a verification tool, not a hot-path call."""
    from zest_tpu.cas import hashing

    leaves = []
    for name in sorted(params):
        arr = np.asarray(jax.device_get(params[name]))
        leaves.append(hashing.blake3_hash(
            name.encode() + b"\x00" + str(arr.dtype).encode()
            + b"\x00" + repr(arr.shape).encode() + b"\x00"
            + arr.tobytes()
        ))
    return hashing.blake3_hash(b"".join(leaves)).hex()


def _commit_stats(
    params: dict, dt: float, mesh: Mesh | None, direct: bool
) -> dict:
    total = sum(int(a.nbytes) for a in params.values())
    return {
        "tensors": len(params),
        "bytes": total,
        "elapsed_s": round(dt, 3),
        "gbps": round(total / dt / 1e9, 3) if dt > 0 else 0.0,
        "sharded": mesh is not None,
        "direct": direct,
    }


def stage_snapshot_to_hbm(
    snapshot_dir: str | Path,
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
) -> tuple[dict[str, jax.Array], dict]:
    """Disk-path HBM commit: read a pulled snapshot's files into device
    arrays.

    Returns ``(params, stats)`` — the caller (normally ``PullResult``)
    owns the param tree and with it the HBM lifetime; drop the result to
    release the buffers. ``stats`` is the block reported under
    ``stats["hbm"]`` (tensors, bytes, wall time, effective host→HBM GB/s
    — the "HBM commit" stage of the BASELINE per-stage timing).
    """
    t0 = time.monotonic()
    params = load_checkpoint(snapshot_dir, mesh=mesh, rules=rules,
                             dtype=dtype)
    for arr in params.values():
        arr.block_until_ready()
    dt = time.monotonic() - t0
    return params, _commit_stats(params, dt, mesh, direct=False)


@contextlib.contextmanager
def _gc_frozen():
    """Suspend cyclic GC across the landing's timed region.

    A GB-scale landing allocates enough container churn (term memos,
    futures, span records) to trip several gen-2 collections mid-commit;
    each one walks every live object — including the multi-GB staging
    buffers' containers — at an arbitrary point in the pipeline, which
    is exactly the run-to-run ``hbm_commit`` spread the bench flagged.
    Freezing the current population out of the collector and disabling
    collection for the window removes that noise source; one explicit
    collect afterwards reclaims the window's garbage deterministically,
    *outside* the timed region. No-op (restore-exact) when the caller
    already runs with GC off."""
    was_enabled = gc.isenabled()
    gc.freeze()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()
        gc.unfreeze()
        gc.collect()


class RingClosed(RuntimeError):
    """The ring was torn down (consumer error) while a producer waited."""


class _RingSlot:
    """One in-flight staging buffer: decode writes it, the device
    transfer reads it, and (optionally) the write-behind file sink
    reads it too. Reference-counted — the buffer returns to the ring's
    free list only when every consumer is done with it."""

    __slots__ = ("ring", "buffer", "view", "acct", "refs", "detached")

    def __init__(self, ring: "HostRing", buffer: np.ndarray, nbytes: int,
                 acct: int):
        self.ring = ring
        self.buffer = buffer
        self.view = buffer[:nbytes]
        self.acct = acct          # capacity bytes charged to the ring
        self.refs = 1
        self.detached = False

    def addref(self) -> "_RingSlot":
        with self.ring._cv:
            self.refs += 1
        return self

    def detach(self) -> None:
        """Move this slot's bytes OUT of the ring's accounting — the
        file sink calls it when it keeps a reference past the commit,
        so a slow disk can never stall the landing's ring (total host
        memory stays bounded: ring budget + the sink's own cap). A
        detached buffer is not pooled for reuse."""
        with self.ring._cv:
            if not self.detached:
                self.detached = True
                self.ring._in_use_bytes -= self.acct
                self.ring._in_use -= 1
                self.ring._cv.notify_all()

    def release(self) -> None:
        self.ring._unref(self)


class HostRing:
    """Fixed-capacity pool of reusable host staging buffers — the
    streaming landing's bounded-memory core (ISSUE 8; the fixed-byte-
    budget argument from "Bounded-Memory Parallel Image Pulling",
    PAPERS.md).

    ``acquire(n)`` admits a slot while the in-flight capacity stays
    within ``budget_bytes`` and the slot count within ``max_slots``;
    otherwise it waits (a *stall* — counted, and a ``ring_stall``
    flight-recorder event) until the consumer recycles one. A tensor
    larger than the whole budget is admitted alone once nothing else is
    in flight (the ByteBudget oversized rule — a 1 GB embedding must
    still land, serially, not deadlock). Freed buffers are kept for
    reuse (smallest adequate fit) so a steady-state landing stops
    paying allocation + page-fault cost per tensor; the invariant
    ``in_use + free ≤ budget`` holds at all times except inside an
    oversized-alone admission.

    ``reuse=False`` makes every slot single-use (buffers drop after
    their transfer drains instead of pooling). Required on backends
    whose ``device_put`` may ZERO-COPY an aligned host buffer — the
    CPU backend does (measured: a 64-byte-aligned numpy array becomes
    the committed array's own storage), so reusing the buffer there
    would rewrite already-committed params. The byte bound still
    holds; only the allocation amortization is lost, on the backend
    where transfers are memcpy-cheap anyway."""

    def __init__(self, budget_bytes: int, max_slots: int,
                 reuse: bool = True):
        self.budget_bytes = max(1, int(budget_bytes))
        self.max_slots = max(1, int(max_slots))
        self.reuse = bool(reuse)
        self._cv = threading.Condition()
        self._free: list[np.ndarray] = []
        self._free_bytes = 0
        self._in_use = 0
        self._in_use_bytes = 0
        self._closed = False
        self.peak_bytes = 0
        self.stalls = 0
        self.stall_s = 0.0
        self.oversized = 0
        self.allocs = 0
        self.reuses = 0
        self.detached = 0
        self._waiting = False
        # Live ring occupancy/stall gauges for the timeline sampler
        # (ISSUE 15): one landing ring is live at a time in practice —
        # register_probe's replace semantics make the newest ring the
        # one sampled; close() unregisters so a finished landing stops
        # reporting. Flag-gated no-ops when timelines are off. The
        # bound methods are captured ONCE: attribute access mints a
        # fresh bound-method object each time, so close()'s
        # identity-checked unregister needs the same objects that were
        # registered.
        self._probe_in_use = self._probe_in_use_bytes
        self._probe_stall_count = self._probe_stalls
        telemetry.timeline.register_probe(
            "ring.in_use_bytes", self._probe_in_use)
        telemetry.timeline.register_probe(
            "ring.stalls", self._probe_stall_count)

    def _probe_in_use_bytes(self) -> int:
        with self._cv:
            return self._in_use_bytes

    def _probe_stalls(self) -> int:
        with self._cv:
            return self.stalls

    def _trim_free_locked(self, incoming: int) -> None:
        while self._free and (self._in_use_bytes + self._free_bytes
                              + incoming > self.budget_bytes):
            dropped = self._free.pop()
            self._free_bytes -= dropped.nbytes

    def acquire(self, nbytes: int,
                block: bool = True) -> _RingSlot | None:
        """Admit a slot of ``nbytes``; with ``block=False`` return
        ``None`` instead of waiting (no stall is counted) — callers
        holding a slot of their own use it to avoid waiting on
        capacity their own reference may be pinning."""
        nbytes = max(0, int(nbytes))
        stalled_at = None
        with self._cv:
            try:
                while True:
                    if self._closed:
                        raise RingClosed("landing ring closed")
                    if self._in_use == 0:
                        break  # oversized-alone admission
                    if (self._in_use < self.max_slots
                            and self._in_use_bytes + nbytes
                            <= self.budget_bytes):
                        break
                    if not block:
                        return None
                    if stalled_at is None:
                        stalled_at = time.monotonic()
                        self.stalls += 1
                        _M_RING_STALLS.inc()
                        telemetry.record(
                            "ring_stall", bytes=nbytes,
                            in_use_bytes=self._in_use_bytes,
                            slots=self._in_use)
                    # Visible to the consumer (producer_waiting): a
                    # stalled producer may need the very slots the
                    # consumer's half-built commit group pins.
                    self._waiting = True
                    self._cv.wait(0.05)
            finally:
                self._waiting = False
            if stalled_at is not None:
                self.stall_s += time.monotonic() - stalled_at
            if nbytes > self.budget_bytes:
                self.oversized += 1
            # Reuse the smallest free buffer that fits — but only when
            # its CAPACITY also fits the budget (a roomy buffer reused
            # for a small tensor must not bust the byte bound).
            best = None
            for i, b in enumerate(self._free):
                if b.nbytes >= nbytes and (
                        best is None
                        or b.nbytes < self._free[best].nbytes):
                    best = i
            buf = None
            if best is not None:
                cand = self._free[best]
                if (self._in_use == 0
                        or self._in_use_bytes + cand.nbytes
                        <= self.budget_bytes):
                    buf = self._free.pop(best)
                    self._free_bytes -= buf.nbytes
                    self.reuses += 1
            if buf is None:
                self._trim_free_locked(nbytes)
                buf = np.empty(nbytes, dtype=np.uint8)
                self.allocs += 1
            self._in_use += 1
            self._in_use_bytes += buf.nbytes
            self.peak_bytes = max(self.peak_bytes, self._in_use_bytes)
            return _RingSlot(self, buf, nbytes, buf.nbytes)

    def _unref(self, slot: _RingSlot) -> None:
        with self._cv:
            slot.refs -= 1
            if slot.refs > 0:
                return
            if slot.detached:
                self.detached += 1
                return  # accounting already surrendered; don't pool
            self._in_use -= 1
            self._in_use_bytes -= slot.acct
            # Pool the buffer for reuse when it keeps the invariant;
            # oversized (or budget-crowding) buffers are dropped.
            if (self.reuse and not self._closed
                    and self._in_use_bytes + self._free_bytes
                    + slot.acct <= self.budget_bytes):
                self._free.append(slot.buffer)
                self._free_bytes += slot.acct
            self._cv.notify_all()

    @property
    def producer_waiting(self) -> bool:
        """True while a producer is parked inside :meth:`acquire`."""
        with self._cv:
            return self._waiting

    def close(self) -> None:
        """Wake any waiter with :class:`RingClosed` — the consumer's
        error path, so a failing commit can never leave the decode
        thread parked in ``acquire`` forever."""
        telemetry.timeline.unregister_probe(
            "ring.in_use_bytes", self._probe_in_use)
        telemetry.timeline.unregister_probe(
            "ring.stalls", self._probe_stall_count)
        with self._cv:
            self._closed = True
            self._free.clear()
            self._free_bytes = 0
            self._cv.notify_all()

    def summary(self) -> dict:
        with self._cv:
            return {
                "budget_bytes": self.budget_bytes,
                "slots": self.max_slots,
                "peak_bytes": self.peak_bytes,
                "stalls": self.stalls,
                "stall_s": round(self.stall_s, 4),
                "buffers_allocated": self.allocs,
                "buffer_reuses": self.reuses,
                "oversized": self.oversized,
                "detached": self.detached,
            }


# Streaming commit grouping: tensors accumulate until a group reaches
# this many bytes (or a quarter of the ring's slots) and then commit as
# ONE batched device_put — tensor-granularity overlap without paying
# the per-shape transfer-setup round trip per tensor that
# commit_tensors' docstring measures at ~0.1 s/shape on a remote chip.
_STREAM_COMMIT_BYTES = 64 * 1024 * 1024


def _stage_streaming(
    bridge,
    recs_with_headers,
    mesh,
    rules,
    dtype,
    prefetch_next,
    decode_workers,
    clock,
    ring_bytes: int,
    ring_slots: int,
    tensor_gate=None,
    on_first_layer=None,
    stream_file_sink=None,
    preloaded=None,
    swap_from=None,
    exchange_landed: bool = False,
) -> tuple[dict[str, jax.Array], dict]:
    """The ring scheduler: decode of tensor N+k overlaps the device
    transfer of tensor N, in layer order, through a :class:`HostRing`
    of reusable staging buffers. See ``stage_cached_to_hbm`` for the
    contract; this is its ``land_stream`` path."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor
    from queue import Empty, SimpleQueue

    from zest_tpu.models.direct import StreamingShardReader
    from zest_tpu.models.registry import first_layer_names, order_names

    t0 = time.monotonic()
    preloaded = preloaded or {}
    # Slot reuse is only safe when the device transfer COPIES: the CPU
    # backend zero-copy-aliases aligned host buffers into the committed
    # arrays (see HostRing), so there every slot is single-use.
    ring = HostRing(ring_bytes, ring_slots,
                    reuse=jax.default_backend() != "cpu")
    group_bytes = max(1, min(_STREAM_COMMIT_BYTES, ring.budget_bytes // 4))
    group_count = max(1, ring.max_slots // 4)
    # first_set is judged over ALL tensors — delta-preloaded ones
    # included: "first layer resident" is about what a forward pass can
    # touch, not about which bytes this landing happened to move.
    all_names = frozenset(
        name for _r, h in recs_with_headers for name in h.tensors)
    first_set = first_layer_names(all_names)
    # Eager flushing only buys latency when the first-layer set is a
    # PROPER subset: for a layer-less checkpoint first_layer_names
    # returns the FULL set ("first layer" honestly == whole landing,
    # the stat still fires at the end), so flushing every queue blip
    # would spend a device_put dispatch+sync per decode gap for a
    # first-layer instant that cannot arrive early anyway.
    eager = bool(first_set) and first_set < all_names
    q: SimpleQueue = SimpleQueue()
    cancel = threading.Event()
    _DONE = object()

    # Decode unit: a RUN of file-contiguous tensors (layer order keeps
    # a layer's tensors adjacent, so runs ≈ layers up to the cap). One
    # read per run keeps intra-run boundary terms on the native batch
    # path — per-tensor reads pushed every such term through the
    # per-term memo (decoded to a bytes object, copied twice), which
    # cost ~25% of the warm decode wall. The cap keeps runs rotating
    # through the ring; a single tensor larger than the cap is its own
    # run (admitted alone if it outsizes the whole ring). Twice the
    # commit-group size, not equal to it: every run CUT re-decodes up
    # to one boundary term (see ``produce``), so fewer, larger runs
    # trade a little gate granularity for measurably less double
    # decode — the commit still flushes per ``group_bytes``, so
    # first-layer latency keeps its granularity from the commit side.
    run_cap = 2 * group_bytes

    def shard_runs(header):
        runs: list[list[str]] = []
        run_lo = run_hi = None
        prev_name = None
        for name in order_names(header.tensors):
            if name in preloaded:
                # Delta short-circuit (ISSUE 10): the tensor's chunk
                # cover is unchanged from the resident base revision —
                # no fetch gate, no decode, no device_put. The gap it
                # leaves in the file span naturally cuts the run.
                continue
            lo, hi = header.tensors[name].file_range(header.data_start)
            # Hard boundary at the first-layer-set edge: a shard
            # smaller than run_cap would otherwise be ONE run, so the
            # first-layer set could not decode (or gate its fetch)
            # ahead of the rest of its shard — first-layer latency
            # would silently degrade to shard granularity, the exact
            # unit of overlap streaming exists to break.
            if (runs and lo == run_hi
                    and hi - run_lo <= run_cap
                    and not (prev_name in first_set
                             and name not in first_set)):
                runs[-1].append(name)
                run_hi = hi
            else:
                runs.append([name])
                run_lo, run_hi = lo, hi
            prev_name = name
        return runs

    def produce():
        import bisect

        try:
            for i, (rec, header) in enumerate(recs_with_headers):
                if cancel.is_set():
                    return
                if prefetch_next is not None:
                    prefetch_next(i)
                # Lossy-staged exchange payloads (ISSUE 20) are HBM-
                # only: the overlay arms exactly when no file sink will
                # share the decoded bytes — a write-behind landing must
                # stay byte-exact, so it refetches through the verified
                # waterfall instead.
                sr = StreamingShardReader(
                    bridge.cache, rec, header, bridge=bridge,
                    workers=decode_workers,
                    allow_lossy=stream_file_sink is None)
                sink = (stream_file_sink(i, sr)
                        if stream_file_sink is not None else None)
                # Term boundaries (cumulative unpacked offsets): each
                # run's READ range rounds out to them, so every term a
                # run touches is wholly contained and decodes on the
                # native in-place batch path. A term straddling two
                # runs decodes once per run — an extra GIL-released
                # in-place pass over ≤ one term — instead of riding
                # the per-term memo (a side bytes buffer plus two
                # copies; measured ~0.5 s/2 GB when 32 MiB units put a
                # term under most run cuts).
                bounds = [0]
                for t in rec.terms:
                    bounds.append(bounds[-1] + t.unpacked_length)
                # (slot, r_lo, r_hi) of the previous run, held by an
                # extra ref: adjacent runs share the straddling term,
                # and its bytes are already decoded in that slot — the
                # next run memcpys the overlap out of it and decodes
                # only its fresh tail, instead of decoding the term a
                # second time (measured ~0.6 s/2 GB of extra decode
                # wall when 32 MiB terms put one under most run cuts).
                prev: tuple | None = None
                try:
                    for run in shard_runs(header):
                        if cancel.is_set():
                            return
                        if tensor_gate is not None:
                            # cancel lets the consumer's error path
                            # interrupt a gate parked on a slow fetch —
                            # the executor-exit join must not wait out
                            # the network.
                            for name in run:
                                tensor_gate(i, name, cancel)
                        if cancel.is_set():
                            return
                        spans = [header.tensors[n].file_range(
                            header.data_start) for n in run]
                        lo, hi = spans[0][0], spans[-1][1]
                        r_lo = bounds[
                            max(0, bisect.bisect_right(bounds, lo) - 1)]
                        r_hi = bounds[
                            min(len(bounds) - 1,
                                bisect.bisect_left(bounds, hi))]
                        r_hi = max(r_hi, hi)  # hi past the last term
                        # The held prev slot is capacity the ring
                        # counts: blocking on acquire while holding it
                        # can deadlock (with the sink inert nothing
                        # else ever detaches it, and oversized-alone
                        # needs in_use == 0). Keep prev only when it
                        # actually overlaps this run AND the ring
                        # admits both without waiting; otherwise drop
                        # it — the straddling term just re-decodes,
                        # the pre-overlap-copy behavior.
                        if prev is not None and not (
                                prev[1] <= r_lo < prev[2]):
                            prev[0].release()
                            prev = None
                        slot = None
                        if prev is not None:
                            slot = ring.acquire(r_hi - r_lo,
                                                block=False)
                            if slot is None:
                                prev[0].release()
                                prev = None
                        if slot is None:
                            slot = ring.acquire(r_hi - r_lo)
                        try:
                            d_lo = r_lo
                            if prev is not None:
                                p_slot, p_lo, p_hi = prev
                                if p_lo <= r_lo < p_hi:
                                    ov = min(p_hi, r_hi) - r_lo
                                    src_lo = r_lo - p_lo
                                    np.copyto(
                                        slot.view[:ov],
                                        p_slot.view[src_lo:src_lo + ov])
                                    d_lo = r_lo + ov
                            with (clock("decode") if clock is not None
                                  else contextlib.nullcontext()):
                                if d_lo < r_hi:
                                    sr.decode_range_into(
                                        d_lo, r_hi,
                                        memoryview(
                                            slot.view[d_lo - r_lo:]),
                                        label=f"{run[0]}+{len(run) - 1}"
                                        if len(run) > 1 else run[0])
                            if clock is not None:
                                clock.note_bytes("decode", r_hi - d_lo)
                        except BaseException:
                            slot.release()
                            raise
                        if prev is not None:
                            prev[0].release()
                        slot.addref()
                        prev = (slot, r_lo, r_hi)
                        # One ring slot, len(run) consumers: the queue
                        # releases once per tensor (plus the sink's own
                        # refs), so pre-add the extra references.
                        for _ in range(len(run) - 1):
                            slot.addref()
                        for name, (t_lo, t_hi) in zip(run, spans):
                            info = header.tensors[name]
                            arr = (slot.view[t_lo - r_lo:t_hi - r_lo]
                                   .view(info.np_dtype)
                                   .reshape(info.shape))
                            if sink is not None:
                                # The sink addrefs + detaches the slot
                                # if it keeps the bytes; never blocks.
                                sink.offer(name, info, arr, slot)
                            q.put((name, arr, slot))
                finally:
                    if prev is not None:
                        prev[0].release()
                    sr.close()
                    if sink is not None:
                        sink.done_decoding()
            q.put(_DONE)
        except BaseException as exc:  # noqa: BLE001 - consumer re-raises
            q.put(exc)

    params: dict[str, jax.Array] = dict(preloaded)
    committed_names: set[str] = set(preloaded)
    fired = not first_set or first_set <= committed_names
    if fired and first_set and preloaded and on_first_layer is not None:
        # The whole first-layer set rode the delta short-circuit: it is
        # resident NOW (the base revision's identical bytes), so the
        # stat honestly fires at landing start.
        on_first_layer()
    batch: dict[str, np.ndarray] = {}
    batch_slots: list[_RingSlot] = []
    batch_bytes = 0
    batch_slot_ids: set[int] = set()
    pending: deque = deque()

    def drain_one():
        nonlocal fired
        arrays, slots, names = pending.popleft()
        for a in arrays:
            a.block_until_ready()
        for s in slots:
            s.release()
        committed_names.update(names)
        if swap_from:
            # In-place hot-swap: the replacement is resident — release
            # the superseded base tensors NOW, so HBM peak stays ~one
            # tree + one in-flight commit group instead of two trees.
            for n in names:
                swap_from.pop(n, None)
        if (not fired and first_set
                and first_set <= committed_names):
            fired = True
            if on_first_layer is not None:
                on_first_layer()

    def flush():
        nonlocal batch, batch_slots, batch_bytes
        if not batch:
            return
        # Coalesce only on the re-land/hot-swap path (ROADMAP item 5):
        # a delta or pool re-land of one checkpoint repeats the same
        # small-tensor group layouts pull after pull, so the jitted
        # splitter's per-layout cache amortizes — whereas a cold
        # stream's group composition varies with wire timing and would
        # pay an XLA compile per flush (the reason coalescing was
        # bypassed here originally). Exchange-received landings
        # (ISSUE 20) coalesce too: the collective completes before the
        # landing starts, so the whole working set decodes from a warm
        # cache and group cuts land on the same deterministic layer
        # boundaries pull after pull — same amortization, no wire
        # timing in the group composition.
        committed = commit_tensors(
            batch, mesh, rules, dtype=dtype, donate=True,
            coalesce=bool(preloaded or swap_from is not None
                          or exchange_landed))
        params.update(committed)
        pending.append((list(committed.values()), batch_slots,
                        list(batch)))
        batch, batch_slots, batch_bytes = {}, [], 0
        batch_slot_ids.clear()
        # Double buffer: keep ONE committed group in flight (its
        # transfer drains while the next group decodes), drain older
        # ones — their slots are what feeds the ring.
        while len(pending) > 1:
            drain_one()

    error: BaseException | None = None
    with _gc_frozen():
        with ThreadPoolExecutor(
                1, thread_name_prefix="zest-land-stream") as staging:
            staging.submit(produce)
            try:
                while True:
                    try:
                        item = q.get_nowait()
                    except Empty:
                        # Queue momentarily dry. Recycle committed
                        # groups (free — their transfers have had the
                        # whole gap to drain) but do NOT flush the
                        # half-built batch on every blip: the queue
                        # empties between decode runs, so that was one
                        # device_put per run remainder (38 calls per
                        # 2 GB pull vs 3, each a real dispatch+sync).
                        # Park unbounded only while holding nothing;
                        # while the batch pins ring slots, poll and
                        # flush the moment the producer actually
                        # stalls in acquire (it may need these very
                        # bytes — the 50 ms poll bounds the race of it
                        # stalling right after a check) or stays quiet
                        # past a grace period (a fetch-bound gap, where
                        # committing early is exactly the streaming
                        # win: first layers land while later ones are
                        # still on the wire). Until the first-layer
                        # set has committed, stay EAGER — flush every
                        # blip: those few extra dispatches are what
                        # time_to_first_layer is buying, and on a pull
                        # smaller than one commit group they are the
                        # only thing that commits anything early.
                        while pending:
                            drain_one()
                        waited = 0.0
                        while True:
                            if batch and ((eager and not fired)
                                          or ring.producer_waiting
                                          or waited >= 0.25):
                                flush()
                                while pending:
                                    drain_one()
                            try:
                                item = q.get(
                                    timeout=0.05 if batch else None)
                                break
                            except Empty:
                                waited += 0.05
                    if item is _DONE:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    name, arr, slot = item
                    batch[name] = arr
                    batch_slots.append(slot)
                    batch_bytes += int(arr.nbytes)
                    batch_slot_ids.add(id(slot))
                    # The slot guard counts DISTINCT slots (a run's
                    # tensors share one) — it bounds how many ring
                    # buffers a half-built group pins, not how many
                    # tensors it holds; counting tensors made a
                    # small-tensor checkpoint flush far under
                    # group_bytes (2× the flush/sync count at the
                    # scale=2 bench geometry).
                    if (batch_bytes >= group_bytes
                            or len(batch_slot_ids) >= group_count):
                        flush()
                flush()
                while pending:
                    drain_one()
            except BaseException as exc:
                error = exc
                cancel.set()
                ring.close()
                raise
            finally:
                if error is not None:
                    # Unblock the producer (ring closed ⇒ its next
                    # acquire raises; cancel ⇒ its loops exit) and
                    # drop anything already queued.
                    while True:
                        try:
                            item = q.get_nowait()
                        except Empty:
                            break
                        if isinstance(item, tuple):
                            item[2].release()
                    # Release arrays this landing already committed:
                    # the raised exception's frames keep ``params``
                    # reachable until the pull exits, which would
                    # strand the partial tree in HBM — fatal for a
                    # pool re-land that aborts and retries under a
                    # byte watermark. The preloaded reuse set is the
                    # caller's base tree and must survive the abort.
                    for n in list(params):
                        if n in preloaded:
                            continue
                        try:
                            params.pop(n).delete()
                        except Exception:  # noqa: BLE001 - best effort
                            pass
        for arr in params.values():
            arr.block_until_ready()
        dt = time.monotonic() - t0
    stats = _commit_stats(params, dt, mesh, direct=True)
    stats["decode_ahead"] = True
    stats["streamed"] = True
    stats["ring"] = ring.summary()
    if preloaded or swap_from is not None:
        # A consumed base tree IS a hot-swap even when nothing reused
        # (e.g. the dtype guard re-landed everything): the mesh ends
        # holding the new revision and the old arrays were released
        # progressively.
        stats["swap"] = _swap_stats(preloaded, params)
    return params, stats


def _swap_stats(preloaded: dict, params: dict) -> dict:
    """The hot-swap evidence block under ``stats["hbm"]["swap"]``: how
    much of the tree rode the per-tensor short-circuit (reused — zero
    decode/verify/transfer) vs actually landed."""
    reused_bytes = sum(int(a.nbytes) for a in preloaded.values())
    return {
        "reused_tensors": len(preloaded),
        "reused_bytes": reused_bytes,
        "landed_tensors": len(params) - len(preloaded),
        "landed_bytes": sum(int(a.nbytes) for a in params.values())
        - reused_bytes,
    }


def stage_cached_to_hbm(
    bridge,
    recs_with_headers,
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    prefetch_next=None,
    decode_ahead: int | None = None,
    decode_workers: int | None = None,
    on_host_ready=None,
    clock=None,
    stream: bool | None = None,
    ring_bytes: int | None = None,
    ring_slots: int | None = None,
    tensor_gate=None,
    on_first_layer=None,
    stream_file_sink=None,
    preloaded=None,
    swap_from=None,
    exchange_landed: bool = False,
) -> tuple[dict[str, jax.Array], dict]:
    """Direct-path HBM commit: land tensors straight from cached xorb
    units — zero file reads on the landing path (SURVEY.md §7 hard part
    #2; the reference always round-trips disk, SURVEY.md §3.1).

    ``recs_with_headers`` is ``[(Reconstruction, SafetensorsHeader)]``,
    one per safetensors file (headers via transfer.pod.fetch_file_header).
    Units the distribution round missed are pulled through the bridge's
    waterfall. ``prefetch_next(i)``, when given, is called before shard
    ``i`` lands — the pull path passes a one-shard-lookahead warm fetch
    so shard ``i+1``'s network time hides under shard ``i``'s decode +
    commit (see transfer.pull._PipelinedWarm).

    The decode and the device transfer are double-buffered (the
    ``decode_ahead`` knob, default on, ``Config.land_decode_ahead``): a
    single staging thread decodes shard ``i+1``'s host tensors while
    shard ``i``'s batched ``jax.device_put`` is in flight — JAX's async
    dispatch returns before the transfer drains, so the CPU-bound term
    decode hides under it. Host peak stays bounded at ~two checkpoint
    shards (the decoded-ahead shard plus the committing one).
    ``decode_workers`` sizes the per-shard term-decode pool
    (models.direct.resolve_decode_workers). Both default from
    ``bridge.cfg``.

    ``on_host_ready(i, host)``, when given, fires right after shard
    ``i``'s host tensors are decoded (before the commit, in the staging
    thread when pipelined) — the pull's write-behind hands the decoded
    bytes to the file pipeline there, so the HF-cache file is written
    without decoding the shard a second time. The callback may retain
    ``host``'s arrays (the commit never mutates them; a dtype cast
    copies) and may block, which backpressures the decode-ahead.
    ``clock``, when given (a transfer.pull.StageClock), records each
    shard's cache→host decode under stage ``"decode"`` with its bytes
    attributed — the stage the ISSUE-3 engine is judged on.
    Returns ``(params, stats)`` like stage_snapshot_to_hbm, with
    ``stats["direct"] = True``.

    **Streaming** (``stream``, default ``Config.land_stream``, ISSUE 8):
    the landing flows at *tensor* granularity through a
    :class:`HostRing` of reusable staging buffers — tensors decode
    straight into ring slots (no per-shard host buffer), commit in
    layer order (``models.registry.order_names``) as batched groups,
    and slots recycle as transfers drain. ``tensor_gate(i, name,
    cancel)``, when given, blocks until tensor ``name``'s fetch units
    are cached (the pull's layer-ordered warm publishes them) so decode
    can chase the fetch sub-shard; ``cancel`` (a ``threading.Event``)
    is the landing's abort signal — the gate must return when it sets. ``on_first_layer()`` fires once, the moment
    the first-token-capable set (embedding + layer 0,
    ``registry.first_layer_names``) is resident. ``stream_file_sink(i,
    reader)`` returns the shard's write-behind consumer (or None): its
    ``offer(name, info, arr, slot)`` may keep slot references (addref +
    detach) to assemble the HF-cache file without re-decoding.
    ``ring_bytes``/``ring_slots`` bound the in-flight staging memory
    (``Config.land_ring_bytes``/``land_ring_slots``). Streaming
    requires ``decode_ahead`` (a serial landing has no pipeline to
    ring) and is mutually exclusive with the shard-level
    ``on_host_ready`` write-behind; with ``stream`` off the PR-1
    shard-level double buffer runs unchanged, stats schema included.

    **Delta hot-swap** (``preloaded``/``swap_from``, ISSUE 10):
    ``preloaded`` maps tensor names to ALREADY-RESIDENT device arrays
    whose bytes the delta plan proved unchanged from the base revision
    — they skip fetch gating, decode, verify, and ``device_put``
    entirely and appear in the returned tree as-is (the per-tensor
    short-circuit). ``swap_from``, when given, is the base revision's
    param dict, CONSUMED in place: each changed tensor's superseded
    base array is popped the moment its replacement's transfer drains,
    so a live mesh swaps revisions at ~one-tree HBM peak instead of
    two. ``stats["swap"]`` records the reused/landed split. Both paths
    (streaming and shard-level) honor them; byte identity with a cold
    landing of the new revision is pinned by ``params_digest`` tests.

    ``exchange_landed`` marks a landing whose working set a completed
    collective exchange prewarmed (ISSUE 20): group composition is then
    deterministic (no wire timing), so the streaming flush coalesces
    small-tensor groups exactly like the re-land path.
    """
    import contextlib
    from concurrent.futures import ThreadPoolExecutor

    from zest_tpu.models.direct import land_tensors

    # Every landing knob resolves through Config uniformly — the
    # fallback constants ARE the config defaults, so a bridge without a
    # cfg can never disagree with ``Config()`` about the defaults.
    cfg = getattr(bridge, "cfg", None)
    if decode_ahead is None:
        decode_ahead = getattr(cfg, "land_decode_ahead",
                               DEFAULT_LAND_DECODE_AHEAD)
    if decode_workers is None:
        decode_workers = getattr(cfg, "decode_workers", None)
    if stream is None:
        stream = getattr(cfg, "land_stream", DEFAULT_LAND_STREAM)
    if ring_bytes is None:
        ring_bytes = getattr(cfg, "land_ring_bytes",
                             DEFAULT_LAND_RING_BYTES)
        # Auto-tuner override (ISSUE 17): the remediation engine may
        # hold a railed override for this knob — nudged up (×2, capped
        # at 8× the configured base) when the ring-stall series grows,
        # decayed back toward the base after a quiet observation
        # window. An explicit ring_bytes argument always wins; with
        # ZEST_REMEDIATE=0 the override is always None.
        telemetry.remediate.set_knob_base("land_ring_bytes", ring_bytes)
        _override = telemetry.remediate.knob_override("land_ring_bytes")
        if _override:
            ring_bytes = _override
    if ring_slots is None:
        ring_slots = getattr(cfg, "land_ring_slots",
                             DEFAULT_LAND_RING_SLOTS)
    if (stream and decode_ahead and on_host_ready is None
            and recs_with_headers):
        return _stage_streaming(
            bridge, recs_with_headers, mesh, rules, dtype,
            prefetch_next, decode_workers, clock,
            ring_bytes, ring_slots,
            tensor_gate=tensor_gate, on_first_layer=on_first_layer,
            stream_file_sink=stream_file_sink,
            preloaded=preloaded, swap_from=swap_from,
            exchange_landed=exchange_landed)

    t0 = time.monotonic()
    preloaded = preloaded or {}
    params: dict[str, jax.Array] = dict(preloaded)
    n = len(recs_with_headers)
    predicate = None
    if preloaded:
        # Per-tensor short-circuit, shard-level flavor: only changed
        # tensors decode (land_tensors predicate); the whole-shard
        # single-read lane is traded away exactly where most of the
        # shard would be skipped anyway.
        def predicate(name, _skip=frozenset(preloaded)):
            return name not in _skip

    def decode(i: int) -> dict:
        if prefetch_next is not None:
            prefetch_next(i)
        rec, header = recs_with_headers[i]
        with (clock("decode") if clock is not None
              else contextlib.nullcontext()):
            host = land_tensors(bridge.cache, rec, header, bridge=bridge,
                                workers=decode_workers,
                                predicate=predicate,
                                allow_lossy=on_host_ready is None)
        if clock is not None:
            clock.note_bytes("decode",
                             sum(int(a.nbytes) for a in host.values()))
        if on_host_ready is not None:
            on_host_ready(i, host)
        return host

    def commit(host: dict) -> None:
        params.update(commit_tensors(host, mesh, rules, dtype=dtype,
                                     donate=True))
        if swap_from:
            for name in host:
                swap_from.pop(name, None)

    pipelined = bool(decode_ahead) and n > 1
    # GC frozen over the whole decode→commit window (see _gc_frozen):
    # the deferred collect runs in the context exit, after ``dt`` is
    # captured — reclamation cost lands outside the timed region.
    with _gc_frozen():
        if pipelined:
            # One staging thread, one shard of lookahead: deeper
            # lookahead would only grow the host peak — the commit is
            # the narrower pipe and a single buffered shard already
            # keeps it fed.
            with ThreadPoolExecutor(
                    1, thread_name_prefix="zest-land-decode") as staging:
                pending = staging.submit(decode, 0)
                for i in range(n):
                    host = pending.result()
                    if i + 1 < n:
                        pending = staging.submit(decode, i + 1)
                    # One batched commit per checkpoint shard (see
                    # load_checkpoint's note: amortized transfer setup,
                    # file-bounded host peak); async dispatch means this
                    # returns while the transfer is still draining.
                    commit(host)
                    del host
        else:
            for i in range(n):
                host = decode(i)
                commit(host)
                del host
        for arr in params.values():
            arr.block_until_ready()
        dt = time.monotonic() - t0
    stats = _commit_stats(params, dt, mesh, direct=True)
    stats["decode_ahead"] = pipelined
    if preloaded or swap_from is not None:
        stats["swap"] = _swap_stats(preloaded, params)
    return params, stats
