"""Checkpoint landing: safetensors → (sharded) device arrays in HBM.

The reference stops at the filesystem — reassembled files sit in the HF
cache and torch loads them later (SURVEY.md §3.1). The TPU build's north
star continues one hop: pulled tensors land as ``jax.Array``s laid out for
a pjit mesh, so ``pull --device=tpu`` ends with weights already resident
where the model will run (BASELINE config #3).

Sharding is rule-driven: an ordered list of ``(name_regex, PartitionSpec)``
pairs, first match wins, falling back to sharding the largest evenly
divisible axis over the mesh's last axis (the ICI-contiguous one, see
zest_tpu.parallel.mesh.model_mesh). Tensors indivisible by every axis
replicate.
"""

from __future__ import annotations

import contextlib
import functools
import gc
import re
import time
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zest_tpu import telemetry
from zest_tpu.models.safetensors_io import SafetensorsFile

_M_COMMIT_BYTES = telemetry.counter(
    "zest_hbm_commit_bytes_total", "Bytes committed host→HBM")
_M_COMMIT_TENSORS = telemetry.counter(
    "zest_hbm_commit_tensors_total", "Tensors committed host→HBM")

ShardRules = list[tuple[str, P]]


def infer_spec(
    shape: tuple[int, ...], mesh: Mesh, axis: str
) -> P:
    """Default policy: shard the largest dim divisible by the axis size."""
    n = int(mesh.shape[axis])
    if n <= 1 or not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % n == 0 and shape[i] >= n:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def _spec_fits(shape: tuple[int, ...], mesh: Mesh, spec: P) -> bool:
    """A rule spec is usable iff every named axis exists in the mesh and
    divides its tensor dim. Family rules are written against a family's
    canonical mesh; on a different topology (e.g. Mixtral rules on a
    {data, model} mesh with no 'expert' axis) the landing must degrade
    to infer_spec, not fail the whole HBM commit."""
    if len(spec) > len(shape):
        return False
    for dim, axes in zip(shape, spec):
        if axes is None:
            continue
        for ax in axes if isinstance(axes, tuple) else (axes,):
            if ax not in mesh.shape:
                return False
            if dim % int(mesh.shape[ax]):
                return False
            dim //= int(mesh.shape[ax])
    return True


def spec_for(
    name: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    rules: ShardRules | None = None,
    default_axis: str | None = None,
) -> P:
    for pattern, spec in rules or []:
        if re.search(pattern, name):
            if _spec_fits(shape, mesh, spec):
                return spec
            break  # first match wins; unusable → generic fallback
    axis = default_axis or mesh.axis_names[-1]
    return infer_spec(shape, mesh, axis)


def land_tensor(
    arr: np.ndarray, mesh: Mesh, spec: P
) -> jax.Array:
    """One host-resident tensor → device array under ``spec``.

    ``device_put`` with a NamedSharding splits the host buffer across the
    addressable devices; under multi-process each process must hold the
    full tensor (the pull pipeline guarantees that — every host reassembles
    every file, bytes having arrived over ICI, not N× over DCN).
    """
    return jax.device_put(arr, NamedSharding(mesh, spec))


def snapshot_files(snapshot_dir: str | Path) -> list[Path]:
    return sorted(Path(snapshot_dir).glob("*.safetensors"))


def load_checkpoint(
    snapshot_dir: str | Path,
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    predicate=None,
) -> dict[str, jax.Array]:
    """All tensors of a snapshot as a flat name→array dict on device.

    With no mesh, arrays land on the default device unsharded (single-chip
    path). ``dtype`` optionally casts on the way in (checkpoints are often
    f32; TPU wants bf16). ``predicate(name)`` filters tensors.
    """
    out: dict[str, jax.Array] = {}
    for path in snapshot_files(snapshot_dir):
        host: dict[str, np.ndarray] = {}
        with SafetensorsFile(path) as sf:
            for name in sf.names():
                if predicate is not None and not predicate(name):
                    continue
                host[name] = sf.tensor(name)
            # Commit per file: one batched transfer per shard keeps host
            # peak at ~one safetensors file (the sharding contract) while
            # still amortizing the per-shape transfer setup; casting
            # lives in commit_tensors (one implementation, both paths).
            out.update(commit_tensors(host, mesh, rules, dtype=dtype,
                                      donate=True))
    return out


def resolve_dtype(name: str | None):
    """Landing-dtype names (config/CLI) → jnp dtype, None = keep."""
    if name is None:
        return None
    import jax.numpy as jnp

    table = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
             "f16": jnp.float16, "float16": jnp.float16,
             "f32": jnp.float32, "float32": jnp.float32}
    try:
        return table[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown landing dtype {name!r} "
            f"(supported: {', '.join(sorted(table))})"
        ) from None


# Tensors below this size coalesce into one transfer per dtype (the
# norm/bias vectors: a Llama shard carries dozens of KB-scale 1-D
# weights whose per-buffer transfer setup costs more than their bytes).
_COALESCE_MAX_BYTES = 256 * 1024
# Minimum group size worth the on-device split dispatch.
_COALESCE_MIN_TENSORS = 2


@functools.lru_cache(maxsize=64)
def _coalesced_split(bounds: tuple[int, ...],
                     shapes: tuple[tuple[int, ...], ...]):
    """Jitted flat-buffer → per-tensor views splitter, cached per layout
    so a repeated commit geometry (every shard of one checkpoint) pays
    one compile and ONE dispatch per group — not a slice round-trip per
    tensor."""
    def split(flat):
        return tuple(
            flat[bounds[i]:bounds[i + 1]].reshape(shapes[i])
            for i in range(len(shapes))
        )

    return jax.jit(split)


def commit_tensors(
    host: dict[str, np.ndarray],
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    donate: bool = False,
) -> dict[str, jax.Array]:
    """One BATCHED ``device_put`` for a whole tensor dict.

    Committing per tensor costs a transfer-setup round trip per unique
    shape — seconds for a checkpoint of ~dozens of shapes on a remote
    chip (measured ~0.1s/shape vs ~30ms for the whole batched commit);
    a single call lets the runtime pipeline every buffer. ``dtype``
    optionally casts *non-integer* tensors on the host first (f32
    checkpoints land bf16 at half the HBM and half the transfer bytes);
    integer/bool tensors keep their dtype — casting a token-id or
    position buffer would silently corrupt it. The filter excludes
    int/bool rather than matching np.floating because ml_dtypes
    extension types (the bf16 most modern checkpoints ship) are NOT
    np.floating subtypes. ``copy=False`` keeps the matched-dtype case
    free (no doubled host peak).

    Two commit-side optimizations from ISSUE 3:

    - **Small-tensor coalescing**: sub-``_COALESCE_MAX_BYTES`` tensors
      that land replicated (the norm/bias vectors — sharded smalls keep
      their own buffer, a concat would misalign the shard boundaries)
      are concatenated per dtype into ONE transfer and split back on
      device by a single jitted dispatch, so a shard's dozens of tiny
      buffers stop paying per-buffer transfer setup.
    - **Donation** (``donate=True``): callers that promise not to reuse
      the staging buffers let the runtime alias/free inputs eagerly —
      a no-op for host numpy staging, but device-resident inputs
      (re-landing, resharding) release their source HBM immediately
      instead of at the next GC.
    """
    # .nbytes, never np.asarray: inputs may be device-resident (the
    # resharding path) and asarray would round-trip them through host.
    nbytes = sum(int(getattr(a, "nbytes", 0)) for a in host.values())
    with telemetry.span("hbm.commit", tensors=len(host), bytes=nbytes):
        out = _commit_tensors(host, mesh, rules, dtype, donate)
    _M_COMMIT_BYTES.inc(nbytes)
    _M_COMMIT_TENSORS.inc(len(host))
    return out


def _commit_tensors(
    host: dict[str, np.ndarray],
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    donate: bool = False,
) -> dict[str, jax.Array]:
    if dtype is not None:
        def cast(a):
            a = np.asarray(a)
            if np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
                return a
            return a.astype(dtype, copy=False)

        host = {n: cast(a) for n, a in host.items()}
    names = list(host)
    specs = None
    if mesh is not None:
        specs = {n: spec_for(n, host[n].shape, mesh, rules) for n in names}

    # Group coalescible names per dtype (order-preserving). Keyed by the
    # np.dtype OBJECT, not its .str: ml_dtypes sub-byte types (uint4,
    # float8_e8m0fnu, ...) all stringify as '<V1', and a string key
    # would concat distinct dtypes into one group — DTypePromotionError
    # at best, silently mis-typed split views at worst.
    by_dtype: dict[np.dtype, list[str]] = {}
    for n in names:
        a = host[n]
        if not 0 < a.nbytes < _COALESCE_MAX_BYTES:
            continue
        if specs is not None and specs[n] != P():
            continue
        by_dtype.setdefault(np.dtype(a.dtype), []).append(n)
    groups = [g for g in by_dtype.values()
              if len(g) >= _COALESCE_MIN_TENSORS]
    grouped = {n for g in groups for n in g}

    payloads, payload_shardings = [], []
    singles = [n for n in names if n not in grouped]
    for n in singles:
        payloads.append(host[n])
        payload_shardings.append(
            None if specs is None else NamedSharding(mesh, specs[n]))
    for g in groups:
        flat = np.concatenate([np.ascontiguousarray(host[n]).reshape(-1)
                               for n in g])
        payloads.append(flat)
        payload_shardings.append(
            None if specs is None else NamedSharding(mesh, P()))

    if specs is None:
        arrays = jax.device_put(payloads, donate=donate)
    else:
        arrays = jax.device_put(payloads, payload_shardings, donate=donate)

    out = dict(zip(singles, arrays[:len(singles)]))
    for g, flat_dev in zip(groups, arrays[len(singles):]):
        bounds, shapes, off = [0], [], 0
        for n in g:
            off += int(np.prod(host[n].shape, dtype=np.int64))
            bounds.append(off)
            shapes.append(tuple(host[n].shape))
        parts = _coalesced_split(tuple(bounds), tuple(shapes))(flat_dev)
        out.update(zip(g, parts))
    return {n: out[n] for n in names}  # caller-visible order preserved


def params_digest(params: dict) -> str:
    """Order-independent BLAKE3 digest of a landed param tree — name,
    dtype, shape, and raw bytes of every tensor, device arrays fetched
    back to host. The byte-identity oracle the cooperative-pull smoke
    (scripts/coop_smoke.py) compares against a solo pull: two landings
    agree iff every tensor's HBM contents agree bit-for-bit. O(model
    bytes) — a verification tool, not a hot-path call."""
    from zest_tpu.cas import hashing

    leaves = []
    for name in sorted(params):
        arr = np.asarray(jax.device_get(params[name]))
        leaves.append(hashing.blake3_hash(
            name.encode() + b"\x00" + str(arr.dtype).encode()
            + b"\x00" + repr(arr.shape).encode() + b"\x00"
            + arr.tobytes()
        ))
    return hashing.blake3_hash(b"".join(leaves)).hex()


def _commit_stats(
    params: dict, dt: float, mesh: Mesh | None, direct: bool
) -> dict:
    total = sum(int(a.nbytes) for a in params.values())
    return {
        "tensors": len(params),
        "bytes": total,
        "elapsed_s": round(dt, 3),
        "gbps": round(total / dt / 1e9, 3) if dt > 0 else 0.0,
        "sharded": mesh is not None,
        "direct": direct,
    }


def stage_snapshot_to_hbm(
    snapshot_dir: str | Path,
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
) -> tuple[dict[str, jax.Array], dict]:
    """Disk-path HBM commit: read a pulled snapshot's files into device
    arrays.

    Returns ``(params, stats)`` — the caller (normally ``PullResult``)
    owns the param tree and with it the HBM lifetime; drop the result to
    release the buffers. ``stats`` is the block reported under
    ``stats["hbm"]`` (tensors, bytes, wall time, effective host→HBM GB/s
    — the "HBM commit" stage of the BASELINE per-stage timing).
    """
    t0 = time.monotonic()
    params = load_checkpoint(snapshot_dir, mesh=mesh, rules=rules,
                             dtype=dtype)
    for arr in params.values():
        arr.block_until_ready()
    dt = time.monotonic() - t0
    return params, _commit_stats(params, dt, mesh, direct=False)


@contextlib.contextmanager
def _gc_frozen():
    """Suspend cyclic GC across the landing's timed region.

    A GB-scale landing allocates enough container churn (term memos,
    futures, span records) to trip several gen-2 collections mid-commit;
    each one walks every live object — including the multi-GB staging
    buffers' containers — at an arbitrary point in the pipeline, which
    is exactly the run-to-run ``hbm_commit`` spread the bench flagged.
    Freezing the current population out of the collector and disabling
    collection for the window removes that noise source; one explicit
    collect afterwards reclaims the window's garbage deterministically,
    *outside* the timed region. No-op (restore-exact) when the caller
    already runs with GC off."""
    was_enabled = gc.isenabled()
    gc.freeze()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()
        gc.unfreeze()
        gc.collect()


def stage_cached_to_hbm(
    bridge,
    recs_with_headers,
    mesh: Mesh | None = None,
    rules: ShardRules | None = None,
    dtype=None,
    prefetch_next=None,
    decode_ahead: int | None = None,
    decode_workers: int | None = None,
    on_host_ready=None,
    clock=None,
) -> tuple[dict[str, jax.Array], dict]:
    """Direct-path HBM commit: land tensors straight from cached xorb
    units — zero file reads on the landing path (SURVEY.md §7 hard part
    #2; the reference always round-trips disk, SURVEY.md §3.1).

    ``recs_with_headers`` is ``[(Reconstruction, SafetensorsHeader)]``,
    one per safetensors file (headers via transfer.pod.fetch_file_header).
    Units the distribution round missed are pulled through the bridge's
    waterfall. ``prefetch_next(i)``, when given, is called before shard
    ``i`` lands — the pull path passes a one-shard-lookahead warm fetch
    so shard ``i+1``'s network time hides under shard ``i``'s decode +
    commit (see transfer.pull._PipelinedWarm).

    The decode and the device transfer are double-buffered (the
    ``decode_ahead`` knob, default on, ``Config.land_decode_ahead``): a
    single staging thread decodes shard ``i+1``'s host tensors while
    shard ``i``'s batched ``jax.device_put`` is in flight — JAX's async
    dispatch returns before the transfer drains, so the CPU-bound term
    decode hides under it. Host peak stays bounded at ~two checkpoint
    shards (the decoded-ahead shard plus the committing one).
    ``decode_workers`` sizes the per-shard term-decode pool
    (models.direct.resolve_decode_workers). Both default from
    ``bridge.cfg``.

    ``on_host_ready(i, host)``, when given, fires right after shard
    ``i``'s host tensors are decoded (before the commit, in the staging
    thread when pipelined) — the pull's write-behind hands the decoded
    bytes to the file pipeline there, so the HF-cache file is written
    without decoding the shard a second time. The callback may retain
    ``host``'s arrays (the commit never mutates them; a dtype cast
    copies) and may block, which backpressures the decode-ahead.
    ``clock``, when given (a transfer.pull.StageClock), records each
    shard's cache→host decode under stage ``"decode"`` with its bytes
    attributed — the stage the ISSUE-3 engine is judged on.
    Returns ``(params, stats)`` like stage_snapshot_to_hbm, with
    ``stats["direct"] = True``.
    """
    import contextlib
    from concurrent.futures import ThreadPoolExecutor

    from zest_tpu.models.direct import land_tensors

    cfg = getattr(bridge, "cfg", None)
    if decode_ahead is None:
        decode_ahead = getattr(cfg, "land_decode_ahead", 1)
    if decode_workers is None:
        decode_workers = getattr(cfg, "decode_workers", None)

    t0 = time.monotonic()
    params: dict[str, jax.Array] = {}
    n = len(recs_with_headers)

    def decode(i: int) -> dict:
        if prefetch_next is not None:
            prefetch_next(i)
        rec, header = recs_with_headers[i]
        with (clock("decode") if clock is not None
              else contextlib.nullcontext()):
            host = land_tensors(bridge.cache, rec, header, bridge=bridge,
                                workers=decode_workers)
        if clock is not None:
            clock.note_bytes("decode",
                             sum(int(a.nbytes) for a in host.values()))
        if on_host_ready is not None:
            on_host_ready(i, host)
        return host

    pipelined = bool(decode_ahead) and n > 1
    # GC frozen over the whole decode→commit window (see _gc_frozen):
    # the deferred collect runs in the context exit, after ``dt`` is
    # captured — reclamation cost lands outside the timed region.
    with _gc_frozen():
        if pipelined:
            # One staging thread, one shard of lookahead: deeper
            # lookahead would only grow the host peak — the commit is
            # the narrower pipe and a single buffered shard already
            # keeps it fed.
            with ThreadPoolExecutor(
                    1, thread_name_prefix="zest-land-decode") as staging:
                pending = staging.submit(decode, 0)
                for i in range(n):
                    host = pending.result()
                    if i + 1 < n:
                        pending = staging.submit(decode, i + 1)
                    # One batched commit per checkpoint shard (see
                    # load_checkpoint's note: amortized transfer setup,
                    # file-bounded host peak); async dispatch means this
                    # returns while the transfer is still draining.
                    params.update(commit_tensors(host, mesh, rules,
                                                 dtype=dtype, donate=True))
                    del host
        else:
            for i in range(n):
                host = decode(i)
                params.update(commit_tensors(host, mesh, rules, dtype=dtype,
                                             donate=True))
                del host
        for arr in params.values():
            arr.block_until_ready()
        dt = time.monotonic() - t0
    stats = _commit_stats(params, dt, mesh, direct=True)
    stats["decode_ahead"] = pipelined
    return params, stats
