"""Optimizer-equipped training over the family models (optax).

The reference distributes checkpoints and never trains (SURVEY.md §2.4);
the TPU build's training plane does, so it needs more than the models'
inline SGD steps: this module adds the production loop — AdamW with
warmup+cosine schedule and global-norm clipping, a ``TrainState``, and a
jitted step factory that works with any family's ``loss_fn``
(gpt2/llama/moe) and any mesh.

Sharding needs no spec plumbing: optimizer moments are created eagerly
with ``zeros_like`` over the params and so inherit each param's
NamedSharding — land a checkpoint TP-sharded via zest_tpu.models.loader
and the whole optimizer state follows its layout (see
:func:`create_state` for why init stays out of jit).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def adamw(
    lr: float = 3e-4,
    weight_decay: float = 0.01,
    warmup_steps: int = 100,
    total_steps: int = 10_000,
    clip_norm: float = 1.0,
) -> optax.GradientTransformation:
    """The standard LLM recipe: linear warmup → cosine decay, AdamW,
    global-norm clipping. Weight decay is masked by *leaf name*, not
    ndim — the family trees stack layers on a leading axis, so a norm
    gain is (L, E) and raw dimensionality can't tell it from a matmul
    weight. Norm gains (``g``/``b``) and biases (``*_b``) are excluded,
    as in the GPT-3 / Llama training setups; embeddings decay."""
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr,
        warmup_steps=warmup_steps, decay_steps=total_steps,
    )
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(sched, weight_decay=weight_decay, mask=decay_mask),
    )


def decay_mask(params) -> Any:
    """True for leaves weight decay applies to, keyed on the tree path:
    norm gains/offsets (leaf ``g``/``b``) and biases (``*_b``) are
    excluded; matmul weights and embeddings are decayed."""
    import jax.tree_util as jtu

    def decide(path, _leaf):
        last = path[-1]
        key = last.key if hasattr(last, "key") else str(last)
        return not (key in ("g", "b") or key.endswith("_b"))

    return jtu.tree_map_with_path(decide, params)


def create_state(params, tx: optax.GradientTransformation) -> TrainState:
    """Fresh state; moments inherit the params' shardings (zeros_like).

    Call this EAGERLY (not under jit): eager ``zeros_like`` of a sharded
    array keeps its NamedSharding, whereas under jit GSPMD is free to
    choose output shardings unless constrained — init runs once, so
    there is nothing to win by compiling it.
    """
    return TrainState(jnp.zeros((), jnp.int32), params, tx.init(params))


def make_train_step(
    tx: optax.GradientTransformation,
    loss_fn: Callable,
) -> Callable:
    """``step(state, batch) -> (state, loss)``, jitted.

    ``loss_fn(params, batch) -> scalar`` — partial in the family module's
    config first (e.g. ``functools.partial(llama.loss_fn, cfg=cfg)``).
    Under a mesh, GSPMD propagates the param/batch shardings through
    grads, optimizer update, and the new state. The incoming state is
    DONATED — its buffers are dead after the call, and without donation
    peak HBM doubles (old + new params and both moment trees live at
    once), which OOMs meshes that otherwise fit. Corollary: don't keep
    other references to the state's buffers (note ``device_put`` with a
    replicated spec can *alias* its source rather than copy).
    """

    @functools.partial(jax.jit, donate_argnums=0)
    def step(state: TrainState, batch) -> tuple[TrainState, jax.Array]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(state.step + 1, params, opt_state), loss

    return step
