"""Sparse-MoE flagship: Mixtral-style mixture-of-experts in pure JAX.

BASELINE config #4 is "Mixtral-8x7B expert-sharded": the pull pipeline must
route each expert's xorbs to the host that will hold that expert
(zest_tpu.parallel.expert) and the landed checkpoint must be consumable by
an expert-parallel model. This module is that consumer — the same role
models/gpt2.py plays for config #1's verify-model loop
(test/local/verify-model.sh:90-147 in the reference).

Design notes (TPU-first):
- experts are *stacked*: every MoE leaf carries a leading (layer, expert)
  pair of axes, so one ``P(None, EXPERT_AXIS, ...)`` spec shards all
  experts and ``lax.scan`` over layers compiles one block.
- token→expert dispatch is the GShard/Mesh-TF einsum formulation: a dense
  one-hot dispatch tensor of static shape [tokens, experts, capacity] and
  two einsums around the expert FFN. No gather/scatter, no ragged shapes —
  everything lands on the MXU, and GSPMD turns the dispatch einsums into
  the expert all-to-all when experts are sharded.
- the expert axis doubles as the tensor-parallel axis for the dense
  (attention) params — the standard TP=EP group layout — so one 2-D
  ``{data, expert}`` mesh covers the whole model.
- RMSNorm + RoPE + GQA + SwiGLU match the Mixtral architecture family so
  real checkpoints map on (HF tensor names in ``params_from_hf``).
"""

from __future__ import annotations

import dataclasses
import math
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    n_ctx: int = 4096
    n_embd: int = 4096
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 8
    d_ff: int = 14336
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    rms_eps: float = 1e-5
    rope_theta: float = 1e6
    aux_loss_weight: float = 1e-2

    @staticmethod
    def tiny(**over) -> "MoEConfig":
        """Test/dryrun-sized config (divisible by 4-wide expert axes)."""
        base = dict(vocab_size=256, n_ctx=64, n_embd=64, n_layer=2,
                    n_head=4, n_kv_head=2, d_ff=128, n_experts=8, top_k=2)
        base.update(over)
        return MoEConfig(**base)

    @staticmethod
    def mixtral_8x7b() -> "MoEConfig":
        return MoEConfig()  # defaults are Mixtral-8x7B's config.json

    @staticmethod
    def from_hf(cfg_json: dict) -> "MoEConfig":
        return MoEConfig(
            vocab_size=cfg_json["vocab_size"],
            n_ctx=cfg_json.get("max_position_embeddings", 4096),
            n_embd=cfg_json["hidden_size"],
            n_layer=cfg_json["num_hidden_layers"],
            n_head=cfg_json["num_attention_heads"],
            n_kv_head=cfg_json.get("num_key_value_heads",
                                   cfg_json["num_attention_heads"]),
            d_ff=cfg_json["intermediate_size"],
            n_experts=cfg_json.get("num_local_experts", 8),
            top_k=cfg_json.get("num_experts_per_tok", 2),
            rms_eps=cfg_json.get("rms_norm_eps", 1e-5),
            rope_theta=cfg_json.get("rope_theta", 1e6),
        )

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


# ── Parameters ──


def init_params(rng: jax.Array, cfg: MoEConfig, dtype=jnp.float32) -> dict:
    """Random-init tree; MoE leaves are stacked [layer, expert, ...]."""
    E, L, X, F = cfg.n_embd, cfg.n_layer, cfg.n_experts, cfg.d_ff
    D, kvE = cfg.head_dim, cfg.n_kv_head * cfg.head_dim
    k = iter(jax.random.split(rng, 12))

    def dense(key, shape, scale=0.02):
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    return {
        "wte": dense(next(k), (cfg.vocab_size, E)),
        "ln_f": {"g": jnp.ones((E,), dtype)},
        "lm_head": dense(next(k), (E, cfg.vocab_size)),
        "blocks": {
            "ln_attn": {"g": jnp.ones((L, E), dtype)},
            "ln_moe": {"g": jnp.ones((L, E), dtype)},
            "attn": {
                "q_w": dense(next(k), (L, E, E)),
                "k_w": dense(next(k), (L, E, kvE)),
                "v_w": dense(next(k), (L, E, kvE)),
                "o_w": dense(next(k), (L, E, E),
                             0.02 / math.sqrt(2 * L)),
            },
            "moe": {
                "router_w": dense(next(k), (L, E, X)),
                # SwiGLU expert FFN: w1 gate, w3 up, w2 down (HF names)
                "w1": dense(next(k), (L, X, E, F)),
                "w3": dense(next(k), (L, X, E, F)),
                "w2": dense(next(k), (L, X, F, E),
                            0.02 / math.sqrt(2 * L)),
            },
        },
    }


# ── HF checkpoint mapping (Mixtral tensor names) ──

_HF_ATTN = {
    "self_attn.q_proj": ("attn", "q_w"),
    "self_attn.k_proj": ("attn", "k_w"),
    "self_attn.v_proj": ("attn", "v_w"),
    "self_attn.o_proj": ("attn", "o_w"),
}
_HF_NORM = {
    "input_layernorm": ("ln_attn", "g"),
    "post_attention_layernorm": ("ln_moe", "g"),
}


def expert_of_tensor(name: str) -> int | None:
    """Expert index owning a checkpoint tensor, or None for dense/shared.

    Understands the HF Mixtral layout (…block_sparse_moe.experts.N.w1…);
    this is the routing key zest_tpu.parallel.expert uses to decide which
    host's xorbs a tensor's bytes belong to.
    """
    m = re.search(r"\bexperts\.(\d+)\b", name)
    return int(m.group(1)) if m else None


def params_from_hf(
    tensors: dict[str, np.ndarray], cfg: MoEConfig, dtype=jnp.float32
) -> dict:
    """Map a Mixtral-family HF checkpoint onto the stacked param tree.

    HF Linear weights are stored [out, in]; everything is transposed into
    the x @ W layout on the way in. Per-(layer, expert) tensors stack into
    the [L, X, ...] leaves. Missing tensors raise with their names.
    """
    E, L, X = cfg.n_embd, cfg.n_layer, cfg.n_experts

    def take(name):
        arr = tensors.get(name)
        if arr is None:
            raise ValueError(f"checkpoint missing {name}")
        return np.asarray(arr)

    out = {
        "wte": jnp.asarray(take("model.embed_tokens.weight"), dtype),
        "ln_f": {"g": jnp.asarray(take("model.norm.weight"), dtype)},
        "lm_head": jnp.asarray(take("lm_head.weight").T, dtype),
    }
    blocks: dict = {
        "ln_attn": {"g": []}, "ln_moe": {"g": []},
        "attn": {leaf: [] for _, leaf in _HF_ATTN.values()},
        "moe": {"router_w": [], "w1": [], "w3": [], "w2": []},
    }
    for layer in range(L):
        pre = f"model.layers.{layer}."
        for hf, (grp, leaf) in _HF_NORM.items():
            blocks[grp][leaf].append(take(f"{pre}{hf}.weight"))
        for hf, (grp, leaf) in _HF_ATTN.items():
            blocks[grp][leaf].append(take(f"{pre}{hf}.weight").T)
        blocks["moe"]["router_w"].append(
            take(f"{pre}block_sparse_moe.gate.weight").T
        )
        for leaf in ("w1", "w3", "w2"):
            per_expert = [
                take(f"{pre}block_sparse_moe.experts.{x}.{leaf}.weight").T
                for x in range(X)
            ]
            blocks["moe"][leaf].append(np.stack(per_expert))
    out["blocks"] = jax.tree.map(
        lambda leaves: jnp.asarray(np.stack(leaves), dtype),
        blocks, is_leaf=lambda v: isinstance(v, list),
    )
    return out


# ── Sharding (data + expert parallel; expert axis doubles as TP) ──


def param_specs(cfg: MoEConfig) -> dict:
    """PartitionSpec tree matching ``init_params``.

    Experts shard over EXPERT_AXIS on their stacked axis — each mesh slot
    holds n_experts / axis_size experts, the layout
    zest_tpu.parallel.expert routes checkpoint bytes to. Attention rides
    the same axis Megatron-style (heads on q/k/v out-dim, o on in-dim).
    """
    return {
        "wte": P(),
        "ln_f": {"g": P()},
        "lm_head": P(None, EXPERT_AXIS),
        "blocks": {
            "ln_attn": {"g": P()},
            "ln_moe": {"g": P()},
            "attn": {
                "q_w": P(None, None, EXPERT_AXIS),
                "k_w": P(None, None, EXPERT_AXIS),
                "v_w": P(None, None, EXPERT_AXIS),
                "o_w": P(None, EXPERT_AXIS, None),
            },
            "moe": {
                "router_w": P(),
                "w1": P(None, EXPERT_AXIS, None, None),
                "w3": P(None, EXPERT_AXIS, None, None),
                "w2": P(None, EXPERT_AXIS, None, None),
            },
        },
    }


def checkpoint_shard_rules() -> list[tuple[str, P]]:
    """Name-pattern rules for landing raw HF Mixtral safetensors via
    zest_tpu.models.loader (HF [out, in] orientation).

    Raw landing balances *bytes* across the mesh; per-expert tensors
    shard their feature dims TP-style here. Expert *placement* (which
    host's cache owns which expert's xorbs) is the separate routing
    concern handled by zest_tpu.parallel.expert during the pull; the
    stacked expert-parallel tree layout comes from ``params_from_hf`` +
    ``param_specs`` afterwards.
    """
    return [
        (r"self_attn\.[qkv]_proj\.weight$", P(EXPERT_AXIS, None)),
        (r"self_attn\.o_proj\.weight$", P(None, EXPERT_AXIS)),
        (r"experts\.\d+\.w[13]\.weight$", P(EXPERT_AXIS, None)),
        (r"experts\.\d+\.w2\.weight$", P(None, EXPERT_AXIS)),
        (r"block_sparse_moe\.gate\.weight$", P()),
        (r"^lm_head\.weight$", P(EXPERT_AXIS, None)),
    ]


# ── Forward ──


def _rms_norm(x, g, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * g


def _rope(x, theta, pos0=0):
    """Rotary embedding over (B, T, H, D) with D split in interleaved
    halves; ``pos0`` offsets positions (incremental decode)."""
    B, T, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = pos0 + jnp.arange(T, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos[None, :, None, :].astype(x.dtype)
                           - x2 * sin[None, :, None, :].astype(x.dtype),
                           x1 * sin[None, :, None, :].astype(x.dtype)
                           + x2 * cos[None, :, None, :].astype(x.dtype)],
                          axis=-1)
    return rot


def _attention(x, p, cfg: MoEConfig):
    B, T, E = x.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    q = (x @ p["q_w"]).reshape(B, T, H, D)
    k = (x @ p["k_w"]).reshape(B, T, KV, D)
    v = (x @ p["v_w"]).reshape(B, T, KV, D)
    q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)
    if KV != H:  # GQA: broadcast kv heads across their query group
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    scores = (q @ k.transpose(0, 1, 3, 2)) / math.sqrt(D)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
    out = (att @ v).transpose(0, 2, 1, 3).reshape(B, T, E)
    return out @ p["o_w"]


def _capacity(n_tokens: int, cfg: MoEConfig) -> int:
    cap = int(cfg.capacity_factor * cfg.top_k * n_tokens / cfg.n_experts)
    return max(cap, cfg.top_k)


def _moe_block(x, p, cfg: MoEConfig):
    """Top-k expert FFN via dense dispatch einsums. Returns (out, aux_loss).

    x: (B, T, E). Static-shape GShard dispatch: tokens over capacity C per
    expert; overflow tokens drop to the residual path (standard capacity
    semantics — the router aux loss keeps overflow rare).
    """
    B, T, E = x.shape
    N, X = B * T, cfg.n_experts
    C = _capacity(N, cfg)
    flat = x.reshape(N, E)

    logits = (flat @ p["router_w"]).astype(jnp.float32)      # (N, X)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, cfg.top_k)    # (N, k)
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)  # Mixtral

    # Load-balance aux loss (Switch §2.2): X * Σ_e fraction_e * prob_e.
    sel = jax.nn.one_hot(gate_idx[:, 0], X)                  # top-1 counts
    aux = X * jnp.sum(sel.mean(0) * probs.mean(0))

    # Position of each (token, slot) in its expert's capacity buffer.
    onehot = jax.nn.one_hot(gate_idx, X, dtype=jnp.int32)    # (N, k, X)
    flat_sel = onehot.reshape(N * cfg.top_k, X)
    pos = jnp.cumsum(flat_sel, axis=0) * flat_sel - 1        # (N*k, X)
    pos = pos.reshape(N, cfg.top_k, X)
    in_cap = (pos >= 0) & (pos < C)

    # combine[n, x, c] = gate weight of token n in slot c of expert x
    pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, C - 1), C)      # (N, k, X, C)
    combine = jnp.einsum(
        "nk,nkxc->nxc",
        gate_vals.astype(x.dtype),
        (pos_oh * in_cap[..., None]).astype(x.dtype),
    )
    dispatch = (combine > 0).astype(x.dtype)                 # (N, X, C)

    expert_in = jnp.einsum("nxc,ne->xce", dispatch, flat)    # (X, C, E)
    h = jnp.einsum("xce,xef->xcf", expert_in, p["w1"])
    up = jnp.einsum("xce,xef->xcf", expert_in, p["w3"])
    h = jax.nn.silu(h) * up                                  # SwiGLU
    expert_out = jnp.einsum("xcf,xfe->xce", h, p["w2"])
    out = jnp.einsum("nxc,xce->ne", combine, expert_out)
    return out.reshape(B, T, E), aux


def forward(
    params: dict, input_ids: jax.Array, cfg: MoEConfig,
    remat: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(B, T) ids → ((B, T, vocab) logits, scalar aux loss). Jittable."""
    x = params["wte"][input_ids]

    def body(carry, layer_params):
        x, aux = carry
        h = _rms_norm(x, layer_params["ln_attn"]["g"], cfg.rms_eps)
        x = x + _attention(h, layer_params["attn"], cfg)
        h = _rms_norm(x, layer_params["ln_moe"]["g"], cfg.rms_eps)
        moe_out, layer_aux = _moe_block(h, layer_params["moe"], cfg)
        return (x + moe_out, aux + layer_aux), None

    if remat:
        # Per-layer rematerialization — especially valuable here, where
        # the dispatch tensors ([tokens, experts, capacity]) dominate
        # activation memory.
        body = jax.checkpoint(body)
    (x, aux), _ = jax.lax.scan(
        body, (x, jnp.float32(0.0)), params["blocks"]
    )
    x = _rms_norm(x, params["ln_f"]["g"], cfg.rms_eps)
    return x @ params["lm_head"], aux / cfg.n_layer


# ── Incremental decode (serving) ──


def init_kv_cache(cfg: MoEConfig, batch: int, max_len: int,
                  dtype=jnp.float32) -> dict:
    """Static-shape per-layer K/V cache: (L, B, max_len, KV, head_dim)."""
    shape = (cfg.n_layer, batch, max_len, cfg.n_kv_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def decode_window(params, cache: dict, tokens: jax.Array, pos,
                  cfg: MoEConfig, last_only: bool = False):
    """Cached step over a token window: (B, S) ids occupying positions
    ``pos``..``pos+S-1`` → ((B, S, vocab) logits, updated cache).
    S=1 is one incremental decode step; S=len(prompt) is the batched
    prefill. Every token dispatches with per-token expert capacity
    (≥ top_k), so no token ever drops to the residual path — the
    correct serving semantics (the training-time capacity contention
    is a batch phenomenon), identical for any window size."""
    B, S = tokens.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    x = params["wte"][tokens]                              # (B, S, E)

    def body(carry, inp):
        x, pos = carry
        lp, ck, cv = inp
        h = _rms_norm(x, lp["ln_attn"]["g"], cfg.rms_eps)
        q = (h @ lp["attn"]["q_w"]).reshape(B, S, H, D)
        k = (h @ lp["attn"]["k_w"]).reshape(B, S, KV, D)
        v = (h @ lp["attn"]["v_w"]).reshape(B, S, KV, D)
        q, k = _rope(q, cfg.rope_theta, pos), _rope(k, cfg.rope_theta, pos)
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, pos, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, pos, axis=1)
        kk, vv = ck, cv
        if KV != H:
            kk = jnp.repeat(kk, H // KV, axis=2)
            vv = jnp.repeat(vv, H // KV, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / math.sqrt(D)
        valid = (jnp.arange(ck.shape[1])[None, :]
                 <= pos + jnp.arange(S)[:, None])
        scores = jnp.where(valid[None, None, :, :], scores,
                           jnp.finfo(scores.dtype).min)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", att.astype(x.dtype), vv)
        x = x + out.reshape(B, S, cfg.n_embd) @ lp["attn"]["o_w"]
        h = _rms_norm(x, lp["ln_moe"]["g"], cfg.rms_eps)
        # vmap over every token (batch × window): each dispatches with
        # its own capacity (C >= top_k), so windowed decode never hits
        # the batch-capacity contention of the training-time dispatch.
        moe_out = jax.vmap(
            lambda hh: _moe_block(hh[None, None], lp["moe"], cfg)[0][0, 0]
        )(h.reshape(B * S, cfg.n_embd)).reshape(B, S, cfg.n_embd)
        return (x + moe_out, pos), (ck, cv)

    (x, _), (new_k, new_v) = jax.lax.scan(
        body, (x, pos), (params["blocks"], cache["k"], cache["v"])
    )
    x = _rms_norm(x, params["ln_f"]["g"], cfg.rms_eps)
    if last_only:
        # Prefill wants one next-token distribution: skip the (B, S,
        # vocab) unembedding for all but the final position.
        x = x[:, -1:, :]
    return x @ params["lm_head"], {"k": new_k, "v": new_v}


def decode_step(params, cache: dict, token: jax.Array, pos, cfg: MoEConfig):
    """One incremental decode step: (B,) ids at ``pos`` → ((B, vocab)
    logits, updated cache); the S=1 specialization of
    :func:`decode_window`."""
    logits, cache = decode_window(params, cache, token[:, None], pos, cfg)
    return logits[:, 0, :], cache


def generate_cached(params, cfg: MoEConfig, prompt_ids, steps: int,
                    temperature: float = 0.0, top_k: int | None = None,
                    top_p: float | None = None,
                    rng: jax.Array | None = None,
                    eos_id: int | tuple[int, ...] | None = None,
                    on_token=None):
    """KV-cached decode (O(T) per token; sampling.cached_decode_loop);
    greedy by default, sampling via ``temperature``/``top_k``."""
    from zest_tpu.models.sampling import cached_decode_loop

    return cached_decode_loop(
        init_kv_cache, decode_step, params, cfg, prompt_ids, steps,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
        eos_id=eos_id, on_token=on_token,
        prefill_step=decode_window,
    )


def loss_fn(params, batch, cfg: MoEConfig, remat: bool = False):
    inputs, targets = batch[:, :-1], batch[:, 1:]
    logits, aux = forward(params, inputs, cfg, remat=remat)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll) + cfg.aux_loss_weight * aux


def train_step(params, batch, cfg: MoEConfig, lr: float = 1e-3,
               remat: bool = False):
    """One SGD step; under a {data, expert} mesh GSPMD inserts the expert
    all-to-alls around the dispatch einsums and the DP gradient psum.
    ``remat=True`` applies per-layer jax.checkpoint."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, remat)
    params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                          params, grads)
    return params, loss
