"""Model-family registry: config.json → landing shard rules.

The reference is model-agnostic — it reassembles files and lets torch
load them later (SURVEY.md §3.1). The TPU build lands tensors into mesh
HBM during the pull, so it must know *how a family shards* at landing
time. This module is that dispatch: read the snapshot's ``config.json``
``model_type`` and return the family's ``checkpoint_shard_rules`` for
zest_tpu.models.loader. Unknown families return ``None`` — the loader's
``infer_spec`` fallback (shard the largest divisible dim) still lands
them balanced, just without family-aware TP placement.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:  # the alias is annotation-only; keep jax off the
    from zest_tpu.models.loader import ShardRules  # import path here


def _gpt2_rules() -> ShardRules:
    from zest_tpu.models import gpt2

    return gpt2.checkpoint_shard_rules()


def _llama_rules() -> ShardRules:
    from zest_tpu.models import llama

    return llama.checkpoint_shard_rules()


def _moe_rules() -> ShardRules:
    from zest_tpu.models import moe

    return moe.checkpoint_shard_rules()


# model_type (HF config.json) → rules factory. Mistral/Qwen dense share
# the Llama tensor layout; Mixtral is the expert-sharded family.
_FAMILIES: dict[str, Callable[[], ShardRules]] = {
    "gpt2": _gpt2_rules,
    "llama": _llama_rules,
    "mistral": _llama_rules,
    "qwen2": _llama_rules,
    "mixtral": _moe_rules,
}


# Families whose checkpoints carry per-expert weight tensors: their
# pulls route expert-private xorbs to the owner host instead of
# all-gathering every byte (BASELINE config #4, transfer.pod.
# expert_pod_round). The reference replicates whole files to every
# asker (src/swarm.zig:279-314); this set is what opts a family out.
_EXPERT_SHARDED = {"mixtral"}


def is_expert_sharded(model_type: str | None) -> bool:
    return (model_type or "") in _EXPERT_SHARDED


def shard_rules_for_model_type(model_type: str | None) -> ShardRules | None:
    factory = _FAMILIES.get(model_type or "")
    return factory() if factory else None


def detect_model_type(snapshot_dir: str | Path) -> str | None:
    """``model_type`` from the snapshot's config.json, or None."""
    cfg_path = Path(snapshot_dir) / "config.json"
    try:
        cfg = json.loads(cfg_path.read_text())
    except (OSError, ValueError):
        return None
    # Valid-but-non-object JSON (a list, a bare string) is still "no
    # detectable family", not an exception.
    return cfg.get("model_type") if isinstance(cfg, dict) else None


def shard_rules_for_snapshot(snapshot_dir: str | Path) -> ShardRules | None:
    return shard_rules_for_model_type(detect_model_type(snapshot_dir))
