"""Model-family registry: config.json → landing shard rules.

The reference is model-agnostic — it reassembles files and lets torch
load them later (SURVEY.md §3.1). The TPU build lands tensors into mesh
HBM during the pull, so it must know *how a family shards* at landing
time. This module is that dispatch: read the snapshot's ``config.json``
``model_type`` and return the family's ``checkpoint_shard_rules`` for
zest_tpu.models.loader. Unknown families return ``None`` — the loader's
``infer_spec`` fallback (shard the largest divisible dim) still lands
them balanced, just without family-aware TP placement.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import TYPE_CHECKING, Callable, Iterable

if TYPE_CHECKING:  # the alias is annotation-only; keep jax off the
    from zest_tpu.models.loader import ShardRules  # import path here


def _gpt2_rules() -> ShardRules:
    from zest_tpu.models import gpt2

    return gpt2.checkpoint_shard_rules()


def _llama_rules() -> ShardRules:
    from zest_tpu.models import llama

    return llama.checkpoint_shard_rules()


def _moe_rules() -> ShardRules:
    from zest_tpu.models import moe

    return moe.checkpoint_shard_rules()


# model_type (HF config.json) → rules factory. Mistral/Qwen dense share
# the Llama tensor layout; Mixtral is the expert-sharded family.
_FAMILIES: dict[str, Callable[[], ShardRules]] = {
    "gpt2": _gpt2_rules,
    "llama": _llama_rules,
    "mistral": _llama_rules,
    "qwen2": _llama_rules,
    "mixtral": _moe_rules,
}


# Families whose checkpoints carry per-expert weight tensors: their
# pulls route expert-private xorbs to the owner host instead of
# all-gathering every byte (BASELINE config #4, transfer.pod.
# expert_pod_round). The reference replicates whole files to every
# asker (src/swarm.zig:279-314); this set is what opts a family out.
_EXPERT_SHARDED = {"mixtral"}


def is_expert_sharded(model_type: str | None) -> bool:
    return (model_type or "") in _EXPERT_SHARDED


def shard_rules_for_model_type(model_type: str | None) -> ShardRules | None:
    factory = _FAMILIES.get(model_type or "")
    return factory() if factory else None


def detect_model_type(snapshot_dir: str | Path) -> str | None:
    """``model_type`` from the snapshot's config.json, or None."""
    cfg_path = Path(snapshot_dir) / "config.json"
    try:
        cfg = json.loads(cfg_path.read_text())
    except (OSError, ValueError):
        return None
    # Valid-but-non-object JSON (a list, a bare string) is still "no
    # detectable family", not an exception.
    return cfg.get("model_type") if isinstance(cfg, dict) else None


def shard_rules_for_snapshot(snapshot_dir: str | Path) -> ShardRules | None:
    return shard_rules_for_model_type(detect_model_type(snapshot_dir))


# ── Landing order: which tensors a serving mesh needs first ──
#
# The streaming landing (models.loader._stage_streaming) commits
# tensors in "usefulness" order — the Petals insight applied to
# loading: a decoder can start token generation once the embedding and
# layer 0 are resident, while layer N is still on the wire. The
# priority is a pure function of the tensor NAME so every host (and
# the cooperative fetch ordering in transfer.coop) computes the same
# order with no coordination.

# Per-layer tensors across the families the registry knows: Llama/
# Mistral/Qwen/Mixtral use ``model.layers.N.``, GPT-2 uses ``h.N.``
# (optionally ``transformer.h.N.``), generic exports use ``blocks.N.``.
_LAYER_RE = re.compile(r"(?:^|\.)(?:layers|h|blocks)\.(\d+)\.")
# Embedding tensors — needed before ANY layer can run.
_EMBED_RE = re.compile(
    r"(?:^|\.)(?:embed_tokens|tok_embeddings|embed_positions|wte|wpe)"
    r"(?:$|\.)")

# Priority groups: 0 = embeddings, 1 = transformer layers (by index),
# 2 = everything else (final norm, lm_head, unclassified) — the
# tensors a forward pass touches LAST.
LayerPriority = tuple[int, int]


def layer_priority(name: str) -> LayerPriority:
    """Sortable landing priority for one tensor name.

    ``(group, layer_index)`` — embeddings first, then layer 0, 1, ...,
    then the rest. Comparisons are total, so any tensor set sorts
    deterministically; unrecognized names all land in the tail group
    (sorted stably, i.e. file order) — an unknown checkpoint streams in
    file order, losing nothing."""
    m = _LAYER_RE.search(name)
    if m:
        return (1, int(m.group(1)))
    if _EMBED_RE.search(name):
        return (0, 0)
    return (2, 0)


def order_names(names: Iterable[str]) -> list[str]:
    """Names in landing order — a STABLE sort by :func:`layer_priority`
    so equal-priority tensors keep their original (file) order, which
    keeps the streaming decode walking the shard mostly forward."""
    return sorted(names, key=layer_priority)


def first_layer_names(names: Iterable[str]) -> frozenset[str]:
    """The first-token-capable set: embeddings plus every tensor of the
    lowest-indexed layer present. ``time_to_first_layer_s`` is the
    instant this whole set is resident in HBM.

    A checkpoint with no recognizable layer structure returns the FULL
    set — "first layer usable" then honestly coincides with the whole
    landing instead of claiming an early readiness no forward pass
    could use."""
    names = list(names)
    by_prio = [(layer_priority(n), n) for n in names]
    layer_idxs = [p[1] for p, _n in by_prio if p[0] == 1]
    if not layer_idxs:
        return frozenset(names)
    first = min(layer_idxs)
    return frozenset(
        n for p, n in by_prio
        if p[0] == 0 or p == (1, first)
    )
