"""Snapshot → running model: build a family model from a pulled snapshot
and decode.

This closes the reference's verify loop natively (`zest pull` then "load
with transformers and generate", test/local/verify-model.sh:103-147):
here the pulled safetensors feed the pure-JAX family modules directly —
no torch on the path — selected by the same config.json dispatch the
landing registry uses (zest_tpu.models.registry).
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np


class UnsupportedModelError(ValueError):
    """config.json names a family with no generation support."""


GENERATE_FAMILIES = ("gpt2", "llama", "mistral", "qwen2", "mixtral")

_COMPILE_CACHE_ARMED = False


def enable_compile_cache() -> str | None:
    """Arm jax's persistent compilation cache for the serving path.

    The daemon's first `/v1/generate` for a model pays the decode-loop
    XLA compile — the dominant share of serve cold-start (VERDICT r5
    weak #5: first_s 7.5 s against a ≤3 s target). Compiled executables
    are a pure function of (program, jax version, backend), so they are
    *machine*-state, not repo-cache state: persisting them under
    ``~/.cache/zest/jit-cache`` (override: ``ZEST_JIT_CACHE=path``,
    disable: ``ZEST_JIT_CACHE=0``) makes every daemon restart — the
    cold start users actually repeat — hit the cache and compile in
    milliseconds. First-ever compile on a machine still pays full
    price; nothing else can avoid that honestly.

    Idempotent; returns the cache dir in use, or None when disabled or
    unavailable (old jax). Hermetic tests disable it via conftest so
    test runs never write to the user's home."""
    global _COMPILE_CACHE_ARMED
    import os

    spec = os.environ.get("ZEST_JIT_CACHE", "").strip()
    if spec == "0":
        return None
    path = spec or os.path.expanduser("~/.cache/zest/jit-cache")
    if _COMPILE_CACHE_ARMED:
        return path
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Default thresholds skip "cheap" compiles — but a tiny model's
        # 2-4 s CPU decode-loop compile is exactly the cold start being
        # cut, so cache everything.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    except Exception:  # noqa: BLE001 - cache is an accelerator, never a gate
        return None
    _COMPILE_CACHE_ARMED = True
    return path


def snapshot_tensors(snapshot_dir: str | Path) -> dict[str, np.ndarray]:
    """All tensors of a snapshot as a flat host-side name→numpy dict
    (the input ``params_from_hf`` wants; contrast loader.load_checkpoint,
    which lands on device). Public — examples and user code build on it.
    """
    from zest_tpu.models.loader import snapshot_files
    from zest_tpu.models.safetensors_io import SafetensorsFile

    tensors: dict[str, np.ndarray] = {}
    for path in snapshot_files(Path(snapshot_dir)):
        with SafetensorsFile(path) as sf:
            for name in sf.names():
                tensors[name] = sf.tensor(name)
    if not tensors:
        raise FileNotFoundError(
            f"no .safetensors files under {snapshot_dir}"
        )
    return tensors



def load_generator(snapshot_dir: str | Path):
    """Build ``(model_type, generate_fn)`` from a pulled snapshot.

    ``generate_fn(prompt_ids, steps, temperature=0.0, top_k=None,
    top_p=None, seed=0, stop_at_eos=True, on_token=None) -> np.ndarray``
    decodes with a KV cache (O(T) per token, every family); greedy by
    default, sampling when ``temperature>0``, optionally top-k- and/or
    nucleus-restricted. When the snapshot's config.json names an
    ``eos_token_id`` and ``stop_at_eos`` is true, generation freezes
    rows at their first generated EOS and the returned ids are trimmed
    just past it (HF stop semantics; pass ``stop_at_eos=False`` for the
    full fixed-length buffer). ``on_token(pos, tokens)`` streams each
    *generated* position from inside the compiled decode (the prompt
    lands in one prefill dispatch; see sampling.cached_decode_loop).
    Raises
    :class:`UnsupportedModelError` for families without generation
    support and ``FileNotFoundError`` for missing config/weights.
    """
    snapshot_dir = Path(snapshot_dir)
    cfg_json = json.loads((snapshot_dir / "config.json").read_text())
    model_type = cfg_json.get("model_type")
    if model_type not in GENERATE_FAMILIES:
        raise UnsupportedModelError(
            f"model_type {model_type!r} has no generation support "
            f"(supported: {', '.join(GENERATE_FAMILIES)})"
        )
    # Armed before any compile: a daemon restart then replays the
    # decode-loop executable from the persistent cache instead of
    # re-paying serve cold-start's dominant term.
    enable_compile_cache()
    tensors = snapshot_tensors(snapshot_dir)

    if model_type == "gpt2":
        from zest_tpu.models import gpt2 as fam

        cfg = fam.GPT2Config.from_hf(cfg_json)
    elif model_type == "mixtral":
        from zest_tpu.models import moe as fam

        cfg = fam.MoEConfig.from_hf(cfg_json)
    else:  # llama family
        from zest_tpu.models import llama as fam

        cfg = fam.LlamaConfig.from_hf(cfg_json)
    params = fam.params_from_hf(tensors, cfg)
    decode = fam.generate_cached
    eos_ids = _eos_token_ids(cfg_json)

    def generate(prompt_ids, steps, temperature=0.0, top_k=None,
                 top_p=None, seed=0, stop_at_eos=True, on_token=None):
        import jax

        eos = eos_ids if stop_at_eos else None
        out = np.asarray(decode(
            params, cfg, prompt_ids, steps, temperature=temperature,
            top_k=top_k, top_p=top_p, rng=jax.random.key(seed),
            eos_id=eos, on_token=on_token,
        ))
        if eos is not None:
            out = trim_at_eos(out, np.shape(prompt_ids)[-1], eos)
        return out

    generate.eos_ids = eos_ids  # callers (SSE streaming) filter on it
    return model_type, generate


def _eos_token_ids(cfg_json: dict) -> tuple[int, ...] | None:
    """config.json's ``eos_token_id`` as a tuple of stop ids (HF allows
    a single int OR a list of several, e.g. Llama-3's two ids — all of
    them stop generation) or None when absent."""
    from zest_tpu.models.sampling import normalize_eos

    return normalize_eos(cfg_json.get("eos_token_id"))


def trim_at_eos(out: np.ndarray, n_prompt: int,
                eos_id: int | tuple[int, ...]) -> np.ndarray:
    """Cut a decoded row just past its first *generated* stop id — one
    id or several (prompt occurrences don't count). Batched (B, T)
    input keeps its rectangular shape — frozen rows already pad with
    the first stop id, so trimming to the longest row loses nothing."""
    from zest_tpu.models.sampling import normalize_eos

    eos_ids = normalize_eos(eos_id)
    if eos_ids is None:
        return out
    if out.ndim == 2:
        keep = 0
        for row in out:
            keep = max(keep, _row_end(row, n_prompt, eos_ids))
        return out[:, :keep]
    return out[: _row_end(out, n_prompt, eos_ids)]


def _row_end(row: np.ndarray, n_prompt: int,
             eos_ids: tuple[int, ...]) -> int:
    hits = np.nonzero(np.isin(row[n_prompt:], eos_ids))[0]
    return len(row) if hits.size == 0 else n_prompt + int(hits[0]) + 1


# Files whose presence means "this snapshot ships a tokenizer". Checked
# BEFORE importing transformers: that import costs ~20 s cold (it pulls
# in torch) and was the dominant term of serve cold-start (VERDICT r5
# weak #5, first_s 7.5 s) — paid even for snapshots with no tokenizer
# at all, where the import's only job was to fail.
_TOKENIZER_FILES = (
    "tokenizer.json", "tokenizer_config.json", "tokenizer.model",
    "spiece.model", "vocab.json", "vocab.txt", "merges.txt",
)


def try_tokenizer(snapshot_dir: str | Path):
    """The snapshot's tokenizer via transformers, or None (fixture repos
    and minimal pulls carry no tokenizer files; callers then work in raw
    token ids). Offline only — the snapshot is local by construction.
    The transformers import is gated on a tokenizer file actually being
    present, so tokenizer-less serving never pays it."""
    snapshot_dir = Path(snapshot_dir)
    if not any((snapshot_dir / n).exists() for n in _TOKENIZER_FILES):
        return None
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(
            str(snapshot_dir), local_files_only=True
        )
    except Exception:  # noqa: BLE001 - absence of a tokenizer is normal
        return None
