"""Model landing + flagship consumers of pulled checkpoints.

- safetensors_io: the checkpoint byte format (header → tensor byte ranges)
- loader: safetensors → (pjit-sharded) jax.Arrays in HBM
- registry: config.json model_type → family landing shard rules
- gpt2 / llama / moe: pure-JAX family models consuming the pulled bytes
- generate: snapshot → running model (the `zest-tpu generate` path)
- training: optax loop (AdamW, warmup+cosine, donation)
- checkpoint: orbax TrainState save/restore + HF safetensors export
"""

from zest_tpu.models.loader import (
    infer_spec,
    land_tensor,
    load_checkpoint,
    spec_for,
    stage_snapshot_to_hbm,
)
from zest_tpu.models.safetensors_io import (
    SafetensorsFile,
    parse_header,
    write_safetensors,
)

__all__ = [
    "SafetensorsFile",
    "parse_header",
    "write_safetensors",
    "infer_spec",
    "land_tensor",
    "load_checkpoint",
    "spec_for",
    "stage_snapshot_to_hbm",
]
