"""Model landing + flagship consumers of pulled checkpoints.

- safetensors_io: the checkpoint byte format (header → tensor byte ranges)
- loader: safetensors → (pjit-sharded) jax.Arrays in HBM
- gpt2: pure-JAX flagship model proving the pulled bytes run on the MXU
"""

from zest_tpu.models.loader import (
    infer_spec,
    land_tensor,
    load_checkpoint,
    spec_for,
    stage_snapshot_to_hbm,
)
from zest_tpu.models.safetensors_io import (
    SafetensorsFile,
    parse_header,
    write_safetensors,
)

__all__ = [
    "SafetensorsFile",
    "parse_header",
    "write_safetensors",
    "infer_spec",
    "land_tensor",
    "load_checkpoint",
    "spec_for",
    "stage_snapshot_to_hbm",
]
