"""``python -m zest_tpu`` — the CLI shim (reference: python/zest/cli.py)."""

from zest_tpu.cli import main

raise SystemExit(main())
