"""Fused on-device decode→verify: BG4 byte-plane regroup as a Pallas
kernel, chained in front of the BLAKE3 verify kernel — the device front
of ISSUE 3's decode engine.

ByteGrouping4 (cas.compression, the dominant tensor-data scheme) stores
a chunk as four byte planes — byte ``k`` of every 4-byte group,
contiguously — because fp32/bf16 exponent bytes compress far better
planar. The inverse transform (``out[4i+k] = plane_k[i]``) is a pure
byte shuffle: exactly the kind of work the EQuARX argument (PAPERS.md)
says belongs where the FLOPs are. With this kernel, a BG4 chunk's wire
payload crosses PCIe in its *planar* (still-compressed-form) layout and
is regrouped AND BLAKE3-verified in one fused device pass:

- stored BG4 frames (incompressible tails): the wire payload IS the
  device input — zero host transform, the bytes `device_put` as they
  arrived;
- LZ4-compressed BG4 frames: the host runs only the entropy stage
  (native LZ4, GIL-released) to planar bytes; the regroup — the full
  extra pass over every byte that `_bg4_inverse` used to burn host
  time on — moves to the VPU.

The regroup lowers as wide u32 lane ops, no gathers: the host stages
each plane at a word-aligned slot (capacity/4), so output word ``w``
is a static byte-pack of the four planes' word ``w//4`` — vectorized
over 128 chunk lanes like the BLAKE3 kernel's layout
(ops/blake3_pallas.py).

On non-TPU backends the kernel runs in interpreter mode; the identity
test against the host reference (tests/test_decode_engine.py) runs on
``JAX_PLATFORMS=cpu`` exactly as for the BLAKE3 kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zest_tpu.cas.blake3 import CHUNK_LEN, IV, KEYED_HASH
from zest_tpu.ops.blake3 import MAX_LEAVES, WORDS_PER_LEAF
from zest_tpu.ops.blake3_pallas import _CompilerParams, _hash_pallas

_U32 = jnp.uint32
_TILE = 128          # chunk lanes per grid step (Mosaic lane width)
_GROUP_WORDS = 256   # plane words per grid step (VMEM knob: ~2.5 MiB/step)


def bg4_plane_sizes(n: int) -> tuple[int, int, int, int]:
    """Byte count of each BG4 plane for an ``n``-byte chunk."""
    return tuple((n - k + 3) // 4 for k in range(4))


def _make_regroup_kernel(gw: int):
    """Kernel over grid (batch_tile, word_group): block in is the four
    planes' words (4, gw, T), block out the regrouped words (4·gw, T).
    Output word ``w = 4g + s`` packs byte ``s`` of each plane's word
    ``g`` — static shifts and masks only, no in-kernel gather."""

    def kernel(a_ref, out_ref):
        p = a_ref[:]                       # (4, gw, T) u32
        parts = []
        for s in range(4):
            sh = 8 * s
            b0 = (p[0] >> sh) & 0xFF
            b1 = (p[1] >> sh) & 0xFF
            b2 = (p[2] >> sh) & 0xFF
            b3 = (p[3] >> sh) & 0xFF
            parts.append(b0 | (b1 << 8) | (b2 << 16) | (b3 << 24))
        out_ref[:] = jnp.stack(parts, axis=1).reshape(4 * gw, p.shape[2])

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _regroup_pallas(planar_words, interpret):
    """(B, W) u32 planar words (plane k at word offset k·W/4) →
    (B, W) u32 regrouped words."""
    B, W = planar_words.shape
    if W % 4:
        raise ValueError("planar capacity must be a 16-byte multiple")
    w4 = W // 4  # words per plane

    pad_b = (-B) % _TILE
    if pad_b:
        planar_words = jnp.pad(planar_words, ((0, pad_b), (0, 0)))
    Bp = B + pad_b

    gw = min(_GROUP_WORDS, w4)
    n_groups = pl.cdiv(w4, gw)
    w4p = n_groups * gw
    # Planes split into separate leading-axis rows BEFORE the kernel, so
    # each grid step's block is a clean (4, gw, T) slab — padding the
    # per-plane word count never shifts a plane's base offset.
    planes = planar_words.reshape(Bp, 4, w4)
    if w4p != w4:
        planes = jnp.pad(planes, ((0, 0), (0, 0), (0, w4p - w4)))
    a = planes.transpose(1, 2, 0)                     # (4, w4p, Bp)

    out_t = pl.pallas_call(
        _make_regroup_kernel(gw),
        out_shape=jax.ShapeDtypeStruct((4 * w4p, Bp), _U32),
        grid=(Bp // _TILE, n_groups),
        in_specs=[
            pl.BlockSpec((4, gw, _TILE), lambda i, g: (0, g, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((4 * gw, _TILE), lambda i, g: (g, i),
                               memory_space=pltpu.VMEM),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(a)
    return out_t.T[:B, : 4 * w4]


@functools.partial(
    jax.jit, static_argnames=("key_words", "base_flags", "interpret")
)
def _fused_regroup_hash(planar_words, lengths, key_words, base_flags,
                        interpret):
    """The fused pass: BG4 regroup chained straight into the BLAKE3
    verify kernel (ops.blake3_pallas._hash_pallas) inside one jit — the
    interleaved bytes exist only on device."""
    words = _regroup_pallas(planar_words, interpret)
    return _hash_pallas(words, lengths.astype(jnp.int32), key_words,
                        base_flags, interpret)


class FusedBg4Verifier:
    """Drop-in sibling of ops.blake3_pallas.PallasHasher whose input is
    BG4 *planar* payloads: one call regroups and hashes on device."""

    def __init__(self, key: bytes | None = None,
                 interpret: bool | None = None):
        if key is not None:
            if len(key) != 32:
                raise ValueError("key must be 32 bytes")
            self.key_words = tuple(
                int(w) for w in np.frombuffer(key, dtype="<u4")
            )
            self.base_flags = int(KEYED_HASH)
        else:
            self.key_words = tuple(int(w) for w in IV)
            self.base_flags = 0
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret

    @staticmethod
    def stage_planar(payloads: list[bytes], lengths: list[int]):
        """Planar payloads → (words, lengths) device-ready arrays: each
        chunk's four planes land at word-aligned quarter-capacity slots
        (the kernel's static layout), zero-padded — a few memcpys per
        chunk, never a byte-level transform."""
        if len(payloads) != len(lengths):
            raise ValueError("payloads and lengths differ in count")
        max_len = max(lengths) if lengths else 0
        cap = max(
            (max_len + CHUNK_LEN - 1) // CHUNK_LEN * CHUNK_LEN, CHUNK_LEN
        )
        if cap > MAX_LEAVES * CHUNK_LEN:
            raise ValueError(
                f"chunks larger than {MAX_LEAVES} KiB unsupported"
            )
        slot = cap // 4
        buf = np.zeros((len(payloads), cap), dtype=np.uint8)
        for i, (payload, n) in enumerate(zip(payloads, lengths)):
            sizes = bg4_plane_sizes(n)
            if len(payload) != sum(sizes):
                raise ValueError(
                    f"planar payload {i} is {len(payload)} bytes for a "
                    f"{n}-byte chunk"
                )
            off = 0
            for k, size_k in enumerate(sizes):
                buf[i, k * slot : k * slot + size_k] = np.frombuffer(
                    payload, dtype=np.uint8, count=size_k, offset=off
                )
                off += size_k
        return (jnp.asarray(buf.view("<u4")),
                jnp.asarray(np.asarray(lengths, dtype=np.int32)))

    def hash_planar_device(self, words: jax.Array,
                           lengths: jax.Array) -> jax.Array:
        """(B, padded_words) u32 plane-slotted words + (B,) original
        chunk lengths → (B, 8) u32 digests of the REGROUPED bytes."""
        if words.shape[-1] % WORDS_PER_LEAF:
            raise ValueError("padded capacity must be a 1 KiB multiple")
        return _fused_regroup_hash(words, lengths, self.key_words,
                                   self.base_flags, self.interpret)

    def hash_planar_batch(self, payloads: list[bytes],
                          lengths: list[int]) -> list[bytes]:
        """Planar BG4 payloads → digests of the original chunk bytes,
        without the host ever materializing those bytes."""
        if not payloads:
            return []
        words, lens = self.stage_planar(payloads, lengths)
        digests = np.asarray(self.hash_planar_device(words, lens))
        return [d.astype("<u4").tobytes() for d in digests]


def fused_verifier_for_backend(key: bytes | None = None):
    """A FusedBg4Verifier on TPU (the fused path pays off exactly where
    the VPU is), None elsewhere — production CPU keeps the host decode,
    interpret mode being a test vehicle, not a fast path.

    ``ZEST_FUSED_INTERPRET=1`` opts a non-TPU backend into the
    interpret-mode kernel anyway: the cooperative exchange
    (transfer.coop) verifies received whole xorbs through this exact
    fused pass on real pods, and the 8-device CPU dryrun/smoke can then
    drive the identical code path — slow, so never on by default."""
    import os

    if jax.default_backend() != "tpu":
        if os.environ.get("ZEST_FUSED_INTERPRET") == "1":
            return FusedBg4Verifier(key, interpret=True)
        return None
    return FusedBg4Verifier(key)
