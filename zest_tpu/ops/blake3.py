"""On-device BLAKE3: batched chunk verification as pure XLA ops.

The reference verifies chunk hashes on the CPU via zig-xet (BASELINE
blake3_64kb = 3.5 GB/s, README.md:309-319). Here verification runs where
the bytes already live — HBM — so the gathered pool never round-trips to
host: a batch of padded chunks (e.g. GatheredPool rows) is hashed entirely
with u32 vector ops under jit. ``zest_tpu.ops.blake3_pallas`` wraps the same
math in a Pallas kernel; this module is the lowering-agnostic version and
the bit-exactness anchor against ``zest_tpu.cas.blake3``.

Vectorization strategy (all shapes static, no data-dependent control flow):

- one **leaf** = one 1024-byte BLAKE3 chunk = 16 sequential block
  compressions → ``lax.scan`` carrying the CV, lanes masked by each leaf's
  real block count;
- per-chunk leaf counts vary, so the chunk tree is built as **7 fixed merge
  levels** of pairwise parent compressions with odd-tail promotion — which
  is exactly BLAKE3's largest-power-of-two tree shape, expressed as dense
  masked selects instead of a CV stack (cas/blake3.py:218-226);
- ROOT finalization selects between "last parent" (multi-leaf) and a saved
  deferred "last block" (single-leaf) per batch element.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from zest_tpu.cas.blake3 import (
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_LEN,
    CHUNK_START,
    IV,
    KEYED_HASH,
    MSG_PERMUTATION,
    PARENT,
    ROOT,
)

BLOCKS_PER_LEAF = CHUNK_LEN // BLOCK_LEN      # 16
WORDS_PER_BLOCK = BLOCK_LEN // 4              # 16
WORDS_PER_LEAF = CHUNK_LEN // 4               # 256
MAX_LEAVES = 128                              # 128 KiB: xet max chunk size
_U32 = jnp.uint32


_PERM = np.asarray(MSG_PERMUTATION, dtype=np.int32)


def _rotr(x, n: int):
    return (x >> n) | (x << (32 - n))


def _g_vec(va, vb, vc, vd, mx, my):
    """Four G functions at once: lane *i* of each row vector is column/
    diagonal *i* of the state matrix — the classic SIMD formulation, which
    is also what keeps the traced graph small enough for XLA (a fully
    scalar-unrolled compress explodes CPU compile times)."""
    va = va + vb + mx
    vd = _rotr(vd ^ va, 16)
    vc = vc + vd
    vb = _rotr(vb ^ vc, 12)
    va = va + vb + my
    vd = _rotr(vd ^ va, 8)
    vc = vc + vd
    vb = _rotr(vb ^ vc, 7)
    return va, vb, vc, vd


def compress(cv, m, counter, block_len, flags):
    """Vectorized BLAKE3 compression (cas/blake3.py:70-92).

    ``cv``: (..., 8) u32; ``m``: (..., 16) u32; ``counter``/``block_len``/
    ``flags``: (...) broadcastable u32 (counter high word is always 0 here —
    leaf indices stay < 2^32). Returns the full (..., 16) output state.
    """
    shape = jnp.broadcast_shapes(
        cv.shape[:-1], m.shape[:-1], jnp.shape(counter),
        jnp.shape(block_len), jnp.shape(flags),
    )
    cv = jnp.broadcast_to(cv, shape + (8,))
    m = jnp.broadcast_to(m, shape + (16,)).astype(_U32)
    va, vb = cv[..., 0:4], cv[..., 4:8]
    vc = jnp.broadcast_to(
        jnp.asarray(IV[:4], _U32), shape + (4,)
    )
    vd = jnp.stack(
        [
            jnp.broadcast_to(counter, shape).astype(_U32),
            jnp.zeros(shape, _U32),
            jnp.broadcast_to(block_len, shape).astype(_U32),
            jnp.broadcast_to(flags, shape).astype(_U32),
        ],
        axis=-1,
    )

    def round_fn(_i, carry):
        va, vb, vc, vd, m = carry
        va, vb, vc, vd = _g_vec(
            va, vb, vc, vd, m[..., 0:8:2], m[..., 1:8:2]
        )
        # Diagonalize: lane i addresses (i, 4+(i+1)%4, 8+(i+2)%4, 12+(i+3)%4).
        vb = jnp.roll(vb, -1, axis=-1)
        vc = jnp.roll(vc, -2, axis=-1)
        vd = jnp.roll(vd, -3, axis=-1)
        va, vb, vc, vd = _g_vec(
            va, vb, vc, vd, m[..., 8:16:2], m[..., 9:16:2]
        )
        vb = jnp.roll(vb, 1, axis=-1)
        vc = jnp.roll(vc, 2, axis=-1)
        vd = jnp.roll(vd, 3, axis=-1)
        return va, vb, vc, vd, m[..., _PERM]

    va, vb, vc, vd, _ = jax.lax.fori_loop(
        0, 7, round_fn, (va, vb, vc, vd, m)
    )
    lo = jnp.concatenate([va, vb], axis=-1)
    hi = jnp.concatenate([vc, vd], axis=-1)
    return jnp.concatenate([lo ^ hi, hi ^ cv], axis=-1)


def _leaf_cvs(words, lengths, key_words, base_flags):
    """CVs of every leaf plus the deferred single-leaf root inputs.

    ``words``: (B, MAX_LEAVES * 256) u32 — zero-padded little-endian view of
    each chunk. ``lengths``: (B,) i32 byte lengths. Returns
    (leaf_cv (B, L, 8), n_leaves (B,), deferred) where ``deferred`` is the
    (cv_in, block, block_len, flags) of leaf 0's final block, needed when a
    chunk has a single leaf and the ROOT flag belongs on that block
    (cas/blake3.py:170-174).
    """
    B = words.shape[0]
    L = words.shape[1] // WORDS_PER_LEAF
    words = words.reshape(B, L, BLOCKS_PER_LEAF, WORDS_PER_BLOCK)
    lengths = lengths.astype(jnp.int32)

    leaf_idx = jnp.arange(L, dtype=jnp.int32)
    # Bytes belonging to each leaf, then blocks per leaf. Leaf 0 always has
    # one block (the empty input compresses one zero block, block_len 0).
    leaf_bytes = jnp.clip(lengths[:, None] - leaf_idx[None, :] * CHUNK_LEN,
                          0, CHUNK_LEN)                       # (B, L)
    n_blocks = jnp.maximum((leaf_bytes + BLOCK_LEN - 1) // BLOCK_LEN,
                           jnp.where(leaf_idx[None, :] == 0, 1, 0))
    leaf_active = n_blocks > 0
    n_leaves = jnp.maximum(jnp.sum(leaf_active, axis=1), 1)   # (B,)

    # Mask padding inside the final partial word of each chunk (device
    # buffers may hold garbage past `length`).
    word_idx = jnp.arange(L * WORDS_PER_LEAF, dtype=jnp.int32)
    rem = jnp.clip(lengths[:, None] - word_idx[None, :] * 4, 0, 4)
    word_mask = jnp.where(
        rem >= 4,
        jnp.asarray(0xFFFFFFFF, _U32),
        (jnp.asarray(1, _U32) << (8 * rem.astype(_U32))) - 1,
    )
    words = words & word_mask.reshape(B, L, BLOCKS_PER_LEAF, WORDS_PER_BLOCK)

    blk = jnp.arange(BLOCKS_PER_LEAF, dtype=jnp.int32)
    blk_active = blk[None, None, :] < n_blocks[:, :, None]     # (B, L, 16)
    is_last = blk[None, None, :] == n_blocks[:, :, None] - 1
    blk_len = jnp.clip(leaf_bytes[:, :, None] - blk[None, None, :] * BLOCK_LEN,
                       0, BLOCK_LEN)
    flags = (
        base_flags
        | jnp.where(blk[None, None, :] == 0, CHUNK_START, 0)
        | jnp.where(is_last, CHUNK_END, 0)
    ).astype(_U32)

    key = jnp.broadcast_to(key_words, (B, L, 8))
    counter = jnp.broadcast_to(leaf_idx[None, :], (B, L)).astype(_U32)

    def step(carry, xs):
        cv, dcv, dblk, dlen, dflags = carry
        m, active, last, bl, fl = xs
        out = compress(cv, m, counter, bl.astype(_U32), fl)
        new_cv = jnp.where(active[..., None], out[..., :8], cv)
        # Defer the last block's inputs for the single-leaf ROOT path.
        dcv = jnp.where(last[..., None], cv, dcv)
        dblk = jnp.where(last[..., None], m, dblk)
        dlen = jnp.where(last, bl, dlen)
        dflags = jnp.where(last, fl, dflags)
        return (new_cv, dcv, dblk, dlen, dflags), None

    xs = (
        jnp.moveaxis(words, 2, 0),        # (16, B, L, 16)
        jnp.moveaxis(blk_active, 2, 0),   # (16, B, L)
        jnp.moveaxis(is_last, 2, 0),
        jnp.moveaxis(blk_len, 2, 0),
        jnp.moveaxis(flags, 2, 0),
    )
    init = (
        key,
        jnp.zeros((B, L, 8), _U32),
        jnp.zeros((B, L, WORDS_PER_BLOCK), _U32),
        jnp.zeros((B, L), jnp.int32),
        jnp.zeros((B, L), _U32),
    )
    (cv, dcv, dblk, dlen, dflags), _ = jax.lax.scan(step, init, xs)
    deferred = (dcv[:, 0], dblk[:, 0], dlen[:, 0], dflags[:, 0])
    return cv, n_leaves, deferred


def _merge_tree(leaf_cv, n_leaves, key_words, base_flags):
    """Fold leaf CVs into the root state via fixed pairwise levels.

    Pairwise merge with odd-tail promotion reproduces BLAKE3's
    largest-power-of-two tree (verified exhaustively in tests). The unique
    merge with exactly two live nodes is the root and carries ROOT.
    """
    B, L, _ = leaf_cv.shape
    cv = leaf_cv
    count = n_leaves.astype(jnp.int32)
    root = jnp.zeros((B, 16), _U32)
    while L > 1:
        if L % 2:  # odd capacity: zero-pad; live odd tails promote via mask
            cv = jnp.concatenate(
                [cv, jnp.zeros((B, 1, 8), _U32)], axis=1
            )
            L += 1
        half = L // 2
        left = cv[:, 0::2]
        right = cv[:, 1::2]
        m = jnp.concatenate([left, right], axis=-1)            # (B, half, 16)
        is_root = count == 2  # the unique two-live-node merge is the root
        flags = (
            jnp.full((B, half), base_flags | PARENT, _U32)
            | jnp.where(is_root, ROOT, 0).astype(_U32)[:, None]
        )
        out = compress(
            jnp.broadcast_to(key_words, (B, half, 8)),
            m,
            jnp.zeros((B, half), _U32),
            jnp.full((B, half), BLOCK_LEN, _U32),
            flags,
        )
        j = jnp.arange(half, dtype=jnp.int32)
        merged = (2 * j[None, :] + 1) < count[:, None]
        cv = jnp.where(merged[..., None], out[..., :8], left)
        root = jnp.where(is_root[:, None], out[:, 0], root)
        count = (count + 1) // 2
        L = half
    return root


@functools.partial(jax.jit, static_argnames=("base_flags",))
def _hash_chunks_impl(words, lengths, key_words, base_flags):
    leaf_cv, n_leaves, deferred = _leaf_cvs(
        words, lengths, key_words, base_flags
    )
    root_multi = _merge_tree(leaf_cv, n_leaves, key_words, base_flags)
    dcv, dblk, dlen, dflags = deferred
    root_single = compress(
        dcv, dblk, jnp.zeros(words.shape[0], _U32),
        dlen.astype(_U32), dflags | ROOT,
    )
    root = jnp.where((n_leaves == 1)[:, None], root_single, root_multi)
    return root[:, :8]


class DeviceHasher:
    """Batched on-device BLAKE3 for equal-capacity chunk buffers."""

    def __init__(self, key: bytes | None = None):
        if key is not None:
            if len(key) != 32:
                raise ValueError("key must be 32 bytes")
            self.key_words = jnp.asarray(
                np.frombuffer(key, dtype="<u4"), _U32
            )
            self.base_flags = KEYED_HASH
        else:
            self.key_words = jnp.asarray(np.asarray(IV, dtype="<u4"), _U32)
            self.base_flags = 0

    def hash_device(self, words: jax.Array, lengths: jax.Array) -> jax.Array:
        """(B, padded_words) u32 + (B,) lengths → (B, 8) u32 digests.

        ``words`` stays on device — this is the path the gathered pool
        uses. Padded capacity must be a multiple of 256 words (1 KiB) and
        at most ``MAX_LEAVES`` KiB.
        """
        if words.shape[-1] % WORDS_PER_LEAF:
            raise ValueError("padded capacity must be a 1 KiB multiple")
        if words.shape[-1] > MAX_LEAVES * WORDS_PER_LEAF:
            raise ValueError(f"chunks larger than {MAX_LEAVES} KiB unsupported")
        return _hash_chunks_impl(
            words, lengths, self.key_words, self.base_flags
        )

    def hash_batch(self, chunks: list[bytes]) -> list[bytes]:
        """Host convenience: list of byte strings → list of 32-byte digests."""
        if not chunks:
            return []
        max_len = max(len(c) for c in chunks)
        cap = max(
            (max_len + CHUNK_LEN - 1) // CHUNK_LEN * CHUNK_LEN, CHUNK_LEN
        )
        buf = np.zeros((len(chunks), cap), dtype=np.uint8)
        lengths = np.empty(len(chunks), dtype=np.int32)
        for i, c in enumerate(chunks):
            buf[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
            lengths[i] = len(c)
        words = jnp.asarray(buf.view("<u4"))
        digests = np.asarray(self.hash_device(words, jnp.asarray(lengths)))
        return [d.astype("<u4").tobytes() for d in digests]


def verify_chunks_device(
    words: jax.Array,
    lengths: jax.Array,
    expected: jax.Array,
    key: bytes | None = None,
) -> jax.Array:
    """(B,) bool: does each padded chunk hash to ``expected`` (B, 8) u32?

    The post-gather integrity gate: runs entirely in HBM, one scalar per
    chunk comes back to host.
    """
    got = DeviceHasher(key).hash_device(words, lengths)
    return jnp.all(got == expected, axis=-1)
