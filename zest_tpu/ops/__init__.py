"""On-device ops: Pallas/XLA kernels for the byte-level hot paths.

The reference's hot byte work (BLAKE3 verification, chunk extraction) runs
on host CPU in Zig; here it runs where the bytes land — TPU HBM — so the
gathered pool is verified without a host round-trip (BASELINE north star).
"""

from zest_tpu.ops.blake3 import (  # noqa: F401
    DeviceHasher,
    verify_chunks_device,
)
