"""On-device ops: Pallas/XLA kernels for the byte-level hot paths.

The reference's hot byte work (BLAKE3 verification, chunk extraction) runs
on host CPU in Zig; here it runs where the bytes land — TPU HBM — so the
gathered pool is verified without a host round-trip (BASELINE north star).
"""

from zest_tpu.ops.blake3 import (  # noqa: F401
    DeviceHasher,
    verify_chunks_device,
)
from zest_tpu.ops.blake3_pallas import PallasHasher  # noqa: F401
from zest_tpu.ops.decode_pallas import (  # noqa: F401
    FusedBg4Verifier,
    fused_verifier_for_backend,
)


def best_hasher(key: bytes | None = None):
    """The fastest verifier for the current backend: the Pallas kernel on
    TPU (~13% over the XLA lowering, measured v5e), XLA elsewhere (the
    Pallas interpreter is for tests, not production CPU hashing)."""
    import jax

    if jax.default_backend() == "tpu":
        return PallasHasher(key)
    return DeviceHasher(key)


class HostBatchHasher:
    """``hash_batch`` on the host's native SIMD BLAKE3 — the right
    verifier when no accelerator is attached (the XLA-on-CPU lowering
    is a correctness vehicle, ~3 orders slower than the native path;
    a CPU-backend pod/coop round verifying peer blobs through it would
    be bottlenecked on its own trust boundary). Enforces the same
    ``MAX_LEAVES``-KiB chunk cap as the device hashers (ValueError),
    so a hostile over-cap chunk is rejected identically on every
    backend."""

    def __init__(self, key: bytes | None = None):
        self.key = key

    def hash_batch(self, chunks: list[bytes]) -> list[bytes]:
        from zest_tpu.cas import hashing
        from zest_tpu.ops.blake3 import MAX_LEAVES

        cap = MAX_LEAVES * 1024
        for c in chunks:
            if len(c) > cap:
                raise ValueError(
                    f"chunk of {len(c)} bytes over the {cap}-byte leaf cap")
        if self.key is None:
            return [hashing.blake3_hash(c) for c in chunks]
        return [hashing.blake3_keyed(self.key, c) for c in chunks]


def unit_verify_hasher(key: bytes | None = None):
    """Hasher for whole-unit trust-boundary verification (pod fill,
    coop exchange): the device kernel where a device is the point
    (TPU), native host SIMD everywhere else."""
    import jax

    if jax.default_backend() == "tpu":
        return PallasHasher(key)
    return HostBatchHasher(key)
