"""On-device ops: Pallas/XLA kernels for the byte-level hot paths.

The reference's hot byte work (BLAKE3 verification, chunk extraction) runs
on host CPU in Zig; here it runs where the bytes land — TPU HBM — so the
gathered pool is verified without a host round-trip (BASELINE north star).
"""

from zest_tpu.ops.blake3 import (  # noqa: F401
    DeviceHasher,
    verify_chunks_device,
)
from zest_tpu.ops.blake3_pallas import PallasHasher  # noqa: F401
from zest_tpu.ops.decode_pallas import (  # noqa: F401
    FusedBg4Verifier,
    fused_verifier_for_backend,
)


def best_hasher(key: bytes | None = None):
    """The fastest verifier for the current backend: the Pallas kernel on
    TPU (~13% over the XLA lowering, measured v5e), XLA elsewhere (the
    Pallas interpreter is for tests, not production CPU hashing)."""
    import jax

    if jax.default_backend() == "tpu":
        return PallasHasher(key)
    return DeviceHasher(key)
