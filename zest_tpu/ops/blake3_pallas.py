"""BLAKE3 as a Pallas TPU kernel — the north-star on-device verifier.

Same math as zest_tpu.ops.blake3 (the lowering-agnostic XLA version, which
remains the bit-exactness anchor), reformulated for the TPU VPU:

- **chunks ride the lane dimension**: all state is shaped (..., TILE) with
  one hashed chunk per lane, so every compression step is an (8×128)-wide
  vector op. The XLA version's (..., 4) lane layout wastes 31/32 lanes on
  TPU; here utilization is TILE/128.
- **block-major word layout**: the host view is pre-arranged as
  ``A[block, leaf·16 + word, chunk]`` so the per-block message load inside
  the 16-iteration compression loop is one contiguous ref slice
  (``a_ref[b]``) — no strided gathers in VMEM.
- **word masking runs outside the kernel** (cheap XLA elementwise on the
  way in), so the kernel sees zero-padded words and only needs per-leaf
  block counts.
- the chunk merge tree unrolls into log2(MAX_LEAVES) static pairwise
  levels with odd-tail promotion, exactly like the XLA version
  (ops/blake3.py:207-246) but transposed.

VMEM is bounded by the **leaf-group grid**, not a smaller batch tile
(Mosaic requires the lane dim to be a multiple of 128): the second grid
dimension walks the chunk capacity ``_LEAVES_PER_GROUP`` KiB at a time,
accumulating per-leaf CVs in scratch, and the last step folds the merge
tree — so the input block stays at ``_LEAVES_PER_GROUP·1 KiB × 128 lanes``
(1 MiB at the swept G=8) regardless of chunk size. ``_LEAVES_PER_GROUP``
is the VMEM knob.

On non-TPU backends the kernel runs in interpreter mode (tests); the XLA
version stays the production path for CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zest_tpu.cas.blake3 import (
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_LEN,
    CHUNK_START,
    IV,
    KEYED_HASH,
    MSG_PERMUTATION,
    PARENT,
    ROOT,
)
from zest_tpu.ops.blake3 import (
    BLOCKS_PER_LEAF,
    MAX_LEAVES,
    WORDS_PER_BLOCK,
    WORDS_PER_LEAF,
)

_U32 = jnp.uint32

# jax renamed TPUCompilerParams → CompilerParams around 0.4.3x/0.5;
# resolve whichever this build ships so the kernel runs on both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Static per-round message schedules (word index per G-function input):
# round r reads the identity permutation advanced r times. Baking the
# schedule in lets the kernel index message words with *static* slices —
# no in-kernel gather, which Mosaic lowers poorly.
_SCHEDULES: list[tuple[int, ...]] = []
_s = list(range(16))
for _ in range(7):
    _SCHEDULES.append(tuple(_s))
    _s = [_s[i] for i in MSG_PERMUTATION]


def _rotr(x, n: int):
    return (x >> n) | (x << (32 - n))


def _g(va, vb, vc, vd, mx, my):
    va = va + vb + mx
    vd = _rotr(vd ^ va, 16)
    vc = vc + vd
    vb = _rotr(vb ^ vc, 12)
    va = va + vb + my
    vd = _rotr(vd ^ va, 8)
    vc = vc + vd
    vb = _rotr(vb ^ vc, 7)
    return va, vb, vc, vd


def _roll1(x, k: int):
    """Rotate axis 1 (the 4-row state group) by k via static slices —
    jnp.roll on a middle axis does not lower well in Mosaic."""
    k %= x.shape[1]
    if k == 0:
        return x
    return jnp.concatenate([x[:, k:], x[:, :k]], axis=1)


def _cols(m, idxs):
    """Stack static message columns: (L, 16, T)[idxs] → (L, len, T)."""
    return jnp.stack([m[:, i] for i in idxs], axis=1)


def _compress_t(cv, m, counter, block_len, flags, key4):
    """Transposed compression: cv (L, 8, T), m (L, 16, T); counter /
    block_len / flags (L, T). Lane axis T is the chunk batch. Rounds are
    statically unrolled with baked message schedules."""
    L, _, T = cv.shape
    va, vb = cv[:, 0:4], cv[:, 4:8]
    vc = jnp.broadcast_to(key4, (L, 4, T))
    vd = jnp.stack(
        [
            counter.astype(_U32),
            jnp.zeros_like(counter, _U32),
            block_len.astype(_U32),
            flags.astype(_U32),
        ],
        axis=1,
    )

    for sched in _SCHEDULES:
        va, vb, vc, vd = _g(
            va, vb, vc, vd,
            _cols(m, sched[0:8:2]), _cols(m, sched[1:8:2]),
        )
        vb = _roll1(vb, 1)
        vc = _roll1(vc, 2)
        vd = _roll1(vd, 3)
        va, vb, vc, vd = _g(
            va, vb, vc, vd,
            _cols(m, sched[8:16:2]), _cols(m, sched[9:16:2]),
        )
        vb = _roll1(vb, 3)
        vc = _roll1(vc, 2)
        vd = _roll1(vd, 1)
    lo = jnp.concatenate([va, vb], axis=1)
    hi = jnp.concatenate([vc, vd], axis=1)
    return jnp.concatenate([lo ^ hi, hi ^ cv], axis=1)


_TILE = 128           # lane width: Mosaic requires last block dim % 128
# 8 leaves × 1 KiB × 128 lanes = 1 MiB VMEM/block. Swept on a v5e chip
# (device-time method, bench.py): G=8 → 67.5 GB/s, G=16 → 65.8, G=32 →
# 42 — smaller groups double-buffer better against the compute phase.
_LEAVES_PER_GROUP = 8


def _make_kernel(n_leaves_cap: int, leaves_per_group: int, n_groups: int,
                 key_words: tuple[int, ...], base_flags: int):
    """Kernel over grid (batch_tile, leaf_group). The leaf-group axis is
    sequential: each step compresses its group's leaves into the CV
    scratch; the last step folds the merge tree and writes digests. This
    keeps the VMEM block at ``leaves_per_group`` KiB × 128 lanes no matter
    how large the chunk capacity is."""
    L, G = n_leaves_cap, leaves_per_group
    Lp = n_groups * G  # scratch rows (≥ L; tail rows never go live)
    key8 = tuple(int(w) for w in key_words)
    iv4 = tuple(int(w) for w in IV[:4])

    def kernel(a_ref, len_ref, out_ref,
               cv_ref, dcv_ref, dblk_ref, dmeta_ref):
        g = pl.program_id(1)
        T = out_ref.shape[1]
        key4 = jnp.stack(
            [jnp.full((T,), w, _U32) for w in iv4], axis=0
        )[None]                                               # (1, 4, T)
        key_row = jnp.stack(
            [jnp.full((T,), w, _U32) for w in key8], axis=0
        )                                                     # (8, T)
        lengths = len_ref[0, :]                               # (T,) i32

        # ── group phase: compress this group's G leaves ──
        leaf_l = jax.lax.broadcasted_iota(jnp.int32, (G, T), 0)
        leaf = leaf_l + g * G                                  # global idx
        leaf_bytes = jnp.clip(
            lengths[None, :] - leaf * CHUNK_LEN, 0, CHUNK_LEN
        )
        n_blocks = jnp.maximum(
            (leaf_bytes + BLOCK_LEN - 1) // BLOCK_LEN,
            jnp.where(leaf == 0, 1, 0),
        )

        def body(b, carry):
            cv, dcv, dblk, dlen, dfl = carry
            m = a_ref[pl.ds(b, 1)].reshape(G, WORDS_PER_BLOCK, T)
            active = b < n_blocks
            is_last = b == n_blocks - 1
            bl = jnp.clip(leaf_bytes - b * BLOCK_LEN, 0, BLOCK_LEN)
            fl = (
                jnp.full((G, T), base_flags, _U32)
                | jnp.where(b == 0, CHUNK_START, 0).astype(_U32)
                | jnp.where(is_last, CHUNK_END, 0).astype(_U32)
            )
            out = _compress_t(cv, m, leaf, bl, fl, key4)
            new_cv = jnp.where(active[:, None, :], out[:, :8], cv)
            # Defer leaf 0's final-block inputs for the single-leaf ROOT
            # (leaf 0 lives in group 0 only).
            last0 = is_last[0][None, :]
            dcv = jnp.where(last0, cv[0], dcv)
            dblk = jnp.where(last0, m[0], dblk)
            dlen = jnp.where(is_last[0], bl[0], dlen)
            dfl = jnp.where(is_last[0], fl[0], dfl)
            return new_cv, dcv, dblk, dlen, dfl

        init_cv = jnp.broadcast_to(key_row[None], (G, 8, T))
        init = (
            init_cv,
            jnp.zeros((8, T), _U32),
            jnp.zeros((WORDS_PER_BLOCK, T), _U32),
            jnp.zeros((T,), jnp.int32),
            jnp.zeros((T,), _U32),
        )
        cv_g, dcv, dblk, dlen, dfl = jax.lax.fori_loop(
            0, BLOCKS_PER_LEAF, body, init
        )
        cv_ref[pl.ds(g * G, G)] = cv_g

        @pl.when(g == 0)
        def _():
            dcv_ref[:] = dcv
            dblk_ref[:] = dblk
            dmeta_ref[0, :] = dlen
            dmeta_ref[1, :] = dfl.astype(jnp.int32)

        # ── final phase: fold the tree and emit digests ──
        @pl.when(g == n_groups - 1)
        def _():
            full_leaf = jax.lax.broadcasted_iota(jnp.int32, (Lp, T), 0)
            live = (
                jnp.clip(lengths[None, :] - full_leaf * CHUNK_LEN,
                         0, CHUNK_LEN) > 0
            ) | (full_leaf == 0)
            n_leaves = jnp.maximum(
                jnp.sum(live.astype(jnp.int32), axis=0), 1
            )
            cv = cv_ref[:]                                    # (Lp, 8, T)
            count = n_leaves
            root = jnp.zeros((16, T), _U32)
            lvl = Lp
            while lvl > 1:
                if lvl % 2:
                    cv = jnp.concatenate(
                        [cv, jnp.zeros((1, 8, T), _U32)], axis=0
                    )
                    lvl += 1
                half = lvl // 2
                # Adjacent rows pair up, so the parent message is just a
                # reshape: (2h, 8, T) → (h, 16, T) puts left in cols 0:8,
                # right in 8:16. (Strided slices like cv[0::2] lower to
                # gathers, which Mosaic rejects beyond 2-D.)
                m = cv.reshape(half, 16, T)
                left = m[:, :8]
                is_root = count == 2
                fl = (
                    jnp.full((half, T), base_flags | PARENT, _U32)
                    | jnp.where(is_root, ROOT, 0).astype(_U32)[None, :]
                )
                out = _compress_t(
                    jnp.broadcast_to(key_row[None], (half, 8, T)),
                    m,
                    jnp.zeros((half, T), _U32),
                    jnp.full((half, T), BLOCK_LEN, _U32),
                    fl,
                    key4,
                )
                j = jax.lax.broadcasted_iota(jnp.int32, (half, T), 0)
                merged = (2 * j + 1) < count[None, :]
                cv = jnp.where(merged[:, None, :], out[:, :8], left)
                root = jnp.where(is_root[None, :], out[0], root)
                count = (count + 1) // 2
                lvl = half

            single = _compress_t(
                dcv_ref[:][None],
                dblk_ref[:][None],
                jnp.zeros((1, T), _U32),
                dmeta_ref[0, :][None].astype(_U32),
                (dmeta_ref[1, :][None].astype(_U32) | ROOT),
                key4,
            )[0]
            root = jnp.where((n_leaves == 1)[None, :], single, root)
            out_ref[:] = root[:8]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("key_words", "base_flags", "interpret"),
)
def _hash_pallas(words, lengths, key_words, base_flags, interpret):
    B, W = words.shape
    L = W // WORDS_PER_LEAF

    # Mask garbage bytes past each chunk's length (XLA elementwise).
    widx = jnp.arange(W, dtype=jnp.int32)
    rem = jnp.clip(lengths[:, None] - widx[None, :] * 4, 0, 4)
    mask = jnp.where(
        rem >= 4,
        jnp.asarray(0xFFFFFFFF, _U32),
        (jnp.asarray(1, _U32) << (8 * rem.astype(_U32))) - 1,
    )
    words = words & mask

    pad = (-B) % _TILE
    if pad:
        words = jnp.pad(words, ((0, pad), (0, 0)))
        lengths = jnp.pad(lengths, (0, pad))
    Bp = B + pad

    G = min(_LEAVES_PER_GROUP, L)
    n_groups = pl.cdiv(L, G)
    Lp = n_groups * G
    if Lp != L:  # pad capacity so every group is full
        words = jnp.pad(words, ((0, 0), (0, (Lp - L) * WORDS_PER_LEAF)))
        L = Lp

    # Block-major transposed view: A[block, leaf*16 + word, chunk].
    a = (
        words.reshape(Bp, L, BLOCKS_PER_LEAF, WORDS_PER_BLOCK)
        .transpose(2, 1, 3, 0)
        .reshape(BLOCKS_PER_LEAF, L * WORDS_PER_BLOCK, Bp)
    )
    len2d = lengths.astype(jnp.int32).reshape(1, Bp)

    kernel = _make_kernel(L, G, n_groups, key_words, base_flags)
    digests_t = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, Bp), _U32),
        grid=(Bp // _TILE, n_groups),
        in_specs=[
            pl.BlockSpec(
                (BLOCKS_PER_LEAF, G * WORDS_PER_BLOCK, _TILE),
                lambda i, g: (0, g, i),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, _TILE), lambda i, g: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, _TILE), lambda i, g: (0, i),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((L, 8, _TILE), _U32),       # per-leaf CVs
            pltpu.VMEM((8, _TILE), _U32),          # deferred cv
            pltpu.VMEM((WORDS_PER_BLOCK, _TILE), _U32),  # deferred block
            pltpu.VMEM((2, _TILE), jnp.int32),     # deferred len/flags
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(a, len2d)
    return digests_t[:, :B].T


class PallasHasher:
    """Drop-in sibling of ops.blake3.DeviceHasher lowering via Pallas."""

    def __init__(self, key: bytes | None = None, interpret: bool | None = None):
        if key is not None:
            if len(key) != 32:
                raise ValueError("key must be 32 bytes")
            self.key_words = tuple(
                int(w) for w in np.frombuffer(key, dtype="<u4")
            )
            self.base_flags = int(KEYED_HASH)
        else:
            self.key_words = tuple(int(w) for w in IV)
            self.base_flags = 0
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret

    def hash_device(self, words: jax.Array, lengths: jax.Array) -> jax.Array:
        """(B, padded_words) u32 + (B,) lengths → (B, 8) u32 digests."""
        if words.shape[-1] % WORDS_PER_LEAF:
            raise ValueError("padded capacity must be a 1 KiB multiple")
        if words.shape[-1] > MAX_LEAVES * WORDS_PER_LEAF:
            raise ValueError(
                f"chunks larger than {MAX_LEAVES} KiB unsupported"
            )
        return _hash_pallas(
            words, lengths.astype(jnp.int32),
            self.key_words, self.base_flags, self.interpret,
        )

    def hash_batch(self, chunks: list[bytes]) -> list[bytes]:
        if not chunks:
            return []
        max_len = max(len(c) for c in chunks)
        cap = max(
            (max_len + CHUNK_LEN - 1) // CHUNK_LEN * CHUNK_LEN, CHUNK_LEN
        )
        buf = np.zeros((len(chunks), cap), dtype=np.uint8)
        lengths = np.empty(len(chunks), dtype=np.int32)
        for i, c in enumerate(chunks):
            buf[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
            lengths[i] = len(c)
        words = jnp.asarray(buf.view("<u4"))
        digests = np.asarray(self.hash_device(words, jnp.asarray(lengths)))
        return [d.astype("<u4").tobytes() for d in digests]
