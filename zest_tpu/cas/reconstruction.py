"""Reconstruction metadata: how a file is reassembled from xorb chunks.

Mirrors the shapes the reference consumes from zig-xet's `cas_client`
(SURVEY.md §2.2): a file maps to an ordered list of **terms** — (xorb hash,
chunk range) — plus a **fetch_info** map telling the client where each
xorb's bytes can be fetched (URL + byte range) and which chunk range that
URL covers. Three distinct coordinate frames meet here (the reference's
trickiest seam, xet_bridge.zig:162-214):

  - term.range         — absolute chunk indices within the xorb
  - fetch_info.range   — absolute chunk indices covered by one URL
  - local indices      — term.range rebased into the fetched blob:
                         ``local = term.range - chunk_offset``
"""

from __future__ import annotations

from dataclasses import dataclass, field

from zest_tpu.cas import hashing


@dataclass(frozen=True)
class ChunkRange:
    """Half-open chunk-index range [start, end)."""

    start: int
    end: int

    def __post_init__(self):
        if not (0 <= self.start < self.end):
            raise ValueError(f"invalid chunk range [{self.start},{self.end})")

    def covers(self, other: "ChunkRange") -> bool:
        return self.start <= other.start and self.end >= other.end


@dataclass(frozen=True)
class Term:
    """One segment of a file: chunks [range.start, range.end) of ``xorb_hash``."""

    xorb_hash: bytes
    range: ChunkRange
    unpacked_length: int

    @property
    def hash_hex(self) -> str:
        return hashing.hash_to_hex(self.xorb_hash)


@dataclass(frozen=True)
class FetchInfo:
    """Where to fetch (part of) a xorb: ``url`` serves byte range
    [url_range_start, url_range_end) which decodes to chunks
    [range.start, range.end) of the xorb."""

    url: str
    url_range_start: int
    url_range_end: int
    range: ChunkRange


@dataclass
class Reconstruction:
    """Full reconstruction plan for one file."""

    file_hash: bytes
    terms: list[Term]
    fetch_info: dict[str, list[FetchInfo]] = field(default_factory=dict)

    @property
    def total_bytes(self) -> int:
        return sum(t.unpacked_length for t in self.terms)

    def find_fetch_info(self, term: Term) -> FetchInfo | None:
        """The fetch_info entry covering this term's chunk range
        (reference: xet_bridge.zig:221-228)."""
        for fi in self.fetch_info.get(term.hash_hex, []):
            if fi.range.covers(term.range):
                return fi
        return None


class ReconstructionError(ValueError):
    pass


def from_json(file_hash_hex: str, doc: dict) -> Reconstruction:
    """Parse the CAS reconstruction response.

    Wire shape (our CAS protocol; field names follow HF's Xet API):

        {"terms": [{"hash": hex, "range": {"start": s, "end": e},
                    "unpacked_length": n}, ...],
         "fetch_info": {hex: [{"url": u,
                               "url_range": {"start": b0, "end": b1},
                               "range": {"start": s, "end": e}}, ...]}}
    """
    try:
        terms = [
            Term(
                xorb_hash=hashing.hex_to_hash(t["hash"]),
                range=ChunkRange(t["range"]["start"], t["range"]["end"]),
                unpacked_length=int(t["unpacked_length"]),
            )
            for t in doc["terms"]
        ]
        fetch_info = {
            h: [
                FetchInfo(
                    url=fi["url"],
                    url_range_start=int(fi["url_range"]["start"]),
                    # The wire "url_range.end" is INCLUSIVE (production
                    # semantics: the client requests exactly
                    # ``Range: bytes={start}-{end}``); internally we keep
                    # half-open [start, end).
                    url_range_end=int(fi["url_range"]["end"]) + 1,
                    range=ChunkRange(fi["range"]["start"], fi["range"]["end"]),
                )
                for fi in entries
            ]
            for h, entries in doc.get("fetch_info", {}).items()
        }
    except (KeyError, TypeError, ValueError) as exc:
        raise ReconstructionError(f"malformed reconstruction: {exc}") from exc
    return Reconstruction(
        file_hash=hashing.hex_to_hash(file_hash_hex),
        terms=terms,
        fetch_info=fetch_info,
    )


def to_json(rec: Reconstruction) -> dict:
    """Serialize (used by the fixture CAS server and the pod-local CAS).

    ``offset_into_first_range`` is part of the production response schema
    (cas_types ``QueryReconstructionResponse``) — nonzero only for ranged
    file queries, which we don't issue; the real client requires the field.
    """
    return {
        "offset_into_first_range": 0,
        "terms": [
            {
                "hash": t.hash_hex,
                "range": {"start": t.range.start, "end": t.range.end},
                "unpacked_length": t.unpacked_length,
            }
            for t in rec.terms
        ],
        "fetch_info": {
            h: [
                {
                    "url": fi.url,
                    # Inclusive end on the wire (see from_json).
                    "url_range": {
                        "start": fi.url_range_start,
                        "end": fi.url_range_end - 1,
                    },
                    "range": {"start": fi.range.start, "end": fi.range.end},
                }
                for fi in entries
            ]
            for h, entries in rec.fetch_info.items()
        },
    }
