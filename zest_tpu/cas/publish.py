"""CDC-dedup publishing — the CAS *write* path (ISSUE 19).

This is the server-side encoding the fixtures have exercised since the
first pull test, promoted to production: a file becomes gearhash CDC
chunks (:mod:`zest_tpu.cas.chunking`), every chunk is looked up in a
first-occurrence-wins index over the xorb set the publisher already
holds, and the file's reconstruction comes out as a term list where

- a run of chunks that sit CONTIGUOUSLY in one existing xorb collapses
  into a single *referencing* term (no bytes re-uploaded — that is the
  dedup that makes revision-to-revision pushes structurally cheap), and
- genuinely new chunks are packed into new :class:`XorbBuilder` frames
  (respecting the xorb's chunk-count cap) and referenced by *defining*
  terms.

``tests/fixtures.py:FixtureRepo`` is now a thin wrapper over
:class:`Publisher` (same promotion pattern as ``_TokenBucket`` →
``zest_tpu.shaping``), so the loopback hub the integration tests pull
from and the ``zest push`` write path share one implementation — the
ISSUE 19 satellite contract.

The publisher is transport-agnostic: it never touches the network or
the disk cache. ``transfer/push.py`` feeds it base-revision xorbs from
the local :class:`~zest_tpu.storage.XorbCache` (via :meth:`Publisher.
seed_xorb`), collects the new xorbs it mints, and decides where the
bytes go; the fixture keeps them in memory and serves them over HTTP.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from zest_tpu.cas import chunking, hashing
from zest_tpu.cas import reconstruction as recon
from zest_tpu.cas.xorb import XorbBuilder

# File suffixes stored in Xet CAS (everything else is a "regular" file
# carried verbatim), mirroring how HF stores configs vs weights.
XET_SUFFIXES = (".safetensors", ".bin", ".pt", ".h5", ".msgpack")


def is_xet_path(path: str) -> bool:
    return path.endswith(XET_SUFFIXES)


@dataclass
class PublishedXorb:
    """A xorb minted by this publisher (new bytes entering the CAS)."""

    hash_hex: str
    blob: bytes               # frame stream (the in-pipeline blob shape)
    frame_offsets: list[int]  # len = num_chunks + 1
    full: bytes               # frames + XETBLOB footer (the CDN artifact)


@dataclass
class PublishedFile:
    """One file's publish outcome: identity, terms, and dedup split."""

    path: str
    size: int
    xet_hash: str                      # LE-u64 hex of the merkle file hash
    terms: list[recon.Term]
    reconstruction: recon.Reconstruction
    new_bytes: int = 0                 # bytes that entered NEW xorbs
    reused_bytes: int = 0              # bytes served by referencing terms

    @property
    def dedup_ratio(self) -> float:
        """Fraction of the file's bytes that did NOT become new xorbs."""
        return (self.reused_bytes / self.size) if self.size else 1.0


@dataclass
class ChunkIndex:
    """chunk hash → (xorb_hex, chunk_index, length), first-occurrence-wins.

    Any occurrence serves identical bytes (content addressing), so the
    first registered location is as good as any and keeps term runs
    stable across re-registration.
    """

    _by_hash: dict[bytes, tuple[str, int, int]] = field(default_factory=dict)

    def add_xorb(self, xorb_hex: str,
                 chunk_hashes: list[tuple[bytes, int]]) -> None:
        for idx, (ch, clen) in enumerate(chunk_hashes):
            self._by_hash.setdefault(ch, (xorb_hex, idx, clen))

    def lookup(self, chunk_hash: bytes) -> tuple[str, int, int] | None:
        return self._by_hash.get(chunk_hash)

    def __len__(self) -> int:
        return len(self._by_hash)

    def __contains__(self, chunk_hash: bytes) -> bool:
        return chunk_hash in self._by_hash


class Publisher:
    """Stateful CDC-dedup encoder over a growing xorb set.

    ``chunks_per_xorb`` forces files to split across several xorbs so
    callers (fixtures, stress tests) exercise multi-term reconstruction;
    0 means unlimited (one xorb per flush run, still bounded by the
    format's own caps through :class:`XorbBuilder`).

    ``url_prefix`` shapes the fetch_info URLs baked into emitted
    reconstructions (``{url_prefix}{xorb_hex}``). Both the fixture hub
    and the publisher daemon serve the ``/xorbs/{hex}`` route, and both
    absolutize the URL at serve time, so the default is relative.
    """

    def __init__(self, chunks_per_xorb: int = 0,
                 url_prefix: str = "/xorbs/"):
        self.chunks_per_xorb = chunks_per_xorb
        self.url_prefix = url_prefix
        self.index = ChunkIndex()
        # xorb_hex -> frame offsets; covers seeded (base) AND minted
        # xorbs — referencing terms need the offsets to place their
        # fetch_info byte ranges whichever side the xorb came from.
        self._frame_offsets: dict[str, list[int]] = {}
        self._minted: dict[str, PublishedXorb] = {}
        self._drained: set[str] = set()

    # ── xorb registration ──

    def seed_xorb(self, xorb_hex: str, frame_offsets: list[int],
                  chunk_hashes: list[tuple[bytes, int]]) -> None:
        """Register an ALREADY-STORED xorb (e.g. the base revision's,
        read back from the local cache) as dedup material. Its bytes
        are never re-emitted; terms may reference into it."""
        if xorb_hex in self._frame_offsets:
            return
        self._frame_offsets[xorb_hex] = list(frame_offsets)
        self.index.add_xorb(xorb_hex, chunk_hashes)

    def _register_built(self, builder: XorbBuilder) -> str:
        xh_hex = hashing.hash_to_hex(builder.xorb_hash())
        if xh_hex not in self._frame_offsets:
            px = PublishedXorb(xh_hex, builder.serialize(),
                               builder.frame_offsets(),
                               builder.serialize_full())
            self._frame_offsets[xh_hex] = px.frame_offsets
            self.index.add_xorb(xh_hex, builder.chunk_hashes())
            self._minted[xh_hex] = px
        return xh_hex

    def drain_new_xorbs(self) -> list[PublishedXorb]:
        """Xorbs minted since the last drain — the bytes the caller
        must now store/serve. Each xorb is handed out exactly once."""
        fresh = [px for h, px in self._minted.items()
                 if h not in self._drained]
        self._drained.update(px.hash_hex for px in fresh)
        return fresh

    @property
    def known_xorbs(self) -> set[str]:
        return set(self._frame_offsets)

    # ── the dedup encode ──

    def publish_file(self, path: str, data: bytes, dedup: bool = True,
                     chunks_per_xorb: int | None = None) -> PublishedFile:
        """Encode ``data`` against the current xorb set.

        With ``dedup=False`` every chunk is packed into new xorbs even
        when the index already holds it — the base-revision behaviour
        (fixture geometry is pinned by existing tests, and a cold push
        has no base to reference anyway).
        """
        pieces = [(hashing.chunk_hash(piece), piece)
                  for _, piece in chunking.chunk_stream(data)]
        limit = (chunks_per_xorb if chunks_per_xorb is not None
                 else self.chunks_per_xorb) or len(pieces) or 1
        terms: list[recon.Term] = []
        fetch_info: dict[str, list[recon.FetchInfo]] = {}
        new_bytes = reused_bytes = 0

        def add_term(xh_hex: str, start: int, end: int,
                     nbytes: int) -> None:
            offs = self._frame_offsets[xh_hex]
            terms.append(recon.Term(
                xorb_hash=hashing.hex_to_hash(xh_hex),
                range=recon.ChunkRange(start, end),
                unpacked_length=nbytes,
            ))
            fi = recon.FetchInfo(
                url=f"{self.url_prefix}{xh_hex}",
                url_range_start=offs[start],
                url_range_end=offs[end],
                range=recon.ChunkRange(start, end),
            )
            entries = fetch_info.setdefault(xh_hex, [])
            if fi not in entries:
                entries.append(fi)

        pending: list[tuple[bytes, bytes]] = []  # new chunks to pack

        def flush_pending() -> None:
            nonlocal new_bytes
            for i in range(0, len(pending), limit):
                group = pending[i:i + limit]
                builder = XorbBuilder()
                for _h, piece in group:
                    builder.add_chunk(piece)
                xh_hex = self._register_built(builder)
                add_term(xh_hex, 0, len(group),
                         sum(len(p) for _h, p in group))
                new_bytes += sum(len(p) for _h, p in group)
            pending.clear()

        i = 0
        while i < len(pieces):
            hit = self.index.lookup(pieces[i][0]) if dedup else None
            if hit is None:
                pending.append(pieces[i])
                i += 1
                continue
            flush_pending()
            # Extend a run of chunks that sit CONTIGUOUSLY in one
            # existing xorb — the run becomes one referencing term.
            xh_hex, idx, _len = hit
            j, expect, run_bytes = i, idx, 0
            while j < len(pieces):
                nxt = self.index.lookup(pieces[j][0])
                if nxt is None or nxt[0] != xh_hex or nxt[1] != expect:
                    break
                run_bytes += len(pieces[j][1])
                expect += 1
                j += 1
            add_term(xh_hex, idx, expect, run_bytes)
            reused_bytes += run_bytes
            i = j
        flush_pending()
        file_hash = hashing.file_hash([(h, len(p)) for h, p in pieces])
        file_hex = hashing.hash_to_hex(file_hash)
        rec = recon.Reconstruction(
            file_hash=file_hash, terms=terms, fetch_info=fetch_info)
        return PublishedFile(path=path, size=len(data), xet_hash=file_hex,
                             terms=terms, reconstruction=rec,
                             new_bytes=new_bytes, reused_bytes=reused_bytes)
