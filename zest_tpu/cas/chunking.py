"""GearHash content-defined chunking (the zig-xet `chunking` equivalent).

Splits byte streams into content-defined chunks (min 8KB / target 64KB /
max 128KB — the Xet parameters, reference DESIGN.md:265-273) so identical
content produces identical chunk boundaries regardless of surrounding bytes;
this is what makes chunk-level dedup work across model revisions.

Algorithm: GearHash rolling hash — ``h = (h << 1) + GEAR[byte]`` — with a cut
when the top ``log2(target - min)`` bits of ``h`` are all zero. The gear
table is deterministic (derived from BLAKE3 of the table index under a
documented context) and is a compatibility seam: substitute the production
Xet table for boundary-level interop with HF's CAS.

Hot path dispatches to the native C++ scanner (zest_tpu/native/gearhash.cc)
when available; the pure-Python implementation is the correctness anchor.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator

from zest_tpu.cas import blake3 as _b3

MIN_CHUNK = 8 * 1024
TARGET_CHUNK = 64 * 1024
MAX_CHUNK = 128 * 1024

# Cut when the top bits of the rolling hash are zero. With 16 mask bits the
# expected gap between qualifying positions is 2^16 = 64 KiB; the MIN_CHUNK
# skip shifts the mean to ~MIN + 64 KiB and MAX_CHUNK truncates the
# geometric tail, landing the realized average near the 64 KiB Xet target.
_MASK_BITS = TARGET_CHUNK.bit_length() - 1  # 16
MASK = ((1 << _MASK_BITS) - 1) << (64 - _MASK_BITS)

_GEAR_CONTEXT = "zest-tpu gearhash table v1"


def _make_gear_table() -> tuple[int, ...]:
    # 256 pseudorandom u64s, deterministically derived so every
    # implementation (Python, C++, tests) agrees byte-for-byte.
    material = _b3.blake3_derive_key(_GEAR_CONTEXT, b"gear", 256 * 8)
    return struct.unpack("<256Q", material)


GEAR = _make_gear_table()

_U64 = (1 << 64) - 1


@dataclass(frozen=True)
class Chunk:
    offset: int
    length: int


def _cut_points_py(data: memoryview) -> list[int]:
    """Return chunk end offsets (exclusive) for ``data``."""
    cuts: list[int] = []
    n = len(data)
    start = 0
    h = 0
    i = 0
    while i < n:
        h = ((h << 1) + GEAR[data[i]]) & _U64
        i += 1
        length = i - start
        if length >= MIN_CHUNK and (h & MASK) == 0 or length >= MAX_CHUNK:
            cuts.append(i)
            start = i
            h = 0
    if start < n:
        cuts.append(n)
    return cuts


def cut_points(data: bytes | memoryview) -> list[int]:
    data = memoryview(data)
    native = _get_native()
    if native is not None and len(data) > 0:
        return native.gear_cut_points(bytes(data), MIN_CHUNK, MAX_CHUNK, MASK)
    return _cut_points_py(data)


def chunk_stream(data: bytes | memoryview) -> Iterator[tuple[Chunk, bytes]]:
    """Yield (Chunk, chunk bytes) pairs covering ``data`` exactly."""
    data = memoryview(data)
    start = 0
    for end in cut_points(data):
        yield Chunk(start, end - start), bytes(data[start:end])
        start = end


def _get_native():
    try:
        from zest_tpu.native import lib

        return lib if lib.available() and hasattr(lib, "gear_cut_points") else None
    except Exception:
        return None
