"""GearHash content-defined chunking (the zig-xet `chunking` equivalent).

Splits byte streams into content-defined chunks (min 8KB / target 64KB /
max 128KB — the Xet parameters, reference DESIGN.md:265-273) so identical
content produces identical chunk boundaries regardless of surrounding bytes;
this is what makes chunk-level dedup work across model revisions.

Algorithm: GearHash rolling hash — ``h = (h << 1) + GEAR[byte]`` — with a
cut when the top 16 bits of ``h`` are all zero (expected gap 2^16 = 64 KiB;
the MIN_CHUNK skip shifts the mean to ~MIN + 64 KiB and MAX_CHUNK truncates
the geometric tail). Table, mask, and limits are the PRODUCTION Xet
constants (zest_tpu.cas.xet_constants), so chunk boundaries — and therefore
every content address downstream — match HF's CAS exactly (verified against
the official client, tests/test_xet_interop.py).

Hot path dispatches to the native C++ scanner (zest_tpu/native/gearhash.cc)
when available; the pure-Python implementation is the correctness anchor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

from zest_tpu.cas import xet_constants as _xc

MIN_CHUNK = _xc.MIN_CHUNK
TARGET_CHUNK = _xc.TARGET_CHUNK
MAX_CHUNK = _xc.MAX_CHUNK
MASK = _xc.MASK
GEAR = _xc.GEAR_TABLE

_U64 = (1 << 64) - 1


@dataclass(frozen=True)
class Chunk:
    offset: int
    length: int


def _cut_points_py(data: memoryview) -> list[int]:
    """Return chunk end offsets (exclusive) for ``data``."""
    cuts: list[int] = []
    n = len(data)
    start = 0
    h = 0
    i = 0
    while i < n:
        h = ((h << 1) + GEAR[data[i]]) & _U64
        i += 1
        length = i - start
        if length >= MIN_CHUNK and (h & MASK) == 0 or length >= MAX_CHUNK:
            cuts.append(i)
            start = i
            h = 0
    if start < n:
        cuts.append(n)
    return cuts


def cut_points(data: bytes | memoryview) -> list[int]:
    """Chunk end offsets (exclusive) covering ``data`` exactly.

    Edge-case contract — pinned byte-identical across the python and
    native paths by tests/test_chunking.py (the write path publishes
    through this, so a divergence would fork content addresses):

    - empty input → ``[]`` (no zero-length chunk; ``chunk_stream``
      yields nothing),
    - input shorter than MIN_CHUNK → exactly one cut at ``len(data)``
      (the min-size skip means no mask cut can fire earlier),
    - a mask/max cut landing exactly on ``len(data)`` is emitted once —
      never followed by a trailing zero-length cut.
    """
    data = memoryview(data)
    if len(data) == 0:
        # Explicit, not an artifact of dispatch: the empty stream has
        # no chunks on EITHER path (previously this relied on the
        # native branch being skipped for len 0).
        return []
    native = _get_native()
    if native is not None:
        return native.gear_cut_points(bytes(data), MIN_CHUNK, MAX_CHUNK, MASK)
    return _cut_points_py(data)


def chunk_stream(data: bytes | memoryview) -> Iterator[tuple[Chunk, bytes]]:
    """Yield (Chunk, chunk bytes) pairs covering ``data`` exactly."""
    data = memoryview(data)
    start = 0
    for end in cut_points(data):
        yield Chunk(start, end - start), bytes(data[start:end])
        start = end


def _get_native():
    try:
        from zest_tpu.native import lib

        return lib if lib.available() and hasattr(lib, "gear_cut_points") else None
    except Exception:
        return None
