"""Content-addressing hash conventions (the zig-xet `hashing` equivalent).

Three things live here, mirroring what the reference gets from zig-xet
(SURVEY.md §2.2, row `hashing`):

1. **BLAKE3 dispatch** — one-shot hashing routed to the fastest available
   backend: native C++ (zest_tpu/native) when built, else pure Python.
   The Pallas on-device kernel (zest_tpu.ops.blake3_pallas) is used by the
   HBM verification path, not here.

2. **MerkleHash hex convention** — xorb cache keys and CAS API hex use the
   *little-endian u64* encoding: the 32-byte hash is read as 4 u64 (LE) and
   each is printed as 16 hex digits. This differs from plain byte hex and
   MUST be used for xorb cache keys (reference: src/server.zig:201-204,
   plain-hex counterpart at src/storage.zig:91-99).

3. **Domain-separated chunk/node keys** — chunk hashes and Merkle interior
   nodes use distinct BLAKE3 keyed modes so a chunk can never collide with
   a subtree. The keys, merkle grouping, and file-salt step are the
   PRODUCTION Xet constants (zest_tpu.cas.xet_constants), verified
   bit-for-bit against the official client — hashes computed here are
   real HF CAS addresses.
"""

from __future__ import annotations

import struct

from zest_tpu.cas import blake3 as _py_blake3
from zest_tpu.cas import xet_constants as _xc

# Native backend is optional; loaded lazily to keep import cheap.
_native = None
_native_checked = False


def _get_native():
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from zest_tpu.native import lib as _lib

            _native = _lib if _lib.available() else None
        except Exception:
            _native = None
    return _native


HASH_LEN = 32

# ── Domain-separation keys (production Xet constants) ──
CHUNK_KEY = _xc.CHUNK_KEY
NODE_KEY = _xc.NODE_KEY
FILE_SALT = _xc.FILE_SALT


def blake3_hash(data: bytes) -> bytes:
    """Plain BLAKE3-256 of ``data`` via the fastest host backend."""
    native = _get_native()
    if native is not None:
        return native.blake3(data)
    return _py_blake3.blake3(data)


def blake3_keyed(key: bytes, data: bytes) -> bytes:
    native = _get_native()
    if native is not None:
        return native.blake3_keyed(key, data)
    return _py_blake3.blake3_keyed(key, data)


def chunk_hash(data: bytes) -> bytes:
    """Content hash of one CDC chunk (keyed, chunk domain)."""
    return blake3_keyed(CHUNK_KEY, data)


# ── Merkle aggregation (production Xet tree) ──
#
# Leaves are (chunk_hash, byte_length). Children group left-to-right:
# a group closes at its k-th child (k >= GROUP_MIN) when the child hash's
# last u64 (LE) % GROUP_MOD == 0, or unconditionally at k == GROUP_MAX.
# Each parent is the keyed BLAKE3 (node domain) of the text
# ``"{hash_hex} : {size}\n"`` per child, carrying the summed length.
# Iterate to a single root; one leaf is its own root. Verified bit-for-bit
# against the official client (tests/test_xet_interop.py).


def node_hash(children: list[tuple[bytes, int]]) -> bytes:
    buf = []
    for h, length in children:
        if len(h) != HASH_LEN:
            raise ValueError("child hash must be 32 bytes")
        buf.append(f"{hash_to_hex(h)} : {length}\n")
    return blake3_keyed(NODE_KEY, "".join(buf).encode())


def _closes_group(child_hash: bytes, k: int) -> bool:
    if k >= _xc.GROUP_MAX:
        return True
    if k < _xc.GROUP_MIN:
        return False
    last = struct.unpack("<Q", child_hash[24:32])[0]
    return last % _xc.GROUP_MOD == 0


def merkle_root(leaves: list[tuple[bytes, int]]) -> tuple[bytes, int]:
    """Production Xet merkle root over (hash, length) leaves."""
    if not leaves:
        return chunk_hash(b""), 0
    level = list(leaves)
    while len(level) > 1:
        nxt: list[tuple[bytes, int]] = []
        group: list[tuple[bytes, int]] = []
        for child in level:
            group.append(child)
            if _closes_group(child[0], len(group)):
                nxt.append((node_hash(group), sum(s for _, s in group)))
                group = []
        if group:
            nxt.append((node_hash(group), sum(s for _, s in group)))
        level = nxt
    return level[0]


def xorb_hash(chunk_hashes: list[tuple[bytes, int]]) -> bytes:
    """Content address of a xorb = Merkle root over its chunks."""
    return merkle_root(chunk_hashes)[0]


def file_hash(chunk_hashes: list[tuple[bytes, int]]) -> bytes:
    """Content address of a file: the merkle root over the file's chunk
    sequence, salted — ``blake3_keyed(FILE_SALT, root)`` — so file
    addresses never collide with xorb addresses. HF uses the zero salt.

    An empty file's address is the all-zero hash (official-client
    behavior, cross-checked in tests/test_xet_interop.py), not a salted
    empty root."""
    if not chunk_hashes:
        return bytes(HASH_LEN)
    return blake3_keyed(FILE_SALT, merkle_root(chunk_hashes)[0])


# ── Hex conventions ──


def hash_to_hex(h: bytes) -> str:
    """MerkleHash hex: 4 little-endian u64 groups, each printed %016x.

    Used for xorb cache keys and CAS API hex so keys match across writer
    and reader (reference: src/server.zig:201-204).
    """
    if len(h) != HASH_LEN:
        raise ValueError(f"hash must be {HASH_LEN} bytes, got {len(h)}")
    a, b, c, d = struct.unpack("<4Q", h)
    return f"{a:016x}{b:016x}{c:016x}{d:016x}"


def hex_to_hash(s: str) -> bytes:
    """Inverse of :func:`hash_to_hex` (zig-xet ``apiHexToHash``)."""
    if len(s) != 64:
        raise ValueError(f"hex hash must be 64 chars, got {len(s)}")
    words = [int(s[i : i + 16], 16) for i in (0, 16, 32, 48)]
    return struct.pack("<4Q", *words)


def bytes_to_hex(h: bytes) -> str:
    """Plain byte-order hex (chunk cache keys; src/storage.zig:91-99)."""
    return h.hex()
