"""Content-addressing hash conventions (the zig-xet `hashing` equivalent).

Three things live here, mirroring what the reference gets from zig-xet
(SURVEY.md §2.2, row `hashing`):

1. **BLAKE3 dispatch** — one-shot hashing routed to the fastest available
   backend: native C++ (zest_tpu/native) when built, else pure Python.
   The Pallas on-device kernel (zest_tpu.ops.blake3_pallas) is used by the
   HBM verification path, not here.

2. **MerkleHash hex convention** — xorb cache keys and CAS API hex use the
   *little-endian u64* encoding: the 32-byte hash is read as 4 u64 (LE) and
   each is printed as 16 hex digits. This differs from plain byte hex and
   MUST be used for xorb cache keys (reference: src/server.zig:201-204,
   plain-hex counterpart at src/storage.zig:91-99).

3. **Domain-separated chunk/node keys** — chunk hashes and Merkle interior
   nodes use distinct BLAKE3 keyed modes so a chunk can never collide with
   a subtree (xet-core convention). The concrete 32-byte keys are derived
   from documented context strings; they are a compatibility seam — wire
   them to the production Xet constants to interoperate with HF's CAS.
"""

from __future__ import annotations

import struct

from zest_tpu.cas import blake3 as _py_blake3

# Native backend is optional; loaded lazily to keep import cheap.
_native = None
_native_checked = False


def _get_native():
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from zest_tpu.native import lib as _lib

            _native = _lib if _lib.available() else None
        except Exception:
            _native = None
    return _native


HASH_LEN = 32

# ── Domain-separation keys (compatibility seam; see module docstring) ──
CHUNK_KEY = _py_blake3.blake3_derive_key("zest-tpu xet chunk hash v1", b"zest")
NODE_KEY = _py_blake3.blake3_derive_key("zest-tpu xet merkle node v1", b"zest")


def blake3_hash(data: bytes) -> bytes:
    """Plain BLAKE3-256 of ``data`` via the fastest host backend."""
    native = _get_native()
    if native is not None:
        return native.blake3(data)
    return _py_blake3.blake3(data)


def blake3_keyed(key: bytes, data: bytes) -> bytes:
    native = _get_native()
    if native is not None:
        return native.blake3_keyed(key, data)
    return _py_blake3.blake3_keyed(key, data)


def chunk_hash(data: bytes) -> bytes:
    """Content hash of one CDC chunk (keyed, chunk domain)."""
    return blake3_keyed(CHUNK_KEY, data)


# ── Merkle aggregation ──
#
# Leaves are (chunk_hash, byte_length); interior nodes hash the concatenation
# of each child's ``hash || u64le(length)`` under the node key and carry the
# summed length. Xorb hashes and file hashes use the same tree so dedup is
# consistent at every level.


def node_hash(children: list[tuple[bytes, int]]) -> bytes:
    buf = bytearray()
    for h, length in children:
        if len(h) != HASH_LEN:
            raise ValueError("child hash must be 32 bytes")
        buf += h
        buf += struct.pack("<Q", length)
    return blake3_keyed(NODE_KEY, bytes(buf))


def merkle_root(leaves: list[tuple[bytes, int]]) -> tuple[bytes, int]:
    """Binary Merkle root over (hash, length) leaves.

    Pairs children level by level; an odd tail node is promoted unchanged
    (so a single chunk's xorb hash is that chunk's hash).
    """
    if not leaves:
        return chunk_hash(b""), 0
    level = list(leaves)
    while len(level) > 1:
        nxt: list[tuple[bytes, int]] = []
        for i in range(0, len(level) - 1, 2):
            pair = [level[i], level[i + 1]]
            nxt.append((node_hash(pair), pair[0][1] + pair[1][1]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def xorb_hash(chunk_hashes: list[tuple[bytes, int]]) -> bytes:
    """Content address of a xorb = Merkle root over its chunks."""
    return merkle_root(chunk_hashes)[0]


def file_hash(chunk_hashes: list[tuple[bytes, int]]) -> bytes:
    """Content address of a file = Merkle root over its chunk sequence."""
    return merkle_root(chunk_hashes)[0]


# ── Hex conventions ──


def hash_to_hex(h: bytes) -> str:
    """MerkleHash hex: 4 little-endian u64 groups, each printed %016x.

    Used for xorb cache keys and CAS API hex so keys match across writer
    and reader (reference: src/server.zig:201-204).
    """
    if len(h) != HASH_LEN:
        raise ValueError(f"hash must be {HASH_LEN} bytes, got {len(h)}")
    a, b, c, d = struct.unpack("<4Q", h)
    return f"{a:016x}{b:016x}{c:016x}{d:016x}"


def hex_to_hash(s: str) -> bytes:
    """Inverse of :func:`hash_to_hex` (zig-xet ``apiHexToHash``)."""
    if len(s) != 64:
        raise ValueError(f"hex hash must be 64 chars, got {len(s)}")
    words = [int(s[i : i + 16], 16) for i in (0, 16, 32, 48)]
    return struct.pack("<4Q", *words)


def bytes_to_hex(h: bytes) -> str:
    """Plain byte-order hex (chunk cache keys; src/storage.zig:91-99)."""
    return h.hex()
