"""Xorb container format (the zig-xet `xorb` equivalent).

A xorb is a content-addressed bundle of CDC chunks — the unit of transfer
and caching in the whole system (reference behavior: SURVEY.md §2.2 rows
`xorb`/`chunking`; 64 MiB max matching the wire message cap,
src/bt_wire.zig:22). The xorb's identity is the Merkle root over its chunk
hashes (zest_tpu.cas.hashing.xorb_hash).

Layout — ZXORB v2, a **self-framed chunk stream** with no container header,
so any contiguous chunk range is a contiguous byte range. This is what makes
the whole transfer economy work: CDN ``fetch_info.url_range`` byte ranges,
partial cache entries (``{hash}.{range_start}``), BEP XET range responses,
and ICI shard slices are all just frame subsequences.

    per chunk frame (40 + compressed_len bytes, integers little-endian):
        u8   scheme          (cas.compression.Scheme)
        u24  compressed_len
        u32  uncompressed_len
        32B  chunk hash      (keyed BLAKE3, chunk domain)
        ...  payload

Chunk extraction is range-addressed — ``extract_chunk_range(start, end)`` —
because reconstruction terms and BEP XET requests address *chunk index
ranges within a xorb*, not whole xorbs (reference: src/bep_xet.zig:66-74,
src/swarm.zig:25-31).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from zest_tpu.cas import chunking, compression, hashing

FRAME_HEADER_LEN = 40
# Cap on the SERIALIZED xorb (frames included) so a full xorb always fits
# in one wire message (wire.MAX_MESSAGE_SIZE = 64 MiB + 1 KiB, minus BEP 10
# and XET framing overhead).
MAX_XORB_BYTES = 64 * 1024 * 1024 - 64
MAX_CHUNKS = 8 * 1024
# Largest single chunk a reader will decode. CDC chunks are <= 128 KiB
# (chunking.MAX_CHUNK); the slack allows hand-built chunks while still
# bounding what an untrusted frame header can make us allocate.
MAX_CHUNK_BYTES = 4 * 1024 * 1024
_MAX_COMPRESSED = (1 << 24) - 1


class XorbFormatError(ValueError):
    pass


@dataclass(frozen=True)
class ChunkEntry:
    frame_offset: int          # byte offset of the frame within this blob
    compressed_len: int
    uncompressed_len: int
    scheme: compression.Scheme
    hash: bytes

    @property
    def frame_len(self) -> int:
        return FRAME_HEADER_LEN + self.compressed_len


def encode_frame(data: bytes) -> tuple[bytes, bytes]:
    """Encode one chunk into a frame; returns (frame, chunk_hash)."""
    if len(data) > MAX_CHUNK_BYTES:
        raise XorbFormatError(f"chunk of {len(data)} bytes exceeds cap")
    scheme, payload = compression.compress_auto(data)
    if len(payload) > _MAX_COMPRESSED:
        raise XorbFormatError("chunk payload too large")
    h = hashing.chunk_hash(data)
    header = struct.pack(
        "<I", int(scheme) | (len(payload) << 8)
    ) + struct.pack("<I", len(data)) + h
    return header + payload, h


class XorbBuilder:
    """Accumulates chunks into a serialized xorb.

    Compression is chosen per chunk (`compress_auto`); identity is computed
    over the *uncompressed* chunk hashes so the same content always produces
    the same xorb hash regardless of encoding.
    """

    def __init__(self) -> None:
        self._frames: list[bytes] = []
        self._hashes: list[tuple[bytes, int]] = []
        self._uncompressed_total = 0
        self._serialized_total = 0

    def __len__(self) -> int:
        return len(self._frames)

    @property
    def uncompressed_total(self) -> int:
        return self._uncompressed_total

    def would_overflow(self, chunk_len: int) -> bool:
        # Worst case the new chunk stores uncompressed: header + chunk_len.
        return (
            self._serialized_total + FRAME_HEADER_LEN + chunk_len > MAX_XORB_BYTES
            or len(self._frames) + 1 > MAX_CHUNKS
        )

    def add_chunk(self, data: bytes) -> bytes:
        """Append one chunk; returns its hash."""
        if self.would_overflow(len(data)):
            raise XorbFormatError("xorb full")
        frame, h = encode_frame(data)
        self._frames.append(frame)
        self._hashes.append((h, len(data)))
        self._uncompressed_total += len(data)
        self._serialized_total += len(frame)
        return h

    def add_data(self, data: bytes) -> list[bytes]:
        """CDC-chunk ``data`` and append every chunk; returns chunk hashes."""
        return [self.add_chunk(piece) for _, piece in chunking.chunk_stream(data)]

    def chunk_hashes(self) -> list[tuple[bytes, int]]:
        return list(self._hashes)

    def xorb_hash(self) -> bytes:
        return hashing.xorb_hash(self._hashes)

    def frame_offsets(self) -> list[int]:
        """Byte offset of each frame plus the end offset (len N+1).

        ``offsets[s]:offsets[e]`` is the byte range serving chunk range
        [s, e) — this is what populates CAS ``fetch_info.url_range``.
        """
        offs = [0]
        for f in self._frames:
            offs.append(offs[-1] + len(f))
        return offs

    def serialize(self) -> bytes:
        return b"".join(self._frames)


class XorbReader:
    """Parses a frame stream and extracts verified chunk ranges.

    ``data`` may be a *full* xorb or any frame subsequence (a partial cache
    entry, a CDN byte-range response, a BEP XET chunk response); chunk
    indices here are local to the blob — callers rebase absolute term
    indices by the blob's ``chunk_offset``.
    """

    def __init__(self, data: bytes | memoryview):
        data = memoryview(data)
        self.entries: list[ChunkEntry] = []
        pos = 0
        n = len(data)
        while pos < n:
            if pos + FRAME_HEADER_LEN > n:
                raise XorbFormatError("truncated frame header")
            (word0,) = struct.unpack("<I", data[pos : pos + 4])
            scheme_raw = word0 & 0xFF
            compressed_len = word0 >> 8
            (uncompressed_len,) = struct.unpack("<I", data[pos + 4 : pos + 8])
            h = bytes(data[pos + 8 : pos + 40])
            try:
                scheme = compression.Scheme(scheme_raw)
            except ValueError as exc:
                raise XorbFormatError(f"unknown scheme {scheme_raw}") from exc
            if uncompressed_len > MAX_CHUNK_BYTES:
                # Untrusted header must not dictate our allocations.
                raise XorbFormatError(
                    f"chunk claims {uncompressed_len} bytes (cap "
                    f"{MAX_CHUNK_BYTES})"
                )
            end = pos + FRAME_HEADER_LEN + compressed_len
            if end > n:
                raise XorbFormatError("frame payload extends past end")
            if len(self.entries) >= MAX_CHUNKS:
                raise XorbFormatError("too many chunks")
            self.entries.append(
                ChunkEntry(pos, compressed_len, uncompressed_len, scheme, h)
            )
            pos = end
        self._data = data

    def __len__(self) -> int:
        return len(self.entries)

    def chunk_hashes(self) -> list[tuple[bytes, int]]:
        return [(e.hash, e.uncompressed_len) for e in self.entries]

    def xorb_hash(self) -> bytes:
        return hashing.xorb_hash(self.chunk_hashes())

    def extract_chunk(self, index: int, verify: bool = True) -> bytes:
        e = self.entries[index]
        payload_start = e.frame_offset + FRAME_HEADER_LEN
        payload = bytes(
            self._data[payload_start : payload_start + e.compressed_len]
        )
        data = compression.decompress(payload, e.scheme, e.uncompressed_len)
        if verify and hashing.chunk_hash(data) != e.hash:
            raise XorbFormatError(f"chunk {index} hash mismatch")
        return data

    def extract_chunk_range(
        self, start: int, end: int, verify: bool = True
    ) -> bytes:
        """Concatenated bytes of chunks [start, end) — the term-fetch shape
        (reference: xet_bridge.zig:256-258, parallel_download.zig:65-66)."""
        self._check_range(start, end)
        return b"".join(
            self.extract_chunk(i, verify=verify) for i in range(start, end)
        )

    def slice_range(self, start: int, end: int) -> bytes:
        """Raw frame bytes for chunks [start, end) — what a seeder sends on
        the wire and what lands in a partial cache entry."""
        self._check_range(start, end)
        first = self.entries[start].frame_offset
        last = self.entries[end - 1]
        return bytes(self._data[first : last.frame_offset + last.frame_len])

    def _check_range(self, start: int, end: int) -> None:
        if not (0 <= start < end <= len(self.entries)):
            raise XorbFormatError(
                f"chunk range [{start},{end}) out of bounds for "
                f"{len(self.entries)} chunks"
            )


def build_from_data(data: bytes) -> tuple[bytes, bytes, list[tuple[bytes, int]]]:
    """Convenience: CDC-chunk ``data`` into one xorb.

    Returns (xorb_hash, serialized_xorb, chunk_hashes). Raises if the data
    exceeds one xorb's capacity — callers split first.
    """
    builder = XorbBuilder()
    builder.add_data(data)
    return builder.xorb_hash(), builder.serialize(), builder.chunk_hashes()
