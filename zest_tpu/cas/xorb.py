"""Xorb container format — production XETBLOB (the zig-xet `xorb` equivalent).

A xorb is a content-addressed bundle of CDC chunks — the unit of transfer
and caching in the whole system (reference behavior: SURVEY.md §2.2 rows
`xorb`/`chunking`; 64 MiB max matching the wire message cap,
src/bt_wire.zig:22). The xorb's identity is the Merkle root over its chunk
hashes (zest_tpu.cas.hashing.xorb_hash).

This module implements the XETBLOB layout. The chunk/xorb/file content
addresses it computes ARE production HF CAS addresses (pinned against the
official hf_xet client in tests/test_xet_interop.py); the container
byte layout itself is pinned by a frozen golden fixture in the same
suite — no production xorb can be captured offline, so layout compat
with the official writer rests on the format description below:

    per chunk frame (8 + compressed_len bytes, integers little-endian):
        u8   version          (0)
        u24  compressed_len
        u8   scheme           (cas.compression.Scheme)
        u24  uncompressed_len
        ...  payload

    full-xorb footer (40*n + 96 bytes):
        "XETBLOB" u8(1)                     ident + version
        32B xorb hash
        "XBLBHSH" u8(0) u32 n  n×32B        chunk hashes
        "XBLBBND" u8(1) u32 n  n×u32 n×u32  serialized / uncompressed
                                            cumulative end offsets
        u32 n, u32 footer_len-40, u32 8n+40, 4×u32 0, u32 footer_len

The chunk frames are **self-framed**: any contiguous chunk range is a
contiguous byte range, which is what makes the whole transfer economy work —
CDN ``fetch_info.url_range`` byte ranges, partial cache entries
(``{hash}.{range_start}``), BEP XET range responses, and ICI shard slices
are all frame subsequences. The footer travels only with *full* xorbs
(CDN storage artifacts, full-xorb cache entries); range reads never touch
it, exactly as HF's CAS serves S3 byte ranges of the frame region.

Chunk extraction is range-addressed — ``extract_chunk_range(start, end)`` —
because reconstruction terms and BEP XET requests address *chunk index
ranges within a xorb*, not whole xorbs (reference: src/bep_xet.zig:66-74,
src/swarm.zig:25-31).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from zest_tpu.cas import chunking, compression, hashing

FRAME_HEADER_LEN = 8
FOOTER_IDENT = b"XETBLOB"
_HSH_IDENT = b"XBLBHSH"
_BND_IDENT = b"XBLBBND"
# Cap on the SERIALIZED xorb (frames included) so a full xorb always fits
# in one wire message (wire.MAX_MESSAGE_SIZE = 64 MiB + 1 KiB, minus BEP 10
# and XET framing overhead).
MAX_XORB_BYTES = 64 * 1024 * 1024 - 64
MAX_CHUNKS = 8 * 1024
# Largest single chunk a reader will decode. CDC chunks are <= 128 KiB
# (chunking.MAX_CHUNK); the slack allows hand-built chunks while still
# bounding what an untrusted frame header can make us allocate.
MAX_CHUNK_BYTES = 4 * 1024 * 1024
_MAX_U24 = (1 << 24) - 1


class XorbFormatError(ValueError):
    pass


@dataclass(frozen=True)
class ChunkEntry:
    frame_offset: int          # byte offset of the frame within this blob
    compressed_len: int
    uncompressed_len: int
    scheme: compression.Scheme
    hash: bytes | None         # known only when a footer was present

    @property
    def frame_len(self) -> int:
        return FRAME_HEADER_LEN + self.compressed_len


def encode_frame(data: bytes) -> tuple[bytes, bytes]:
    """Encode one chunk into a frame; returns (frame, chunk_hash)."""
    if len(data) > MAX_CHUNK_BYTES:
        raise XorbFormatError(f"chunk of {len(data)} bytes exceeds cap")
    scheme, payload = compression.compress_auto(data)
    if len(payload) > _MAX_U24:
        raise XorbFormatError("chunk payload too large")
    h = hashing.chunk_hash(data)
    header = (
        bytes([0])
        + len(payload).to_bytes(3, "little")
        + bytes([int(scheme)])
        + len(data).to_bytes(3, "little")
    )
    return header + payload, h


def _encode_footer(
    xorb_hash: bytes,
    hashes: list[tuple[bytes, int]],
    ser_ends: list[int],
) -> bytes:
    n = len(hashes)
    unc_ends, total = [], 0
    for _, size in hashes:
        total += size
        unc_ends.append(total)
    out = bytearray()
    out += FOOTER_IDENT + bytes([1]) + xorb_hash
    out += _HSH_IDENT + bytes([0]) + struct.pack("<I", n)
    for h, _ in hashes:
        out += h
    out += _BND_IDENT + bytes([1]) + struct.pack("<I", n)
    out += struct.pack(f"<{n}I", *ser_ends)
    out += struct.pack(f"<{n}I", *unc_ends)
    footer_len = 40 * n + 92
    out += struct.pack("<8I", n, footer_len - 40, 8 * n + 40, 0, 0, 0, 0,
                       footer_len)
    return bytes(out)


class XorbBuilder:
    """Accumulates chunks into a serialized xorb.

    Compression is chosen per chunk (`compress_auto`); identity is computed
    over the *uncompressed* chunk hashes so the same content always produces
    the same xorb hash regardless of encoding.
    """

    def __init__(self) -> None:
        self._frames: list[bytes] = []
        self._hashes: list[tuple[bytes, int]] = []
        self._uncompressed_total = 0
        self._serialized_total = 0

    def __len__(self) -> int:
        return len(self._frames)

    @property
    def uncompressed_total(self) -> int:
        return self._uncompressed_total

    def would_overflow(self, chunk_len: int) -> bool:
        # Worst case the new chunk stores uncompressed: header + chunk_len.
        return (
            self._serialized_total + FRAME_HEADER_LEN + chunk_len > MAX_XORB_BYTES
            or len(self._frames) + 1 > MAX_CHUNKS
        )

    def add_chunk(self, data: bytes) -> bytes:
        """Append one chunk; returns its hash."""
        if self.would_overflow(len(data)):
            raise XorbFormatError("xorb full")
        frame, h = encode_frame(data)
        self._frames.append(frame)
        self._hashes.append((h, len(data)))
        self._uncompressed_total += len(data)
        self._serialized_total += len(frame)
        return h

    def add_data(self, data: bytes) -> list[bytes]:
        """CDC-chunk ``data`` and append every chunk; returns chunk hashes."""
        return [self.add_chunk(piece) for _, piece in chunking.chunk_stream(data)]

    def chunk_hashes(self) -> list[tuple[bytes, int]]:
        return list(self._hashes)

    def xorb_hash(self) -> bytes:
        return hashing.xorb_hash(self._hashes)

    def frame_offsets(self) -> list[int]:
        """Byte offset of each frame plus the end offset (len N+1).

        ``offsets[s]:offsets[e]`` is the byte range serving chunk range
        [s, e) — this is what populates CAS ``fetch_info.url_range``.
        """
        offs = [0]
        for f in self._frames:
            offs.append(offs[-1] + len(f))
        return offs

    def serialize(self) -> bytes:
        """Frame stream only — the in-pipeline blob shape."""
        return b"".join(self._frames)

    def serialize_full(self) -> bytes:
        """Frames + XETBLOB footer — the storage/CDN artifact shape
        (layout frozen by tests/test_xet_interop.py golden fixtures)."""
        return self.serialize() + _encode_footer(
            self.xorb_hash(), self._hashes, self.frame_offsets()[1:]
        )


def parse_footer(data: bytes | memoryview) -> tuple[int, bytes, list[bytes]]:
    """If ``data`` ends with a XETBLOB footer, return
    (frames_end, xorb_hash, chunk_hashes); raise XorbFormatError otherwise.
    """
    data = memoryview(data)
    if len(data) < 96 + 4:
        raise XorbFormatError("too short for a XETBLOB footer")
    (footer_len,) = struct.unpack("<I", data[-4:])
    start = len(data) - 4 - footer_len
    if footer_len < 92 or start < 0:
        raise XorbFormatError("bad footer length")
    foot = bytes(data[start : len(data) - 4])
    if foot[:7] != FOOTER_IDENT:
        raise XorbFormatError("missing XETBLOB ident")
    xorb_hash = foot[8:40]
    if foot[40:47] != _HSH_IDENT:
        raise XorbFormatError("missing hash section")
    (n,) = struct.unpack_from("<I", foot, 48)
    if footer_len != 40 * n + 92 or n > MAX_CHUNKS:
        raise XorbFormatError("footer length inconsistent with chunk count")
    off = 52
    hashes = [foot[off + 32 * i : off + 32 * (i + 1)] for i in range(n)]
    off += 32 * n
    if foot[off : off + 7] != _BND_IDENT:
        raise XorbFormatError("missing boundary section")
    return start, xorb_hash, hashes


class XorbReader:
    """Parses a frame stream and extracts chunk ranges.

    ``data`` may be a *full* XETBLOB (frames + footer — a CDN storage
    artifact or full-xorb cache entry) or any frame subsequence (a partial
    cache entry, a CDN byte-range response, a BEP XET chunk response).
    Chunk indices here are local to the blob — callers rebase absolute
    term indices by the blob's ``chunk_offset``. With a footer, per-chunk
    hashes are known and extraction verifies them; bare frame streams are
    verified downstream (device BLAKE3 before full-xorb cache writes,
    file-level hashes after reassembly) — the same trust model as the
    production CDN path, whose range responses carry no hashes either.
    """

    def __init__(self, data: bytes | memoryview):
        data = memoryview(data)
        self.xorb_hash_footer: bytes | None = None
        frames_end = len(data)
        footer_hashes: list[bytes] | None = None
        try:
            frames_end, self.xorb_hash_footer, footer_hashes = \
                parse_footer(data)
        except XorbFormatError:
            pass
        self.entries: list[ChunkEntry] = []
        pos = 0
        while pos < frames_end:
            if pos + FRAME_HEADER_LEN > frames_end:
                raise XorbFormatError("truncated frame header")
            if data[pos] != 0:
                raise XorbFormatError(
                    f"unknown chunk frame version {data[pos]}"
                )
            compressed_len = int.from_bytes(data[pos + 1 : pos + 4], "little")
            scheme_raw = data[pos + 4]
            uncompressed_len = int.from_bytes(
                data[pos + 5 : pos + 8], "little"
            )
            try:
                scheme = compression.Scheme(scheme_raw)
            except ValueError as exc:
                raise XorbFormatError(f"unknown scheme {scheme_raw}") from exc
            if uncompressed_len > MAX_CHUNK_BYTES:
                # Untrusted header must not dictate our allocations.
                raise XorbFormatError(
                    f"chunk claims {uncompressed_len} bytes (cap "
                    f"{MAX_CHUNK_BYTES})"
                )
            end = pos + FRAME_HEADER_LEN + compressed_len
            if end > frames_end:
                raise XorbFormatError("frame payload extends past end")
            if len(self.entries) >= MAX_CHUNKS:
                raise XorbFormatError("too many chunks")
            i = len(self.entries)
            h = footer_hashes[i] if footer_hashes and i < len(footer_hashes) \
                else None
            self.entries.append(
                ChunkEntry(pos, compressed_len, uncompressed_len, scheme, h)
            )
            pos = end
        if footer_hashes is not None and len(footer_hashes) != len(self.entries):
            raise XorbFormatError(
                f"footer lists {len(footer_hashes)} chunks, "
                f"frames hold {len(self.entries)}"
            )
        self._data = data

    def __len__(self) -> int:
        return len(self.entries)

    def chunk_hashes(self) -> list[tuple[bytes, int]]:
        """(hash, uncompressed length) per chunk — from the footer when
        present, else computed by decoding (the authoritative source)."""
        out = []
        for i, e in enumerate(self.entries):
            h = e.hash if e.hash is not None else hashing.chunk_hash(
                self.extract_chunk(i, verify=False)
            )
            out.append((h, e.uncompressed_len))
        return out

    def xorb_hash(self) -> bytes:
        return hashing.xorb_hash(self.chunk_hashes())

    def extract_chunk(self, index: int, verify: bool = True) -> bytes:
        e = self.entries[index]
        payload_start = e.frame_offset + FRAME_HEADER_LEN
        payload = bytes(
            self._data[payload_start : payload_start + e.compressed_len]
        )
        data = compression.decompress(payload, e.scheme, e.uncompressed_len)
        if verify and e.hash is not None and hashing.chunk_hash(data) != e.hash:
            raise XorbFormatError(f"chunk {index} hash mismatch")
        return data

    def extract_chunk_range(
        self, start: int, end: int, verify: bool = True
    ) -> bytes:
        """Concatenated bytes of chunks [start, end) — the term-fetch shape
        (reference: xet_bridge.zig:256-258, parallel_download.zig:65-66)."""
        self._check_range(start, end)
        return b"".join(
            self.extract_chunk(i, verify=verify) for i in range(start, end)
        )

    def extract_range_into(self, start: int, end: int, out) -> int:
        """Decode chunks [start, end) directly into ``out`` (a writable
        buffer of exactly the range's uncompressed size); returns the
        byte count.

        The GB-scale landing path decodes most bytes through here:
        stored chunks (scheme NONE, the common case for incompressible
        bf16 weights) copy frame→destination with no intermediate bytes
        object, skipping the per-chunk allocation and the final join
        that ``extract_chunk_range`` pays. Chunks that are compressed
        or carry a footer hash take the verifying
        :meth:`extract_chunk` path and are then copied in."""
        self._check_range(start, end)
        view = memoryview(out).cast("B")
        total = sum(self.entries[i].uncompressed_len
                    for i in range(start, end))
        if view.nbytes != total:
            raise XorbFormatError(
                f"out buffer is {view.nbytes} bytes for a "
                f"{total}-byte chunk range"
            )
        pos = 0
        for i in range(start, end):
            e = self.entries[i]
            if e.scheme == compression.Scheme.NONE and e.hash is None:
                if e.compressed_len != e.uncompressed_len:
                    # Same contract as compression.decompress's stored
                    # path — a hostile frame must raise the module's
                    # error type, not a bare memoryview ValueError.
                    raise XorbFormatError(
                        f"stored chunk {i} claims {e.uncompressed_len} "
                        f"bytes but frames {e.compressed_len}"
                    )
                p0 = e.frame_offset + FRAME_HEADER_LEN
                view[pos:pos + e.uncompressed_len] = \
                    self._data[p0:p0 + e.compressed_len]
                pos += e.uncompressed_len
            else:
                data = self.extract_chunk(i)
                view[pos:pos + len(data)] = data
                pos += len(data)
        return pos

    def slice_range(self, start: int, end: int) -> bytes:
        """Raw frame bytes for chunks [start, end) — what a seeder sends on
        the wire and what lands in a partial cache entry."""
        self._check_range(start, end)
        first = self.entries[start].frame_offset
        last = self.entries[end - 1]
        return bytes(self._data[first : last.frame_offset + last.frame_len])

    def _check_range(self, start: int, end: int) -> None:
        if not (0 <= start < end <= len(self.entries)):
            raise XorbFormatError(
                f"chunk range [{start},{end}) out of bounds for "
                f"{len(self.entries)} chunks"
            )


def build_from_data(data: bytes) -> tuple[bytes, bytes, list[tuple[bytes, int]]]:
    """Convenience: CDC-chunk ``data`` into one xorb.

    Returns (xorb_hash, serialized frame stream, chunk_hashes). Raises if
    the data exceeds one xorb's capacity — callers split first.
    """
    builder = XorbBuilder()
    builder.add_data(data)
    return builder.xorb_hash(), builder.serialize(), builder.chunk_hashes()
