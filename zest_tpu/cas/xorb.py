"""Xorb container format — production XETBLOB (the zig-xet `xorb` equivalent).

A xorb is a content-addressed bundle of CDC chunks — the unit of transfer
and caching in the whole system (reference behavior: SURVEY.md §2.2 rows
`xorb`/`chunking`; 64 MiB max matching the wire message cap,
src/bt_wire.zig:22). The xorb's identity is the Merkle root over its chunk
hashes (zest_tpu.cas.hashing.xorb_hash).

This module implements the XETBLOB layout. The chunk/xorb/file content
addresses it computes ARE production HF CAS addresses (pinned against the
official hf_xet client in tests/test_xet_interop.py); the container
byte layout itself is pinned by a frozen golden fixture in the same
suite — no production xorb can be captured offline, so layout compat
with the official writer rests on the format description below:

    per chunk frame (8 + compressed_len bytes, integers little-endian):
        u8   version          (0)
        u24  compressed_len
        u8   scheme           (cas.compression.Scheme)
        u24  uncompressed_len
        ...  payload

    full-xorb footer (40*n + 96 bytes):
        "XETBLOB" u8(1)                     ident + version
        32B xorb hash
        "XBLBHSH" u8(0) u32 n  n×32B        chunk hashes
        "XBLBBND" u8(1) u32 n  n×u32 n×u32  serialized / uncompressed
                                            cumulative end offsets
        u32 n, u32 footer_len-40, u32 8n+40, 4×u32 0, u32 footer_len

The chunk frames are **self-framed**: any contiguous chunk range is a
contiguous byte range, which is what makes the whole transfer economy work —
CDN ``fetch_info.url_range`` byte ranges, partial cache entries
(``{hash}.{range_start}``), BEP XET range responses, and ICI shard slices
are all frame subsequences. The footer travels only with *full* xorbs
(CDN storage artifacts, full-xorb cache entries); range reads never touch
it, exactly as HF's CAS serves S3 byte ranges of the frame region.

Chunk extraction is range-addressed — ``extract_chunk_range(start, end)`` —
because reconstruction terms and BEP XET requests address *chunk index
ranges within a xorb*, not whole xorbs (reference: src/bep_xet.zig:66-74,
src/swarm.zig:25-31).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from zest_tpu.cas import chunking, compression, hashing

FRAME_HEADER_LEN = 8
FOOTER_IDENT = b"XETBLOB"
_HSH_IDENT = b"XBLBHSH"
_BND_IDENT = b"XBLBBND"
# Cap on the SERIALIZED xorb (frames included) so a full xorb always fits
# in one wire message (wire.MAX_MESSAGE_SIZE = 64 MiB + 1 KiB, minus BEP 10
# and XET framing overhead).
MAX_XORB_BYTES = 64 * 1024 * 1024 - 64
MAX_CHUNKS = 8 * 1024
# Largest single chunk a reader will decode. CDC chunks are <= 128 KiB
# (chunking.MAX_CHUNK); the slack allows hand-built chunks while still
# bounding what an untrusted frame header can make us allocate.
MAX_CHUNK_BYTES = 4 * 1024 * 1024
_MAX_U24 = (1 << 24) - 1


class XorbFormatError(ValueError):
    pass


@dataclass(frozen=True)
class ChunkEntry:
    frame_offset: int          # byte offset of the frame within this blob
    compressed_len: int
    uncompressed_len: int
    scheme: compression.Scheme
    hash: bytes | None         # known only when a footer was present

    @property
    def frame_len(self) -> int:
        return FRAME_HEADER_LEN + self.compressed_len


def encode_frame(data: bytes) -> tuple[bytes, bytes]:
    """Encode one chunk into a frame; returns (frame, chunk_hash)."""
    if len(data) > MAX_CHUNK_BYTES:
        raise XorbFormatError(f"chunk of {len(data)} bytes exceeds cap")
    scheme, payload = compression.compress_auto(data)
    if len(payload) > _MAX_U24:
        raise XorbFormatError("chunk payload too large")
    h = hashing.chunk_hash(data)
    header = (
        bytes([0])
        + len(payload).to_bytes(3, "little")
        + bytes([int(scheme)])
        + len(data).to_bytes(3, "little")
    )
    return header + payload, h


def _encode_footer(
    xorb_hash: bytes,
    hashes: list[tuple[bytes, int]],
    ser_ends: list[int],
) -> bytes:
    n = len(hashes)
    unc_ends, total = [], 0
    for _, size in hashes:
        total += size
        unc_ends.append(total)
    out = bytearray()
    out += FOOTER_IDENT + bytes([1]) + xorb_hash
    out += _HSH_IDENT + bytes([0]) + struct.pack("<I", n)
    for h, _ in hashes:
        out += h
    out += _BND_IDENT + bytes([1]) + struct.pack("<I", n)
    out += struct.pack(f"<{n}I", *ser_ends)
    out += struct.pack(f"<{n}I", *unc_ends)
    footer_len = 40 * n + 92
    out += struct.pack("<8I", n, footer_len - 40, 8 * n + 40, 0, 0, 0, 0,
                       footer_len)
    return bytes(out)


class XorbBuilder:
    """Accumulates chunks into a serialized xorb.

    Compression is chosen per chunk (`compress_auto`); identity is computed
    over the *uncompressed* chunk hashes so the same content always produces
    the same xorb hash regardless of encoding.
    """

    def __init__(self) -> None:
        self._frames: list[bytes] = []
        self._hashes: list[tuple[bytes, int]] = []
        self._uncompressed_total = 0
        self._serialized_total = 0

    def __len__(self) -> int:
        return len(self._frames)

    @property
    def uncompressed_total(self) -> int:
        return self._uncompressed_total

    def would_overflow(self, chunk_len: int) -> bool:
        # Worst case the new chunk stores uncompressed: header + chunk_len.
        return (
            self._serialized_total + FRAME_HEADER_LEN + chunk_len > MAX_XORB_BYTES
            or len(self._frames) + 1 > MAX_CHUNKS
        )

    def add_chunk(self, data: bytes) -> bytes:
        """Append one chunk; returns its hash."""
        if self.would_overflow(len(data)):
            raise XorbFormatError("xorb full")
        frame, h = encode_frame(data)
        self._frames.append(frame)
        self._hashes.append((h, len(data)))
        self._uncompressed_total += len(data)
        self._serialized_total += len(frame)
        return h

    def add_data(self, data: bytes) -> list[bytes]:
        """CDC-chunk ``data`` and append every chunk; returns chunk hashes."""
        return [self.add_chunk(piece) for _, piece in chunking.chunk_stream(data)]

    def chunk_hashes(self) -> list[tuple[bytes, int]]:
        return list(self._hashes)

    def xorb_hash(self) -> bytes:
        return hashing.xorb_hash(self._hashes)

    def frame_offsets(self) -> list[int]:
        """Byte offset of each frame plus the end offset (len N+1).

        ``offsets[s]:offsets[e]`` is the byte range serving chunk range
        [s, e) — this is what populates CAS ``fetch_info.url_range``.
        """
        offs = [0]
        for f in self._frames:
            offs.append(offs[-1] + len(f))
        return offs

    def serialize(self) -> bytes:
        """Frame stream only — the in-pipeline blob shape."""
        return b"".join(self._frames)

    def serialize_full(self) -> bytes:
        """Frames + XETBLOB footer — the storage/CDN artifact shape
        (layout frozen by tests/test_xet_interop.py golden fixtures)."""
        return self.serialize() + _encode_footer(
            self.xorb_hash(), self._hashes, self.frame_offsets()[1:]
        )


def parse_footer(data: bytes | memoryview) -> tuple[int, bytes, list[bytes]]:
    """If ``data`` ends with a XETBLOB footer, return
    (frames_end, xorb_hash, chunk_hashes); raise XorbFormatError otherwise.
    """
    data = memoryview(data)
    if len(data) < 96 + 4:
        raise XorbFormatError("too short for a XETBLOB footer")
    (footer_len,) = struct.unpack("<I", data[-4:])
    start = len(data) - 4 - footer_len
    if footer_len < 92 or start < 0:
        raise XorbFormatError("bad footer length")
    foot = bytes(data[start : len(data) - 4])
    if foot[:7] != FOOTER_IDENT:
        raise XorbFormatError("missing XETBLOB ident")
    xorb_hash = foot[8:40]
    if foot[40:47] != _HSH_IDENT:
        raise XorbFormatError("missing hash section")
    (n,) = struct.unpack_from("<I", foot, 48)
    if footer_len != 40 * n + 92 or n > MAX_CHUNKS:
        raise XorbFormatError("footer length inconsistent with chunk count")
    off = 52
    hashes = [foot[off + 32 * i : off + 32 * (i + 1)] for i in range(n)]
    off += 32 * n
    if foot[off : off + 7] != _BND_IDENT:
        raise XorbFormatError("missing boundary section")
    return start, xorb_hash, hashes


def _parse_frames_py(data: memoryview, frames_end: int):
    """Pure-Python frame-table parse (the native fallback — and the
    precise-error path when the native pass reports a malformed
    stream). Returns the same columnar arrays as
    ``native.lib.parse_frames``."""
    offs, comps, uncs, schemes = [], [], [], []
    pos = 0
    while pos < frames_end:
        if pos + FRAME_HEADER_LEN > frames_end:
            raise XorbFormatError("truncated frame header")
        if data[pos] != 0:
            raise XorbFormatError(
                f"unknown chunk frame version {data[pos]}"
            )
        compressed_len = int.from_bytes(data[pos + 1 : pos + 4], "little")
        end = pos + FRAME_HEADER_LEN + compressed_len
        if end > frames_end:
            raise XorbFormatError("frame payload extends past end")
        if len(offs) >= MAX_CHUNKS:
            raise XorbFormatError("too many chunks")
        offs.append(pos)
        comps.append(compressed_len)
        uncs.append(int.from_bytes(data[pos + 5 : pos + 8], "little"))
        schemes.append(data[pos + 4])
        pos = end
    return (np.asarray(offs, dtype=np.uint64),
            np.asarray(comps, dtype=np.uint32),
            np.asarray(uncs, dtype=np.uint32),
            np.asarray(schemes, dtype=np.uint8))


class XorbReader:
    """Parses a frame stream and extracts chunk ranges.

    ``data`` may be a *full* XETBLOB (frames + footer — a CDN storage
    artifact or full-xorb cache entry) or any frame subsequence (a partial
    cache entry, a CDN byte-range response, a BEP XET chunk response).
    Chunk indices here are local to the blob — callers rebase absolute
    term indices by the blob's ``chunk_offset``. With a footer, per-chunk
    hashes are known and extraction verifies them; bare frame streams are
    verified downstream (device BLAKE3 before full-xorb cache writes,
    file-level hashes after reassembly) — the same trust model as the
    production CDN path, whose range responses carry no hashes either.
    """

    def __init__(self, data: bytes | memoryview):
        data = memoryview(data)
        self.xorb_hash_footer: bytes | None = None
        frames_end = len(data)
        footer_hashes: list[bytes] | None = None
        try:
            frames_end, self.xorb_hash_footer, footer_hashes = \
                parse_footer(data)
        except XorbFormatError:
            pass
        self._data = data
        self._footer_hashes = footer_hashes
        # The chunk table is COLUMNAR (numpy arrays), parsed by one
        # native pass when available: a GB-scale shard walks tens of
        # thousands of frames, and the old per-chunk Python loop (plus
        # a ChunkEntry object per frame) cost more than the decode it
        # was setting up. ``entries`` materializes lazily for the
        # object-shaped consumers.
        cols = None
        if frames_end:
            native = compression._get_native()
            if native is not None and hasattr(native, "parse_frames"):
                cols = native.parse_frames(data, frames_end, MAX_CHUNKS)
        if cols is None:
            cols = _parse_frames_py(data, frames_end)
        self._frame_offs, self._comp_lens, self._unc_lens, self._schemes \
            = cols
        self._n = len(self._frame_offs)
        if self._n:
            # Vectorized hostile-header checks (same contracts as the
            # old per-chunk loop; the native parse validates structure
            # only). Untrusted headers must not dictate allocations.
            if int(self._schemes.max()) > int(max(compression.Scheme)):
                bad = int(self._schemes.max())
                raise XorbFormatError(f"unknown scheme {bad}")
            if int(self._unc_lens.max()) > MAX_CHUNK_BYTES:
                raise XorbFormatError(
                    f"chunk claims {int(self._unc_lens.max())} bytes "
                    f"(cap {MAX_CHUNK_BYTES})"
                )
        if footer_hashes is not None and len(footer_hashes) != self._n:
            raise XorbFormatError(
                f"footer lists {len(footer_hashes)} chunks, "
                f"frames hold {self._n}"
            )
        self._entries_cache: list[ChunkEntry] | None = None

    @property
    def entries(self) -> list[ChunkEntry]:
        """Object view of the chunk table, built on first access (the
        decode hot paths stay on the columnar arrays)."""
        if self._entries_cache is None:
            fh = self._footer_hashes
            self._entries_cache = [
                ChunkEntry(o, c, u, compression.Scheme(s),
                           fh[i] if fh else None)
                for i, (o, c, u, s) in enumerate(zip(
                    self._frame_offs.tolist(), self._comp_lens.tolist(),
                    self._unc_lens.tolist(), self._schemes.tolist()))
            ]
        return self._entries_cache

    def __len__(self) -> int:
        return self._n

    def frame_offsets(self) -> list[int]:
        """Builder-parity offsets (len N+1): ``offsets[s]:offsets[e]``
        is the byte range serving chunk range [s, e) within this blob —
        what the write path (cas.publish / transfer.push) needs to aim
        referencing terms' ``fetch_info`` at a cached base xorb."""
        offs = [int(o) for o in self._frame_offs.tolist()]
        if not offs:
            return [0]
        end = offs[-1] + FRAME_HEADER_LEN + int(self._comp_lens[-1])
        return offs + [end]

    def chunk_hashes(self) -> list[tuple[bytes, int]]:
        """(hash, uncompressed length) per chunk — from the footer when
        present, else computed by decoding (the authoritative source)."""
        fh = self._footer_hashes
        sizes = self._unc_lens.tolist()
        out = []
        for i in range(self._n):
            h = fh[i] if fh else hashing.chunk_hash(
                self.extract_chunk(i, verify=False)
            )
            out.append((h, sizes[i]))
        return out

    def xorb_hash(self) -> bytes:
        return hashing.xorb_hash(self.chunk_hashes())

    def extract_chunk(self, index: int, verify: bool = True) -> bytes:
        payload_start = int(self._frame_offs[index]) + FRAME_HEADER_LEN
        payload = bytes(
            self._data[payload_start
                       : payload_start + int(self._comp_lens[index])]
        )
        data = compression.decompress(
            payload, compression.Scheme(int(self._schemes[index])),
            int(self._unc_lens[index]),
        )
        h = self._footer_hashes[index] if self._footer_hashes else None
        if verify and h is not None and hashing.chunk_hash(data) != h:
            raise XorbFormatError(f"chunk {index} hash mismatch")
        return data

    def extract_chunk_range(
        self, start: int, end: int, verify: bool = True
    ) -> bytes:
        """Concatenated bytes of chunks [start, end) — the term-fetch shape
        (reference: xet_bridge.zig:256-258, parallel_download.zig:65-66)."""
        self._check_range(start, end)
        return b"".join(
            self.extract_chunk(i, verify=verify) for i in range(start, end)
        )

    @property
    def chunk_sizes(self):
        """Uncompressed chunk lengths as a numpy u32 column — the
        object-free view for consumers that only need sizes (the
        entries list costs a ChunkEntry per frame)."""
        return self._unc_lens

    @property
    def chunk_schemes(self):
        """Per-chunk compression.Scheme values as a numpy u8 column."""
        return self._schemes

    def decode_columns(self, start: int, end: int):
        """Columnar batch-decode descriptors for chunks [start, end):
        ``(src_offs u64, src_lens u64, schemes u8, dst_lens u64)`` numpy
        views/arrays, payload offsets view-relative to this reader's
        buffer — the zero-Python-per-chunk shape
        ``compression.decode_columns_into`` consumes. Returns ``None``
        when the blob carries footer hashes (those chunks must verify
        through :meth:`extract_chunk`); raises the usual
        :class:`XorbFormatError` for hostile stored-chunk frames."""
        self._check_range(start, end)
        if self._footer_hashes is not None:
            return None
        comp = self._comp_lens[start:end]
        unc = self._unc_lens[start:end]
        schemes = self._schemes[start:end]
        bad = (schemes == int(compression.Scheme.NONE)) & (comp != unc)
        if bad.any():
            i = start + int(np.argmax(bad))
            # Same contract as compression.decompress's stored path — a
            # hostile frame must raise the module's error type, not a
            # bare memoryview ValueError.
            raise XorbFormatError(
                f"stored chunk {i} claims {int(self._unc_lens[i])} "
                f"bytes but frames {int(self._comp_lens[i])}"
            )
        src_offs = self._frame_offs[start:end] + np.uint64(FRAME_HEADER_LEN)
        return (src_offs, comp.astype(np.uint64), schemes,
                unc.astype(np.uint64))

    def extract_range_into(self, start: int, end: int, out,
                           workers: int = 1) -> int:
        """Decode chunks [start, end) directly into ``out`` (a writable
        buffer of exactly the range's uncompressed size); returns the
        byte count.

        The GB-scale landing path decodes most bytes through here. The
        whole range is submitted as ONE columnar batch
        (``compression.decode_columns_into``): with the native engine,
        that is a single GIL-released call decoding every chunk — LZ4,
        BG4, and stored alike — straight into ``out`` across ``workers``
        native threads; without it, stored chunks still copy
        frame→destination with no intermediate bytes object. Chunks
        that carry a footer hash take the verifying
        :meth:`extract_chunk` path and are then copied in."""
        self._check_range(start, end)
        view = memoryview(out).cast("B")
        total = int(self._unc_lens[start:end].sum(dtype=np.uint64))
        if view.nbytes != total:
            raise XorbFormatError(
                f"out buffer is {view.nbytes} bytes for a "
                f"{total}-byte chunk range"
            )
        cols = self.decode_columns(start, end)
        if cols is not None:
            src_offs, src_lens, schemes, dst_lens = cols
            dst_offs = _exclusive_cumsum(dst_lens)
            return compression.decode_columns_into(
                [(self._data, src_offs, src_lens, schemes, dst_offs,
                  dst_lens)],
                view, workers=workers,
            )
        pos = 0
        for i in range(start, end):
            data = self.extract_chunk(i)
            view[pos:pos + len(data)] = data
            pos += len(data)
        return pos

    def extract_chunk_planar(self, index: int) -> bytes:
        """A BG4 chunk's PLANAR bytes: the LZ4 frame decoded but the
        byte-grouping inverse NOT applied — the staging form the fused
        on-device decode→verify pass consumes (ops.decode_pallas): the
        regroup happens on the accelerator, chained in front of the
        BLAKE3 verify kernel, so the host never materializes the
        interleaved bytes. For a stored BG4 frame this is a straight
        payload slice — the wire bytes ARE the device input."""
        scheme = compression.Scheme(int(self._schemes[index]))
        if scheme != compression.Scheme.BG4_LZ4:
            raise XorbFormatError(
                f"chunk {index} is scheme {scheme!s}, not BG4"
            )
        p0 = int(self._frame_offs[index]) + FRAME_HEADER_LEN
        payload = bytes(self._data[p0:p0 + int(self._comp_lens[index])])
        return compression.lz4_frame_decompress(
            payload, int(self._unc_lens[index]))

    def slice_range(self, start: int, end: int) -> bytes:
        """Raw frame bytes for chunks [start, end) — what a seeder sends on
        the wire and what lands in a partial cache entry."""
        self._check_range(start, end)
        first = int(self._frame_offs[start])
        last_end = (int(self._frame_offs[end - 1]) + FRAME_HEADER_LEN
                    + int(self._comp_lens[end - 1]))
        return bytes(self._data[first:last_end])

    def _check_range(self, start: int, end: int) -> None:
        if not (0 <= start < end <= self._n):
            raise XorbFormatError(
                f"chunk range [{start},{end}) out of bounds for "
                f"{self._n} chunks"
            )


def _exclusive_cumsum(lens) -> "np.ndarray":
    out = np.empty(len(lens), dtype=np.uint64)
    if len(lens):
        out[0] = 0
        np.cumsum(lens[:-1], dtype=np.uint64, out=out[1:])
    return out


def build_from_data(data: bytes) -> tuple[bytes, bytes, list[tuple[bytes, int]]]:
    """Convenience: CDC-chunk ``data`` into one xorb.

    Returns (xorb_hash, serialized frame stream, chunk_hashes). Raises if
    the data exceeds one xorb's capacity — callers split first.
    """
    builder = XorbBuilder()
    builder.add_data(data)
    return builder.xorb_hash(), builder.serialize(), builder.chunk_hashes()
