"""CAS client: reconstruction queries and ranged xorb fetches.

The zig-xet `cas_client` equivalent (SURVEY.md §2.2): authenticated
requests against the CAS endpoint obtained from the xet-read-token
exchange, returning reconstruction plans and raw xorb bytes (full or HTTP
byte-range). Every byte that leaves this client is still untrusted until
chunk hashes verify during extraction.

Unlike the reference's single-shot client, every GET here is treated as
the idempotent request it is: transient failures (5xx, 429, connection
reset, timeout) retry with capped exponential backoff + jitter, a
mid-stream drop resumes from the byte where it died via an adjusted
``Range`` header, and a 401/403 against the CAS origin refreshes the
xet-read token once and retries — tokens expire during long pulls.
An optional per-pull :class:`~zest_tpu.resilience.Deadline` caps both
the per-request timeouts and the retry sleeps.
"""

from __future__ import annotations

import os
import threading

import requests

from zest_tpu import faults, telemetry
from zest_tpu.cas import reconstruction as recon
from zest_tpu.resilience import Backoff, Deadline, DeadlineExceeded


def _span_url(url: str) -> str:
    """Trace-safe URL: scheme+host+path only — presigned CDN URLs carry
    auth in the query string, which must never land in a trace file."""
    return url.split("?", 1)[0]


class CasError(RuntimeError):
    pass


class CasTransientError(CasError):
    """A failure worth retrying (server hiccup, connection reset)."""

    def __init__(self, message: str, status: int | None = None):
        super().__init__(message)
        self.status = status


class _RefreshNeeded(Exception):
    """Internal: CAS origin said 401/403 — try a token refresh."""

    def __init__(self, status: int):
        super().__init__(f"status {status}")
        self.status = status


_RETRYABLE_STATUS = frozenset({429, 500, 502, 503, 504})
_NETWORK_ERRORS = (
    requests.exceptions.ConnectionError,
    requests.exceptions.Timeout,
    requests.exceptions.ChunkedEncodingError,
)

DEFAULT_RETRIES = int(os.environ.get("ZEST_CDN_RETRIES", "3"))
DEFAULT_BACKOFF_BASE_S = float(os.environ.get("ZEST_CDN_BACKOFF_S", "0.2"))
_BACKOFF_CAP_S = 5.0


class CasClient:
    def __init__(
        self,
        cas_url: str,
        access_token: str | None = None,
        session: requests.Session | None = None,
        retries: int | None = None,
        backoff_base_s: float | None = None,
        token_refresher=None,
        deadline: Deadline | None = None,
        on_event=None,
    ):
        """``token_refresher`` is ``() -> (cas_url, access_token)`` — the
        hub's xet-read-token exchange, re-run at most once per request on
        401/403. ``on_event(name)`` is the caller's counter hook (the
        bridge feeds ``FetchStats.bump``); ``deadline`` caps timeouts and
        retry sleeps."""
        self.cas_url = cas_url.rstrip("/")
        self.access_token = access_token
        self.session = session or requests.Session()
        self.retries = DEFAULT_RETRIES if retries is None else max(0, retries)
        self.backoff_base_s = (DEFAULT_BACKOFF_BASE_S if backoff_base_s is None
                               else backoff_base_s)
        self.token_refresher = token_refresher
        self.deadline = deadline
        self._on_event = on_event
        self._refresh_lock = threading.Lock()

    def _headers(self) -> dict[str, str]:
        if self.access_token:
            return {"Authorization": f"Bearer {self.access_token}"}
        return {}

    def _bump(self, name: str) -> None:
        if self._on_event is not None:
            self._on_event(name)

    def _timeout(self, base_s: float) -> float:
        if self.deadline is not None:
            self.deadline.check("CDN request")
            return self.deadline.cap(base_s)
        return base_s

    def _get(self, url: str, headers: dict, timeout: float,
             stream: bool = False):
        """The one chokepoint every CAS/CDN GET goes through — where the
        chaos harness injects server hiccups and connection resets."""
        if faults.fire("cdn_503"):
            raise CasTransientError(f"GET {url} -> 503 (injected)", 503)
        if faults.fire("cdn_reset"):
            raise requests.exceptions.ConnectionError(
                f"injected cdn_reset for {url}")
        return self.session.get(url, headers=headers, timeout=timeout,
                                stream=stream)

    def _refresh_token(self) -> bool:
        """Re-run the xet-read-token exchange; True when a new token was
        installed. Serialized: concurrent 401s from parallel term fetches
        must not stampede the hub."""
        if self.token_refresher is None:
            return False
        with self._refresh_lock:
            try:
                cas_url, token = self.token_refresher()
            except Exception:
                return False
            if cas_url:
                self.cas_url = cas_url.rstrip("/")
            self.access_token = token
        self._bump("token_refreshes")
        return True

    def get_reconstruction(self, file_hash_hex: str) -> recon.Reconstruction:
        """GET /v1/reconstructions/{hex} -> terms + fetch_info."""
        with telemetry.span("cas.reconstruction", file=file_hash_hex):
            return self._get_reconstruction(file_hash_hex)

    def _get_reconstruction(self, file_hash_hex: str) -> recon.Reconstruction:
        url = f"{self.cas_url}/v1/reconstructions/{file_hash_hex}"
        backoff = Backoff(self.backoff_base_s, _BACKOFF_CAP_S)
        attempt = 0
        refreshed = False
        while True:
            try:
                resp = self._get(url, self._headers(),
                                 timeout=self._timeout(30))
            except CasTransientError as exc:
                err = exc
            except _NETWORK_ERRORS as exc:
                err = CasTransientError(f"GET {url}: {exc}")
            else:
                status = resp.status_code
                if status == 200:
                    return recon.from_json(file_hash_hex, resp.json())
                if status == 404:
                    raise CasError(f"no reconstruction for {file_hash_hex}")
                if status in (401, 403) and not refreshed:
                    refreshed = True
                    if self._refresh_token():
                        continue
                if status in _RETRYABLE_STATUS:
                    err = CasTransientError(f"GET {url} -> {status}", status)
                else:
                    raise CasError(f"GET {url} -> {status}")
            attempt += 1
            if attempt > self.retries:
                raise CasError(
                    f"GET {url} failed after {attempt} attempts: {err}"
                ) from err
            self._bump("cdn_retries")
            if not backoff.sleep(deadline=self.deadline):
                raise DeadlineExceeded(
                    f"pull deadline exhausted retrying {url}") from err

    def fetch_xorb_from_url(
        self, url: str, byte_range: tuple[int, int] | None = None
    ) -> bytes:
        """Fetch xorb bytes; ``byte_range`` is half-open [start, end).

        Presigned CDN URLs carry their own auth — the bearer header is only
        sent to the CAS origin itself (same-origin check on the URL).
        """
        return b"".join(self.fetch_xorb_iter(url, byte_range))

    def fetch_xorb_iter(self, url: str,
                        byte_range: tuple[int, int] | None = None):
        """Same fetch as :meth:`fetch_xorb_from_url`, yielded as ~1 MiB
        chunks — the streaming shape the GB-scale warm path writes
        straight into cache files (storage.atomic_write_stream) so no
        whole-unit buffer is built. 1 MiB reads, not ``resp.content``:
        requests accumulates bodies in 10 KiB chunks, which measures
        ~2x slower on multi-MB xorb units (per-chunk allocation and
        socket wakeups dominate).

        Resumable: a transient failure after N yielded bytes re-requests
        from byte N (the GET is idempotent and ranged), so a multi-GB
        unit doesn't restart from zero on a mid-stream reset — and the
        consumer sees one uninterrupted byte stream either way."""
        with telemetry.span("cdn.get", url=_span_url(url)) as sp:
            for chunk in self._fetch_xorb_iter_inner(url, byte_range):
                sp.add_bytes(len(chunk))
                yield chunk

    def _fetch_xorb_iter_inner(self, url: str,
                               byte_range: tuple[int, int] | None = None):
        if byte_range is not None:
            start, end = byte_range
            if not (0 <= start < end):
                raise CasError(f"invalid byte range [{start},{end})")
        backoff = Backoff(self.backoff_base_s, _BACKOFF_CAP_S)
        attempt = 0
        refreshed = False
        yielded = 0
        while True:
            try:
                for chunk in self._stream_once(url, byte_range, yielded):
                    yielded += len(chunk)
                    yield chunk
                return
            except _RefreshNeeded as exc:
                if not refreshed:
                    refreshed = True
                    if self._refresh_token():
                        continue
                raise CasError(f"GET {url} -> {exc.status}") from exc
            except (CasTransientError, *_NETWORK_ERRORS) as exc:
                attempt += 1
                if attempt > self.retries:
                    raise CasError(
                        f"GET {url} failed after {attempt} attempts: {exc}"
                    ) from exc
                self._bump("cdn_retries")
                if not backoff.sleep(deadline=self.deadline):
                    raise DeadlineExceeded(
                        f"pull deadline exhausted retrying {url}") from exc

    def _stream_once(self, url: str, byte_range: tuple[int, int] | None,
                     skip: int):
        """One streaming GET of the requested window minus its first
        ``skip`` bytes (already delivered by a previous attempt)."""
        headers: dict[str, str] = {}
        same_origin = url.startswith(self.cas_url)
        if same_origin:
            headers.update(self._headers())
        if byte_range is not None:
            lo, hi = byte_range[0] + skip, byte_range[1]
            if lo >= hi:
                return  # previous attempts already delivered the window
            headers["Range"] = f"bytes={lo}-{hi - 1}"
        else:
            lo, hi = skip, None
            if skip:
                headers["Range"] = f"bytes={skip}-"
        resp = self._get(url, headers, timeout=self._timeout(120),
                         stream=True)
        try:
            status = resp.status_code
            if status in (401, 403) and same_origin:
                raise _RefreshNeeded(status)
            if status in _RETRYABLE_STATUS:
                raise CasTransientError(f"GET {url} -> {status}", status)
            if status not in (200, 206):
                raise CasError(f"GET {url} -> {status}")
            if status == 200 and (byte_range is not None or skip):
                # Origin ignored the Range header; trim the full body to
                # the window as it streams past.
                pos = 0
                for chunk in resp.iter_content(1024 * 1024):
                    a = max(lo - pos, 0)
                    b = len(chunk) if hi is None else min(hi - pos,
                                                          len(chunk))
                    if a < b:
                        yield (chunk[a:b] if (a, b) != (0, len(chunk))
                               else chunk)
                    pos += len(chunk)
                    if hi is not None and pos >= hi:
                        break
                return
            yield from resp.iter_content(1024 * 1024)
        finally:
            # Also runs when the CONSUMER abandons the generator (write
            # error mid-stream → GeneratorExit lands at the yield):
            # without the close, the pooled connection stays checked out
            # with an unread body and every retry burns a new socket.
            resp.close()
