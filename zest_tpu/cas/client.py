"""CAS client: reconstruction queries and ranged xorb fetches.

The zig-xet `cas_client` equivalent (SURVEY.md §2.2): authenticated
requests against the CAS endpoint obtained from the xet-read-token
exchange, returning reconstruction plans and raw xorb bytes (full or HTTP
byte-range). Every byte that leaves this client is still untrusted until
chunk hashes verify during extraction.
"""

from __future__ import annotations

import requests

from zest_tpu.cas import reconstruction as recon


class CasError(RuntimeError):
    pass


class CasClient:
    def __init__(
        self,
        cas_url: str,
        access_token: str | None = None,
        session: requests.Session | None = None,
    ):
        self.cas_url = cas_url.rstrip("/")
        self.access_token = access_token
        self.session = session or requests.Session()

    def _headers(self) -> dict[str, str]:
        if self.access_token:
            return {"Authorization": f"Bearer {self.access_token}"}
        return {}

    def get_reconstruction(self, file_hash_hex: str) -> recon.Reconstruction:
        """GET /v1/reconstructions/{hex} -> terms + fetch_info."""
        url = f"{self.cas_url}/v1/reconstructions/{file_hash_hex}"
        resp = self.session.get(url, headers=self._headers(), timeout=30)
        if resp.status_code == 404:
            raise CasError(f"no reconstruction for {file_hash_hex}")
        if resp.status_code != 200:
            raise CasError(f"GET {url} -> {resp.status_code}")
        return recon.from_json(file_hash_hex, resp.json())

    def fetch_xorb_from_url(
        self, url: str, byte_range: tuple[int, int] | None = None
    ) -> bytes:
        """Fetch xorb bytes; ``byte_range`` is half-open [start, end).

        Presigned CDN URLs carry their own auth — the bearer header is only
        sent to the CAS origin itself (same-origin check on the URL).
        """
        return b"".join(self.fetch_xorb_iter(url, byte_range))

    def fetch_xorb_iter(self, url: str,
                        byte_range: tuple[int, int] | None = None):
        """Same fetch as :meth:`fetch_xorb_from_url`, yielded as ~1 MiB
        chunks — the streaming shape the GB-scale warm path writes
        straight into cache files (storage.atomic_write_stream) so no
        whole-unit buffer is built. 1 MiB reads, not ``resp.content``:
        requests accumulates bodies in 10 KiB chunks, which measures
        ~2x slower on multi-MB xorb units (per-chunk allocation and
        socket wakeups dominate)."""
        headers: dict[str, str] = {}
        if url.startswith(self.cas_url):
            headers.update(self._headers())
        if byte_range is not None:
            start, end = byte_range
            if not (0 <= start < end):
                raise CasError(f"invalid byte range [{start},{end})")
            headers["Range"] = f"bytes={start}-{end - 1}"
        resp = self.session.get(url, headers=headers, timeout=120,
                                stream=True)
        try:
            if resp.status_code not in (200, 206):
                raise CasError(f"GET {url} -> {resp.status_code}")
            if byte_range is not None and resp.status_code == 200:
                # Origin ignored the Range header; trim the full body to
                # the window as it streams past.
                lo, hi = byte_range
                pos = 0
                for chunk in resp.iter_content(1024 * 1024):
                    a, b = max(lo - pos, 0), min(hi - pos, len(chunk))
                    if a < b:
                        yield (chunk[a:b] if (a, b) != (0, len(chunk))
                               else chunk)
                    pos += len(chunk)
                    if pos >= hi:
                        break
                return
            yield from resp.iter_content(1024 * 1024)
        finally:
            # Also runs when the CONSUMER abandons the generator (write
            # error mid-stream → GeneratorExit lands at the yield):
            # without the close, the pooled connection stays checked out
            # with an unread body and every retry burns a new socket.
            resp.close()
