"""Chunk compression schemes (the zig-xet `compression` equivalent).

Four schemes, matching the production Xet set (SURVEY.md §2.2, row
`compression`): None, LZ4, ByteGrouping4LZ4, FullBitsliceLZ4.

- **LZ4** payloads are the standard **LZ4 frame** format (magic
  ``0x184D2204``, independent blocks, 256 KiB block max — a chunk is
  always a single block) wrapping LZ4 block data. The decoder is checked
  against spec-derived hand-built vectors (every FLG bit, overlap-copy
  matches, varlen extensions) and the encoder output is pinned by frozen
  golden frames — both in tests/test_xet_interop.py. No offline oracle
  for production chunk payloads exists in this environment; frame-level
  compat rests on following the published LZ4 frame spec.
- **ByteGrouping4LZ4** regroups bytes into 4 planes (byte k of every 4-byte
  group) before LZ4 — fp32/bf16 tensor bytes compress far better planar,
  because exponent bytes are highly repetitive. Plane layout matches
  production bit-for-bit.
- **FullBitsliceLZ4** slices each byte into 8 bit-planes first; best for
  quantized weights, costliest to (de)code.

``compress_auto`` picks the smallest encoding per chunk, falling back to
None when compression doesn't pay.  Hot paths dispatch to the native C++
codec (zest_tpu/native/lz4.cc) when available.
"""

from __future__ import annotations

import enum
import struct

import numpy as np


class Scheme(enum.IntEnum):
    NONE = 0
    LZ4 = 1
    BG4_LZ4 = 2
    BITSLICE_LZ4 = 3


class CompressionError(ValueError):
    pass


# ── LZ4 block format (pure Python; spec: lz4 block format description) ──

_MIN_MATCH = 4
_HASH_LOG = 16
_MAX_OFFSET = 0xFFFF
# Incompressible-run acceleration (reference LZ4 "skip trigger"): after
# every 2**_SKIP_TRIGGER consecutive misses the scan step grows by one,
# so random data degenerates to a fast skip + one literal run instead of
# a per-byte probe. Same schedule as the native codec (lz4.cc).
_SKIP_TRIGGER = 6


def _lz4_compress_py(data: bytes) -> bytes:
    n = len(data)
    out = bytearray()
    if n == 0:
        return b"\x00"  # single empty-literals token
    table: dict[int, int] = {}
    anchor = 0
    pos = 0
    # Spec end conditions: last 5 bytes are literals; last match starts
    # at least 12 bytes before the end.
    match_limit = n - 12
    search = 1 << _SKIP_TRIGGER
    while pos < match_limit:
        seq = data[pos : pos + 4]
        key = int.from_bytes(seq, "little")
        cand = table.get(key)
        table[key] = pos
        if cand is None or pos - cand > _MAX_OFFSET or data[cand : cand + 4] != seq:
            pos += search >> _SKIP_TRIGGER
            search += 1
            continue
        search = 1 << _SKIP_TRIGGER
        # Extend match forward (may run up to the 5-byte literal tail).
        mlen = 4
        limit = n - 5
        while pos + mlen < limit and data[cand + mlen] == data[pos + mlen]:
            mlen += 1
        _emit_sequence(out, data, anchor, pos, pos - cand, mlen)
        pos += mlen
        anchor = pos
    _emit_literal_tail(out, data, anchor)
    return bytes(out)


def _emit_varlen(out: bytearray, value: int) -> None:
    while value >= 255:
        out.append(255)
        value -= 255
    out.append(value)


def _emit_sequence(out: bytearray, data: bytes, anchor: int, pos: int,
                   offset: int, mlen: int) -> None:
    lit_len = pos - anchor
    ml = mlen - _MIN_MATCH
    token = (min(lit_len, 15) << 4) | min(ml, 15)
    out.append(token)
    if lit_len >= 15:
        _emit_varlen(out, lit_len - 15)
    out += data[anchor:pos]
    out += offset.to_bytes(2, "little")
    if ml >= 15:
        _emit_varlen(out, ml - 15)


def _emit_literal_tail(out: bytearray, data: bytes, anchor: int) -> None:
    lit_len = len(data) - anchor
    out.append(min(lit_len, 15) << 4)
    if lit_len >= 15:
        _emit_varlen(out, lit_len - 15)
    out += data[anchor:]


def _lz4_decompress_py(data: bytes, expected_len: int) -> bytes:
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if pos >= n:
                    raise CompressionError("truncated literal length")
                b = data[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        if pos + lit_len > n:
            raise CompressionError("literals extend past input")
        out += data[pos : pos + lit_len]
        pos += lit_len
        if pos == n:
            break  # last sequence: literals only
        if pos + 2 > n:
            raise CompressionError("truncated match offset")
        offset = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        if offset == 0 or offset > len(out):
            raise CompressionError(f"invalid match offset {offset}")
        mlen = (token & 0xF) + _MIN_MATCH
        if (token & 0xF) == 15:
            while True:
                if pos >= n:
                    raise CompressionError("truncated match length")
                b = data[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        # Overlapping copy must be byte-sequential.
        start = len(out) - offset
        for i in range(mlen):
            out.append(out[start + i])
        if len(out) > expected_len:
            raise CompressionError("output exceeds expected length")
    if len(out) != expected_len:
        raise CompressionError(
            f"decompressed {len(out)} bytes, expected {expected_len}"
        )
    return bytes(out)


def lz4_compress(data: bytes) -> bytes:
    native = _get_native()
    if native is not None:
        return native.lz4_compress(data)
    return _lz4_compress_py(data)


def lz4_decompress(data: bytes, expected_len: int) -> bytes:
    native = _get_native()
    if native is not None:
        return native.lz4_decompress(data, expected_len)
    return _lz4_decompress_py(data, expected_len)


# ── LZ4 frame format (what production xorb payloads actually hold) ──

_LZ4F_MAGIC = b"\x04\x22\x4d\x18"
# FLG 0x60: version 01, independent blocks, no checksums/content-size.
# BD 0x50: 256 KiB block max — every CDC chunk (<= 128 KiB) is one block.
_LZ4F_DESCRIPTOR = b"\x60\x50"

_XXH_P1, _XXH_P2, _XXH_P3, _XXH_P4, _XXH_P5 = (
    2654435761, 2246822519, 3266489917, 668265263, 374761393
)
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def xxh32(data: bytes, seed: int = 0) -> int:
    """XXH32 (needed for the LZ4 frame header checksum byte)."""
    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + _XXH_P1 + _XXH_P2) & _M32
        v2 = (seed + _XXH_P2) & _M32
        v3 = seed & _M32
        v4 = (seed - _XXH_P1) & _M32
        while i <= n - 16:
            for k, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 4 * k : i + 4 * k + 4], "little")
                v = (v + lane * _XXH_P2) & _M32
                v = (_rotl32(v, 13) * _XXH_P1) & _M32
                if k == 0: v1 = v
                elif k == 1: v2 = v
                elif k == 2: v3 = v
                else: v4 = v
            i += 16
        h = (_rotl32(v1, 1) + _rotl32(v2, 7) + _rotl32(v3, 12)
             + _rotl32(v4, 18)) & _M32
    else:
        h = (seed + _XXH_P5) & _M32
    h = (h + n) & _M32
    while i + 4 <= n:
        h = (h + int.from_bytes(data[i:i+4], "little") * _XXH_P3) & _M32
        h = (_rotl32(h, 17) * _XXH_P4) & _M32
        i += 4
    while i < n:
        h = (h + data[i] * _XXH_P5) & _M32
        h = (_rotl32(h, 11) * _XXH_P1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * _XXH_P2) & _M32
    h ^= h >> 13
    h = (h * _XXH_P3) & _M32
    h ^= h >> 16
    return h


def lz4_frame_compress(data: bytes) -> bytes:
    """One-block LZ4 frame (the production chunk-payload shape)."""
    hc = (xxh32(_LZ4F_DESCRIPTOR) >> 8) & 0xFF
    out = bytearray(_LZ4F_MAGIC + _LZ4F_DESCRIPTOR + bytes([hc]))
    if data:
        block = lz4_compress(data)
        if len(block) < len(data):
            out += struct.pack("<I", len(block)) + block
        else:
            out += struct.pack("<I", 0x80000000 | len(data)) + data
    out += b"\x00\x00\x00\x00"  # end mark
    return bytes(out)


def lz4_frame_decompress(data: bytes, expected_len: int) -> bytes:
    """Decode an LZ4 frame to exactly ``expected_len`` bytes."""
    if data[:4] != _LZ4F_MAGIC:
        raise CompressionError("not an LZ4 frame")
    if len(data) < 7:
        raise CompressionError("truncated LZ4 frame header")
    flg, bd = data[4], data[5]
    if flg >> 6 != 1:
        raise CompressionError("unsupported LZ4 frame version")
    block_max = 1 << (8 + 2 * ((bd >> 4) & 0x7))
    pos = 6
    if flg & 0x08:
        pos += 8  # content size (unused; the chunk header is authoritative)
    if flg & 0x01:
        pos += 4  # DictID (FLG bit 0): 4-byte dictionary ID before HC
    # FLG bit 2 (0x04) = content checksum after the end mark; the block
    # loop stops at the end mark, so it needs no skip here.
    pos += 1  # header checksum byte
    out = bytearray()
    while True:
        if pos + 4 > len(data):
            raise CompressionError("truncated LZ4 frame block")
        (bsz,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if bsz == 0:
            break
        stored = bool(bsz & 0x80000000)
        bsz &= 0x7FFFFFFF
        if pos + bsz > len(data):
            raise CompressionError("LZ4 frame block extends past input")
        block = data[pos : pos + bsz]
        pos += bsz
        if flg & 0x10:
            pos += 4  # block checksum; ignored
        if stored:
            out += block
        else:
            # Encoders fill blocks to block_max; only the final block is
            # short, and its size is pinned by expected_len.
            remaining = expected_len - len(out)
            out += lz4_decompress(block, min(block_max, remaining))
    if len(out) != expected_len:
        raise CompressionError(
            f"LZ4 frame decoded {len(out)} bytes, expected {expected_len}"
        )
    return bytes(out)


# ── Byte-grouping and bit-slicing transforms ──


def _bg4(data: bytes) -> bytes:
    a = np.frombuffer(data, dtype=np.uint8)
    return b"".join(a[k::4].tobytes() for k in range(4))


def _bg4_inverse(data: bytes) -> bytes:
    n = len(data)
    sizes = [(n - k + 3) // 4 for k in range(4)]
    out = np.empty(n, dtype=np.uint8)
    pos = 0
    a = np.frombuffer(data, dtype=np.uint8)
    for k in range(4):
        out[k::4] = a[pos : pos + sizes[k]]
        pos += sizes[k]
    return out.tobytes()


def _bitslice(data: bytes) -> bytes:
    a = np.frombuffer(data, dtype=np.uint8)
    planes = [np.packbits((a >> b) & 1) for b in range(8)]
    return b"".join(p.tobytes() for p in planes)


def _bitslice_inverse(data: bytes, orig_len: int) -> bytes:
    plane_len = (orig_len + 7) // 8
    a = np.frombuffer(data, dtype=np.uint8)
    if len(a) != plane_len * 8:
        raise CompressionError("bitslice payload length mismatch")
    out = np.zeros(orig_len, dtype=np.uint8)
    for b in range(8):
        bits = np.unpackbits(a[b * plane_len : (b + 1) * plane_len])[:orig_len]
        out |= bits.astype(np.uint8) << b
    return out.tobytes()


# ── Scheme-level API used by the xorb container ──


def compress(data: bytes, scheme: Scheme) -> bytes:
    if scheme == Scheme.NONE:
        return data
    if scheme == Scheme.LZ4:
        return lz4_frame_compress(data)
    if scheme == Scheme.BG4_LZ4:
        return lz4_frame_compress(_bg4(data))
    if scheme == Scheme.BITSLICE_LZ4:
        return lz4_frame_compress(_bitslice(data))
    raise CompressionError(f"unknown scheme {scheme}")


def decompress(data: bytes, scheme: Scheme, expected_len: int) -> bytes:
    if scheme == Scheme.NONE:
        if len(data) != expected_len:
            raise CompressionError("stored chunk length mismatch")
        return data
    if scheme == Scheme.LZ4:
        return lz4_frame_decompress(data, expected_len)
    if scheme == Scheme.BG4_LZ4:
        return _bg4_inverse(lz4_frame_decompress(data, expected_len))
    if scheme == Scheme.BITSLICE_LZ4:
        plane_bytes = ((expected_len + 7) // 8) * 8
        return _bitslice_inverse(
            lz4_frame_decompress(data, plane_bytes), expected_len
        )
    raise CompressionError(f"unknown scheme {scheme}")


def decompress_into(data, scheme: Scheme, out) -> int:
    """Decode one chunk payload into a writable buffer of exactly its
    uncompressed size; returns the byte count. Stored chunks copy
    payload→destination with no intermediate bytes object; compressed
    schemes decode then copy (the batch engine below is the
    no-intermediate path for those)."""
    view = memoryview(out).cast("B")
    if scheme == Scheme.NONE:
        if len(data) != view.nbytes:
            raise CompressionError("stored chunk length mismatch")
        view[:] = data
        return view.nbytes
    view[:] = decompress(bytes(data), scheme, view.nbytes)
    return view.nbytes


# ── Batch decode engine (the host front of ISSUE 3's decode tentpole) ──
#
# A decode descriptor is ``(src_buf, src_off, src_len, scheme, dst_off,
# dst_len)``: the chunk's compressed payload is ``src_buf[src_off :
# src_off + src_len]`` and its uncompressed bytes land at ``out[dst_off :
# dst_off + dst_len]``. ``src_buf`` repeats across descriptors drawn from
# the same blob — the native dispatch computes one base pointer per
# unique buffer, so a whole shard's chunks cost one ctypes call total.


def native_batch_available() -> bool:
    """True when the native decode engine can take descriptor batches."""
    native = _get_native()
    return native is not None and hasattr(native, "decode_batch")


def decode_batch_into(descs, out, workers: int = 1,
                      use_native: bool | None = None) -> int:
    """Decode a batch of tuple descriptors into ``out``; returns the
    byte count written.

    Thin adapter over :func:`decode_columns_into` (ONE implementation
    of the native dispatch): descriptors are grouped per source buffer
    into columnar arrays and delegated. Useful for callers assembling
    heterogeneous batches by hand; the decode hot paths build columns
    directly (XorbReader.decode_columns)."""
    import numpy as np

    descs = list(descs)
    if not descs:
        # Still surface a read-only destination (same contract as the
        # non-empty path).
        if memoryview(out).readonly:
            raise CompressionError("decode destination is read-only")
        return 0
    for _buf, src_off, src_len, _scheme, dst_off, dst_len in descs:
        if min(src_off, src_len, dst_off, dst_len) < 0:
            raise CompressionError("negative descriptor range")
    by_buf: dict[int, tuple] = {}
    for d in descs:
        by_buf.setdefault(id(d[0]), (d[0], []))[1].append(d)
    groups = [
        (buf,
         np.asarray([d[1] for d in items], dtype=np.uint64),
         np.asarray([d[2] for d in items], dtype=np.uint64),
         np.asarray([int(d[3]) for d in items], dtype=np.uint8),
         np.asarray([d[4] for d in items], dtype=np.uint64),
         np.asarray([d[5] for d in items], dtype=np.uint64))
        for buf, items in by_buf.values()
    ]
    return decode_columns_into(groups, out, workers=workers,
                               use_native=use_native)


def decode_columns_into(groups, out, workers: int = 1,
                        use_native: bool | None = None) -> int:
    """Columnar sibling of :func:`decode_batch_into` — zero Python work
    per chunk. Each group is ``(buf, src_offs, src_lens, schemes,
    dst_offs, dst_lens)`` with numpy arrays (u64/u64/u8/u64/u64) of one
    length, offsets relative to ``buf``/``out``; a whole shard's chunk
    table (XorbReader.decode_columns) flows through a handful of numpy
    ops into ONE native call. Validation (bounds, pairwise-disjoint
    destinations) is vectorized. Returns the byte count written."""
    import numpy as np

    view = memoryview(out).cast("B")
    if view.readonly:
        raise CompressionError("decode destination is read-only")
    groups = [g for g in groups if len(g[1])]
    if not groups:
        return 0
    all_dst_offs = (np.concatenate([g[4] for g in groups])
                    if len(groups) > 1 else groups[0][4])
    all_dst_lens = (np.concatenate([g[5] for g in groups])
                    if len(groups) > 1 else groups[0][5])
    ends = all_dst_offs + all_dst_lens
    if int(ends.max(initial=0)) > view.nbytes or bool(
            (ends < all_dst_offs).any()):
        raise CompressionError(
            f"descriptor dst range outside a {view.nbytes}-byte buffer"
        )
    order = np.argsort(all_dst_offs, kind="stable")
    if bool((all_dst_offs[order][1:] < ends[order][:-1]).any()):
        raise CompressionError("overlapping descriptor dst ranges")
    total = int(all_dst_lens.sum(dtype=np.uint64))
    for buf, src_offs, src_lens, _schemes, _do, _dl in groups:
        nbytes = np.frombuffer(buf, dtype=np.uint8).nbytes
        src_ends = src_offs + src_lens
        if int(src_ends.max(initial=0)) > nbytes or bool(
                (src_ends < src_offs).any()):
            raise CompressionError(
                "descriptor src range outside its buffer")

    if use_native is None:
        use_native = native_batch_available()
    if use_native:
        import ctypes

        native = _get_native()
        ptr_groups, keep_alive = [], []
        for buf, src_offs, src_lens, schemes, dst_offs, dst_lens in groups:
            arr = np.frombuffer(buf, dtype=np.uint8)
            keep_alive.append((buf, arr))
            ptr_groups.append(src_offs.astype(np.uint64)
                              + np.uint64(arr.ctypes.data))
        cat = (lambda xs: np.ascontiguousarray(np.concatenate(xs))
               if len(xs) > 1 else np.ascontiguousarray(xs[0]))
        src_ptrs = cat(ptr_groups)
        src_lens = cat([g[2].astype(np.uint64) for g in groups])
        schemes = cat([g[3].astype(np.uint8) for g in groups])
        dst_offs = cat([g[4].astype(np.uint64) for g in groups])
        dst_lens = cat([g[5].astype(np.uint64) for g in groups])
        dst_ptr = ctypes.addressof(ctypes.c_char.from_buffer(view))
        rc = native.decode_batch(src_ptrs, src_lens, schemes, dst_offs,
                                 dst_lens, dst_ptr, view.nbytes, workers)
        del keep_alive
        if rc == 0:
            return total
        # Fall through: the pure loop reproduces the precise error.
    for buf, src_offs, src_lens, schemes, dst_offs, dst_lens in groups:
        mv = memoryview(buf)
        for i in range(len(src_offs)):
            so, sl = int(src_offs[i]), int(src_lens[i])
            do, dl = int(dst_offs[i]), int(dst_lens[i])
            decompress_into(mv[so:so + sl], Scheme(int(schemes[i])),
                            view[do:do + dl])
    return total


def compress_auto(data: bytes) -> tuple[Scheme, bytes]:
    """Pick the smallest encoding; None when compression doesn't pay."""
    best_scheme, best = Scheme.NONE, data
    for scheme in (Scheme.LZ4, Scheme.BG4_LZ4):
        candidate = compress(data, scheme)
        if len(candidate) < len(best):
            best_scheme, best = scheme, candidate
    return best_scheme, best


def _get_native():
    try:
        from zest_tpu.native import lib

        return lib if lib.available() and hasattr(lib, "lz4_compress") else None
    except Exception:
        return None
