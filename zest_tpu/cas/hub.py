"""HuggingFace Hub API client: file listing, xet detection, revision resolve.

The zig-xet `model_download` equivalent (SURVEY.md §2.2): list a repo's
files with their sizes and optional xet file hashes, resolve a ref to a
commit SHA, and stream regular (non-xet) files. Endpoint shapes follow the
real Hub API and are served identically by the local fixture server in
tests (zero-egress environment):

    GET  /api/models/{repo}/revision/{rev}        -> {"sha", "siblings": [...]}
    POST /api/models/{repo}/paths-info/{rev}      -> [{"path","size","xetHash"?}]
    GET  /{repo}/resolve/{rev}/{file}             -> raw bytes (redirects ok)
    GET  /api/models/{repo}/xet-read-token/{rev}  -> {"accessToken","casUrl"}

(reference call sites: main.zig:142-154, main.zig:638-677, main.zig:696-728,
xet_bridge.zig:83-109)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path

import requests

from zest_tpu.config import Config


class HubError(RuntimeError):
    pass


@dataclass(frozen=True)
class FileEntry:
    path: str
    size: int
    xet_hash: str | None = None  # 64-char hex when stored in Xet CAS

    @property
    def is_xet(self) -> bool:
        return self.xet_hash is not None


class HubClient:
    def __init__(self, cfg: Config, session: requests.Session | None = None):
        self.cfg = cfg
        self.session = session or requests.Session()

    def _headers(self) -> dict[str, str]:
        if self.cfg.hf_token:
            return {"Authorization": f"Bearer {self.cfg.hf_token}"}
        return {}

    def _get_json(self, url: str) -> dict | list:
        resp = self.session.get(url, headers=self._headers(), timeout=30)
        if resp.status_code != 200:
            raise HubError(f"GET {url} -> {resp.status_code}")
        return resp.json()

    def resolve_revision(self, repo_id: str, revision: str = "main") -> str:
        """Ref -> commit SHA (reference: main.zig:638-677)."""
        doc = self._get_json(
            f"{self.cfg.endpoint}/api/models/{repo_id}/revision/{revision}"
        )
        sha = doc.get("sha") if isinstance(doc, dict) else None
        if not isinstance(sha, str) or not sha:
            raise HubError(f"no sha in revision response for {repo_id}@{revision}")
        return sha

    def list_files(self, repo_id: str, revision: str = "main") -> list[FileEntry]:
        """All files in the repo with sizes and xet hashes."""
        doc = self._get_json(
            f"{self.cfg.endpoint}/api/models/{repo_id}/revision/{revision}"
        )
        siblings = doc.get("siblings", []) if isinstance(doc, dict) else []
        paths = [s["rfilename"] for s in siblings if "rfilename" in s]
        if not paths:
            return []
        resp = self.session.post(
            f"{self.cfg.endpoint}/api/models/{repo_id}/paths-info/{revision}",
            json={"paths": paths},
            headers=self._headers(),
            timeout=30,
        )
        if resp.status_code != 200:
            raise HubError(f"paths-info -> {resp.status_code}")
        entries = []
        for item in resp.json():
            if item.get("type") == "directory":
                continue
            entries.append(
                FileEntry(
                    path=item["path"],
                    size=int(item.get("size", 0)),
                    xet_hash=item.get("xetHash"),
                )
            )
        return entries

    def download_regular_file(
        self, repo_id: str, revision: str, filename: str, dest: Path
    ) -> int:
        """Stream a non-xet file to ``dest``; returns byte count.

        Streams to a tmp file and renames — unlike the reference, which
        buffers whole files in memory (quirk at main.zig:713-728). The
        tmp name is unique per call (mkstemp, not a fixed
        ``.tmp-<name>``): the early-config prefetch and the file loop
        may both stream the same dest concurrently, and a shared tmp
        would let one rename steal the other's file out from under its
        own ``os.replace``.
        """
        import tempfile

        url = f"{self.cfg.endpoint}/{repo_id}/resolve/{revision}/{filename}"
        dest.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=dest.parent,
                                   prefix=f".tmp-{dest.name}.")
        total = 0
        try:
            with os.fdopen(fd, "wb") as f:
                with self.session.get(
                    url, headers=self._headers(), timeout=60, stream=True
                ) as resp:
                    if resp.status_code != 200:
                        raise HubError(f"GET {url} -> {resp.status_code}")
                    for piece in resp.iter_content(chunk_size=1 << 20):
                        f.write(piece)
                        total += len(piece)
            os.replace(tmp, dest)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return total

    def xet_read_token(
        self, repo_id: str, revision: str = "main"
    ) -> tuple[str, str]:
        """Exchange the HF token for (cas_url, access_token)
        (reference: xet_bridge.zig:83-130)."""
        doc = self._get_json(
            f"{self.cfg.endpoint}/api/models/{repo_id}/xet-read-token/{revision}"
        )
        if not isinstance(doc, dict):
            raise HubError("malformed xet-read-token response")
        try:
            return doc["casUrl"], doc["accessToken"]
        except KeyError as exc:
            raise HubError(f"xet-read-token missing {exc}") from exc
