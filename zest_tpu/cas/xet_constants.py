"""Production Xet content-addressing constants (interop-critical).

These are the public constants of the HF Xet stack, verified bit-for-bit
against the installed official ``hf_xet`` client: the golden tests in
tests/test_xet_interop.py reproduce its file hashes on inputs from empty
through 70 MiB, which pins every constant below (a single wrong bit in
the table, mask, keys, grouping rule, or salt changes the final hex):

- ``GEAR_TABLE``: the 256-entry u64 table of the public ``gearhash`` crate
  (MIT) used by xet-core's content-defined chunker. Boundary rule: roll
  ``h = (h << 1) + GEAR[byte]``; cut when ``h & MASK == 0`` at >= 8 KiB,
  force at 128 KiB (reference behavior: SURVEY.md section 2.2 row
  `chunking`; spec deltas at reference DESIGN.md:265-273).
- ``CHUNK_KEY`` / ``NODE_KEY``: BLAKE3 keyed-mode domain keys for chunk
  hashes and merkle interior nodes (xet-core merklehash).
- ``FILE_SALT``: the salt applied to a file's merkle root —
  ``file_hash = blake3_keyed(FILE_SALT, root)`` — distinguishing file
  addresses from xorb addresses. HF uploads use the all-zero salt.

Merkle aggregation (hashing.merkle_root): children group left-to-right;
a group closes at its k-th child (k >= 3) when the child hash's last u64
(little-endian) is divisible by 4, or unconditionally at k == 9; the parent
hashes the text ``"{hash_hex} : {size}\n"`` per child under NODE_KEY.
A single leaf is its own root.
"""

from __future__ import annotations

import base64
import struct

CHUNK_KEY = bytes.fromhex(
    "6697f5775b9550de3135cbaca597181c9de421109beb2b58b4d0b04b93adf229"
)
NODE_KEY = bytes.fromhex(
    "017ec5c7a5472996fd946666b48a02e65ddd536f37c76dd2f86352e64a53713f"
)
FILE_SALT = bytes(32)

MASK = 0xFFFF_0000_0000_0000
MIN_CHUNK = 8 * 1024
TARGET_CHUNK = 64 * 1024
MAX_CHUNK = 128 * 1024

# Merkle grouping parameters (see module docstring).
GROUP_MIN = 3
GROUP_MAX = 9
GROUP_MOD = 4

_GEAR_B85 = (
    "S@l5ZsndwC)*$UU_s3FJt8$5nX^FB$cgK#l)rksgw=sH)K39)6OW8K*+&0D?w$)TsPE2|r%bFu7M"
    "zeIJ61k%sKBxve_qqZvY>nrTikb}-_btict<#1OIt7)EA_qFS@$@cQu77}^l&lY>f!5f7>jQHR53"
    "O(&+<vf`b};=_wJ)7$Xr!JIf=~beD9n$j27|D~{Yp3Xif6DsHgv6qtB0TQHpgqezn>EF?WCAoS4j"
    "~_#d}AU1_1y7%<7yPfH%4yZJVTH)^G4TONZlvmwsrO<JeHDa|WS#AoLMpp3ki0agbMsALk$?n~EB"
    "E{nOPebI1|h%}w3_FFA)=95Jct|3JJKwvKe$Z@(b+jha`lvBC)(+U2H(E}<gB3d1kVXF~VE{wiKI"
    "d!!-2^vIbl!_NeiN=x3lX?<`Vau~JuAT0BHShj{T3@5LKh_(O#d60_R<kl6Tnc=dRoPJM80pk-_w"
    "|mOWVDYdkri3NG$h=)*6X@ryT9t<llyCq5Z(^Pk2$ANBqB!0!*J^jY&%pe`9{_9nC&YW^xOAk)SY"
    "7K77V-AF(QxN&TOZ2_M@)T}<{7fEO0B1EIK0dN0Y8`D3Y~<^aQA8feea`l{=L11)6xgXFc*1jmdP"
    "rA+62YMYp6HSonWdSW_<DUhvuqHuqLLg2U|){H<?;>-mWZL=6-{ag$Gvsldb4(*zHpg;n@HH*V`P"
    "@vO5NzveE%6@pr2SL>px0RRUG)#uS`HsFeG7_4UBU8xB#&<I3bV3o-xzEgFj~CNq43L_z9E3twjx"
    ">+Fq5rqg>SUIX=l`EQZ&i2F43e;Xo(*hz0V=gz*JN{<i7%J^97*kt^Yh8c|jHk!Y>PjEPGOupyQ2"
    "g@?xkO)Ps_92ZTrHLiLzp-3nan7QgbD<zcSo`Iw<KO3}>kT|Blgbbbd+o1;=>8`sAFJZ|Z5-dCBl"
    "UC#tkKO)PU17I|8M{R7I>G%d+4m9Nnj|nS|^g2S&8x8ff0tn89qotpmLeJcra0BH_dSGWSIP`69o"
    "VOgFwkx7`9lz-ryydg;3}Tlzl$<JNuWB&P%ouZDJmN(N}waP*e<379`?yae3jshgIn!GOXEeKEL0"
    "Ze3c^r6~0R&etjIEyNsi_SjVEPFe^Un&Y&L+k-1=gi>0;;HD;opTir85qrm|{eLMZaClPa0B!EHt"
    "%NPx%g<+*-Pql=Hy#jjnV^HR2-4SnTs}!jGA|L<&QrNjIk-pZ9SBkFbwuw<`C=A*PwNsStUW9lWs"
    "b2ipXQxUU8NY_B<rQSewZ;GdE}{sI7Z7tMCCZBj8xSq+`=}<egzZ5)2HYqQ5+ddkCT(#0>-80*&b"
    "k3JPQw<6uKjVloLf2RNEUJ~An7as9LcjQ9ovIKrKYjc8b*EBYliD?<QMGXe8l@XK)&F(1f40>#9*"
    "P`G&YRV$cF5GnWLDW60I2_?}MUYYxK;f7sgoj-p*1igWarY5r?!b>C2w;*6;uTiIgvm$=KCBg!KU"
    "{qH-9D@;rME#H51qmZ2NVEbJhF6XRK);I@-y>w|pE8O{IzJDf-MHCM|ZTUysyH@_}+bvbE0g8q9T"
    ")!Cdg62X1f1AStILW8H9=}}+l6UBWY7I<F#DH?mSEoQqhn9bDyOmlT@fScoSBO3b#@G4h+g&*l^F"
    "Hdu#^4%-~wZ?+K-k@Oz#_Bs<m}x;ryOYSx0e%=<VTBNFU3@33Fs}G_kt`|_fopt`XKB%`nYMLXn+"
    "BTaYR>=w1QFg~`U=GJ!)KADdJ<zGO^MNIBIX@pCPypg9ju7aJZDQ4;x=#)9XeGgU$_7OJ#QlzOiu"
    "6e0`|EPX5QA>9Fa%+ReAiOy^Sd^1O0{T;rhO_FHk4G9%jwNQ9=XbHW&v~(_|AwKA7@#Y_oy@@Mii"
    "J1Cd3s_sU0@oCX{MAyP|P$KMbswWwDdJ~dl&Y}t<KEHOmRW%|7aMJ|-Q8<bZPr-o4Qt<F)?6|%$r"
    "0zwqvRS>QKROCW$gg{I6$h^GB*?#~4{+r$x^PauG5>AEkFE9kC)Y#>vS4qw}gt|9Yq~f{%i-Dk?9"
    "j&=liWeF4%ZdO1IQ#q1$iL!9=(R}bL73x<i=dVU77`~8DkMZp=#~)GI^mLM7rFrvooF#d4|guYY{"
    "k2B^6!Zj>`+#J87ip&PI#fckXd%TuDJY?<3SY0pyV;_EIQ!_O`T37gz3wY#fp8qa`jJ-&^wk3z;K"
    "oe0qX-ASXz~0R006%<%3^jnevFu7Uldw&zO4JNSE5X`b9|ovZ??8Rb};?1{-!6R;{)}gI<q|*&#?"
    "2{Ty9BkIk^6EFN6!f3^Fk{^0le8<rBf(*OVfj<ErR7mz>N>t8zKZIJ(P=WuDjr`0C~=@WclbLZG1"
    "tUEkp-*BtR;}X7#+{UEsisv%`K_Bnz%W|xAvce<)v;e73l?`+T1Y<oin<;u7)#}TbvV6m{n{#+!$"
    "K!^{iuFcIHtMUNR?LO3#T24#gpZ@Q*gm8e>)V<gQS8ia`&u&-3A4)i((V=X#b91av~o63XK4Tchr"
    "3i15*?+T7RbA~6CN^zjomM+wr@TAjS3cy?Orfo=xmhf6idI$!w?%dV^0785Khc*fw$EMRe@@1ayF"
    "&q-G87*G_tQ(l+($p_eS#=J<}T2RmN>&_Vf2SNvn&@dl=op2C2tm"
)

GEAR_TABLE: tuple[int, ...] = struct.unpack(
    "<256Q", base64.b85decode(_GEAR_B85)
)
