"""Pure-Python BLAKE3 — the portable correctness anchor.

Implements the full BLAKE3 spec (hash, keyed hash, derive-key, XOF output,
incremental hashing with the chunk-CV stack). This is the reference
implementation that the native C++ backend (zest_tpu/native/blake3.cc) and
the on-device Pallas kernel (zest_tpu/ops/blake3_pallas.py) are validated
against; hot paths never call this module directly — see
zest_tpu.cas.hashing for dispatch.

Parity note: the reference delegates BLAKE3 to zig-xet (`hashing` module,
SURVEY.md §2.2); chunk verification throughput is its headline benchmark
(blake3_64kb, 3517 MB/s — BASELINE.md).
"""

from __future__ import annotations

import struct

OUT_LEN = 32
KEY_LEN = 32
BLOCK_LEN = 64
CHUNK_LEN = 1024

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3
KEYED_HASH = 1 << 4
DERIVE_KEY_CONTEXT = 1 << 5
DERIVE_KEY_MATERIAL = 1 << 6

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

_MASK = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _MASK


def _g(state: list[int], a: int, b: int, c: int, d: int, mx: int, my: int) -> None:
    state[a] = (state[a] + state[b] + mx) & _MASK
    state[d] = _rotr(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotr(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b] + my) & _MASK
    state[d] = _rotr(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotr(state[b] ^ state[c], 7)


def _round(state: list[int], m: list[int]) -> None:
    # Columns.
    _g(state, 0, 4, 8, 12, m[0], m[1])
    _g(state, 1, 5, 9, 13, m[2], m[3])
    _g(state, 2, 6, 10, 14, m[4], m[5])
    _g(state, 3, 7, 11, 15, m[6], m[7])
    # Diagonals.
    _g(state, 0, 5, 10, 15, m[8], m[9])
    _g(state, 1, 6, 11, 12, m[10], m[11])
    _g(state, 2, 7, 8, 13, m[12], m[13])
    _g(state, 3, 4, 9, 14, m[14], m[15])


def compress(
    chaining_value: tuple[int, ...] | list[int],
    block_words: list[int],
    counter: int,
    block_len: int,
    flags: int,
) -> list[int]:
    """One BLAKE3 compression; returns the full 16-word output state."""
    state = [
        chaining_value[0], chaining_value[1], chaining_value[2], chaining_value[3],
        chaining_value[4], chaining_value[5], chaining_value[6], chaining_value[7],
        IV[0], IV[1], IV[2], IV[3],
        counter & _MASK, (counter >> 32) & _MASK, block_len, flags,
    ]
    m = list(block_words)
    for r in range(7):
        _round(state, m)
        if r < 6:
            m = [m[p] for p in MSG_PERMUTATION]
    for i in range(8):
        state[i] ^= state[i + 8]
        state[i + 8] ^= chaining_value[i]
    return state


def _words_from_block(block: bytes) -> list[int]:
    # Little-endian u32 words; callers zero-pad short blocks.
    if len(block) < BLOCK_LEN:
        block = block + b"\x00" * (BLOCK_LEN - len(block))
    return list(struct.unpack("<16I", block))


def _words_from_key(key: bytes) -> tuple[int, ...]:
    if len(key) != KEY_LEN:
        raise ValueError(f"key must be {KEY_LEN} bytes, got {len(key)}")
    return struct.unpack("<8I", key)


class _Output:
    """Deferred final compression — lets the root node emit arbitrary XOF length."""

    __slots__ = ("input_cv", "block_words", "counter", "block_len", "flags")

    def __init__(self, input_cv, block_words, counter, block_len, flags):
        self.input_cv = input_cv
        self.block_words = block_words
        self.counter = counter
        self.block_len = block_len
        self.flags = flags

    def chaining_value(self) -> list[int]:
        return compress(
            self.input_cv, self.block_words, self.counter, self.block_len, self.flags
        )[:8]

    def root_bytes(self, length: int) -> bytes:
        out = bytearray()
        counter = 0
        while len(out) < length:
            words = compress(
                self.input_cv, self.block_words, counter,
                self.block_len, self.flags | ROOT,
            )
            out += struct.pack("<16I", *words)
            counter += 1
        return bytes(out[:length])


class _ChunkState:
    __slots__ = ("cv", "counter", "block", "blocks_compressed", "flags")

    def __init__(self, key_words, counter: int, flags: int):
        self.cv = list(key_words)
        self.counter = counter
        self.block = bytearray()
        self.blocks_compressed = 0
        self.flags = flags

    def __len__(self) -> int:
        return BLOCK_LEN * self.blocks_compressed + len(self.block)

    def _start_flag(self) -> int:
        return CHUNK_START if self.blocks_compressed == 0 else 0

    def update(self, data: memoryview) -> None:
        pos = 0
        while pos < len(data):
            # Compress a buffered full block only when more input exists, so
            # the final block stays pending for CHUNK_END / ROOT flags.
            if len(self.block) == BLOCK_LEN:
                self.cv = compress(
                    self.cv, _words_from_block(bytes(self.block)),
                    self.counter, BLOCK_LEN, self.flags | self._start_flag(),
                )[:8]
                self.blocks_compressed += 1
                self.block.clear()
            take = min(BLOCK_LEN - len(self.block), len(data) - pos)
            self.block += data[pos : pos + take]
            pos += take

    def output(self) -> _Output:
        return _Output(
            self.cv, _words_from_block(bytes(self.block)), self.counter,
            len(self.block), self.flags | self._start_flag() | CHUNK_END,
        )


def _parent_output(left_cv, right_cv, key_words, flags: int) -> _Output:
    return _Output(key_words, list(left_cv) + list(right_cv), 0, BLOCK_LEN,
                   flags | PARENT)


class Hasher:
    """Incremental BLAKE3 hasher (hash / keyed / derive-key modes)."""

    __slots__ = ("key_words", "flags", "cv_stack", "chunk")

    def __init__(self, key_words=None, flags: int = 0):
        self.key_words = tuple(key_words) if key_words is not None else IV
        self.flags = flags
        self.cv_stack: list[list[int]] = []
        self.chunk = _ChunkState(self.key_words, 0, flags)

    @classmethod
    def new_keyed(cls, key: bytes) -> "Hasher":
        return cls(_words_from_key(key), KEYED_HASH)

    @classmethod
    def new_derive_key(cls, context: str) -> "Hasher":
        ctx_hasher = cls(IV, DERIVE_KEY_CONTEXT)
        ctx_hasher.update(context.encode())
        ctx_key = struct.unpack("<8I", ctx_hasher.digest(KEY_LEN))
        return cls(ctx_key, DERIVE_KEY_MATERIAL)

    def update(self, data: bytes | bytearray | memoryview) -> "Hasher":
        data = memoryview(data)
        pos = 0
        while pos < len(data):
            if len(self.chunk) == CHUNK_LEN:
                cv = self.chunk.output().chaining_value()
                total_chunks = self.chunk.counter + 1
                self._push_cv(cv, total_chunks)
                self.chunk = _ChunkState(self.key_words, total_chunks, self.flags)
            take = min(CHUNK_LEN - len(self.chunk), len(data) - pos)
            self.chunk.update(data[pos : pos + take])
            pos += take
        return self

    def _push_cv(self, cv: list[int], total_chunks: int) -> None:
        # Merge complete subtrees: one merge per trailing zero bit of the
        # total chunk count keeps the stack at O(log n).
        while total_chunks % 2 == 0:
            cv = _parent_output(
                self.cv_stack.pop(), cv, self.key_words, self.flags
            ).chaining_value()
            total_chunks //= 2
        self.cv_stack.append(cv)

    def _final_output(self) -> _Output:
        output = self.chunk.output()
        for cv in reversed(self.cv_stack):
            output = _parent_output(
                cv, output.chaining_value(), self.key_words, self.flags
            )
        return output

    def digest(self, length: int = OUT_LEN) -> bytes:
        return self._final_output().root_bytes(length)

    def hexdigest(self, length: int = OUT_LEN) -> str:
        return self.digest(length).hex()


# ── One-shot conveniences ──


def blake3(data: bytes, length: int = OUT_LEN) -> bytes:
    return Hasher().update(data).digest(length)


def blake3_keyed(key: bytes, data: bytes, length: int = OUT_LEN) -> bytes:
    return Hasher.new_keyed(key).update(data).digest(length)


def blake3_derive_key(context: str, key_material: bytes,
                      length: int = OUT_LEN) -> bytes:
    return Hasher.new_derive_key(context).update(key_material).digest(length)
