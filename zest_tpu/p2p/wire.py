"""BitTorrent wire protocol framing (BEP 3 + BEP 10) — interop plane.

Wire-compatible with the reference (src/bt_wire.zig) so zest-tpu hosts can
join the same swarms as reference/ccbittorrent clients:

    Handshake:  [1 pstrlen][19 "BitTorrent protocol"][8 reserved]
                [20 info_hash][20 peer_id]                       = 68 bytes
    Message:    [4 length BE][1 msg_id][payload...]
    Keepalive:  [4 zeros]
    Extended:   [4 length BE][1 msg_id=20][1 ext_id][payload...]

Reserved byte 5 bit 0x10 advertises BEP 10 support; max message size is
64 MiB + 1 KiB, matching the xorb cap (src/bt_wire.zig:19-22).

Pure codecs operate on bytes (testable fixed-buffer style, SURVEY.md §4);
``SocketStream`` adapts them to a blocking socket.
"""

from __future__ import annotations

import enum
import socket
import struct
from dataclasses import dataclass

PROTOCOL_STRING = b"BitTorrent protocol"
HANDSHAKE_SIZE = 68
RESERVED_BYTES = bytes([0, 0, 0, 0, 0, 0x10, 0, 0])
MAX_MESSAGE_SIZE = 64 * 1024 * 1024 + 1024


class WireError(ValueError):
    pass


class MessageId(enum.IntEnum):
    CHOKE = 0
    UNCHOKE = 1
    INTERESTED = 2
    NOT_INTERESTED = 3
    HAVE = 4
    BITFIELD = 5
    REQUEST = 6
    PIECE = 7
    CANCEL = 8
    EXTENDED = 20  # BEP 10


@dataclass(frozen=True)
class Handshake:
    info_hash: bytes
    peer_id: bytes
    reserved: bytes = RESERVED_BYTES

    @property
    def supports_bep10(self) -> bool:
        return bool(self.reserved[5] & 0x10)


# ── Pure codecs ──


def encode_handshake(info_hash: bytes, peer_id: bytes) -> bytes:
    if len(info_hash) != 20 or len(peer_id) != 20:
        raise WireError("info_hash and peer_id must be 20 bytes")
    return (
        bytes([len(PROTOCOL_STRING)]) + PROTOCOL_STRING + RESERVED_BYTES
        + info_hash + peer_id
    )


def decode_handshake(buf: bytes) -> Handshake:
    if len(buf) != HANDSHAKE_SIZE:
        raise WireError(f"handshake must be {HANDSHAKE_SIZE} bytes")
    if buf[0] != len(PROTOCOL_STRING) or buf[1:20] != PROTOCOL_STRING:
        raise WireError("invalid protocol string")
    return Handshake(
        info_hash=buf[28:48], peer_id=buf[48:68], reserved=buf[20:28]
    )


def encode_message(msg_id: MessageId, payload: bytes = b"") -> bytes:
    total = 1 + len(payload)
    if total > MAX_MESSAGE_SIZE:
        raise WireError(f"message too large: {total}")
    return struct.pack(">IB", total, int(msg_id)) + payload


def encode_keepalive() -> bytes:
    return b"\x00\x00\x00\x00"


def encode_extended(ext_id: int, payload: bytes) -> bytes:
    """BEP 10 framing: [len][20][ext_id][payload] (src/bt_wire.zig:136-146)."""
    return encode_message(MessageId.EXTENDED, bytes([ext_id]) + payload)


def parse_extended(payload: bytes) -> tuple[int, bytes]:
    """Split an EXTENDED message payload into (ext_id, sub-payload)."""
    if not payload:
        raise WireError("empty extended payload")
    return payload[0], payload[1:]


@dataclass(frozen=True)
class Message:
    """A decoded frame; ``msg_id is None`` for keepalives."""

    msg_id: MessageId | None
    payload: bytes = b""


def decode_message_header(header: bytes) -> int:
    """Parse the 4-byte length prefix; validates the size cap."""
    (length,) = struct.unpack(">I", header)
    if length > MAX_MESSAGE_SIZE:
        raise WireError(f"message length {length} exceeds cap")
    return length


# ── Socket adapter ──


class SocketStream:
    """Blocking framed stream over a TCP socket.

    One lock per direction is the caller's concern (zest_tpu.p2p.peer holds
    a per-peer mutex, mirroring src/bt_peer.zig:33-35).
    """

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()

    def _recv_exactly(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            piece = self.sock.recv(n - len(buf))
            if not piece:
                raise WireError("connection closed mid-frame")
            buf += piece
        return bytes(buf)

    # handshake

    def send_handshake(self, info_hash: bytes, peer_id: bytes) -> None:
        self.sock.sendall(encode_handshake(info_hash, peer_id))

    def recv_handshake(self) -> Handshake:
        return decode_handshake(self._recv_exactly(HANDSHAKE_SIZE))

    # messages

    def send_message(self, msg_id: MessageId, payload: bytes = b"") -> None:
        self.sock.sendall(encode_message(msg_id, payload))

    def send_raw(self, data: bytes) -> None:
        self.sock.sendall(data)

    def recv_message(self) -> Message:
        length = decode_message_header(self._recv_exactly(4))
        if length == 0:
            return Message(None)
        body = self._recv_exactly(length)
        try:
            msg_id = MessageId(body[0])
        except ValueError as exc:
            raise WireError(f"invalid message id {body[0]}") from exc
        return Message(msg_id, body[1:])
