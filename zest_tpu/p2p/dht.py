"""Kademlia DHT (BEP 5) — WAN peer discovery (reference: src/dht.zig).

XOR metric over 160-bit node IDs, K=8 buckets, KRPC (bencoded dicts over
UDP) with ping / find_node / get_peers / announce_peer, compact node (26 B)
and peer (6 B) codecs, iterative lookup. In the TPU build this is the
*interop* discovery path for off-pod peers; in-pod discovery is the JAX
coordinator registry (zest_tpu.parallel.coordinator), which replaces DHT
entirely (SURVEY.md §2.1 row 9).

Deliberate fixes of reference quirks (SURVEY.md §7 "quirks to not
replicate"): announce uses the token returned by get_peers, not a static
string (dht.zig:453-454); k-buckets evict the least-recently-seen entry
instead of always dropping newcomers (dht.zig:81-97). Each node also
*serves* KRPC queries, so two zest nodes can find each other with no
external router.
"""

from __future__ import annotations

import os
import secrets
import socket
import struct
import threading
import time
from dataclasses import dataclass, field

from zest_tpu.cas import hashing
from zest_tpu.p2p import bencode

NODE_ID_LEN = 20
K = 8
NUM_BUCKETS = NODE_ID_LEN * 8
ALPHA = 3
COMPACT_NODE_LEN = 26  # 20B id + 4B ip + 2B port
COMPACT_PEER_LEN = 6
# peer_store bounds: this responder runs on a public UDP port, so storage
# must be capped and announcements must expire or an adversary (or a busy
# swarm) grows a seeder's memory without bound.
PEER_TTL_S = 30 * 60
MAX_PEERS_PER_HASH = 64
MAX_STORED_HASHES = 4096

BOOTSTRAP_NODES = [
    ("router.bittorrent.com", 6881),
    ("dht.transmissionbt.com", 6881),
]


class DhtError(RuntimeError):
    pass


# ── Metric + routing table (pure logic, dht.zig:41-166) ──


def xor_distance(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def bucket_index(distance: bytes) -> int:
    """Index of the highest set bit: 0 for the farthest half of the space,
    159 adjacent; -1 for self (zero distance)."""
    for i, byte in enumerate(distance):
        if byte:
            return i * 8 + (7 - byte.bit_length() + 1)
    return -1


@dataclass
class Node:
    node_id: bytes
    addr: tuple[str, int]
    last_seen: float = field(default_factory=time.monotonic)


class KBucket:
    def __init__(self, k: int = K):
        self.k = k
        self.nodes: list[Node] = []  # oldest-seen first

    def update(self, node: Node) -> None:
        for i, n in enumerate(self.nodes):
            if n.node_id == node.node_id:
                n.addr = node.addr
                n.last_seen = time.monotonic()
                self.nodes.append(self.nodes.pop(i))
                return
        if len(self.nodes) < self.k:
            self.nodes.append(node)
        else:
            # LRU eviction: the head is least recently seen.
            self.nodes.pop(0)
            self.nodes.append(node)


class RoutingTable:
    def __init__(self, self_id: bytes, k: int = K):
        self.self_id = self_id
        self.k = k
        self.buckets = [KBucket(k) for _ in range(NUM_BUCKETS)]

    def update(self, node_id: bytes, addr: tuple[str, int]) -> None:
        idx = bucket_index(xor_distance(self.self_id, node_id))
        if idx < 0:
            return  # never insert ourselves
        self.buckets[idx].update(Node(node_id, addr))

    def closest(self, target: bytes, count: int | None = None) -> list[Node]:
        count = count or self.k
        everyone = [n for b in self.buckets for n in b.nodes]
        everyone.sort(key=lambda n: xor_distance(n.node_id, target))
        return everyone[:count]

    def __len__(self) -> int:
        return sum(len(b.nodes) for b in self.buckets)


# ── KRPC codecs (dht.zig:171-299) ──


def build_ping(self_id: bytes, tid: bytes) -> bytes:
    return bencode.encode(
        {b"t": tid, b"y": b"q", b"q": b"ping", b"a": {b"id": self_id}}
    )


def build_find_node(self_id: bytes, target: bytes, tid: bytes) -> bytes:
    return bencode.encode({
        b"t": tid, b"y": b"q", b"q": b"find_node",
        b"a": {b"id": self_id, b"target": target},
    })


def build_get_peers(self_id: bytes, info_hash: bytes, tid: bytes) -> bytes:
    return bencode.encode({
        b"t": tid, b"y": b"q", b"q": b"get_peers",
        b"a": {b"id": self_id, b"info_hash": info_hash},
    })


def build_announce_peer(
    self_id: bytes, info_hash: bytes, port: int, token: bytes, tid: bytes
) -> bytes:
    return bencode.encode({
        b"t": tid, b"y": b"q", b"q": b"announce_peer",
        b"a": {b"id": self_id, b"info_hash": info_hash,
               b"port": port, b"token": token},
    })


def encode_compact_nodes(nodes: list[Node]) -> bytes:
    out = bytearray()
    for n in nodes:
        try:
            ip = socket.inet_aton(n.addr[0])
        except OSError:
            continue  # non-IPv4 addresses are not representable in BEP 5
        out += n.node_id + ip + struct.pack(">H", n.addr[1])
    return bytes(out)


def parse_compact_nodes(raw: bytes) -> list[tuple[bytes, tuple[str, int]]]:
    if len(raw) % COMPACT_NODE_LEN:
        raise DhtError(f"compact nodes length {len(raw)} not 26-aligned")
    out = []
    for off in range(0, len(raw), COMPACT_NODE_LEN):
        node_id = raw[off : off + 20]
        ip = socket.inet_ntoa(raw[off + 20 : off + 24])
        (port,) = struct.unpack_from(">H", raw, off + 24)
        out.append((node_id, (ip, port)))
    return out


def encode_compact_peers(peers: list[tuple[str, int]]) -> list[bytes]:
    out = []
    for ip, port in peers:
        try:
            out.append(socket.inet_aton(ip) + struct.pack(">H", port))
        except OSError:
            continue
    return out


def parse_compact_peers(values: list) -> list[tuple[str, int]]:
    peers = []
    for raw in values:
        if not isinstance(raw, bytes) or len(raw) != COMPACT_PEER_LEN:
            continue
        peers.append(
            (socket.inet_ntoa(raw[:4]), struct.unpack(">H", raw[4:])[0])
        )
    return peers


# ── Node (socket + responder + iterative client) ──


class Dht:
    """One DHT node: client *and* server on a single UDP socket.

    A background responder thread answers queries and routes responses to
    waiting calls by transaction ID; ``get_peers``/``announce_peer`` do
    iterative lookups from the routing table. All public methods are
    thread-safe.
    """

    def __init__(
        self,
        bind: tuple[str, int] = ("0.0.0.0", 0),
        node_id: bytes | None = None,
        request_timeout: float = 2.0,
    ):
        self.node_id = node_id or os.urandom(NODE_ID_LEN)
        self.table = RoutingTable(self.node_id)
        self.request_timeout = request_timeout
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(bind)
        self.sock.settimeout(0.25)
        self.port = self.sock.getsockname()[1]
        # info_hash -> {(ip, port): announced_at}
        self.peer_store: dict[bytes, dict[tuple[str, int], float]] = {}
        self._token_secret = secrets.token_bytes(16)
        self._pending: dict[bytes, tuple[threading.Event, list]] = {}
        self._tid_counter = 0
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()

    # ── Lifecycle ──

    def close(self) -> None:
        self._shutdown.set()
        self._thread.join(timeout=2)
        self.sock.close()

    # ── Tokens (real tokens, unlike dht.zig:453-454) ──

    def make_token(self, addr: tuple[str, int]) -> bytes:
        return hashing.blake3_keyed(
            self._token_secret + bytes(16),
            addr[0].encode() + struct.pack(">H", addr[1]),
        )[:8]

    def valid_token(self, addr: tuple[str, int], token: bytes) -> bool:
        return secrets.compare_digest(self.make_token(addr), token)

    # ── Wire I/O ──

    def _next_tid(self) -> bytes:
        with self._lock:
            self._tid_counter = (self._tid_counter + 1) % 0xFFFF
            return struct.pack(">H", self._tid_counter)

    def _request(
        self, payload_fn, addr: tuple[str, int]
    ) -> dict | None:
        """Send one KRPC query, wait for its response (matched by tid)."""
        tid = self._next_tid()
        event: tuple[threading.Event, list] = (threading.Event(), [])
        with self._lock:
            self._pending[tid] = event
        try:
            self.sock.sendto(payload_fn(tid), addr)
        except OSError:
            with self._lock:
                self._pending.pop(tid, None)
            return None
        if not event[0].wait(self.request_timeout):
            with self._lock:
                self._pending.pop(tid, None)
            return None
        return event[1][0] if event[1] else None

    def _recv_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                data, addr = self.sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg = bencode.decode(data)
            except bencode.BencodeError:
                continue
            if not isinstance(msg, dict):
                continue
            kind = bencode.dict_get_bytes(msg, b"y")
            if kind == b"q":
                try:
                    self._handle_query(msg, addr)
                except (OSError, DhtError):
                    continue
            elif kind == b"r":
                tid = bencode.dict_get_bytes(msg, b"t")
                resp = bencode.dict_get_dict(msg, b"r")
                if resp is not None:
                    rid = bencode.dict_get_bytes(resp, b"id")
                    if rid and len(rid) == NODE_ID_LEN:
                        self.table.update(rid, addr)
                with self._lock:
                    waiter = self._pending.pop(tid, None) if tid else None
                if waiter is not None:
                    waiter[1].append(resp or {})
                    waiter[0].set()

    # ── Server side ──

    def _reply(self, tid: bytes, resp: dict, addr) -> None:
        self.sock.sendto(
            bencode.encode({b"t": tid, b"y": b"r", b"r": resp}), addr
        )

    def _handle_query(self, msg: dict, addr) -> None:
        tid = bencode.dict_get_bytes(msg, b"t") or b""
        q = bencode.dict_get_bytes(msg, b"q")
        args = bencode.dict_get_dict(msg, b"a") or {}
        qid = bencode.dict_get_bytes(args, b"id")
        if qid and len(qid) == NODE_ID_LEN:
            self.table.update(qid, addr)
        if q == b"ping":
            self._reply(tid, {b"id": self.node_id}, addr)
        elif q == b"find_node":
            target = bencode.dict_get_bytes(args, b"target") or self.node_id
            nodes = encode_compact_nodes(self.table.closest(target))
            self._reply(tid, {b"id": self.node_id, b"nodes": nodes}, addr)
        elif q == b"get_peers":
            ih = bencode.dict_get_bytes(args, b"info_hash") or b""
            token = self.make_token(addr)
            known = list(self._live_peers(ih))
            resp: dict = {b"id": self.node_id, b"token": token}
            if known:
                resp[b"values"] = encode_compact_peers(known)
            else:
                resp[b"nodes"] = encode_compact_nodes(self.table.closest(ih))
            self._reply(tid, resp, addr)
        elif q == b"announce_peer":
            ih = bencode.dict_get_bytes(args, b"info_hash") or b""
            token = bencode.dict_get_bytes(args, b"token") or b""
            port = bencode.dict_get_int(args, b"port") or 0
            if not self.valid_token(addr, token):
                return  # silently drop invalid-token announces
            self._store_peer(ih, (addr[0], port))
            self._reply(tid, {b"id": self.node_id}, addr)

    # ── Peer store (bounded, expiring) ──

    def _live_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        """Non-expired announcements for a hash; prunes expired in place."""
        entries = self.peer_store.get(info_hash)
        if not entries:
            return []
        cutoff = time.time() - PEER_TTL_S
        stale = [p for p, ts in entries.items() if ts < cutoff]
        for p in stale:
            del entries[p]
        if not entries:
            self.peer_store.pop(info_hash, None)
            return []
        return list(entries)

    def _store_peer(self, info_hash: bytes, peer: tuple[str, int]) -> None:
        entries = self.peer_store.get(info_hash)
        if entries is None:
            if len(self.peer_store) >= MAX_STORED_HASHES:
                # Evict the hash with the oldest newest-announcement.
                victim = min(
                    self.peer_store,
                    key=lambda ih: max(self.peer_store[ih].values()),
                )
                del self.peer_store[victim]
            entries = self.peer_store.setdefault(info_hash, {})
        if peer not in entries and len(entries) >= MAX_PEERS_PER_HASH:
            del entries[min(entries, key=entries.get)]  # oldest announce
        entries[peer] = time.time()

    # ── Client side ──

    def ping(self, addr: tuple[str, int]) -> bool:
        resp = self._request(
            lambda tid: build_ping(self.node_id, tid), addr
        )
        return resp is not None

    def bootstrap(self, seeds: list[tuple[str, int]] | None = None) -> int:
        """find_node(self) against seed routers (dht.zig:465-470)."""
        for addr in seeds or BOOTSTRAP_NODES:
            resp = self._request(
                lambda tid: build_find_node(self.node_id, self.node_id, tid),
                addr,
            )
            if resp is None:
                continue
            nodes = bencode.dict_get_bytes(resp, b"nodes") or b""
            try:
                for node_id, naddr in parse_compact_nodes(nodes):
                    if node_id != self.node_id:
                        self.table.update(node_id, naddr)
            except DhtError:
                continue
        return len(self.table)

    def get_peers(
        self, info_hash: bytes, depth: int = 2
    ) -> tuple[list[tuple[str, int]], dict[tuple[str, int], bytes]]:
        """Iterative lookup: query the K closest, follow returned nodes up
        to ``depth`` rounds. Returns (peers, token-per-responder) — tokens
        feed announce_peer (fixing dht.zig:453-454).

        The candidate set is kept sorted by XOR distance to ``info_hash``
        each round, so the walk converges toward the nodes that store
        announcements (announcements live only on the closest IDs)."""
        peers: dict[tuple[str, int], None] = {}
        tokens: dict[tuple[str, int], bytes] = {}
        asked: set[tuple[str, int]] = set()
        # addr -> node id; the sort key for convergence
        candidates: dict[tuple[str, int], bytes] = {
            n.addr: n.node_id for n in self.table.closest(info_hash)
        }
        for _ in range(depth + 1):
            batch = sorted(
                (a for a in candidates if a not in asked),
                key=lambda a: xor_distance(candidates[a], info_hash),
            )[:K]
            if not batch:
                break
            for addr in batch:
                asked.add(addr)
                resp = self._request(
                    lambda tid: build_get_peers(self.node_id, info_hash, tid),
                    addr,
                )
                if resp is None:
                    continue
                token = bencode.dict_get_bytes(resp, b"token")
                if token:
                    tokens[addr] = token
                values = bencode.dict_get_list(resp, b"values")
                if values:
                    for p in parse_compact_peers(values):
                        peers[p] = None
                nodes = bencode.dict_get_bytes(resp, b"nodes")
                if nodes:
                    try:
                        for nid, naddr in parse_compact_nodes(nodes):
                            if nid != self.node_id:  # never query ourselves
                                candidates.setdefault(naddr, nid)
                    except DhtError:
                        continue
        return list(peers), tokens

    def announce_peer(self, info_hash: bytes, port: int) -> int:
        """Announce to every node that gave us a token; returns count."""
        _peers, tokens = self.get_peers(info_hash)
        ok = 0
        for addr, token in tokens.items():
            resp = self._request(
                lambda tid: build_announce_peer(
                    self.node_id, info_hash, port, token, tid
                ),
                addr,
            )
            ok += resp is not None
        return ok

    # ── PeerSource protocol (transfer.swarm) ──

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        peers, _ = self.get_peers(info_hash)
        return peers

    def announce(self, info_hash: bytes, port: int) -> None:
        self.announce_peer(info_hash, port)
