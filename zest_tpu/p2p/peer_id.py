"""Peer identity and swarm addressing.

Two identity schemes coexist:

1. **BT interop**: Azureus-style 20-byte peer IDs and per-xorb SHA-1
   info_hashes, wire-compatible with the reference swarms
   (src/peer_id.zig:10-33). The domain-separation prefix ``zest-xet-v1:``
   MUST match byte-for-byte or peers land in disjoint swarms.

2. **Pod-native**: hosts in a TPU pod are identified by their JAX process
   index; xorb→owner assignment is a deterministic function of the xorb hash
   and the host count (see zest_tpu.parallel.plan) — no discovery round-trip
   needed inside a pod.
"""

from __future__ import annotations

import hashlib
import os

from zest_tpu.version import CLIENT_PREFIX

# Domain separation for swarm addressing; byte-compatible with the reference
# (src/peer_id.zig:21-22) so both implementations join the same swarms.
INFO_HASH_PREFIX = b"zest-xet-v1:"


def generate() -> bytes:
    """20-byte Azureus-style peer ID: 8-byte client prefix + 12 random bytes."""
    return CLIENT_PREFIX + os.urandom(12)


def compute_info_hash(xorb_hash: bytes) -> bytes:
    """``info_hash = SHA-1("zest-xet-v1:" || xorb_hash)`` — one swarm per xorb.

    (reference: src/peer_id.zig:28-33)
    """
    if len(xorb_hash) != 32:
        raise ValueError(f"xorb hash must be 32 bytes, got {len(xorb_hash)}")
    return hashlib.sha1(INFO_HASH_PREFIX + xorb_hash).digest()
