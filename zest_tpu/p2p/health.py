"""Per-peer health: latency EWMA, strike circuit breaker, quarantine.

The reference's swarm walks candidates in a fixed order with no memory
of who failed last time (swarm.zig:398-437) — one dead direct peer
costs a full connect timeout on *every* xorb. This registry is the
memory: each peer accumulates a latency EWMA on success and strikes on
failure (connect failure, IO timeout, and corrupt-chunk attribution
from the bridge all count); ``strikes_to_quarantine`` strikes trip a
circuit breaker that removes the peer from candidate ordering for a
quarantine window. Windows double on consecutive quarantines (capped)
and decay again on good behavior — a flapping peer is re-admitted on
probation (one strike from re-quarantine), not with a clean slate.

Ordering: healthy peers sort by observed EWMA round-trip (fast first);
peers with no history slot at a neutral prior so known-fast peers beat
strangers and strangers beat known-slow ones. The sort is stable, so
ties preserve the caller's priority (direct peers before discovered).

The EWMAs also drive adaptive timeouts: connect/IO deadlines start at a
tight default and track a multiple of the observed latency, clamped to
a floor and the legacy ceiling — a peer that answers in 30 ms gets a
sub-second IO timeout instead of the reference's fixed 60 s stall.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from zest_tpu import telemetry

Addr = tuple[str, int]

# Event mirrors into the process registry: strikes and breaker trips are
# fleet-attribution signals ("which host keeps quarantining peers"), so
# they must outlive the swarm session that counted them.
_M_STRIKES = telemetry.counter(
    "zest_peer_strikes_total", "Peer health strikes, by failure kind",
    ("kind",))
_M_QUARANTINES = telemetry.counter(
    "zest_peer_quarantines_total", "Peer circuit-breaker trips")

DEFAULT_STRIKES_TO_QUARANTINE = 3
DEFAULT_QUARANTINE_BASE_S = 15.0
QUARANTINE_CAP_S = 240.0
EWMA_ALPHA = 0.3
# Neutral prior RTT for never-observed peers (seconds): sorts strangers
# between known-fast and known-slow.
PRIOR_RTT_S = 0.25


@dataclass
class PeerHealth:
    ewma_rtt_s: float | None = None
    ewma_connect_s: float | None = None
    strikes: int = 0
    quarantines: int = 0          # consecutive-quarantine depth (backoff)
    quarantined_until: float = 0.0
    successes: int = 0
    failures: int = 0
    corruptions: int = 0


def _ewma(prev: float | None, sample: float) -> float:
    if prev is None:
        return sample
    return (1.0 - EWMA_ALPHA) * prev + EWMA_ALPHA * sample


class HealthRegistry:
    """Thread-safe per-address health book, shared by one swarm."""

    def __init__(
        self,
        strikes_to_quarantine: int | None = None,
        quarantine_base_s: float | None = None,
        time_fn=time.monotonic,
    ):
        if strikes_to_quarantine is None:
            strikes_to_quarantine = int(
                os.environ.get("ZEST_PEER_STRIKES",
                               DEFAULT_STRIKES_TO_QUARANTINE))
        if quarantine_base_s is None:
            quarantine_base_s = float(
                os.environ.get("ZEST_PEER_QUARANTINE_S",
                               DEFAULT_QUARANTINE_BASE_S))
        self.strikes_to_quarantine = max(1, strikes_to_quarantine)
        self.quarantine_base_s = quarantine_base_s
        self._time = time_fn
        self._peers: dict[Addr, PeerHealth] = {}
        self._lock = threading.Lock()
        self.quarantine_events = 0

    def _peer_locked(self, addr: Addr) -> PeerHealth:
        peer = self._peers.get(addr)
        if peer is None:
            peer = self._peers[addr] = PeerHealth()
        return peer

    # ── Recording ──

    def record_success(self, addr: Addr, rtt_s: float | None = None,
                       connect_s: float | None = None) -> None:
        with self._lock:
            p = self._peer_locked(addr)
            p.successes += 1
            p.strikes = 0
            # Good behavior decays the quarantine backoff depth, so a
            # recovered peer that trips again serves a short window, not
            # the doubled one its bad week earned.
            if p.quarantines:
                p.quarantines -= 1
            if rtt_s is not None:
                p.ewma_rtt_s = _ewma(p.ewma_rtt_s, rtt_s)
            if connect_s is not None:
                p.ewma_connect_s = _ewma(p.ewma_connect_s, connect_s)

    def record_failure(self, addr: Addr, kind: str = "error") -> bool:
        """One strike; True when this strike tripped the breaker."""
        peer = f"{addr[0]}:{addr[1]}"
        with self._lock:
            p = self._peer_locked(addr)
            p.failures += 1
            if kind == "corrupt":
                p.corruptions += 1
            p.strikes += 1
            _M_STRIKES.inc(kind=kind)
            if p.strikes < self.strikes_to_quarantine:
                tripped, window = False, 0.0
            else:
                p.quarantines += 1
                window = min(
                    QUARANTINE_CAP_S,
                    self.quarantine_base_s * (2.0 ** (p.quarantines - 1)),
                )
                p.quarantined_until = self._time() + window
                # Probation: on re-admit one more strike re-quarantines
                # (with the doubled window); a success clears it.
                p.strikes = self.strikes_to_quarantine - 1
                self.quarantine_events += 1
                _M_QUARANTINES.inc()
                tripped = True
        # Flight-recorder breadcrumbs, outside the lock (ISSUE 7): the
        # circuit breaker's decisions in event order — what the counters
        # alone can never reconstruct during triage.
        telemetry.record("peer_strike", peer=peer, strike=kind)
        if tripped:
            telemetry.record("peer_quarantined", peer=peer,
                             window_s=round(window, 2))
        return tripped

    # ── Queries ──

    def is_quarantined(self, addr: Addr) -> bool:
        now = self._time()
        with self._lock:
            p = self._peers.get(addr)
            return p is not None and now < p.quarantined_until

    def _score_locked(self, addr: Addr) -> float:
        p = self._peers.get(addr)
        if p is None:
            return PRIOR_RTT_S
        rtt = p.ewma_rtt_s if p.ewma_rtt_s is not None else PRIOR_RTT_S
        # Each outstanding strike pushes the peer behind clean ones of
        # equal speed without hiding it entirely.
        return rtt + 0.5 * p.strikes

    def partition(self, addrs: list[Addr]) -> tuple[list[Addr], list[Addr]]:
        """(healthy ordered best-first, currently-quarantined). Stable
        sort: equal scores keep the caller's priority order."""
        now = self._time()
        with self._lock:
            healthy, shunned = [], []
            for addr in addrs:
                p = self._peers.get(addr)
                if p is not None and now < p.quarantined_until:
                    shunned.append(addr)
                else:
                    healthy.append(addr)
            healthy.sort(key=self._score_locked)
            return healthy, shunned

    # ── Adaptive timeouts ──

    def connect_timeout(self, addr: Addr, default_s: float = 3.0,
                        floor_s: float = 0.75, ceiling_s: float = 5.0,
                        mult: float = 4.0) -> float:
        with self._lock:
            p = self._peers.get(addr)
            observed = p.ewma_connect_s if p is not None else None
        if observed is None:
            return min(default_s, ceiling_s)
        return min(max(mult * observed, floor_s), ceiling_s)

    def io_timeout(self, addr: Addr, default_s: float = 20.0,
                   floor_s: float = 2.0, ceiling_s: float = 60.0,
                   mult: float = 8.0) -> float:
        with self._lock:
            p = self._peers.get(addr)
            observed = p.ewma_rtt_s if p is not None else None
        if observed is None:
            return min(default_s, ceiling_s)
        return min(max(mult * observed, floor_s), ceiling_s)

    # ── Telemetry ──

    def summary(self) -> dict:
        now = self._time()
        with self._lock:
            return {
                "tracked": len(self._peers),
                "quarantined_now": sum(
                    1 for p in self._peers.values()
                    if now < p.quarantined_until
                ),
                "quarantine_events": self.quarantine_events,
                "corrupt_strikes": sum(
                    p.corruptions for p in self._peers.values()
                ),
            }

    def detail(self) -> list[dict]:
        """Per-peer health rows for ``/v1/status`` / ``zest status`` —
        quarantine decisions used to be invisible outside the process;
        this is the operator's view of why a peer is being avoided.
        ``quarantined_for_s`` is the remaining window (0 = not
        quarantined), reported relative so the payload is meaningful to
        a reader without this process' monotonic clock."""
        now = self._time()
        with self._lock:
            rows = []
            for (host, port), p in sorted(self._peers.items()):
                rows.append({
                    "peer": f"{host}:{port}",
                    "ewma_rtt_ms": (None if p.ewma_rtt_s is None
                                    else round(p.ewma_rtt_s * 1e3, 2)),
                    "ewma_connect_ms": (
                        None if p.ewma_connect_s is None
                        else round(p.ewma_connect_s * 1e3, 2)),
                    "strikes": p.strikes,
                    "successes": p.successes,
                    "failures": p.failures,
                    "corruptions": p.corruptions,
                    "quarantines": p.quarantines,
                    "quarantined_for_s": round(
                        max(0.0, p.quarantined_until - now), 2),
                })
            return rows
