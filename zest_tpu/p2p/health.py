"""Per-peer health: latency EWMA, strike circuit breaker, quarantine.

The reference's swarm walks candidates in a fixed order with no memory
of who failed last time (swarm.zig:398-437) — one dead direct peer
costs a full connect timeout on *every* xorb. This registry is the
memory: each peer accumulates a latency EWMA on success and strikes on
failure (connect failure, IO timeout, and corrupt-chunk attribution
from the bridge all count); ``strikes_to_quarantine`` strikes trip a
circuit breaker that removes the peer from candidate ordering for a
quarantine window. Windows double on consecutive quarantines (capped)
and decay again on good behavior — a flapping peer is re-admitted on
probation (one strike from re-quarantine), not with a clean slate.

Ordering: healthy peers sort by observed EWMA round-trip (fast first);
peers with no history slot at a neutral prior so known-fast peers beat
strangers and strangers beat known-slow ones. The sort is stable, so
ties preserve the caller's priority (direct peers before discovered).

The EWMAs also drive adaptive timeouts: connect/IO deadlines start at a
tight default and track a multiple of the observed latency, clamped to
a floor and the legacy ceiling — a peer that answers in 30 ms gets a
sub-second IO timeout instead of the reference's fixed 60 s stall.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from zest_tpu import telemetry

Addr = tuple[str, int]

# Event mirrors into the process registry: strikes and breaker trips are
# fleet-attribution signals ("which host keeps quarantining peers"), so
# they must outlive the swarm session that counted them.
_M_STRIKES = telemetry.counter(
    "zest_peer_strikes_total", "Peer health strikes, by failure kind",
    ("kind",))
_M_QUARANTINES = telemetry.counter(
    "zest_peer_quarantines_total", "Peer circuit-breaker trips")

DEFAULT_STRIKES_TO_QUARANTINE = 3
DEFAULT_QUARANTINE_BASE_S = 15.0
QUARANTINE_CAP_S = 240.0
EWMA_ALPHA = 0.3
# Neutral prior RTT for never-observed peers (seconds): sorts strangers
# between known-fast and known-slow.
PRIOR_RTT_S = 0.25
# Reciprocity memory (seconds): the e-folding time of the decayed
# served-bytes counter behind the seeding tier's unchoke ranking — "the
# K peers that served us the most bytes RECENTLY" means within the last
# minute or two, not all-time (an all-time sum would let one old bulk
# transfer pin an upload slot forever).
RECIPROCITY_TAU_S = 120.0


@dataclass
class PeerHealth:
    ewma_rtt_s: float | None = None
    ewma_connect_s: float | None = None
    strikes: int = 0
    quarantines: int = 0          # consecutive-quarantine depth (backoff)
    quarantined_until: float = 0.0
    in_quarantine: bool = False   # set on trip, cleared at probation
    successes: int = 0
    failures: int = 0
    corruptions: int = 0
    # Per-kind strike breakdown: "error"/"corrupt" from the fetch side,
    # "seed_stall" for a peer that timed out while SERVING us after a
    # good lease (recorded by transfer.swarm), "stalled_reader" for a
    # leecher that stopped draining OUR upload (recorded by the
    # seeding server) — the two sides of a stall stay distinct.
    strike_kinds: dict = field(default_factory=dict)
    # Exponentially-decayed bytes this peer served US (reciprocity).
    recent_bytes: float = 0.0
    recent_bytes_t: float = 0.0


def _ewma(prev: float | None, sample: float) -> float:
    if prev is None:
        return sample
    return (1.0 - EWMA_ALPHA) * prev + EWMA_ALPHA * sample


class HealthRegistry:
    """Thread-safe per-address health book, shared by one swarm."""

    def __init__(
        self,
        strikes_to_quarantine: int | None = None,
        quarantine_base_s: float | None = None,
        time_fn=time.monotonic,
    ):
        if strikes_to_quarantine is None:
            strikes_to_quarantine = int(
                os.environ.get("ZEST_PEER_STRIKES",
                               DEFAULT_STRIKES_TO_QUARANTINE))
        if quarantine_base_s is None:
            quarantine_base_s = float(
                os.environ.get("ZEST_PEER_QUARANTINE_S",
                               DEFAULT_QUARANTINE_BASE_S))
        self.strikes_to_quarantine = max(1, strikes_to_quarantine)
        self.quarantine_base_s = quarantine_base_s
        self._time = time_fn
        self._peers: dict[Addr, PeerHealth] = {}
        self._lock = threading.Lock()
        self.quarantine_events = 0
        self.demotions = 0
        self._listeners: list = []

    def now(self) -> float:
        """This registry's clock (``time_fn``) — shared with the
        swarm's re-announce dedup window so simulated-time tests drive
        both from one fake clock."""
        return self._time()

    def _peer_locked(self, addr: Addr) -> PeerHealth:
        peer = self._peers.get(addr)
        if peer is None:
            peer = self._peers[addr] = PeerHealth()
        return peer

    # ── Transition listeners ──

    def subscribe(self, cb) -> None:
        """``cb(event, addr)`` fires on circuit-breaker transitions —
        ``"quarantined"`` when a strike trips the breaker and
        ``"probation"`` when a quarantine window is first OBSERVED
        expired (re-admit). The swarm's quarantine-aware announce rides
        this: both transitions change which peers this host effectively
        offers/uses, so the tracker's view should be refreshed.
        Callbacks run outside the registry lock; exceptions are the
        caller's problem and must not be raised (wrap if unsure)."""
        self._listeners.append(cb)

    def unsubscribe(self, cb) -> None:
        """Remove a listener registered with :meth:`subscribe`. A
        shared registry outlives the swarms that subscribe to it
        (cmd_serve's daemon registry, benches) — a closed swarm's
        callback must not keep firing zombie re-announces or pin the
        swarm in memory. Unknown callbacks are a no-op."""
        try:
            self._listeners.remove(cb)
        except ValueError:
            pass

    def _notify(self, events: list[tuple[str, Addr]]) -> None:
        for event, addr in events:
            for cb in self._listeners:
                try:
                    cb(event, addr)
                except Exception:  # noqa: BLE001 - observer must not break
                    pass           # the health hot path

    def _observe_expiry_locked(self, p: PeerHealth, now: float,
                               addr: Addr,
                               events: list[tuple[str, Addr]]) -> None:
        """First query to see an expired window flips the peer to
        probation and queues the transition event."""
        if p.in_quarantine and now >= p.quarantined_until:
            p.in_quarantine = False
            events.append(("probation", addr))

    # ── Recording ──

    def record_success(self, addr: Addr, rtt_s: float | None = None,
                       connect_s: float | None = None,
                       nbytes: int | None = None) -> None:
        with self._lock:
            p = self._peer_locked(addr)
            p.successes += 1
            p.strikes = 0
            # Good behavior decays the quarantine backoff depth, so a
            # recovered peer that trips again serves a short window, not
            # the doubled one its bad week earned.
            if p.quarantines:
                p.quarantines -= 1
            if rtt_s is not None:
                p.ewma_rtt_s = _ewma(p.ewma_rtt_s, rtt_s)
            if connect_s is not None:
                p.ewma_connect_s = _ewma(p.ewma_connect_s, connect_s)
            if nbytes:
                now = self._time()
                p.recent_bytes = self._decayed_locked(p, now) + nbytes
                p.recent_bytes_t = now

    @staticmethod
    def _decayed_locked(p: PeerHealth, now: float) -> float:
        if p.recent_bytes <= 0.0:
            return 0.0
        dt = max(0.0, now - p.recent_bytes_t)
        return p.recent_bytes * math.exp(-dt / RECIPROCITY_TAU_S)

    def record_failure(self, addr: Addr, kind: str = "error") -> bool:
        """One strike; True when this strike tripped the breaker."""
        peer = f"{addr[0]}:{addr[1]}"
        events: list[tuple[str, Addr]] = []
        with self._lock:
            p = self._peer_locked(addr)
            p.failures += 1
            if kind == "corrupt":
                p.corruptions += 1
            p.strike_kinds[kind] = p.strike_kinds.get(kind, 0) + 1
            p.strikes += 1
            _M_STRIKES.inc(kind=kind)
            if p.strikes < self.strikes_to_quarantine:
                tripped, window = False, 0.0
            else:
                p.quarantines += 1
                window = min(
                    QUARANTINE_CAP_S,
                    self.quarantine_base_s * (2.0 ** (p.quarantines - 1)),
                )
                p.quarantined_until = self._time() + window
                p.in_quarantine = True
                # Probation: on re-admit one more strike re-quarantines
                # (with the doubled window); a success clears it.
                p.strikes = self.strikes_to_quarantine - 1
                self.quarantine_events += 1
                _M_QUARANTINES.inc()
                tripped = True
                events.append(("quarantined", addr))
        # Flight-recorder breadcrumbs, outside the lock (ISSUE 7): the
        # circuit breaker's decisions in event order — what the counters
        # alone can never reconstruct during triage.
        telemetry.record("peer_strike", peer=peer, strike=kind)
        if tripped:
            telemetry.record("peer_quarantined", peer=peer,
                             window_s=round(window, 2))
        self._notify(events)
        return tripped

    def demote(self, addr: Addr, window_s: float | None = None) -> float:
        """Proactive remediation demotion (ISSUE 17): pull the peer out
        of candidate ordering for one base quarantine window so the
        swarm re-announces and traffic shifts — WITHOUT a strike.

        The failure-semantics rule this encodes: a remediation may
        never *create* a strike against a healthy peer. Strikes (and
        the doubling-window backoff depth they feed) stay reserved for
        observed failures recorded by the subsystems that witnessed
        them; a demotion leaves ``strikes``/``strike_kinds``/
        ``quarantines`` untouched, so the peer re-enters through the
        existing probation path with exactly the record its real
        behavior earned. Returns the window applied."""
        window = (self.quarantine_base_s if window_s is None
                  else max(0.0, window_s))
        with self._lock:
            p = self._peer_locked(addr)
            p.quarantined_until = max(p.quarantined_until,
                                      self._time() + window)
            p.in_quarantine = True
            self.demotions += 1
        telemetry.record("peer_demoted", peer=f"{addr[0]}:{addr[1]}",
                         window_s=round(window, 2))
        # Same transition surface as the breaker: the swarm's
        # re-announce listener treats any membership-changing event
        # alike, and probation fires on expiry as usual.
        self._notify([("demoted", addr)])
        return window

    # ── Queries ──

    def is_quarantined(self, addr: Addr) -> bool:
        now = self._time()
        events: list[tuple[str, Addr]] = []
        with self._lock:
            p = self._peers.get(addr)
            if p is None:
                return False
            self._observe_expiry_locked(p, now, addr, events)
            quarantined = now < p.quarantined_until
        self._notify(events)
        return quarantined

    def served_bytes(self, addr: Addr) -> float:
        """Decayed bytes this peer served us recently — the seeding
        tier's reciprocity score (``transfer.server`` ranks unchoke
        candidates by it)."""
        now = self._time()
        with self._lock:
            p = self._peers.get(addr)
            return 0.0 if p is None else self._decayed_locked(p, now)

    def _score_locked(self, addr: Addr) -> float:
        p = self._peers.get(addr)
        if p is None:
            return PRIOR_RTT_S
        rtt = p.ewma_rtt_s if p.ewma_rtt_s is not None else PRIOR_RTT_S
        # Each outstanding strike pushes the peer behind clean ones of
        # equal speed without hiding it entirely.
        return rtt + 0.5 * p.strikes

    def partition(self, addrs: list[Addr]) -> tuple[list[Addr], list[Addr]]:
        """(healthy ordered best-first, currently-quarantined). Stable
        sort: equal scores keep the caller's priority order."""
        now = self._time()
        events: list[tuple[str, Addr]] = []
        with self._lock:
            healthy, shunned = [], []
            for addr in addrs:
                p = self._peers.get(addr)
                if p is not None:
                    self._observe_expiry_locked(p, now, addr, events)
                if p is not None and now < p.quarantined_until:
                    shunned.append(addr)
                else:
                    healthy.append(addr)
            healthy.sort(key=self._score_locked)
        self._notify(events)
        return healthy, shunned

    # ── Adaptive timeouts ──

    def connect_timeout(self, addr: Addr, default_s: float = 3.0,
                        floor_s: float = 0.75, ceiling_s: float = 5.0,
                        mult: float = 4.0) -> float:
        with self._lock:
            p = self._peers.get(addr)
            observed = p.ewma_connect_s if p is not None else None
        if observed is None:
            return min(default_s, ceiling_s)
        return min(max(mult * observed, floor_s), ceiling_s)

    def io_timeout(self, addr: Addr, default_s: float = 20.0,
                   floor_s: float = 2.0, ceiling_s: float = 60.0,
                   mult: float = 8.0) -> float:
        with self._lock:
            p = self._peers.get(addr)
            observed = p.ewma_rtt_s if p is not None else None
        if observed is None:
            return min(default_s, ceiling_s)
        return min(max(mult * observed, floor_s), ceiling_s)

    # ── Telemetry ──

    def summary(self) -> dict:
        now = self._time()
        with self._lock:
            return {
                "tracked": len(self._peers),
                "quarantined_now": sum(
                    1 for p in self._peers.values()
                    if now < p.quarantined_until
                ),
                "quarantine_events": self.quarantine_events,
                "demotions": self.demotions,
                "corrupt_strikes": sum(
                    p.corruptions for p in self._peers.values()
                ),
            }

    def detail(self) -> list[dict]:
        """Per-peer health rows for ``/v1/status`` / ``zest status`` —
        quarantine decisions used to be invisible outside the process;
        this is the operator's view of why a peer is being avoided.
        ``quarantined_for_s`` is the remaining window (0 = not
        quarantined), reported relative so the payload is meaningful to
        a reader without this process' monotonic clock."""
        now = self._time()
        with self._lock:
            rows = []
            for (host, port), p in sorted(self._peers.items()):
                rows.append({
                    "peer": f"{host}:{port}",
                    "ewma_rtt_ms": (None if p.ewma_rtt_s is None
                                    else round(p.ewma_rtt_s * 1e3, 2)),
                    "ewma_connect_ms": (
                        None if p.ewma_connect_s is None
                        else round(p.ewma_connect_s * 1e3, 2)),
                    "strikes": p.strikes,
                    # Per-kind attribution: "seed_stall" = timed out
                    # while serving OUR fetch; "stalled_reader" =
                    # stopped draining OUR upload — stalls stay
                    # attributed to the right side.
                    "strike_kinds": dict(sorted(p.strike_kinds.items())),
                    "successes": p.successes,
                    "failures": p.failures,
                    "corruptions": p.corruptions,
                    "quarantines": p.quarantines,
                    "quarantined_for_s": round(
                        max(0.0, p.quarantined_until - now), 2),
                    "served_bytes_recent": int(
                        self._decayed_locked(p, now)),
                })
            return rows


class ContentProvenance:
    """Bounded content → source-peer book for UNPROVEN cache entries.

    The bridge merkle-verifies every peer-served blob that is provably
    the whole xorb; blobs it can only check structurally (partial
    ranges, evidence-incomplete pulls) are cached under the documented
    extraction-time trust model. This book remembers WHICH peer those
    unproven bytes came from, so the seeding server can refuse to
    re-serve content whose source has since been quarantined for
    corruption — a loud NOT_AVAILABLE instead of laundering suspect
    bytes into the swarm. Entries clear when the key is later proven
    (full merkle verification) or overwritten by a CDN refetch.

    One key can carry SEVERAL sources: a xorb's ranges may be cached
    from different peers over time, and a later (even verified) blob
    cached under a partial key does not displace an earlier peer's
    bytes — so recording appends rather than overwrites, and the
    refusal check is "is ANY recorded source quarantined". LRU-bounded:
    provenance is a safety hint, not an audit log — the oldest
    suspicion ages out first."""

    # Sources kept per key: beyond this many distinct unproven
    # contributors the oldest attribution rotates out.
    PER_KEY_CAP = 8

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, capacity)
        self._book: OrderedDict[str, tuple[Addr, ...]] = OrderedDict()
        self._lock = threading.Lock()

    def record(self, hash_hex: str, addr: Addr | None) -> None:
        if addr is None:
            return
        with self._lock:
            prior = self._book.pop(hash_hex, ())
            if addr in prior:
                srcs = prior
            else:
                srcs = (prior + (addr,))[-self.PER_KEY_CAP:]
            self._book[hash_hex] = srcs
            while len(self._book) > self.capacity:
                self._book.popitem(last=False)

    def clear(self, hash_hex: str) -> None:
        with self._lock:
            self._book.pop(hash_hex, None)

    def sources(self, hash_hex: str) -> tuple[Addr, ...]:
        with self._lock:
            return self._book.get(hash_hex, ())

    def source(self, hash_hex: str) -> Addr | None:
        """The most recent recorded source (None = no suspicion)."""
        srcs = self.sources(hash_hex)
        return srcs[-1] if srcs else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._book)

    def reset(self) -> None:
        with self._lock:
            self._book.clear()


# Process-global book: the bridge records into it at cache-admission
# time and the seeding server (same process — "the package IS the
# seeder") consults it per chunk request.
PROVENANCE = ContentProvenance()
