"""Peer connection state machine (the src/bt_peer.zig equivalent).

Lifecycle: TCP connect → BT handshake (verify echoed info_hash) → BEP 10
extended handshake (negotiate the peer's ut_xet id) → unchoke/interested →
range-aware chunk request/response with request-id matching. A per-peer
lock serializes use of the TCP stream (reference: bt_peer.zig:33-35) while
still allowing request pipelining: send a batch of CHUNK_REQUESTs, then
drain the responses (bt_peer.zig:188-248).

Improvement over the reference: the responder uses the *negotiated* ext id
rather than hardcoding 1 (quirk at server.zig:194-213).
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass

from zest_tpu import faults, telemetry
from zest_tpu.p2p import bep_xet, wire

# Our local id for the ut_xet extension, advertised in the ext handshake.
LOCAL_UT_XET_ID = 3

# Legacy ceilings. The swarm passes adaptive (EWMA-derived, deadline-
# capped) timeouts per connection; these remain the defaults for direct
# protocol use and the upper bound the adaptive path never exceeds.
_CONNECT_TIMEOUT_S = 5.0
_IO_TIMEOUT_S = 60.0


class PeerError(RuntimeError):
    pass


class ChunkNotFoundError(PeerError):
    """Peer answered CHUNK_NOT_FOUND — connection stays healthy."""


class PeerChokedError(PeerError):
    """Peer answered CHUNK_ERROR(CHOKED): its upload policy denied us a
    slot right now. The peer is healthy and HAS the data — the swarm
    moves to the next candidate without a health strike (striking a
    seeder for enforcing fairness would quarantine the whole tier under
    load)."""


class ContentRefusedError(ChunkNotFoundError):
    """Peer answered CHUNK_ERROR(NOT_AVAILABLE): it is refusing to serve
    this content (quarantined-source bytes it cannot vouch for). Treated
    like CHUNK_NOT_FOUND — healthy peer, no strike, next tier serves —
    but kept distinct so stats/triage show the refusal was deliberate."""


@dataclass(frozen=True)
class ChunkResult:
    data: bytes
    chunk_offset: int


class BtPeer:
    """One outgoing peer connection bound to a single swarm (info_hash)."""

    def __init__(self, stream: wire.SocketStream, peer_ut_xet_id: int,
                 remote_peer_id: bytes,
                 address: tuple[str, int] | None = None):
        self.stream = stream
        self.peer_ut_xet_id = peer_ut_xet_id
        self.remote_peer_id = remote_peer_id
        self.address = address
        self.lock = threading.Lock()
        self._next_request_id = 1

    # ── Connection + handshake (reference: bt_peer.zig:63-115) ──

    @classmethod
    def connect(
        cls,
        host: str,
        port: int,
        info_hash: bytes,
        peer_id: bytes,
        listen_port: int | None = None,
        connect_timeout: float = _CONNECT_TIMEOUT_S,
        io_timeout: float = _IO_TIMEOUT_S,
    ) -> "BtPeer":
        if faults.fire("peer_timeout", key=f"{host}:{port}"):
            raise TimeoutError(f"injected peer_timeout for {host}:{port}")
        with telemetry.span("peer.connect", peer=f"{host}:{port}"):
            return cls._connect(host, port, info_hash, peer_id, listen_port,
                                connect_timeout, io_timeout)

    @classmethod
    def _connect(
        cls,
        host: str,
        port: int,
        info_hash: bytes,
        peer_id: bytes,
        listen_port: int | None,
        connect_timeout: float,
        io_timeout: float,
    ) -> "BtPeer":
        sock = socket.create_connection((host, port), timeout=connect_timeout)
        sock.settimeout(io_timeout)
        stream = wire.SocketStream(sock)
        try:
            stream.send_handshake(info_hash, peer_id)
            their_hs = stream.recv_handshake()
            if their_hs.info_hash != info_hash:
                raise PeerError("info_hash mismatch in handshake")
            if not their_hs.supports_bep10:
                raise PeerError("peer does not support BEP 10 extensions")

            # Extended handshake (ext_id 0), then interested/unchoke.
            stream.send_raw(wire.encode_extended(
                0, bep_xet.make_ext_handshake(LOCAL_UT_XET_ID, listen_port)
            ))
            caps = cls._await_ext_handshake(stream)
            if caps.ut_xet_id is None:
                raise PeerError("peer does not support ut_xet")
            stream.send_message(wire.MessageId.INTERESTED)
            return cls(stream, caps.ut_xet_id, their_hs.peer_id,
                       address=(host, port))
        except BaseException:
            stream.close()
            raise

    @staticmethod
    def _await_ext_handshake(stream: wire.SocketStream) -> bep_xet.ExtCapabilities:
        """Read until the ext handshake arrives, tolerating choke/unchoke/
        bitfield chatter from standard clients."""
        for _ in range(16):
            msg = stream.recv_message()
            if msg.msg_id is None:
                continue
            if msg.msg_id == wire.MessageId.EXTENDED:
                ext_id, payload = wire.parse_extended(msg.payload)
                if ext_id == 0:
                    return bep_xet.parse_ext_handshake(payload)
            # ignore other pre-transfer messages
        raise PeerError("no extended handshake from peer")

    def close(self) -> None:
        self.stream.close()

    def _arm_io_timeout_locked(self, timeout_s: float) -> None:
        """Re-arm the socket's per-op timeout — a pooled connection
        carries the timeout of the request that *created* it, and the
        adaptive/deadline-capped budget of the current request may be
        tighter. MUST be called with ``self.lock`` held: the socket is
        shared across the pull's concurrent term workers, and an
        unlocked settimeout would clobber another thread's in-flight
        recv budget. Best-effort: a torn-down socket surfaces on the
        next recv either way."""
        try:
            self.stream.sock.settimeout(timeout_s)
        except OSError:
            pass

    # ── Requesting (reference: bt_peer.zig:125-248) ──

    def _alloc_request_id(self) -> int:
        rid = self._next_request_id
        self._next_request_id += 1
        return rid

    def request_chunk(
        self, chunk_hash: bytes, range_start: int, range_end: int,
        io_timeout: float | None = None,
    ) -> ChunkResult:
        """Single request/response; holds the stream lock end-to-end.
        ``io_timeout`` re-arms the socket budget for THIS request, under
        the lock so concurrent requests on the shared connection never
        clobber each other's in-flight recv."""
        if self.address is not None:
            faults.sleep_if("peer_slow",
                            key=f"{self.address[0]}:{self.address[1]}")
        peer = (f"{self.address[0]}:{self.address[1]}"
                if self.address is not None else "?")
        with telemetry.span("peer.request", peer=peer) as sp:
            with self.lock:
                if io_timeout is not None:
                    self._arm_io_timeout_locked(io_timeout)
                rid = self._alloc_request_id()
                self._send_request(rid, chunk_hash, range_start, range_end)
                result = self._recv_response(rid)
            sp.add_bytes(len(result.data))
            return result

    def request_chunks_pipelined(
        self, requests: list[tuple[bytes, int, int]]
    ) -> list[ChunkResult | ChunkNotFoundError]:
        """Send all requests, then drain responses; results in request order.

        Per-request failures surface as ChunkNotFoundError entries so one
        missing range doesn't poison the batch.
        """
        with self.lock:
            rids = []
            for chunk_hash, start, end in requests:
                rid = self._alloc_request_id()
                self._send_request(rid, chunk_hash, start, end)
                rids.append(rid)
            by_rid: dict[int, ChunkResult | ChunkNotFoundError] = {}
            for _ in rids:
                try:
                    rid, result = self._recv_any_response()
                except ChunkNotFoundError as exc:
                    rid, result = exc.args[1], exc
                by_rid[rid] = result
            out = []
            for rid in rids:
                out.append(by_rid.get(
                    rid, ChunkNotFoundError("no response for request", rid)
                ))
            return out

    def _send_request(self, rid: int, chunk_hash: bytes,
                      range_start: int, range_end: int) -> None:
        self.stream.send_raw(bep_xet.encode_framed(
            self.peer_ut_xet_id,
            bep_xet.ChunkRequest(rid, chunk_hash, range_start, range_end),
        ))

    def _recv_response(self, expect_rid: int) -> ChunkResult:
        while True:
            rid, result = self._recv_any_response()
            if rid != expect_rid:
                continue  # stale response from a cancelled request
            if isinstance(result, ChunkNotFoundError):
                raise result
            return result

    def _recv_any_response(self) -> tuple[int, ChunkResult]:
        """Read frames until a XET response arrives."""
        while True:
            msg = self.stream.recv_message()
            if msg.msg_id is None:
                continue
            if msg.msg_id != wire.MessageId.EXTENDED:
                continue  # choke/unchoke/have chatter
            ext_id, payload = wire.parse_extended(msg.payload)
            if ext_id == 0:
                continue  # repeated ext handshake
            xet = bep_xet.decode(payload)
            if isinstance(xet, bep_xet.ChunkResponse):
                return xet.request_id, ChunkResult(xet.data, xet.chunk_offset)
            if isinstance(xet, bep_xet.ChunkNotFound):
                raise ChunkNotFoundError(
                    "peer does not have chunk", xet.request_id
                )
            if isinstance(xet, bep_xet.ChunkError):
                if xet.error_code == bep_xet.ERR_CHOKED:
                    raise PeerChokedError(
                        "peer choked us", xet.request_id)
                if xet.error_code == bep_xet.ERR_NOT_AVAILABLE:
                    raise ContentRefusedError(
                        "peer refused content (quarantined source)",
                        xet.request_id)
                raise PeerError(
                    f"peer error {xet.error_code}: "
                    f"{xet.message.decode(errors='replace')}"
                )
            # a ChunkRequest from the peer on an outgoing connection is
            # unexpected chatter; ignore.


def parse_address(spec: str) -> tuple[str, int]:
    """Parse "host:port" (reference: bt_peer.zig:313-315)."""
    host, sep, port = spec.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"invalid peer address {spec!r}")
    return host, int(port)
