"""Peer connection pool (the src/peer_pool.zig equivalent).

Connections are keyed by (host, port) and reused across xorbs whose swarms
land on the same peer. Discipline mirrors the reference (peer_pool.zig:49-95):
connect + handshake happen *outside* the lock (slow I/O must not serialize
the pool), with a re-check on insert — the loser of a connect race closes
its duplicate. Broken connections are removed so the next attempt
reconnects; at ``max_peers`` the least-recently-used *idle* entry is
evicted (every pool access touches its key, so iteration order IS
recency order).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from zest_tpu.p2p.peer import BtPeer


class PeerPool:
    def __init__(self, max_peers: int = 50):
        self.max_peers = max_peers
        self._peers: OrderedDict[tuple[str, int], BtPeer] = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._peers)

    def get_or_connect(
        self,
        host: str,
        port: int,
        info_hash: bytes,
        peer_id: bytes,
        listen_port: int | None = None,
    ) -> BtPeer:
        return self.lease(host, port, info_hash, peer_id, listen_port)[0]

    def lease(
        self,
        host: str,
        port: int,
        info_hash: bytes,
        peer_id: bytes,
        listen_port: int | None = None,
        connect_timeout: float | None = None,
        io_timeout: float | None = None,
    ) -> tuple[BtPeer, bool]:
        """``(peer, reused)`` — ``reused`` tells the caller whether the
        connection predates this request. A reused socket can be stale
        (evicted mid-lease, idle-closed by the remote): an IO failure on
        it warrants one fresh-reconnect retry before the peer itself is
        blamed, which the swarm implements on top of this flag."""
        key = (host, port)
        with self._lock:
            existing = self._peers.get(key)
            if existing is not None:
                self._peers.move_to_end(key)  # LRU touch
                return existing, True

        # Slow path outside the lock.
        kwargs = {}
        if connect_timeout is not None:
            kwargs["connect_timeout"] = connect_timeout
        if io_timeout is not None:
            kwargs["io_timeout"] = io_timeout
        peer = BtPeer.connect(host, port, info_hash, peer_id, listen_port,
                              **kwargs)

        with self._lock:
            raced = self._peers.get(key)
            if raced is not None:
                # Lost the race; keep the established one.
                self._peers.move_to_end(key)
                loser = peer
                peer = raced
                reused = True
            else:
                if len(self._peers) >= self.max_peers:
                    self._evict_one_locked()
                self._peers[key] = peer
                loser = None
                reused = False
        if loser is not None:
            loser.close()
        return peer, reused

    def remove(self, host: str, port: int) -> None:
        with self._lock:
            peer = self._peers.pop((host, port), None)
        if peer is not None:
            peer.close()

    def close_all(self) -> None:
        with self._lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for p in peers:
            p.close()

    def _evict_one_locked(self) -> None:
        # True LRU among idle peers: the OrderedDict iterates least-
        # recently-touched first (get_or_connect touches on every hit),
        # so the first idle entry is the coldest connection — evicting
        # an arbitrary (insertion-ordered) entry used to throw away hot
        # peers while week-old idle sockets survived. Only a peer whose
        # stream lock is free is evicted — closing a socket another
        # thread is mid-request on turns healthy transfers into
        # spurious failures. (A thread that fetched the peer but hasn't
        # locked yet can still lose it; that surfaces as one retried
        # request, which the waterfall absorbs.) All busy -> soft cap:
        # admit the newcomer and let the pool shrink on future evictions.
        for key, peer in self._peers.items():
            if not peer.lock.locked():
                self._peers.pop(key).close()
                return
