"""Strict bencode codec (BEP 3 metadata encoding).

Used by the BT-interop plane only: BEP 10 extended handshakes, KRPC (DHT)
messages, and tracker responses. The pod-native control plane uses its own
framing — bencode exists for wire compatibility with BitTorrent peers
(reference behavior: src/bencode.zig:35-183; strictness rules verified by its
tests at src/bencode.zig:269-345).

Strictness on decode, matching the reference:
  - integers: no leading zeros (except ``i0e``), no negative zero
  - dict keys: byte strings, strictly sorted ascending, no duplicates
  - no trailing bytes after the top-level value
"""

from __future__ import annotations

Value = int | bytes | list["Value"] | dict[bytes, "Value"]

# Nesting cap so hostile input (e.g. b"d"*10000 from an untrusted DHT packet)
# raises BencodeError instead of blowing the interpreter recursion limit.
MAX_DEPTH = 128


class BencodeError(ValueError):
    pass


# ── Encoding ──


def encode(value) -> bytes:
    out = bytearray()
    _encode_into(value, out)
    return bytes(out)


def _encode_into(value, out: bytearray) -> None:
    if isinstance(value, bool):
        raise BencodeError("booleans are not bencodable")
    if isinstance(value, int):
        out += b"i%de" % value
    elif isinstance(value, (bytes, bytearray, memoryview)):
        b = bytes(value)
        out += b"%d:" % len(b)
        out += b
    elif isinstance(value, str):
        _encode_into(value.encode(), out)
    elif isinstance(value, (list, tuple)):
        out += b"l"
        for item in value:
            _encode_into(item, out)
        out += b"e"
    elif isinstance(value, dict):
        out += b"d"
        keys = sorted(k.encode() if isinstance(k, str) else bytes(k) for k in value)
        if len(set(keys)) != len(keys):
            raise BencodeError("duplicate dict keys")
        by_bytes = {
            (k.encode() if isinstance(k, str) else bytes(k)): v
            for k, v in value.items()
        }
        for k in keys:
            _encode_into(k, out)
            _encode_into(by_bytes[k], out)
        out += b"e"
    else:
        raise BencodeError(f"cannot bencode {type(value).__name__}")


# ── Decoding ──


def decode(data: bytes) -> Value:
    """Decode a single bencoded value; reject trailing bytes."""
    value, pos = _decode_at(data, 0)
    if pos != len(data):
        raise BencodeError(f"trailing bytes after value at offset {pos}")
    return value


def decode_prefix(data: bytes) -> tuple[Value, int]:
    """Decode one value from the front of ``data``; return (value, bytes consumed)."""
    return _decode_at(data, 0)


def _decode_at(data: bytes, pos: int, depth: int = 0) -> tuple[Value, int]:
    if depth > MAX_DEPTH:
        raise BencodeError(f"nesting deeper than {MAX_DEPTH}")
    if pos >= len(data):
        raise BencodeError("unexpected end of input")
    c = data[pos]
    if c == ord(b"i"):
        end = data.find(b"e", pos)
        if end < 0:
            raise BencodeError("unterminated integer")
        body = data[pos + 1 : end]
        _validate_int(body)
        return int(body), end + 1
    if c == ord(b"l"):
        pos += 1
        items: list[Value] = []
        while True:
            if pos >= len(data):
                raise BencodeError("unterminated list")
            if data[pos] == ord(b"e"):
                return items, pos + 1
            item, pos = _decode_at(data, pos, depth + 1)
            items.append(item)
    if c == ord(b"d"):
        pos += 1
        d: dict[bytes, Value] = {}
        prev_key: bytes | None = None
        while True:
            if pos >= len(data):
                raise BencodeError("unterminated dict")
            if data[pos] == ord(b"e"):
                return d, pos + 1
            key, pos = _decode_at(data, pos, depth + 1)
            if not isinstance(key, bytes):
                raise BencodeError("dict key is not a string")
            if prev_key is not None and key <= prev_key:
                raise BencodeError("dict keys not strictly sorted")
            prev_key = key
            value, pos = _decode_at(data, pos, depth + 1)
            d[key] = value
    if ord(b"0") <= c <= ord(b"9"):
        colon = data.find(b":", pos)
        if colon < 0:
            raise BencodeError("unterminated string length")
        length_body = data[pos:colon]
        if not length_body.isdigit():
            raise BencodeError(f"invalid string length {length_body!r}")
        if len(length_body) > 1 and length_body[0] == ord(b"0"):
            raise BencodeError("string length has leading zero")
        length = int(length_body)
        start = colon + 1
        if start + length > len(data):
            raise BencodeError("string extends past end of input")
        return data[start : start + length], start + length
    raise BencodeError(f"invalid type byte {bytes([c])!r} at offset {pos}")


def _validate_int(body: bytes) -> None:
    if not body:
        raise BencodeError("empty integer")
    digits = body[1:] if body[:1] == b"-" else body
    if not digits or not digits.isdigit():
        raise BencodeError(f"invalid integer {body!r}")
    if body == b"-0":
        raise BencodeError("negative zero")
    if len(digits) > 1 and digits[0] == ord(b"0"):
        raise BencodeError("integer has leading zero")


# ── Typed dict lookups (reference: src/bencode.zig:188-220) ──


def dict_get_int(d: Value, key: bytes) -> int | None:
    if isinstance(d, dict):
        v = d.get(key)
        if isinstance(v, int):
            return v
    return None


def dict_get_bytes(d: Value, key: bytes) -> bytes | None:
    if isinstance(d, dict):
        v = d.get(key)
        if isinstance(v, bytes):
            return v
    return None


def dict_get_dict(d: Value, key: bytes) -> dict | None:
    if isinstance(d, dict):
        v = d.get(key)
        if isinstance(v, dict):
            return v
    return None


def dict_get_list(d: Value, key: bytes) -> list | None:
    if isinstance(d, dict):
        v = d.get(key)
        if isinstance(v, list):
            return v
    return None
