"""BT HTTP tracker client (reference: src/bt_tracker.zig).

``GET /announce?info_hash=…&peer_id=…&port=…&compact=1&event=…`` with
percent-encoded binary hashes (bt_tracker.zig:65-121), bencoded response
parsed into interval + compact 6-byte peers (``:131-180``), ``failure
reason`` surfaced as a typed error. In the TPU build the tracker is the
optional cross-pod rendezvous service (SURVEY.md §2.1 row 10); in-pod
discovery goes through the coordinator instead.
"""

from __future__ import annotations

import enum
import socket
import struct
from dataclasses import dataclass, field
from urllib.parse import quote_from_bytes

import requests

from zest_tpu.p2p import bencode


class TrackerError(RuntimeError):
    pass


class Event(enum.Enum):
    NONE = ""
    STARTED = "started"
    STOPPED = "stopped"
    COMPLETED = "completed"


@dataclass
class AnnounceResponse:
    interval: int
    peers: list[tuple[str, int]] = field(default_factory=list)


def parse_announce_response(body: bytes) -> AnnounceResponse:
    """Bencoded dict → interval + compact peers (bt_tracker.zig:131-180)."""
    try:
        doc = bencode.decode(body)
    except bencode.BencodeError as exc:
        raise TrackerError(f"malformed tracker response: {exc}") from exc
    if not isinstance(doc, dict):
        raise TrackerError("tracker response is not a dict")
    failure = bencode.dict_get_bytes(doc, b"failure reason")
    if failure is not None:
        raise TrackerError(failure.decode("utf-8", "replace"))
    interval = bencode.dict_get_int(doc, b"interval") or 1800
    raw = bencode.dict_get_bytes(doc, b"peers") or b""
    if len(raw) % 6:
        raise TrackerError(f"compact peers length {len(raw)} not 6-aligned")
    peers = []
    for off in range(0, len(raw), 6):
        ip = socket.inet_ntoa(raw[off : off + 4])
        (port,) = struct.unpack_from(">H", raw, off + 4)
        peers.append((ip, port))
    return AnnounceResponse(interval, peers)


def build_announce_url(
    base: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    uploaded: int = 0,
    downloaded: int = 0,
    left: int = 0,
    event: Event = Event.NONE,
) -> str:
    """Query-string construction with binary-safe percent encoding
    (bt_tracker.zig:110-121; requests' own encoding would mangle bytes)."""
    sep = "&" if "?" in base else "?"
    parts = [
        f"info_hash={quote_from_bytes(info_hash)}",
        f"peer_id={quote_from_bytes(peer_id)}",
        f"port={port}",
        f"uploaded={uploaded}",
        f"downloaded={downloaded}",
        f"left={left}",
        "compact=1",
    ]
    if event is not Event.NONE:
        parts.append(f"event={event.value}")
    return base + sep + "&".join(parts)


class TrackerClient:
    """PeerSource-compatible tracker client (see transfer.swarm.PeerSource)."""

    def __init__(
        self,
        announce_url: str,
        peer_id: bytes,
        listen_port: int = 0,
        timeout: float = 10.0,
    ):
        self.announce_url = announce_url
        self.peer_id = peer_id
        # Trackers treat every /announce as a registration, so even
        # lookup-style find_peers must report our real serving port.
        self.listen_port = listen_port
        self.timeout = timeout
        self.last_interval = 1800
        # Seeding-tier accounting (ISSUE 12): every announce's
        # ``uploaded`` counter reports this process' seed-served bytes
        # so the tracker's economics view sees the host as the seeder
        # it is. The number is read live from the process metrics
        # registry (``zest_seed_bytes_total`` — the counter BtServer
        # bumps per upload), so it needs no plumbing between the server
        # and whichever swarm/CLI constructed this client; ``uploaded``
        # is an additive base for callers with out-of-process counts.
        # Quarantine/probation transitions re-announce through the same
        # path (transfer.swarm subscribes to the health registry and
        # replays ``announce`` per registered swarm), so the refreshed
        # registration carries current counters too.
        self.uploaded = 0

    def uploaded_total(self) -> int:
        """``uploaded`` base + the live seeding counter."""
        from zest_tpu import telemetry

        served = 0
        for m in telemetry.REGISTRY.metrics():
            if m.name == "zest_seed_bytes_total":
                served = int(sum(v for _labels, v in m.samples()))
                break
        return self.uploaded + served

    def announce_event(
        self,
        info_hash: bytes,
        port: int,
        event: Event = Event.NONE,
        **counters,
    ) -> AnnounceResponse:
        url = build_announce_url(
            self.announce_url, info_hash, self.peer_id, port,
            event=event, **counters,
        )
        try:
            r = requests.get(url, timeout=self.timeout)
            r.raise_for_status()
        except requests.RequestException as exc:
            raise TrackerError(f"tracker request failed: {exc}") from exc
        resp = parse_announce_response(r.content)
        self.last_interval = resp.interval
        return resp

    # ── PeerSource protocol ──

    def find_peers(self, info_hash: bytes) -> list[tuple[str, int]]:
        try:
            return self.announce_event(info_hash, self.listen_port,
                                       uploaded=self.uploaded_total()).peers
        except TrackerError:
            return []

    def announce(self, info_hash: bytes, port: int) -> None:
        try:
            self.announce_event(info_hash, port, Event.STARTED,
                                uploaded=self.uploaded_total())
        except TrackerError:
            pass  # announce is best-effort; CDN fallback keeps pulls alive
