"""BEP XET extension messages: chunk-level xorb transfer over BEP 10.

Byte-compatible with the reference (src/bep_xet.zig) and the BEP XET spec
it implements; all messages ride on BEP 10 extended messages (msg_id=20,
ext name "ut_xet"):

    CHUNK_REQUEST  0x01: [1][4 req_id BE][32 hash][4 range_start BE][4 range_end BE] = 45B
    CHUNK_RESPONSE 0x02: [1][4 req_id BE][4 chunk_offset BE][4 len BE][data]
    CHUNK_NOT_FOUND 0x03: [1][4 req_id BE][32 hash] = 37B
    CHUNK_ERROR    0x04: [1][4 req_id BE][4 code BE][message]

The response's ``chunk_offset`` rebases the blob into the xorb's absolute
chunk index space (the range-aware partial-transfer mechanism,
SURVEY.md §5 "long-context" analog).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from zest_tpu.p2p import bencode
from zest_tpu.version import CLIENT_STRING

EXTENSION_NAME = b"ut_xet"

# CHUNK_ERROR codes the seeding tier emits (the wire format leaves
# codes free-form; these two are load-bearing for the requester's
# candidate handling — see transfer.swarm):
ERR_CHOKED = 1         # upload policy denied a slot — peer healthy, retry elsewhere
ERR_NOT_AVAILABLE = 2  # content refused (quarantined source) — loud, never served


class XetMessageError(ValueError):
    pass


@dataclass(frozen=True)
class ChunkRequest:
    request_id: int
    chunk_hash: bytes
    range_start: int
    range_end: int


@dataclass(frozen=True)
class ChunkResponse:
    request_id: int
    chunk_offset: int
    data: bytes


@dataclass(frozen=True)
class ChunkNotFound:
    request_id: int
    chunk_hash: bytes


@dataclass(frozen=True)
class ChunkError:
    request_id: int
    error_code: int
    message: bytes


XetMessage = ChunkRequest | ChunkResponse | ChunkNotFound | ChunkError


def encode_chunk_request(req: ChunkRequest) -> bytes:
    if len(req.chunk_hash) != 32:
        raise XetMessageError("chunk hash must be 32 bytes")
    return (
        b"\x01"
        + struct.pack(">I", req.request_id)
        + req.chunk_hash
        + struct.pack(">II", req.range_start, req.range_end)
    )


def encode_chunk_response(resp: ChunkResponse) -> bytes:
    return (
        b"\x02"
        + struct.pack(">III", resp.request_id, resp.chunk_offset, len(resp.data))
        + resp.data
    )


def encode_chunk_not_found(msg: ChunkNotFound) -> bytes:
    if len(msg.chunk_hash) != 32:
        raise XetMessageError("chunk hash must be 32 bytes")
    return b"\x03" + struct.pack(">I", msg.request_id) + msg.chunk_hash


def encode_chunk_error(msg: ChunkError) -> bytes:
    return (
        b"\x04"
        + struct.pack(">II", msg.request_id, msg.error_code)
        + msg.message
    )


def encode(msg: XetMessage) -> bytes:
    if isinstance(msg, ChunkRequest):
        return encode_chunk_request(msg)
    if isinstance(msg, ChunkResponse):
        return encode_chunk_response(msg)
    if isinstance(msg, ChunkNotFound):
        return encode_chunk_not_found(msg)
    if isinstance(msg, ChunkError):
        return encode_chunk_error(msg)
    raise XetMessageError(f"not a XET message: {type(msg).__name__}")


def decode(payload: bytes) -> XetMessage:
    """Decode one BEP XET sub-payload (reference: bep_xet.zig:129-175)."""
    if not payload:
        raise XetMessageError("empty payload")
    kind = payload[0]
    if kind == 0x01:
        if len(payload) != 45:
            raise XetMessageError(f"CHUNK_REQUEST must be 45 bytes, got {len(payload)}")
        req_id, = struct.unpack(">I", payload[1:5])
        start, end = struct.unpack(">II", payload[37:45])
        return ChunkRequest(req_id, payload[5:37], start, end)
    if kind == 0x02:
        if len(payload) < 13:
            raise XetMessageError("CHUNK_RESPONSE too short")
        req_id, offset, length = struct.unpack(">III", payload[1:13])
        data = payload[13:]
        if len(data) != length:
            raise XetMessageError(
                f"CHUNK_RESPONSE length field {length} != data {len(data)}"
            )
        return ChunkResponse(req_id, offset, data)
    if kind == 0x03:
        if len(payload) != 37:
            raise XetMessageError(f"CHUNK_NOT_FOUND must be 37 bytes, got {len(payload)}")
        req_id, = struct.unpack(">I", payload[1:5])
        return ChunkNotFound(req_id, payload[5:37])
    if kind == 0x04:
        if len(payload) < 9:
            raise XetMessageError("CHUNK_ERROR too short")
        req_id, code = struct.unpack(">II", payload[1:9])
        return ChunkError(req_id, code, payload[9:])
    raise XetMessageError(f"unknown XET message type 0x{kind:02x}")


def encode_framed(ext_id: int, msg: XetMessage) -> bytes:
    """Complete wire frame ([4 len][20][ext_id][XET payload]) for a
    message, ready for one send() call.

    Uses the native one-pass framer when available (zest_tpu/native/
    wire.cc — the chunk data is copied exactly once instead of three
    times through the pure concat chain); the fallback is byte-identical.
    Every guard the pure chain enforces is re-checked here BEFORE the
    native call: ctypes would silently truncate an out-of-range ext_id
    (c_uint8) or request_id (c_uint32) where the pure path raises, and a
    silently corrupt frame desyncs the remote stream.
    """
    from zest_tpu.native import lib
    from zest_tpu.p2p import wire

    if not 0 <= ext_id <= 255:
        raise XetMessageError(f"ext_id {ext_id} out of range")
    if not 0 <= msg.request_id <= 0xFFFFFFFF:
        raise XetMessageError(f"request_id {msg.request_id} out of range")
    if isinstance(msg, (ChunkRequest, ChunkNotFound)) \
            and len(msg.chunk_hash) != 32:
        raise XetMessageError("chunk hash must be 32 bytes")

    if lib.available():
        if isinstance(msg, ChunkResponse):
            if not 0 <= msg.chunk_offset <= 0xFFFFFFFF:
                raise XetMessageError(
                    f"chunk_offset {msg.chunk_offset} out of range"
                )
            # Same cap the pure chain applies in wire.encode_message:
            # frame body = [20][ext][13-byte hdr + data].
            if 2 + 13 + len(msg.data) > wire.MAX_MESSAGE_SIZE:
                raise wire.WireError(
                    f"message too large: {len(msg.data)} data bytes"
                )
            return lib.frame_chunk_response(
                ext_id, msg.request_id, msg.chunk_offset, msg.data
            )
        if isinstance(msg, ChunkRequest):
            if not (0 <= msg.range_start <= 0xFFFFFFFF
                    and 0 <= msg.range_end <= 0xFFFFFFFF):
                raise XetMessageError("chunk range out of range")
            return lib.frame_chunk_request(
                ext_id, msg.request_id, msg.chunk_hash,
                msg.range_start, msg.range_end,
            )
        if isinstance(msg, ChunkNotFound):
            return lib.frame_chunk_not_found(
                ext_id, msg.request_id, msg.chunk_hash
            )
    return wire.encode_extended(ext_id, encode(msg))


# ── BEP 10 extended handshake (reference: bep_xet.zig:180-236) ──


@dataclass(frozen=True)
class ExtCapabilities:
    ut_xet_id: int | None
    listen_port: int | None
    client: bytes | None


def make_ext_handshake(ut_xet_id: int, listen_port: int | None = None) -> bytes:
    """``{"m":{"ut_xet":N},"p":port,"v":"zest-tpu/..."}`` bencoded."""
    doc: dict = {b"m": {EXTENSION_NAME: ut_xet_id}, b"v": CLIENT_STRING.encode()}
    if listen_port is not None:
        doc[b"p"] = listen_port
    return bencode.encode(doc)


def parse_ext_handshake(payload: bytes) -> ExtCapabilities:
    try:
        doc = bencode.decode(payload)
    except bencode.BencodeError as exc:
        raise XetMessageError(f"bad ext handshake: {exc}") from exc
    m = bencode.dict_get_dict(doc, b"m") or {}
    ut_xet = m.get(EXTENSION_NAME)
    return ExtCapabilities(
        ut_xet_id=ut_xet if isinstance(ut_xet, int) else None,
        listen_port=bencode.dict_get_int(doc, b"p"),
        client=bencode.dict_get_bytes(doc, b"v"),
    )
