"""Synthetic benchmark harness (reference: src/bench.zig).

Same shape as the reference's ``zest bench --synthetic [--json]``: per-bench
iteration loops over a monotonic clock, median-of-runs reporting, text or
JSON output consumed by CI (bench.zig:150-165, 273-287). The suite covers
the reference's benches (bencode encode/decode, BLAKE3 64 KiB, SHA-1
info-hash, wire framing) plus the TPU-native stages: on-device BLAKE3 and
the pod-axis ICI all-gather (GB/s) that replaces the TCP wire.
"""

from __future__ import annotations

import json
import statistics
import time
from dataclasses import dataclass

CHUNK_64K = 64 * 1024


class BenchUnavailable(RuntimeError):
    """The environment can't run this bench (no native lib, no loopback
    sockets); distinct from a bench FAILURE, which must propagate."""


@dataclass
class BenchResult:
    name: str
    iters: int
    median_ns: float
    bytes_per_iter: int
    # Fastest repeat window. On this build's single shared vCPU a
    # transient neighbor can slow EVERY window of a 5x3ms measurement;
    # the median then reports the neighbor, the best window reports the
    # code. Both are emitted so the artifact carries the distinction.
    best_ns: float = 0.0

    @property
    def mb_per_s(self) -> float:
        if self.median_ns <= 0:
            return float("inf")
        return self.bytes_per_iter / (self.median_ns / 1e9) / 1e6

    @property
    def best_mb_per_s(self) -> float | None:
        """None when no best window was recorded — emitting the median
        as "best" would be indistinguishable from a genuinely
        zero-variance measurement."""
        if self.best_ns <= 0:
            return None
        return self.bytes_per_iter / (self.best_ns / 1e9) / 1e6

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "iters": self.iters,
            "median_ns": round(self.median_ns, 1),
            "mb_per_s": round(self.mb_per_s, 1),
        }
        if self.best_mb_per_s is not None:
            out["best_mb_per_s"] = round(self.best_mb_per_s, 1)
        return out


def _time_fn(name: str, fn, bytes_per_iter: int, iters: int,
             repeats: int = 5) -> BenchResult:
    fn()  # warm (compile caches, branch predictors, JIT)
    medians = []
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        for _ in range(iters):
            fn()
        medians.append((time.perf_counter_ns() - t0) / iters)
    return BenchResult(name, iters, statistics.median(medians),
                       bytes_per_iter, best_ns=min(medians))


# ── Host benches (reference parity, bench.zig:167-255) ──


def bench_bencode(iters: int = 2000) -> list[BenchResult]:
    from zest_tpu.p2p import bencode

    doc = {
        b"m": {b"ut_xet": 3},
        b"p": 6881,
        b"v": b"zest-tpu/" + b"0.1.0",
        b"payload": b"x" * 512,
    }
    encoded = bencode.encode(doc)
    return [
        _time_fn("bencode_encode", lambda: bencode.encode(doc),
                 len(encoded), iters),
        _time_fn("bencode_decode", lambda: bencode.decode(encoded),
                 len(encoded), iters),
    ]


def bench_blake3_host(iters: int = 200) -> BenchResult:
    from zest_tpu.cas import hashing

    data = bytes(range(256)) * (CHUNK_64K // 256)
    return _time_fn("blake3_64kb", lambda: hashing.blake3_hash(data),
                    CHUNK_64K, iters)


def bench_gearhash_cdc(iters: int = 20) -> BenchResult:
    """CDC boundary scan over 4 MiB of incompressible bytes — the other
    half of the host addressing path (blake3_64kb is the hashing half).
    Native-only: the pure-Python scanner is a correctness anchor, not a
    path worth minutes of benchmarking (bench_wire_frame_native rule)."""
    import numpy as np

    from zest_tpu.cas import chunking

    if chunking._get_native() is None:
        raise RuntimeError("native CDC scanner unavailable")
    data = np.random.default_rng(3).integers(
        0, 256, 4 * 1024 * 1024, dtype=np.uint8
    ).tobytes()
    return _time_fn("gearhash_cdc_4mb", lambda: chunking.cut_points(data),
                    len(data), iters)


def bench_sha1_info_hash(iters: int = 5000) -> BenchResult:
    from zest_tpu.p2p import peer_id

    xorb = bytes(32)
    return _time_fn("sha1_info_hash",
                    lambda: peer_id.compute_info_hash(xorb), 32 + 12, iters)


def bench_wire_frame(iters: int = 5000) -> BenchResult:
    from zest_tpu.p2p import wire

    payload = b"y" * 1024
    def roundtrip():
        framed = wire.encode_message(wire.MessageId.EXTENDED, payload)
        wire.decode_message_header(framed[:4])
    return _time_fn("bt_wire_frame", roundtrip, 1024 + 5, iters)


def bench_wire_frame_native(iters: int = 2000) -> BenchResult:
    """One-pass CHUNK_RESPONSE framing (native/wire.cc) on a 64 KiB blob —
    the serving hot loop's actual workload (reference bt_wire_frame is a
    1 KiB header roundtrip; this measures data-bearing frames). Requires
    the native lib: reporting the pure fallback under this label would be
    a silently wrong comparison."""
    from zest_tpu.native import lib
    from zest_tpu.p2p import bep_xet

    if not lib.available():
        raise RuntimeError("native lib unavailable; xet_frame_64kb skipped")
    data = b"z" * 65536
    msg = bep_xet.ChunkResponse(1, 0, data)
    return _time_fn(
        "xet_frame_64kb",
        lambda: bep_xet.encode_framed(3, msg),
        65536 + 19,
        iters,
    )


# ── Device benches (TPU-native; no reference counterpart) ──


def bench_blake3_device(batch: int = 256, iters: int = 8) -> BenchResult:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from zest_tpu.ops.blake3 import DeviceHasher

    rng = np.random.default_rng(0)
    host = rng.integers(0, 256, size=(batch, CHUNK_64K), dtype=np.uint8)
    words = jnp.asarray(host.view("<u4"))
    lengths = jnp.full((batch,), CHUNK_64K, jnp.int32)
    hasher = DeviceHasher()
    hasher.hash_device(words, lengths).block_until_ready()

    def window():
        outs = [hasher.hash_device(words, lengths) for _ in range(iters)]
        jax.block_until_ready(outs)

    medians = []
    for _ in range(3):
        t0 = time.perf_counter_ns()
        window()
        medians.append((time.perf_counter_ns() - t0) / iters)
    return BenchResult("blake3_64kb_device", iters,
                       statistics.median(medians), batch * CHUNK_64K)


def bench_ici_all_gather(mbytes_per_device: int = 16) -> BenchResult:
    import jax

    from zest_tpu.parallel.collectives import all_gather_throughput
    from zest_tpu.parallel.mesh import pod_mesh

    mesh = pod_mesh()
    n = len(jax.devices())
    gbps = all_gather_throughput(mesh, mbytes_per_device=mbytes_per_device)
    moved = mbytes_per_device * 1024 * 1024 * n * max(n - 1, 1)
    # Express as one "iteration" moving `moved` bytes at the measured rate.
    ns = moved / (gbps * 1e9) * 1e9 if gbps > 0 else 0.0
    return BenchResult("ici_all_gather", 1, ns, moved)


def bench_ring_attention(t_per_device: int = 1024, heads: int = 8,
                         head_dim: int = 64) -> BenchResult:
    """Ring attention (sequence-parallel) throughput: causal self-
    attention over T = t_per_device × n_devices tokens, K/V rotating the
    ring. Bytes/iter counts the q/k/v operand traffic (the quantity the
    ring moves over ICI)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from zest_tpu.parallel.mesh import pod_mesh
    from zest_tpu.parallel.ring import ring_attention

    n = len(jax.devices())
    T = t_per_device * n
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((1, T, heads, head_dim)), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    mesh = pod_mesh()
    fn = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, seq_axis="pod", causal=True
    ))
    fn(q, k, v).block_until_ready()
    medians = []
    for _ in range(3):
        t0 = time.perf_counter_ns()
        fn(q, k, v).block_until_ready()
        medians.append(time.perf_counter_ns() - t0)
    return BenchResult("ring_attention", 1, statistics.median(medians),
                       3 * q.nbytes)


def bench_pipeline(layers: int = 8, width: int = 512,
                   rows: int = 2048) -> BenchResult:
    """GPipe pipeline throughput: ``layers`` dense+tanh layers over
    ``rows`` activations, microbatched 2× the stage count. Bytes/iter is
    the activation traffic entering the pipeline."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from zest_tpu.parallel.pipeline import pipeline_blocks

    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("pipe",))
    L = layers * n
    rng = np.random.default_rng(1)
    params = {
        "w": jnp.asarray(rng.standard_normal((L, width, width)) * 0.1,
                         jnp.bfloat16),
    }
    x = jnp.asarray(rng.standard_normal((rows, width)), jnp.bfloat16)

    def block(x, p):
        return jnp.tanh(x @ p["w"]), None

    fn = jax.jit(lambda p, x: pipeline_blocks(block, p, x, mesh, 2 * n))
    fn(params, x).block_until_ready()
    medians = []
    for _ in range(3):
        t0 = time.perf_counter_ns()
        fn(params, x).block_until_ready()
        medians.append(time.perf_counter_ns() - t0)
    return BenchResult("pipeline_gpipe", 1, statistics.median(medians),
                       x.nbytes)


def bench_dcn_fetch(n_chunks: int = 64, chunk_bytes: int = CHUNK_64K,
                    window: int = 16, repeats: int = 5) -> BenchResult:
    """Loopback DCN chunk-RPC throughput — the cross-pod transport's
    synthetic stage (SURVEY.md §2.1 row 17: "DCN fetch" alongside ICI
    gather and HBM commit; the reference's closest analog is its
    bt_wire_frame bench, which times framing without a socket).

    One DcnServer serves a cached xorb of ``n_chunks`` incompressible
    chunks; a single channel fetches it in ``window``-deep pipelined
    sub-range requests (the pipelining discipline of bt_peer.zig:188-248
    re-expressed over DCN). Measures payload bytes over wall time —
    framing + socket + serve-loop + cache slice, everything a real
    cross-pod fetch pays on loopback.
    """
    import pathlib
    import tempfile

    import numpy as np

    from zest_tpu.cas import hashing
    from zest_tpu.cas.xorb import XorbBuilder
    from zest_tpu.config import Config
    from zest_tpu.storage import XorbCache
    from zest_tpu.transfer import dcn

    rng = np.random.default_rng(0)
    builder = XorbBuilder()
    for _ in range(n_chunks):
        builder.add_chunk(
            rng.integers(0, 256, chunk_bytes, dtype=np.uint8).tobytes()
        )
    blob = builder.serialize_full()
    with tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     dcn_port=0)
        cache = XorbCache(cfg)
        xh = builder.xorb_hash()
        cache.put(hashing.hash_to_hex(xh), blob)
        server = dcn.DcnServer(cfg, cache)
        try:
            server.start()
        except OSError as exc:  # sandbox without sockets: a skip
            raise BenchUnavailable(f"loopback unavailable: {exc}") from exc
        ch = None
        try:
            # Inside the try: a failed channel connect must still shut
            # the server down (otherwise its accept thread + bound
            # socket outlive the bench and its tempdir). Setup-stage
            # socket errors are skips; anything during the timed fetch
            # (protocol errors, timeouts) propagates as a failure.
            try:
                ch = dcn.DcnChannel("127.0.0.1", server.port)
            except OSError as exc:
                raise BenchUnavailable(
                    f"loopback connect failed: {exc}") from exc
            step = max(1, n_chunks // window)
            wants = [(xh, i, min(i + step, n_chunks))
                     for i in range(0, n_chunks, step)]

            def fetch_all():
                replies = ch.request_many(wants)
                for r in replies:
                    if not isinstance(r, dcn.DcnResponse):
                        raise RuntimeError(f"DCN bench got {type(r)}")

            payload = n_chunks * chunk_bytes
            return _time_fn("dcn_fetch_pipelined", fetch_all, payload,
                            iters=3, repeats=repeats)
        finally:
            if ch is not None:
                ch.close()
            server.shutdown()


def run_synthetic(device: bool = True) -> list[BenchResult]:
    results = bench_bencode()
    results += [bench_blake3_host(), bench_sha1_info_hash(),
                bench_wire_frame()]
    try:
        results.append(bench_gearhash_cdc())
    except RuntimeError:
        pass  # no native scanner: skip rather than time the anchor
    try:
        results.append(bench_wire_frame_native())
    except RuntimeError:
        pass  # no native lib: the pure benches above still stand
    try:
        results.append(bench_dcn_fetch())
    except BenchUnavailable:
        pass  # no loopback sockets (sandboxes). Protocol failures and
        # timeouts during the timed fetch are NOT BenchUnavailable —
        # they fail the suite, as a transport regression should.
    if device:
        for bench in (bench_blake3_device, bench_ici_all_gather,
                      bench_ring_attention, bench_pipeline):
            try:
                results.append(bench())
            except Exception:  # no usable accelerator: host suite stands
                pass
    return results


def format_results(results: list[BenchResult], as_json: bool) -> str:
    if as_json:
        return json.dumps([r.as_dict() for r in results], indent=2)
    lines = [f"{'bench':24} {'iters':>7} {'median':>14} {'MB/s':>12}"]
    for r in results:
        lines.append(
            f"{r.name:24} {r.iters:>7} {r.median_ns:>12.0f}ns "
            f"{r.mb_per_s:>12.1f}"
        )
    return "\n".join(lines)
