"""Configuration: cache layout, tokens, ports, and TPU mesh topology.

Mirrors the layered config of the reference (src/config.zig:37-84): compiled
defaults < environment variables < per-command CLI flags. Env compatibility is
preserved (``HF_TOKEN``, ``HF_HOME``, ``ZEST_CACHE_DIR``, ``ZEST_HTTP_PORT``,
``ZEST_MAX_PEERS``) and extended with TPU-native settings (``ZEST_TPU_*``)
for the pod mesh, coordinator, and HBM staging budget that the reference has
no counterpart for (SURVEY.md section 2, row 2).
"""

from __future__ import annotations

import dataclasses
import math
import os
import re
from pathlib import Path

from zest_tpu.telemetry.state import _OFF_VALUES as _TELEMETRY_OFF_VALUES
from zest_tpu.telemetry.timeline import (
    DEFAULT_HZ as DEFAULT_TIMELINE_HZ,
    DEFAULT_WINDOW_S as DEFAULT_ANOMALY_WINDOW_S,
)

# ── Compiled defaults (reference: src/config.zig:6-19) ──
DEFAULT_LISTEN_PORT = 6881          # BT/seed listener + DHT UDP port
DEFAULT_HTTP_PORT = 9847            # localhost REST control plane
DEFAULT_MAX_PEERS = 50              # connection-pool cap
DEFAULT_MAX_CONCURRENT_DOWNLOADS = 16
DEFAULT_BATCH_MULTIPLIER = 8        # terms per batch = 16 * 8 = 128

# TPU-native defaults (no reference counterpart).
DEFAULT_DCN_PORT = 6991             # host-to-host chunk RPC listener
DEFAULT_HBM_STAGING_BYTES = 2 << 30  # per-device staging buffer budget

# Pull-pipeline defaults (the pipelined pull: file reconstruction,
# verification, and HBM commit overlap; see transfer.pull).
DEFAULT_PULL_PIPELINE_WIDTH = 4     # concurrent file reassemblies
DEFAULT_PULL_INFLIGHT_BYTES = 2 << 30  # in-flight reassembly byte budget
# Decode parallelism (ZEST_DECODE_WORKERS): 0 = auto, 1 = serial. Sizes
# BOTH the Python term-decode pool and the native batch-decode engine's
# C++ worker pool (native/decode.cc) — one knob, whichever tier runs.
DEFAULT_DECODE_WORKERS = 0
DEFAULT_LAND_DECODE_AHEAD = 1       # shards decoded ahead of the commit
# Decoded-blob reader cache (ZEST_DECODE_CACHE, bytes): the landing's
# per-term cache-entry reads repeat heavily (a ~32 MB unit serves many
# ~MB terms); a small parsed-reader LRU turns N whole-file reads per
# unit into one. Sized to hold a few units; 0 disables.
DEFAULT_DECODE_CACHE_BYTES = 192 * 1024 * 1024
# Background file materialization (ZEST_FILES_ASYNC): with 1 (default)
# the --device=tpu write-behind lane never blocks the landing — a full
# byte budget makes it decline to the post-commit cache lane instead of
# stalling the decode thread, and tmp files commit (fsync + rename) at
# the pull-exit durability barrier. 0 restores the blocking handoff.
DEFAULT_FILES_ASYNC = True
# Materialization writer pool (ZEST_FILES_WORKERS): how many HF-cache
# files the background lane writes concurrently (pwritev/copy_file_range
# byte movement, disk-bound — distinct from ZEST_PULL_WIDTH, which
# sizes the network-bound waterfall reassembly lane). 0 = auto.
DEFAULT_FILES_WORKERS = 0
# Cooperative pull (transfer.coop): exchange-phase in-flight byte budget
# (ZEST_COOP_INFLIGHT) — bounds how many compressed wire bytes a host
# stages in memory before draining them to the verified cache.
DEFAULT_COOP_INFLIGHT_BYTES = 1 << 30
# Streaming landing (models.loader._stage_streaming): with 1 (default)
# a --device=tpu landing flows fetch → decode → device_put at TENSOR
# granularity through a fixed ring of reusable host staging buffers —
# tensors commit in layer order (embedding + layer 0 first) and the
# decode engine writes straight into the ring slot the transfer reads
# (no per-shard intermediate buffer). 0 restores the PR-1 shard-level
# double buffer bit-for-bit (stats schema included). Requires
# ZEST_LAND_AHEAD nonzero — a serial landing has no pipeline to ring.
DEFAULT_LAND_STREAM = True
# Ring capacity (ZEST_LAND_RING_BYTES): total bytes of staging buffers
# in flight between decode and device transfer. Sized to hold ~3 decode
# runs (a run is up to 2x the 64 MiB commit group, and slots round out
# to term boundaries) so the producer isn't backpressured while one
# group commits and another accumulates — still far below the
# non-streaming path's ~two-shard staging peak (1.3 GB for 650 MB
# shards); a tensor larger than the whole ring is admitted alone (the
# ByteBudget oversized rule) rather than deadlocking.
DEFAULT_LAND_RING_BYTES = 512 * 1024 * 1024
# Ring slot cap (ZEST_LAND_RING_SLOTS): max concurrently-acquired
# buffers — bounds buffer-object churn when a checkpoint is all tiny
# tensors; bytes are the binding constraint for checkpoint-shaped
# tensors.
DEFAULT_LAND_RING_SLOTS = 64
# Seeding tier (transfer.server, ISSUE 12): the upload policy of the
# always-on seeder. ZEST_SEED_RATE_BPS caps this host's TOTAL upload
# rate (one shaping.TokenBucket across every leecher; 0 = unshaped),
# ZEST_SEED_PEER_BPS caps any single leecher (fairness under one
# aggressive puller; 0 = unshaped). ZEST_SEED_SLOTS is the reciprocity
# K: the K peers that served US the most bytes recently hold unchoke
# slots, plus ONE optimistic-unchoke rotation slot (BEP-XET heritage);
# it also bounds concurrent in-flight uploads (K+1 transfer slots).
# ZEST_SEED_DEADLINE_S bounds one chunk response end-to-end so a
# stalled reader can't pin an upload slot; ZEST_SEED_DRAIN_S bounds the
# graceful-shutdown drain of in-flight responses.
DEFAULT_SEED_SLOTS = 8
DEFAULT_SEED_DEADLINE_S = 30.0
DEFAULT_SEED_DRAIN_S = 5.0
# Multi-tenant pull service (transfer.tenancy, ISSUE 13): shared,
# globally-budgeted pools for concurrent pulls — singleflight fetch
# dedupe, fair admission with backpressure, xorb-cache eviction under
# disk pressure. ZEST_TENANCY=0 restores fully independent pulls
# (per-pull budgets, no flights table, no queue, no eviction).
# ZEST_TENANT_MAX_PULLS bounds concurrently-admitted sessions;
# ZEST_TENANT_QUEUE bounds PARKED sessions (beyond it, a new pull is
# rejected with a typed 429 + retry-after — backpressure, never
# unbounded parking); ZEST_TENANT_INFLIGHT is the aggregate in-flight
# reassembly byte budget shared by every admitted session, STACKED on
# top of each pull's own ZEST_PULL_INFLIGHT bound (both hold; a
# single file larger than the whole aggregate budget bypasses the
# shared tier — it stays bounded by its per-pull budget and the
# admission slots, where waiting for global-zero inflight would
# starve it forever);
# ZEST_TENANT_DISK_HIGH / ZEST_TENANT_DISK_LOW are the xorb-cache
# byte watermarks: above HIGH, unpinned entries evict LRU-first down
# to LOW (0 = eviction unarmed; LOW defaults to 80% of HIGH).
DEFAULT_TENANCY = True
DEFAULT_TENANT_MAX_PULLS = 4
DEFAULT_TENANT_QUEUE = 16
DEFAULT_TENANT_INFLIGHT_BYTES = 4 << 30
# HBM serving pool (models.hbm_pool, ISSUE 18): with 1 (default) the
# daemon's /v1/generate serves from a process-wide managed pool of
# resident model trees — byte accounting against ZEST_HBM_POOL_BYTES,
# LRU eviction of cold unpinned trees back to the xorb/snapshot cache,
# and scale-to-zero re-landing where decode starts at first-layer
# commit instead of full land. 0 restores the single-model
# generator-LRU behavior bit-for-bit (stats schema included).
# ZEST_HBM_POOL_BYTES is the pool watermark (0 = unbounded);
# ZEST_SLO_TTFT_S is the time-to-first-token SLO budget (unset/0 =
# unarmed — a breach bumps zest_slo_breaches_total{slo="ttft"} like
# the PR-11 tthbm/ttfl budgets).
DEFAULT_HBM_POOL = True
DEFAULT_HBM_POOL_BYTES = DEFAULT_HBM_STAGING_BYTES
# Delta pulls (transfer.delta, ISSUE 10): with 1 (default) every pull
# persists a revision manifest and a pull of revision B over a cached
# revision A plans a chunk-level delta — unchanged bytes serve from the
# local cache with zero network, a resident rev-A param tree hot-swaps
# at tensor granularity (time_to_swap_s), and stats gain a "delta"
# block. 0 restores the pre-delta behavior bit-for-bit (no manifests,
# no delta stats keys).
DEFAULT_DELTA = True

_REPO_RE = re.compile(r"^[\w.\-]+/[\w.\-]+$")


def parse_host_addr(spec: str) -> tuple[int, tuple[str, int]]:
    """One ``"IDX=HOST:PORT"`` entry → ``(idx, (host, port))`` — the
    single parser behind ``ZEST_COOP_ADDRS`` and the CLI's repeatable
    ``--pod-addr``/``--coop-addr`` flags (one grammar, one place to
    evolve it). Raises ValueError on any malformation — a typo
    silently dropping a host from an exchange would quietly halve the
    cooperative win."""
    idx, eq, addr = spec.strip().partition("=")
    host, colon, port = addr.rpartition(":")
    if not eq or not colon or not idx.strip().isdigit() \
            or not port.isdigit() or not host:
        raise ValueError(f"bad host-addr entry: {spec!r} "
                         "(want IDX=HOST:PORT)")
    return int(idx), (host, int(port))


def _parse_coop_addrs(spec: str) -> dict[int, tuple[str, int]]:
    """``"0=hostA:6991,1=hostB:6991"`` -> {0: ("hostA", 6991), ...}."""
    out: dict[int, tuple[str, int]] = {}
    for part in spec.split(","):
        if part.strip():
            idx, addr = parse_host_addr(part)
            out[idx] = addr
    return out


def parse_topology(spec: str) -> tuple[int, ...]:
    """``"0,0,1,1"`` → ``(0, 0, 1, 1)`` — slice id per coop host index
    (``ZEST_COOP_TOPOLOGY``; transfer.collective classifies each
    exchange link ici/dcn from it). Strict like every other coop knob:
    malformed or negative entries raise — a silently-dropped host would
    misclass every one of its links and quietly route the big
    cross-slice phases as if they were intra-slice."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part.isdigit():
            raise ValueError(
                f"bad ZEST_COOP_TOPOLOGY entry {part!r} "
                "(want comma-separated slice ids, e.g. 0,0,1,1)")
        out.append(int(part))
    if not out:
        raise ValueError("ZEST_COOP_TOPOLOGY is empty")
    return tuple(out)


def _parse_remediate_actions(spec: str) -> tuple[str, ...] | None:
    """The ``ZEST_REMEDIATE_ACTIONS`` enable mask, strictly: empty or
    ``all`` means every action (None); otherwise each comma-separated
    name must be a known action — a typo here silently disables a
    remediation the operator thinks is armed, exactly the failure the
    strict knobs exist for. (The engine's own ``parse_actions`` stays
    lenient: a typo must not crash a pull mid-flight.)"""
    spec = (spec or "").strip().lower()
    if not spec or spec == "all":
        return None
    from zest_tpu.telemetry.remediate import ACTIONS

    names = tuple(p.strip() for p in spec.split(",") if p.strip())
    bad = sorted(set(names) - set(ACTIONS))
    if bad:
        raise ValueError(
            f"ZEST_REMEDIATE_ACTIONS names unknown action(s) {bad}; "
            f"valid: {', '.join(ACTIONS)}")
    return names


def _opt_pos_float(env: dict[str, str], name: str) -> float | None:
    """Optional positive float knob: unset/empty/0 = unarmed (None); a
    malformed OR negative value raises (same typo discipline as
    _strict_bool — a mistyped SLO budget must not silently disarm the
    SLO, and a sign slip is a typo, not "off")."""
    raw = env.get(name)
    if raw is None or not raw.strip():
        return None
    v = float(raw)
    if v < 0 or not math.isfinite(v):
        raise ValueError(f"{name} must be a finite value >= 0 "
                         f"(0 = unarmed), got {raw!r}")
    return v if v > 0 else None


def _strict_nonneg_int(env: dict[str, str], name: str,
                       default: int = 0, floor: int = 0) -> int:
    """Integer knob where a NEGATIVE value raises instead of silently
    clamping to ``floor`` — the seed-rate sign-slip discipline: a
    mistyped ``ZEST_SEED_RATE_BPS=-25000000`` silently meaning
    "unshaped" would pass every test while the fleet saturates
    uplinks (same rationale as _opt_pos_float)."""
    raw = env.get(name)
    if raw is None or not raw.strip():
        return default
    v = int(raw)
    if v < floor:
        raise ValueError(f"{name} must be an integer >= {floor}, "
                         f"got {raw!r}")
    return v


def _strict_pos_float(env: dict[str, str], name: str,
                      default: float, floor: float = 0.0) -> float:
    """Float knob; values below ``floor`` (or non-finite) raise."""
    raw = env.get(name)
    if raw is None or not raw.strip():
        return default
    v = float(raw)
    if v < floor or not math.isfinite(v):
        raise ValueError(f"{name} must be a finite value >= {floor}, "
                         f"got {raw!r}")
    return v


def _strict_bool(name: str, value: str) -> bool:
    """``"0"``/``"1"`` only — anything else raises. The lenient
    ``!= "0"`` idiom would turn ``ZEST_LAND_STREAM=false`` (or a typo)
    into streaming silently staying ON, defeating the rollback knob."""
    v = value.strip()
    if v not in ("0", "1"):
        raise ValueError(f"{name} must be 0 or 1, got {value!r}")
    return v == "1"


def _strict_choice(env: dict[str, str], name: str, default: str,
                   choices: tuple[str, ...]) -> str:
    """Enumerated knob: unset/empty = ``default``; anything outside
    ``choices`` raises (the _strict_bool typo discipline — a mistyped
    ``ZEST_COLLECTIVE_BACKEND=jxa`` must not silently fall back to the
    default transport)."""
    raw = env.get(name)
    if raw is None or not raw.strip():
        return default
    v = raw.strip()
    if v not in choices:
        raise ValueError(
            f"{name} must be one of {'|'.join(choices)}, got {raw!r}")
    return v


def _expand(p: str) -> Path:
    return Path(os.path.expanduser(p))


@dataclasses.dataclass
class MeshConfig:
    """Topology of the pod this process participates in.

    The reference discovers peers dynamically via DHT/tracker; a TPU pod's
    membership is static per job, so topology is configuration: the JAX
    coordinator address, this process' index, total process count, and the
    logical mesh axes used when landing checkpoints into a pjit mesh.
    """

    coordinator: str | None = None       # "host:port" for jax.distributed
    process_id: int = 0
    num_processes: int = 1
    # Logical mesh axes for checkpoint landing, e.g. {"data": 1, "model": 8}.
    mesh_axes: dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @staticmethod
    def from_env(env: dict[str, str]) -> "MeshConfig":
        axes: dict[str, int] = {}
        spec = env.get("ZEST_TPU_MESH", "")
        # Format: "data=2,model=4" (axis order is significant).
        if spec:
            for part in spec.split(","):
                name, _, n = part.partition("=")
                axes[name.strip()] = int(n)
        return MeshConfig(
            coordinator=env.get("ZEST_TPU_COORDINATOR") or None,
            process_id=int(env.get("ZEST_TPU_PROCESS_ID", "0")),
            num_processes=int(env.get("ZEST_TPU_NUM_PROCESSES", "1")),
            mesh_axes=axes,
        )


@dataclasses.dataclass
class Config:
    """Resolved runtime configuration.

    Build with :meth:`Config.load` so env overrides apply; construct directly
    in tests for hermetic behavior (the reference achieves the same with an
    injected ``environ``, src/config.zig:160-166).
    """

    hf_home: Path
    cache_dir: Path                      # zest-private cache root
    hf_token: str | None = None
    listen_port: int = DEFAULT_LISTEN_PORT
    http_port: int = DEFAULT_HTTP_PORT
    dcn_port: int = DEFAULT_DCN_PORT
    max_peers: int = DEFAULT_MAX_PEERS
    max_concurrent_downloads: int = DEFAULT_MAX_CONCURRENT_DOWNLOADS
    hbm_staging_bytes: int = DEFAULT_HBM_STAGING_BYTES
    # Pipelined-pull knobs (transfer.pull / models.direct / models.loader):
    # how many HF-cache files reassemble concurrently, the byte budget
    # bounding their in-flight blobs, the term-decode pool size
    # (0 = auto: min(4, cpu); 1 = serial), and whether the landing
    # decodes one shard ahead of the device commit (0 = off, nonzero =
    # on; the lookahead depth is fixed at one shard — deeper would only
    # grow the host peak past the double-buffer bound).
    pull_pipeline_width: int = DEFAULT_PULL_PIPELINE_WIDTH
    pull_inflight_bytes: int = DEFAULT_PULL_INFLIGHT_BYTES
    decode_workers: int = DEFAULT_DECODE_WORKERS
    land_decode_ahead: int = DEFAULT_LAND_DECODE_AHEAD
    decode_cache_bytes: int = DEFAULT_DECODE_CACHE_BYTES
    # Streaming landing ring (see DEFAULT_LAND_* above).
    land_stream: bool = DEFAULT_LAND_STREAM
    land_ring_bytes: int = DEFAULT_LAND_RING_BYTES
    land_ring_slots: int = DEFAULT_LAND_RING_SLOTS
    # Seeding-tier upload policy (see DEFAULT_SEED_* above).
    seed_rate_bps: int = 0
    seed_peer_bps: int = 0
    seed_slots: int = DEFAULT_SEED_SLOTS
    seed_request_deadline_s: float = DEFAULT_SEED_DEADLINE_S
    seed_drain_s: float = DEFAULT_SEED_DRAIN_S
    # Multi-tenant pull service (see DEFAULT_TENANT_* above).
    tenancy_enabled: bool = DEFAULT_TENANCY
    tenant_max_pulls: int = DEFAULT_TENANT_MAX_PULLS
    tenant_queue: int = DEFAULT_TENANT_QUEUE
    tenant_inflight_bytes: int = DEFAULT_TENANT_INFLIGHT_BYTES
    tenant_disk_high: int = 0
    tenant_disk_low: int = 0
    # HBM serving pool (see DEFAULT_HBM_POOL above).
    hbm_pool_enabled: bool = DEFAULT_HBM_POOL
    hbm_pool_bytes: int = DEFAULT_HBM_POOL_BYTES
    # Delta pulls (see DEFAULT_DELTA above).
    delta_pull: bool = DEFAULT_DELTA
    # Background materialization lane (see DEFAULT_FILES_* above).
    files_async: bool = DEFAULT_FILES_ASYNC
    files_workers: int = DEFAULT_FILES_WORKERS
    # Per-pull wall-clock budget in seconds (ZEST_PULL_DEADLINE_S;
    # None/0 = off). When armed, every tier's timeouts and retry sleeps
    # are capped by the remaining budget and the bridge hedges slow
    # peer fetches against CDN (transfer.bridge). Off by default: an
    # unattended pull should keep trying, an interactive/serving pull
    # wants a bound.
    pull_deadline_s: float | None = None
    # Cooperative pod-scale pull (transfer.coop; ROADMAP item 1).
    # ``coop_pull`` is tri-state: True/False force it on/off (ZEST_COOP
    # =1/0), None = auto — on when a multi-host topology is known
    # (coop_hosts > 1, or a multi-process mesh). ``coop_addrs`` maps
    # host index -> (host, dcn_port) (ZEST_COOP_ADDRS="0=h:p,1=h:p");
    # when absent, a jax.distributed KV exchange discovers them.
    coop_pull: bool | None = None
    coop_hosts: int | None = None
    coop_index: int | None = None
    coop_addrs: dict[int, tuple[str, int]] = dataclasses.field(
        default_factory=dict)
    coop_inflight_bytes: int = DEFAULT_COOP_INFLIGHT_BYTES
    # Collective-native exchange (transfer.collective, ISSUE 14):
    # ``coop_collective`` is the rollback knob (ZEST_COOP_COLLECTIVE,
    # strict 0/1) — 0 restores the PR-6 point-to-point exchange
    # bit-for-bit; ``coop_topology`` is the slice id per coop host
    # (ZEST_COOP_TOPOLOGY="0,0,1,1") from which exchange links are
    # classed ici (intra-slice) vs dcn (cross-slice) — None = infer
    # from the JAX runtime, else one flat slice.
    coop_collective: bool = True
    coop_topology: tuple[int, ...] | None = None
    # Transport/schedule split (transfer.transport, ISSUE 20):
    # ``collective_backend`` picks how phase windows move
    # (ZEST_COLLECTIVE_BACKEND=dcn|jax|loopback, strict) — "dcn" is
    # the pre-split pooled DcnChannel path bit-for-bit, "jax" moves
    # intra-slice phases as device-to-device uint8 lane permutes,
    # "loopback" is the zero-socket in-process fabric the big sims
    # ride. ``collective_lossy`` arms the EQuARX-style quantized tier
    # on the named link classes (ZEST_COLLECTIVE_LOSSY=dcn|wan|0,
    # strict; "dcn" also covers wan) — lossy payloads land HBM-only
    # and never enter the merkle-verified cache.
    collective_backend: str = "dcn"
    collective_lossy: str = "0"
    # Fleet topology (ISSUE 16): ``coop_pods`` is the pod id per coop
    # host (ZEST_COOP_PODS="0,0,1,1", same grammar as the slice map) —
    # names the third link class (wan, cross-pod) and arms the
    # federated gateway schedule; None = one pod, bit-for-bit the
    # PR-13 shapes. ``gossip_enabled`` is the rollback knob
    # (ZEST_GOSSIP, strict 0/1) — 0 restores tracker-only announce
    # bit-for-bit; ``gossip_fanout`` peers per anti-entropy tick
    # (0 = auto, ceil(log2 N)); ``gossip_max_entries`` bounds the
    # digest; ``gossip_interval_s`` is the tick cadence.
    coop_pods: tuple[int, ...] | None = None
    gossip_enabled: bool = True
    gossip_fanout: int = 0
    gossip_max_entries: int = 65536
    gossip_interval_s: float = 5.0
    # Push / continuous fan-out (ISSUE 19): ``watch_enabled`` is the
    # rollback knob (ZEST_WATCH, strict 0/1) for the daemon's
    # ``POST /v1/watch`` subscribe/notify surface and the push-notify
    # fan-out — 0 restores the read-only daemon bit-for-bit (404 on
    # watch, pushes still publish locally but notify no one).
    # ``push_chunks_per_xorb`` caps chunks packed per minted xorb
    # (ZEST_PUSH_CHUNKS_PER_XORB; 0 = format caps only) — small values
    # force multi-xorb layouts in tests/benches.
    watch_enabled: bool = True
    push_chunks_per_xorb: int = 0
    # Pod fleet observability (telemetry.fleet; ISSUE 7): HTTP API
    # endpoints of the OTHER hosts' daemons, ``ZEST_POD_PEERS=
    # "1=hostB:9847,2=hostC:9847"`` (same grammar as coop addrs). The
    # coordinator's daemon scrapes them for ``/v1/metrics?scope=pod``
    # and ``zest trace --coop`` gathers their ``/v1/trace`` snapshots.
    pod_peers: dict[int, tuple[str, int]] = dataclasses.field(
        default_factory=dict)
    # Pod-scope scrape fan-out bound (ISSUE 16 satellite): worker cap
    # for /v1/metrics?scope=pod and /v1/timeline?scope=pod peer
    # scrapes — one shared process-wide pool, not per-request bursts.
    pod_scrape_workers: int = 8
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    endpoint: str = "https://huggingface.co"
    # Landing dtype for --device=tpu (None = checkpoint dtype; "bf16"
    # halves HBM and transfer bytes). Resolved by models.loader.
    land_dtype: str | None = None
    # Telemetry (zest_tpu.telemetry): the observability layer reads the
    # env directly on its hot paths (ZEST_TELEMETRY gates everything,
    # ZEST_TRACE=path arms the span tracer); these fields are the
    # introspection mirror — what /v1/status and `zest status` report
    # as this process' configuration.
    telemetry_enabled: bool = True
    trace_path: str | None = None
    # Pull-session observability (telemetry.session; ISSUE 11): the
    # tenant label stamped on this process' pull sessions
    # (``ZEST_TENANT``; the API's ``tenant`` field overrides per pull),
    # and the SLO budgets in seconds — time-to-HBM and time-to-first-
    # layer (``ZEST_SLO_TTHBM_S`` / ``ZEST_SLO_TTFL_S``; unset/0 =
    # unarmed). A breached budget bumps zest_slo_breaches_total{slo}
    # and records an slo_breach flight event carrying the session id
    # and the critical-path analyzer's top blamed stage.
    tenant: str | None = None
    slo_tthbm_s: float | None = None
    slo_ttfl_s: float | None = None
    slo_ttft_s: float | None = None
    # Live timelines (telemetry.timeline; ISSUE 15): like ZEST_TELEMETRY
    # these are read by the sampler directly on its own paths — the
    # fields here are the introspection mirror for /v1/status. The
    # sampler records registry-counter rates + structural gauges at
    # ZEST_TIMELINE_HZ; ZEST_TIMELINE=0 is hard-off (no sampler thread,
    # empty store, byte-identical pull); ZEST_ANOMALY_WINDOW_S is how
    # long a condition (zero progress, collapsed rate, stuck queue,
    # barrier wait) must hold before the streaming detector fires.
    timeline_enabled: bool = True
    timeline_hz: float = DEFAULT_TIMELINE_HZ
    anomaly_window_s: float = DEFAULT_ANOMALY_WINDOW_S
    # Self-healing control plane (telemetry.remediate; ISSUE 17): the
    # engine reads the env directly like the sampler — these fields are
    # the introspection mirror. ``remediate_actions`` is the enable
    # mask (None = every action); it parses STRICTLY here (an unknown
    # action name silently disabling a remediation is exactly the typo
    # class the strict knobs exist for), while the engine itself stays
    # lenient (a typo must not crash a pull).
    remediate_enabled: bool = True
    remediate_actions: tuple[str, ...] | None = None
    remediate_dry_run: bool = False
    remediate_rate_s: float = 10.0
    remediate_burst: int = 3

    # ── Construction ──

    @staticmethod
    def load(env: dict[str, str] | None = None) -> "Config":
        """Resolve config from the environment.

        Token resolution order matches the reference (src/config.zig:136-158):
        ``HF_TOKEN`` env var, then ``~/.cache/huggingface/token`` file.
        """
        env = dict(os.environ) if env is None else env
        hf_home = _expand(env.get("HF_HOME", "~/.cache/huggingface"))
        cache_dir = _expand(env.get("ZEST_CACHE_DIR", "~/.cache/zest"))

        token = env.get("HF_TOKEN") or None
        if not token:
            token_file = hf_home / "token"
            try:
                token = token_file.read_text().strip() or None
            except OSError:
                token = None

        # Eviction watermarks are cross-validated here, not clamped:
        # LOW >= HIGH would make every watermark pass free zero bytes
        # (while still paying the cache walk per admission), and LOW
        # without HIGH silently disarms eviction — both are knob typos
        # that must fail loud (the same discipline as the strict
        # bools/ints above).
        disk_high = _strict_nonneg_int(env, "ZEST_TENANT_DISK_HIGH")
        disk_low = _strict_nonneg_int(env, "ZEST_TENANT_DISK_LOW")
        if disk_low and not disk_high:
            raise ValueError(
                "ZEST_TENANT_DISK_LOW is set but ZEST_TENANT_DISK_HIGH "
                "is not: eviction arms on HIGH — a LOW alone would "
                "silently do nothing")
        if disk_high and disk_low >= disk_high:
            raise ValueError(
                f"ZEST_TENANT_DISK_LOW ({disk_low}) must be below "
                f"ZEST_TENANT_DISK_HIGH ({disk_high}): an inverted "
                "pair would trigger eviction passes that free nothing")

        return Config(
            hf_home=hf_home,
            cache_dir=cache_dir,
            hf_token=token,
            listen_port=int(env.get("ZEST_LISTEN_PORT", DEFAULT_LISTEN_PORT)),
            http_port=int(env.get("ZEST_HTTP_PORT", DEFAULT_HTTP_PORT)),
            dcn_port=int(env.get("ZEST_DCN_PORT", DEFAULT_DCN_PORT)),
            max_peers=int(env.get("ZEST_MAX_PEERS", DEFAULT_MAX_PEERS)),
            max_concurrent_downloads=int(
                env.get("ZEST_MAX_CONCURRENT", DEFAULT_MAX_CONCURRENT_DOWNLOADS)
            ),
            hbm_staging_bytes=int(
                env.get("ZEST_TPU_HBM_STAGING", DEFAULT_HBM_STAGING_BYTES)
            ),
            pull_pipeline_width=max(1, int(
                env.get("ZEST_PULL_WIDTH", DEFAULT_PULL_PIPELINE_WIDTH))),
            pull_inflight_bytes=max(1, int(
                env.get("ZEST_PULL_INFLIGHT", DEFAULT_PULL_INFLIGHT_BYTES))),
            decode_workers=max(0, int(
                env.get("ZEST_DECODE_WORKERS", DEFAULT_DECODE_WORKERS))),
            land_decode_ahead=max(0, int(
                env.get("ZEST_LAND_AHEAD", DEFAULT_LAND_DECODE_AHEAD))),
            decode_cache_bytes=max(0, int(
                env.get("ZEST_DECODE_CACHE", DEFAULT_DECODE_CACHE_BYTES))),
            # Malformed values raise (_strict_bool / int() ValueError),
            # like every other landing knob — a typo must not silently
            # fall back to a default ring, and ZEST_LAND_STREAM=false
            # must not silently keep streaming ON (it is the rollback
            # knob).
            land_stream=_strict_bool(
                "ZEST_LAND_STREAM",
                env.get("ZEST_LAND_STREAM",
                        "1" if DEFAULT_LAND_STREAM else "0")),
            land_ring_bytes=max(1, int(
                env.get("ZEST_LAND_RING_BYTES",
                        DEFAULT_LAND_RING_BYTES))),
            land_ring_slots=max(1, int(
                env.get("ZEST_LAND_RING_SLOTS",
                        DEFAULT_LAND_RING_SLOTS))),
            # Seeding knobs: malformed AND negative values raise — a
            # sign-slipped rate silently meaning "unshaped" would pass
            # every test while the fleet saturates uplinks.
            seed_rate_bps=_strict_nonneg_int(env, "ZEST_SEED_RATE_BPS"),
            seed_peer_bps=_strict_nonneg_int(env, "ZEST_SEED_PEER_BPS"),
            seed_slots=_strict_nonneg_int(
                env, "ZEST_SEED_SLOTS", DEFAULT_SEED_SLOTS, floor=1),
            seed_request_deadline_s=_strict_pos_float(
                env, "ZEST_SEED_DEADLINE_S", DEFAULT_SEED_DEADLINE_S,
                floor=0.1),
            seed_drain_s=_strict_pos_float(
                env, "ZEST_SEED_DRAIN_S", DEFAULT_SEED_DRAIN_S),
            # Strict like ZEST_LAND_STREAM: ZEST_TENANCY is the
            # multi-tenant rollback knob — "false"/a typo must raise,
            # never silently keep shared pools on; the budget knobs
            # follow the seed-rate sign-slip discipline (a negative
            # budget silently meaning "tiny"/"unbounded" would pass
            # every test while the daemon over- or under-admits).
            tenancy_enabled=_strict_bool(
                "ZEST_TENANCY",
                env.get("ZEST_TENANCY", "1" if DEFAULT_TENANCY else "0")),
            tenant_max_pulls=_strict_nonneg_int(
                env, "ZEST_TENANT_MAX_PULLS", DEFAULT_TENANT_MAX_PULLS,
                floor=1),
            tenant_queue=_strict_nonneg_int(
                env, "ZEST_TENANT_QUEUE", DEFAULT_TENANT_QUEUE),
            tenant_inflight_bytes=_strict_nonneg_int(
                env, "ZEST_TENANT_INFLIGHT",
                DEFAULT_TENANT_INFLIGHT_BYTES, floor=1),
            tenant_disk_high=disk_high,
            tenant_disk_low=disk_low,
            # Strict like ZEST_LAND_STREAM: ZEST_HBM_POOL is the
            # serving-pool rollback knob — "false"/a typo must raise,
            # never silently keep the pool on; the byte watermark
            # follows the seed-rate sign-slip discipline.
            hbm_pool_enabled=_strict_bool(
                "ZEST_HBM_POOL",
                env.get("ZEST_HBM_POOL",
                        "1" if DEFAULT_HBM_POOL else "0")),
            hbm_pool_bytes=_strict_nonneg_int(
                env, "ZEST_HBM_POOL_BYTES", DEFAULT_HBM_POOL_BYTES),
            # Strict like ZEST_LAND_STREAM: ZEST_DELTA is the delta
            # rollback knob — "false"/a typo must raise, never silently
            # keep deltas on.
            delta_pull=_strict_bool(
                "ZEST_DELTA",
                env.get("ZEST_DELTA", "1" if DEFAULT_DELTA else "0")),
            files_async=env.get(
                "ZEST_FILES_ASYNC",
                "1" if DEFAULT_FILES_ASYNC else "0").strip() != "0",
            files_workers=max(0, int(
                env.get("ZEST_FILES_WORKERS", DEFAULT_FILES_WORKERS))),
            pull_deadline_s=(
                float(env["ZEST_PULL_DEADLINE_S"])
                if float(env.get("ZEST_PULL_DEADLINE_S") or 0) > 0
                else None),
            coop_pull={"1": True, "0": False}.get(
                env.get("ZEST_COOP", "").strip()),
            coop_hosts=(int(env["ZEST_COOP_HOSTS"])
                        if env.get("ZEST_COOP_HOSTS") else None),
            coop_index=(int(env["ZEST_COOP_INDEX"])
                        if env.get("ZEST_COOP_INDEX") else None),
            coop_addrs=_parse_coop_addrs(env.get("ZEST_COOP_ADDRS", "")),
            coop_inflight_bytes=max(1, int(
                env.get("ZEST_COOP_INFLIGHT")
                or DEFAULT_COOP_INFLIGHT_BYTES)),
            # Strict like ZEST_LAND_STREAM: ZEST_COOP_COLLECTIVE is
            # the collective-exchange rollback knob — "false"/a typo
            # must raise, never silently keep the collective on; the
            # topology spec parses strictly for the same reason.
            coop_collective=_strict_bool(
                "ZEST_COOP_COLLECTIVE",
                env.get("ZEST_COOP_COLLECTIVE", "1")),
            coop_topology=(parse_topology(env["ZEST_COOP_TOPOLOGY"])
                           if env.get("ZEST_COOP_TOPOLOGY", "").strip()
                           else None),
            collective_backend=_strict_choice(
                env, "ZEST_COLLECTIVE_BACKEND", "dcn",
                ("dcn", "jax", "loopback")),
            collective_lossy=_strict_choice(
                env, "ZEST_COLLECTIVE_LOSSY", "0",
                ("0", "dcn", "wan")),
            coop_pods=(parse_topology(env["ZEST_COOP_PODS"])
                       if env.get("ZEST_COOP_PODS", "").strip()
                       else None),
            gossip_enabled=_strict_bool(
                "ZEST_GOSSIP", env.get("ZEST_GOSSIP", "1")),
            # Strict like ZEST_GOSSIP: ZEST_WATCH is the fan-out
            # rollback knob — a typo must raise, never silently keep
            # the watch surface on.
            watch_enabled=_strict_bool(
                "ZEST_WATCH", env.get("ZEST_WATCH", "1")),
            push_chunks_per_xorb=_strict_nonneg_int(
                env, "ZEST_PUSH_CHUNKS_PER_XORB"),
            gossip_fanout=_strict_nonneg_int(env, "ZEST_GOSSIP_FANOUT"),
            gossip_max_entries=_strict_nonneg_int(
                env, "ZEST_GOSSIP_MAX", default=65536, floor=1),
            gossip_interval_s=_strict_pos_float(
                env, "ZEST_GOSSIP_INTERVAL_S", 5.0, floor=0.05),
            pod_peers=_parse_coop_addrs(env.get("ZEST_POD_PEERS", "")),
            pod_scrape_workers=_strict_nonneg_int(
                env, "ZEST_POD_SCRAPE_WORKERS", default=8, floor=1),
            mesh=MeshConfig.from_env(env),
            endpoint=env.get("HF_ENDPOINT", "https://huggingface.co"),
            land_dtype=env.get("ZEST_TPU_DTYPE") or None,
            # Same off-value set the hot-path gate uses (state._OFF_VALUES)
            # — a divergent inline copy would make this introspection
            # field lie about what the gate actually does.
            telemetry_enabled=env.get("ZEST_TELEMETRY", "").strip().lower()
            not in _TELEMETRY_OFF_VALUES,
            trace_path=env.get("ZEST_TRACE") or None,
            tenant=env.get("ZEST_TENANT") or None,
            slo_tthbm_s=_opt_pos_float(env, "ZEST_SLO_TTHBM_S"),
            slo_ttfl_s=_opt_pos_float(env, "ZEST_SLO_TTFL_S"),
            slo_ttft_s=_opt_pos_float(env, "ZEST_SLO_TTFT_S"),
            # Same off-value convention as ZEST_TELEMETRY (the sampler
            # resolves the env itself; this mirrors it). The hz/window
            # knobs parse strictly HERE — a daemon started with a
            # mistyped sampling rate must fail loud, not silently
            # sample at the default.
            timeline_enabled=env.get("ZEST_TIMELINE", "").strip().lower()
            not in _TELEMETRY_OFF_VALUES,
            timeline_hz=_strict_pos_float(
                env, "ZEST_TIMELINE_HZ", DEFAULT_TIMELINE_HZ,
                floor=0.01),
            anomaly_window_s=_strict_pos_float(
                env, "ZEST_ANOMALY_WINDOW_S", DEFAULT_ANOMALY_WINDOW_S,
                floor=0.05),
            # Same off-value convention as ZEST_TIMELINE; the action
            # mask is the one strict parse (see the field comment).
            remediate_enabled=env.get("ZEST_REMEDIATE", "").strip().lower()
            not in _TELEMETRY_OFF_VALUES,
            remediate_actions=_parse_remediate_actions(
                env.get("ZEST_REMEDIATE_ACTIONS", "")),
            remediate_dry_run=env.get(
                "ZEST_REMEDIATE_DRY", "").strip().lower()
            in ("1", "true", "yes", "on"),
            remediate_rate_s=_strict_pos_float(
                env, "ZEST_REMEDIATE_RATE_S", 10.0, floor=0.01),
            remediate_burst=_strict_nonneg_int(
                env, "ZEST_REMEDIATE_BURST", default=3, floor=1),
        )

    # ── Path builders (reference: src/config.zig:95-133) ──

    def hub_dir(self) -> Path:
        return self.hf_home / "hub"

    def model_cache_dir(self, repo_id: str) -> Path:
        """``hub/models--{org}--{name}`` — HF cache layout."""
        if not _REPO_RE.match(repo_id):
            raise ValueError(f"invalid repo id: {repo_id!r}")
        return self.hub_dir() / ("models--" + repo_id.replace("/", "--"))

    def model_snapshot_dir(self, repo_id: str, commit_sha: str) -> Path:
        """``hub/models--{org}--{name}/snapshots/{commit}`` (config.zig:97-113)."""
        return self.model_cache_dir(repo_id) / "snapshots" / commit_sha

    def model_refs_dir(self, repo_id: str) -> Path:
        return self.model_cache_dir(repo_id) / "refs"

    def xorb_cache_dir(self) -> Path:
        return self.cache_dir / "xorbs"

    def xorb_cache_path(self, hash_hex: str) -> Path:
        """``xorbs/{2-char prefix}/{hash}`` (config.zig:116-123)."""
        return self.xorb_cache_dir() / hash_hex[:2] / hash_hex

    def chunk_cache_dir(self) -> Path:
        return self.cache_dir / "chunks"

    def chunk_cache_path(self, hash_hex: str) -> Path:
        """``chunks/{2-char prefix}/{hash}`` (config.zig:126-133)."""
        return self.chunk_cache_dir() / hash_hex[:2] / hash_hex

    def pid_file(self) -> Path:
        return self.cache_dir / "zest.pid"

    def http_port_file(self) -> Path:
        """Where the daemon records the HTTP port it actually bound.

        ``http_port`` may be 0 ("bind ephemeral" — the test/fixture
        convention); status/stop/client must then discover the real
        port from this file rather than dialing port 0."""
        return self.cache_dir / "zest.http_port"

    def effective_http_port(self) -> int:
        """The daemon's actual HTTP port.

        A concrete configured port always wins — the record file must
        never shadow an explicit ``--http-port``/``ZEST_HTTP_PORT``
        (documented precedence: defaults < env < flags). Only the
        ephemeral convention (``http_port == 0``) consults the record
        the daemon wrote; a stale record then degrades to a failed
        health check — exactly the pid-file staleness model."""
        if self.http_port != 0:
            return self.http_port
        try:
            return int(self.http_port_file().read_text().strip())
        except (OSError, ValueError):
            return self.http_port
