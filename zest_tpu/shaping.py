"""Link shaping: the shared token-bucket rate limiter.

One implementation for every plane that needs a byte-rate bound:

- the seeding server's upload policy (``transfer.server.BtServer`` —
  ``ZEST_SEED_RATE_BPS`` global + ``ZEST_SEED_PEER_BPS`` per-peer);
- the fixture hub's WAN-shaped CDN data plane (``tests/fixtures.py``
  re-exports :class:`TokenBucket`; ``scripts/fixture_hub.py`` and the
  multihost harness ride that knob);
- the swarm capacity bench (``bench_scale.bench_swarm``), where shaped
  CDN + shaped seeders together form the fleet-scale chaos model.

Proven in ``tests/fixtures.py`` first (PR 6's shaped-CDN bench), then
promoted here so the serving hot path and the benches stop importing a
test fixture for production behavior.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    """Thread-safe global token bucket: ``rate_bps`` bytes/second shared
    by every caller of :meth:`acquire`.

    Models a WAN-shaped origin or a bounded upload allocation: N
    concurrent streams share the rate instead of each getting it —
    exactly the asymmetry the reference's tier-3 scenarios measure P2P
    against (DESIGN.md scenario table). Short bursts up to ~250 ms of
    rate are allowed so framing overhead doesn't distort small
    responses; ``capacity`` overrides the burst size."""

    def __init__(self, rate_bps: int, capacity: int | None = None):
        self.rate = max(1, int(rate_bps))
        self.capacity = (max(1, int(capacity)) if capacity is not None
                         else max(64 * 1024, self.rate // 4))
        self.tokens = float(self.capacity)
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def _debit_locked(self, n: int) -> float:
        """Take ``n`` tokens; returns the seconds the caller must wait
        for the bucket to be non-negative again (0.0 = no wait)."""
        now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self._t) * self.rate)
        self._t = now
        self.tokens -= n
        return -self.tokens / self.rate if self.tokens < 0 else 0.0

    def acquire(self, n: int, give_up_at: float | None = None) -> bool:
        """Debit ``n`` bytes and sleep out the induced wait.

        ``give_up_at`` (``time.monotonic()`` deadline) bounds the sleep:
        when honoring the rate would overrun the deadline, the debit is
        ROLLED BACK and False is returned — the caller (e.g. an upload
        holding a serving slot) aborts instead of pinning the slot past
        its request deadline. Unbounded callers always get True."""
        with self._lock:
            wait = self._debit_locked(n)
            if (give_up_at is not None and wait > 0
                    and time.monotonic() + wait > give_up_at):
                self.tokens += n  # roll back: the bytes were never sent
                return False
        if wait > 0:
            time.sleep(wait)
        return True

    def refund(self, n: int) -> None:
        """Return ``n`` tokens debited for bytes that were never sent —
        a caller holding debits from MULTIPLE buckets (per-peer then
        global) must undo the ones that succeeded when a later one
        gives up, or the peer carries phantom debt across requests."""
        with self._lock:
            self.tokens = min(float(self.capacity), self.tokens + n)
