"""Process-wide metrics registry: counters, gauges, histograms.

The pre-telemetry repo kept counters wherever they were born —
``FetchStats`` on the bridge, ``SwarmStats`` on the swarm, the fault
injector's ``fired`` dict, the HBM cache's hit/miss ints — and
``transfer/pull.py`` hand-assembled every view of them. Those
per-session objects stay (they are the per-pull report and many tests'
contract); this registry is the **process-wide** aggregation they now
mirror into, so a long-lived daemon can answer "what has this host done
across every pull" without pull owning the bookkeeping, and a scrape
surface (``GET /v1/metrics``, Prometheus text exposition format) exists
for fleet collection.

Zero dependencies, thread-safe, label sets as ordered tuples. Writes
are gated on :func:`zest_tpu.telemetry.state.enabled` — with
``ZEST_TELEMETRY=0`` every ``inc``/``set``/``observe`` is one flag
check.

Collectors: live state (cache occupancy, quarantine lists) shouldn't be
event-mirrored — register a ``fn(registry)`` collector and it runs at
scrape/snapshot time, setting gauges from the live object it closed
over.
"""

from __future__ import annotations

import math
import threading
import warnings

from zest_tpu.telemetry import state

# Prometheus default buckets suit request latencies; pull stages span
# ms..minutes, so stretch the tail.
DEFAULT_BUCKETS = (0.005, 0.02, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0)

_METRIC_KINDS = ("counter", "gauge", "histogram")


class MetricError(ValueError):
    """Registration conflict (same name, different kind/labels) — fail
    loud: two call sites silently sharing a mistyped metric would
    corrupt both series."""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = "",
                 labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help_text = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}

    def _key(self, labels: dict) -> tuple[str, ...]:
        if labels and set(labels) - set(self.labelnames):
            extra = sorted(set(labels) - set(self.labelnames))
            raise MetricError(
                f"{self.name}: unknown label(s) {extra}; "
                f"declared {list(self.labelnames)}")
        return tuple(str(labels.get(n, "")) for n in self.labelnames)

    def samples(self) -> list[tuple[dict, float]]:
        with self._lock:
            items = list(self._values.items())
        return [
            (dict(zip(self.labelnames, key)), value)
            for key, value in items
        ]

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if not state.enabled():
            return
        if amount < 0:
            raise MetricError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not state.enabled():
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        if not state.enabled():
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str = "",
                 labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, labelnames)
        self.buckets = tuple(sorted(buckets))
        # key → [bucket_counts..., +Inf count, sum]
        self._hist: dict[tuple[str, ...], list[float]] = {}

    def observe(self, value: float, **labels) -> None:
        if not state.enabled():
            return
        key = self._key(labels)
        with self._lock:
            row = self._hist.get(key)
            if row is None:
                row = self._hist[key] = [0.0] * (len(self.buckets) + 2)
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    row[i] += 1
            row[-2] += 1          # +Inf / count
            row[-1] += value      # sum

    def samples(self) -> list[tuple[dict, float]]:
        """Count per labelset (the scalar view for /v1/status)."""
        with self._lock:
            items = list(self._hist.items())
        return [
            (dict(zip(self.labelnames, key)), row[-2])
            for key, row in items
        ]

    def rows(self) -> list[tuple[tuple[str, ...], list[float]]]:
        with self._lock:
            return [(k, list(v)) for k, v in self._hist.items()]

    def clear(self) -> None:
        with self._lock:
            self._values.clear()
            self._hist.clear()


class MetricsRegistry:
    """Name → metric, plus scrape-time collectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list = []

    def _get_or_create(self, cls, name: str, help_text: str,
                       labelnames, **kwargs):
        labelnames = tuple(labelnames or ())
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (existing.kind != cls.kind
                        or existing.labelnames != labelnames):
                    raise MetricError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{list(existing.labelnames)}, "
                        f"requested {cls.kind}{list(labelnames)}")
                return existing
            metric = cls(name, help_text, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str = "",
                labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name: str, help_text: str = "", labelnames=(),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labelnames,
                                   buckets=buckets)

    def add_collector(self, fn) -> None:
        """``fn(registry)`` runs before every render/snapshot — the hook
        live-state surfaces (cache occupancy, peer health) use to set
        gauges at scrape time instead of mirroring every mutation."""
        with self._lock:
            self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 - scrape must not 500 on one
                pass

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # ── Exposition ──

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4): HELP/TYPE headers
        and one escaped sample line per labelset."""
        self._run_collectors()
        out: list[str] = []
        for metric in sorted(self.metrics(), key=lambda m: m.name):
            out.append(f"# HELP {metric.name} "
                       f"{_escape_help(metric.help_text)}")
            out.append(f"# TYPE {metric.name} {metric.kind}")
            if isinstance(metric, Histogram):
                for key, row in sorted(metric.rows()):
                    base = dict(zip(metric.labelnames, key))
                    for i, ub in enumerate(metric.buckets):
                        out.append(_sample(
                            f"{metric.name}_bucket",
                            {**base, "le": _fmt_float(ub)}, row[i]))
                    out.append(_sample(f"{metric.name}_bucket",
                                       {**base, "le": "+Inf"}, row[-2]))
                    out.append(_sample(f"{metric.name}_sum", base, row[-1]))
                    out.append(_sample(f"{metric.name}_count", base,
                                       row[-2]))
            else:
                for labels, value in sorted(
                        metric.samples(), key=lambda s: sorted(s[0].items())):
                    out.append(_sample(metric.name, labels, value))
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-friendly dump for ``/v1/status`` / ``zest stats``:
        ``{name: {kind, samples: [{labels, value}]}}``."""
        self._run_collectors()
        doc: dict = {}
        for metric in self.metrics():
            doc[metric.name] = {
                "kind": metric.kind,
                "samples": [
                    {"labels": labels, "value": value}
                    for labels, value in metric.samples()
                ],
            }
        return doc

    def reset(self) -> None:
        """Zero every metric's samples and drop collectors (tests).

        Metric OBJECTS survive: hot-path modules hold module-level
        handles created at import (``_M_XORBS = telemetry.counter(...)``)
        — dropping the registry entries would orphan those handles from
        the rendered output while they kept counting into the void."""
        with self._lock:
            metrics = list(self._metrics.values())
            self._collectors.clear()
        for m in metrics:
            m.clear()


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    out = repr(float(v))
    return out[:-2] if out.endswith(".0") else out


def _fmt_value(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _sample(name: str, labels: dict, value: float) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{inner}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


# ── The process registry + convenience constructors ──

REGISTRY = MetricsRegistry()


def counter(name: str, help_text: str = "", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help_text, labelnames)


def gauge(name: str, help_text: str = "", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help_text, labelnames)


def histogram(name: str, help_text: str = "", labelnames=(),
              buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help_text, labelnames, buckets)


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


# ── Allowlisted counter merging (the _PipelinedWarm.summary contract) ──

_warned_unsummed: set[tuple[str, str]] = set()
_warned_lock = threading.Lock()


def sum_allowlisted(dicts, allow: frozenset | set, skip=(),
                    context: str = "") -> tuple[dict, list[str]]:
    """Sum the allowlisted additive counters across ``dicts``; unknown
    numeric keys are returned (sorted) instead of summed — and each new
    one raises a **one-time** ``RuntimeWarning`` plus a registry counter
    bump, so a newly added counter that nobody allowlisted shows up in
    CI output and on ``/v1/metrics`` instead of silently vanishing from
    the merged stats (the old inline merge dropped them with no signal
    beyond an ``unsummed_keys`` list nothing asserted on)."""
    sums: dict = {}
    unknown: set[str] = set()
    for d in dicts:
        for k, v in d.items():
            if k in skip or isinstance(v, bool) \
                    or not isinstance(v, (int, float)):
                continue
            if k in allow:
                sums[k] = sums.get(k, 0) + v
            else:
                unknown.add(k)
    for k in unknown:
        mark = (context, k)
        with _warned_lock:
            if mark in _warned_unsummed:
                continue
            _warned_unsummed.add(mark)
        counter(
            "zest_unsummed_counter_keys_total",
            "Numeric counter keys dropped from an allowlisted merge",
            ("context", "key"),
        ).inc(context=context, key=k)
        warnings.warn(
            f"{context or 'counter merge'}: numeric key {k!r} is not in "
            f"the additive-counter allowlist {sorted(allow)}; it was NOT "
            "summed (listed under unsummed_keys). Allowlist it if it is "
            "additive.",
            RuntimeWarning,
            stacklevel=2,
        )
    return sums, sorted(unknown)
