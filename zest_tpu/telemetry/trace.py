"""Span tracer: nested wall-clock spans → Chrome/Perfetto trace JSON.

The pull path is a pipeline of overlapping stages across many threads
(file workers, the landing's staging thread, hedge racers, warm-fetch
lookahead) — exactly the shape ``stats["stages"]`` scalars flatten away
and a trace viewer renders directly. ``span("swarm.fetch", xorb=...)``
records one complete event per exit: name, wall interval, thread, and
attributes (plus byte counts via :meth:`Span.add_bytes`), serialized as
``trace_event`` *X* (complete) events that chrome://tracing and Perfetto
nest by containment per thread track.

Activation mirrors :mod:`zest_tpu.faults`: lazy env resolution
(``ZEST_TRACE=path`` arms a process-global tracer whose file is written
at interpreter exit), ``install()``/``reset()`` for tests and the
``zest trace`` CLI, and a shared no-op span when tracing is off — the
hot path pays one global load and a ``None`` check.

Memory: one small record per finished span. A 2 GB pull emits a few
thousand spans (per-term fetches dominate), single-digit MB; the tracer
also hard-caps recorded spans (:data:`MAX_SPANS`) so a pathological
caller cannot turn the trace buffer into a leak — the drop is counted
and reported in the exported JSON rather than silently truncated.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

from zest_tpu.telemetry import state

ENV_TRACE = "ZEST_TRACE"

# Hard cap on buffered spans per tracer (drops are counted, not silent).
MAX_SPANS = 500_000

# ── Trace context (fleet correlation, ISSUE 7) ──
#
# A pod-scale pull is N processes emitting N traces; what correlates
# them is a shared ``trace_id`` plus a per-process ``host`` index
# stamped on every span. Two scopes:
#
# - the *process* context (``set_context``): one host = one process in
#   production, so stamping happens once at export time — zero per-span
#   cost on the hot path;
# - a *thread* context (``context()`` manager / ``use_context``): the
#   in-process multi-host simulations (tests, the 8-device dryrun
#   smoke) run each "host" as a thread of one process; their spans are
#   stamped at record time so the merged trace can still split them
#   into per-host tracks. Threads spawned inside a round must inherit
#   explicitly (``current_context()`` → ``use_context``) — Python
#   thread-locals do not propagate.

_base_context: dict = {}
_tls = threading.local()


def set_context(**attrs) -> None:
    """Merge ``attrs`` into the process-global trace context (stamped on
    every exported event and recorded in the trace metadata). A value of
    ``None`` removes the key."""
    for k, v in attrs.items():
        if v is None:
            _base_context.pop(k, None)
        else:
            _base_context[k] = v


def clear_context() -> None:
    _base_context.clear()
    _tls.ctx = {}


def current_context() -> dict:
    """Effective context for this thread: process base < thread overlay.
    Pass the result to :func:`use_context` in worker threads a traced
    round spawns."""
    out = dict(_base_context)
    out.update(getattr(_tls, "ctx", None) or {})
    return out


def base_context() -> dict:
    """Snapshot of the process-global context (for save/restore around
    a scope that installs its own — pull_model restores the previous
    context at exit so a daemon's NEXT pull never exports under a
    stale trace_id)."""
    return dict(_base_context)


def replace_context(ctx: dict) -> None:
    """Replace the process-global context wholesale (the restore half
    of :func:`base_context`)."""
    _base_context.clear()
    _base_context.update(ctx or {})


def use_context(ctx: dict | None) -> None:
    """Replace this thread's context overlay (worker-thread inheritance)."""
    _tls.ctx = dict(ctx) if ctx else {}


class context:
    """Thread-local context overlay for a ``with`` block (simulated
    hosts; restores the previous overlay on exit)."""

    def __init__(self, **attrs):
        self._attrs = attrs
        self._prev: dict | None = None

    def __enter__(self) -> "context":
        self._prev = getattr(_tls, "ctx", None) or {}
        merged = dict(self._prev)
        merged.update(self._attrs)
        _tls.ctx = merged
        return self

    def __exit__(self, *exc) -> None:
        _tls.ctx = self._prev or {}


def open_spans() -> tuple[str, ...]:
    """Names of the spans currently open on THIS thread, outermost
    first — the flight recorder stamps events with this to anchor them
    in the trace without holding span references."""
    return tuple(s.name for s in getattr(_tls, "stack", ()) or ())


class Span:
    """One finished (or in-flight) span. Context-manager protocol; the
    ``with`` target supports ``set(key, value)`` / ``add_bytes(n)`` so
    call sites can attach results discovered mid-span (bytes served,
    source tier, error class)."""

    __slots__ = ("name", "attrs", "t0", "t1", "tid", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.t1 = 0.0
        self.tid = 0

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def add_bytes(self, n: int) -> None:
        self.attrs["bytes"] = self.attrs.get("bytes", 0) + int(n)

    def __enter__(self) -> "Span":
        self.t0 = time.monotonic()
        self.tid = threading.get_ident()
        # Context stamp at RECORD time (base < thread overlay; explicit
        # attrs win, so the overlay must stamp before the base): the
        # span keeps the identity that was true when it ran, and a
        # daemon clearing the context after one pull cannot
        # retroactively restamp (or unstamp) earlier spans at export.
        tctx = getattr(_tls, "ctx", None)
        if tctx:
            for k, v in tctx.items():
                self.attrs.setdefault(k, v)
        if _base_context:
            for k, v in _base_context.items():
                self.attrs.setdefault(k, v)
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        self.t1 = time.monotonic()
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack:  # defensive: out-of-order exit must not wedge the stack
            try:
                stack.remove(self)
            except ValueError:
                pass
        if exc_type is not None:
            # The error *class* only: messages can carry URLs/paths and
            # the trace file may be shared more widely than logs.
            self.attrs["error"] = exc_type.__name__
        self._tracer._record(self)


class _NullSpan:
    """Shared no-op span: tracing disabled costs one attribute load."""

    __slots__ = ()

    def set(self, key: str, value) -> None:
        pass

    def add_bytes(self, n: int) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe span recorder for one process (normally one pull)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self.dropped = 0
        # Anchors mapping monotonic span times to an absolute epoch, so
        # traces from several hosts of one pod can be laid side by side.
        self.t_origin = time.monotonic()
        self.epoch_origin = time.time()
        # Free-form export metadata (clock-offset estimates, peer maps):
        # merged into the exported doc's ``otherData``.
        self.metadata: dict = {}

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def add_metadata(self, **kv) -> None:
        with self._lock:
            for k, v in kv.items():
                self.metadata[k] = v

    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) >= MAX_SPANS:
                self.dropped += 1
            else:
                self._spans.append(span)
                return
        # Outside the lock: the overflow used to be invisible outside
        # the process — now it is a first-class metric (ISSUE 7
        # satellite) a fleet scrape can alert on.
        from zest_tpu.telemetry import metrics as _metrics

        _metrics.counter(
            "zest_trace_spans_dropped_total",
            "Spans dropped at the tracer's MAX_SPANS ring bound",
        ).inc()

    # ── Introspection ──

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def coverage_s(self, prefix: str | None = None) -> float:
        """Union wall-clock coverage of recorded spans (optionally only
        those whose name starts with ``prefix``) — the acceptance
        denominator: a trace is useful when its spans cover ~all of the
        pull's wall time, not a sliver of it."""
        with self._lock:
            ivs = sorted(
                (s.t0, s.t1) for s in self._spans
                if prefix is None or s.name.startswith(prefix)
            )
        total, end = 0.0, float("-inf")
        for s, e in ivs:
            if s > end:
                total += e - s
                end = e
            elif e > end:
                total += e - end
                end = e
        return total

    # ── Export (Chrome trace_event JSON) ──

    def to_chrome(self) -> dict:
        """``{"traceEvents": [...]}`` — the Trace Event Format's JSON
        object form. Spans become ``ph: "X"`` complete events with
        microsecond ``ts``/``dur``; viewers nest same-track events by
        containment, which matches how our spans actually nest (a span
        opened inside another on the same thread closes inside it)."""
        pid = os.getpid()
        base = dict(_base_context)
        pname = "zest-tpu"
        if "host" in base:
            pname = f"zest-tpu host {base['host']}"
        events: list[dict] = [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": pname}},
        ]
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
            metadata = dict(self.metadata)
        for s in spans:
            ev = {
                "name": s.name,
                "ph": "X",
                "ts": round((s.t0 - self.t_origin) * 1e6, 1),
                "dur": round((s.t1 - s.t0) * 1e6, 1),
                "pid": pid,
                "tid": s.tid,
                "cat": s.name.split(".", 1)[0],
            }
            # Context attrs (trace_id/host) were stamped at RECORD time
            # (Span.__enter__) — stamping here instead would let a
            # context installed later claim spans that ran before it.
            if s.attrs:
                ev["args"] = {k: _jsonable(v) for k, v in s.attrs.items()}
            events.append(ev)
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "zest-tpu",
                "epoch_origin_s": round(self.epoch_origin, 6),
                "spans": len(spans),
            },
        }
        if base:
            doc["otherData"]["context"] = {
                k: _jsonable(v) for k, v in base.items()}
        if metadata:
            for k, v in metadata.items():
                doc["otherData"][k] = _jsonable_deep(v)
        if dropped:
            doc["otherData"]["dropped_spans"] = dropped
        return doc

    def export(self, path: str | os.PathLike) -> int:
        """Write the Chrome trace JSON; returns the span count written.
        Atomic (tmp + rename): a reader racing the atexit hook must see
        either nothing or a complete valid trace, never a prefix."""
        doc = self.to_chrome()
        path = os.fspath(path)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(doc["traceEvents"]) - 1  # minus the metadata event


# ── Module-level switchboard (lazy env parse, test override) ──

_lock = threading.Lock()
_tracer: Tracer | None = None
_trace_path: str | None = None
_resolved = False
_atexit_armed = False


def _arm_atexit() -> None:
    global _atexit_armed
    if not _atexit_armed:
        _atexit_armed = True
        atexit.register(_export_at_exit)


def _export_at_exit() -> None:
    tracer, path = _tracer, _trace_path
    if tracer is not None and path:
        try:
            tracer.export(path)
        except OSError:
            pass  # interpreter teardown: nowhere sane to report


def install(path: str | None = None) -> Tracer:
    """Install a fresh process tracer (CLI/tests). ``path`` arms the
    atexit export; callers may also :func:`export` explicitly."""
    global _tracer, _trace_path, _resolved
    with _lock:
        _resolved = True
        _tracer = Tracer()
        _trace_path = path
        if path:
            _arm_atexit()
        return _tracer


def uninstall() -> None:
    """Disable tracing (no export). Tests."""
    global _tracer, _trace_path, _resolved
    with _lock:
        _resolved = True
        _tracer = None
        _trace_path = None


def reset() -> None:
    """Back to unresolved: the next ``span()`` re-reads ``ZEST_TRACE``."""
    global _tracer, _trace_path, _resolved
    with _lock:
        _tracer = None
        _trace_path = None
        _resolved = False


def active() -> Tracer | None:
    global _tracer, _resolved, _trace_path
    if _resolved:
        return _tracer
    with _lock:
        if not _resolved:
            path = os.environ.get(ENV_TRACE)
            if path and state.enabled():
                _tracer = Tracer()
                _trace_path = path
                _arm_atexit()
            _resolved = True
    return _tracer


def trace_path() -> str | None:
    active()  # resolve env first
    return _trace_path


def span(name: str, **attrs):
    """The hot-path hook: the shared no-op span unless a tracer is
    armed (``ZEST_TRACE``/:func:`install`) and telemetry is enabled."""
    tracer = _tracer
    if tracer is None:
        if _resolved:
            return NULL_SPAN
        tracer = active()
        if tracer is None:
            return NULL_SPAN
    if not state.enabled():
        return NULL_SPAN
    return tracer.span(name, **attrs)


def export(path: str | os.PathLike) -> int:
    """Export the active tracer's spans; 0 when tracing is off."""
    tracer = active()
    return tracer.export(path) if tracer is not None else 0


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def _jsonable_deep(v):
    """Metadata values can be small nested maps (per-peer clock
    offsets); stringify only the leaves."""
    if isinstance(v, dict):
        return {str(k): _jsonable_deep(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable_deep(x) for x in v]
    return _jsonable(v)
