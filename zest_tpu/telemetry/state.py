"""Process-wide telemetry on/off switch (``ZEST_TELEMETRY``).

One flag gates every telemetry surface — span recording, metric
mirroring, trace export — so the knob-off contract is checkable at a
single point: with ``ZEST_TELEMETRY=0`` the hot path pays one module
load and one ``if`` per call site, nothing else (same zero-cost
discipline as :mod:`zest_tpu.faults`).

Default is ON: the metrics registry is a handful of dict bumps per
fetch (micro-benched far under the 1%% pull budget), and a daemon that
starts with telemetry off can never answer ``/v1/metrics`` usefully.
Tracing has its own opt-in (``ZEST_TRACE=path``) because it accumulates
per-span records for the life of the pull.
"""

from __future__ import annotations

import os
import threading

ENV_TELEMETRY = "ZEST_TELEMETRY"

_OFF_VALUES = frozenset({"0", "false", "off", "no"})

_lock = threading.Lock()
_enabled: bool | None = None  # None = not yet resolved from env


def enabled() -> bool:
    """The hot-path gate: one global load in the common (resolved) case."""
    global _enabled
    on = _enabled
    if on is not None:
        return on
    with _lock:
        if _enabled is None:
            raw = os.environ.get(ENV_TELEMETRY, "").strip().lower()
            _enabled = raw not in _OFF_VALUES
        return _enabled


def set_enabled(on: bool | None) -> None:
    """Test/CLI override; ``None`` returns to env resolution."""
    global _enabled
    with _lock:
        _enabled = on


def reset() -> None:
    """Back to unresolved: the next ``enabled()`` re-reads the env."""
    set_enabled(None)
