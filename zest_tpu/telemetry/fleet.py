"""Fleet observability: cross-host trace correlation + pod metrics.

PR 4's telemetry is strictly per-process: an 8-host cooperative pull
emits 8 disconnected Perfetto traces and 8 ``/v1/metrics`` islands, so
the pod-scale questions — which host is the straggler, where did
``peer_served_ratio`` erode, why did one host fall back to CDN — need
ssh-and-grep. This module is the correlation layer:

- **Trace identity** (:func:`mint_trace_id`): a 16-byte id every host
  of one pull derives identically (``repo@sha`` + a nonce shared over
  the jax KV store, or the ownership-plan fingerprint when addresses
  are explicit — both are common knowledge across the pod by
  construction), stamped on every span via the trace context and
  carried to peers in the DCN hello (transfer.dcn).
- **Trace merging** (:func:`merge_traces`): N per-host Chrome trace
  docs → ONE Perfetto file with a process track per host, timelines
  normalized onto the reference host's clock (epoch anchors corrected
  by the DCN-hello offset estimates, §"Clock normalization" below),
  and client→server flow events binding each ``dcn.request_many``
  window span to the ``dcn.serve`` spans that answered it.
- **Pod metrics aggregation** (:func:`aggregate_prometheus`): N hosts'
  Prometheus texts → one exposition where counters and histograms are
  summed, gauges are labeled ``{host="i"}``, plus derived pod gauges
  (``zest_coop_straggler_seconds``, fetch-share skew, the swarm-wide
  peer-served ratio). Served by the coordinator's daemon at
  ``GET /v1/metrics?scope=pod``.

Clock normalization: hosts' wall clocks are close (NTP) but not equal,
and a merged trace that interleaves two hosts' DCN spans by raw wall
time can show an effect before its cause. Every DCN hello measures a
peer clock-offset estimate: the peer's hello block carries its wall
time at send; the requester reads it within one hello round-trip of
sending its own, so ``offset ≈ peer_epoch − (local_epoch − rtt/2)``
with error bounded by ±rtt/2 (the classic NTP single-exchange bound —
loopback ~µs, DCN ~100 µs, far under span durations). Each host
records its per-peer estimates in its trace metadata; the merge shifts
every host onto the reference host's clock using the reference's
estimate of that host (or the host's own estimate of the reference,
negated), falling back to raw epoch anchors when neither exists.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
import statistics
import threading
import urllib.request

__all__ = [
    "mint_trace_id",
    "merge_traces",
    "split_hosts",
    "gather_traces",
    "parse_prometheus",
    "aggregate_prometheus",
]


def mint_trace_id(pull_key: str, nonce: str = "") -> str:
    """16-byte trace id (32 hex chars) for one cooperative pull.

    Derived, not random: every host must mint the SAME id with no
    extra coordination round. ``pull_key`` is ``repo@sha`` (or the
    ownership-plan fingerprint for a bare ``coop_round``); ``nonce``
    disambiguates repeated pulls of the same revision when the KV
    store is available to share one (pull.py announces it alongside
    the DCN addrs)."""
    return hashlib.blake2b(
        f"zest-trace|{pull_key}|{nonce}".encode(), digest_size=16
    ).hexdigest()


# ── Trace merging ──


def split_hosts(doc: dict, default_host=0) -> dict:
    """Split one trace doc into per-host docs by each span's ``host``
    attr (events without one belong to ``default_host``) — the
    in-process multi-host simulations (tests, the dryrun smoke) record
    every simulated host into one process tracer; this recovers the
    per-host docs :func:`merge_traces` consumes."""
    out: dict = {}
    meta = doc.get("otherData", {})
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        host = ev.get("args", {}).get("host", default_host)
        out.setdefault(host, []).append(ev)
    return {
        host: {"traceEvents": events, "otherData": dict(meta)}
        for host, events in out.items()
    }


def _host_offset_s(host, ref, docs: dict) -> float | None:
    """Estimated (host clock − reference clock), from the hello-RTT
    measurements either side recorded (see module docstring)."""
    if host == ref:
        return 0.0
    ref_meta = docs[ref].get("otherData", {}).get("clock_offsets", {})
    est = ref_meta.get(str(host), ref_meta.get(host))
    if isinstance(est, dict) and "offset_s" in est:
        return float(est["offset_s"])
    own_meta = docs[host].get("otherData", {}).get("clock_offsets", {})
    est = own_meta.get(str(ref), own_meta.get(ref))
    if isinstance(est, dict) and "offset_s" in est:
        return -float(est["offset_s"])
    return None


def merge_traces(host_docs: dict, reference=None) -> dict:
    """Merge per-host Chrome trace docs into one multi-track doc.

    ``host_docs`` maps a host key (index or label) → a trace doc
    (:meth:`Tracer.to_chrome` output or a loaded export). Each host
    becomes its own process track (synthetic pid, ``process_name``
    metadata), timelines are normalized per the module docstring, and
    ``dcn.request_many`` ↔ ``dcn.serve`` spans are bound with flow
    events. ``reference`` picks the clock hosts are normalized onto
    (default: the smallest host key — the coordinator)."""
    if not host_docs:
        raise ValueError("no traces to merge")
    keys = sorted(host_docs, key=str)
    if reference is None:
        reference = keys[0]

    # Per-host epoch anchor corrected by the measured clock offset.
    anchors: dict = {}
    clock_meta: dict = {}
    for host in keys:
        meta = host_docs[host].get("otherData", {})
        epoch = float(meta.get("epoch_origin_s", 0.0))
        offset = _host_offset_s(host, reference, host_docs)
        anchors[host] = epoch - (offset or 0.0)
        clock_meta[str(host)] = {
            "epoch_origin_s": round(epoch, 6),
            "applied_offset_s": (None if offset is None
                                 else round(offset, 6)),
        }
    base = min(anchors.values())

    events: list[dict] = []
    trace_ids: set = set()
    # (client_host, flow_tag) → client event | server events, for flows.
    clients: dict = {}
    servers: dict = {}
    for i, host in enumerate(keys):
        pid = 1000 + i
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"host {host}"
                     + (" (reference clock)" if host == reference else "")},
        })
        shift_us = (anchors[host] - base) * 1e6
        for ev in host_docs[host].get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            out = dict(ev)
            out["pid"] = pid
            out["ts"] = round(ev.get("ts", 0.0) + shift_us, 1)
            args = out.get("args", {})
            args.setdefault("host", host)
            out["args"] = args
            tid_ = args.get("trace_id")
            if tid_:
                trace_ids.add(tid_)
            events.append(out)
            if ev.get("name") == "dcn.request_many" \
                    and args.get("flow_tag") is not None:
                clients[(str(host), int(args["flow_tag"]))] = out
            elif ev.get("name") == "dcn.serve" \
                    and args.get("client_host") is not None \
                    and args.get("tag") is not None:
                servers.setdefault(
                    (str(args["client_host"]), int(args["tag"])), []
                ).append(out)

    # Flow events: ``s`` bound inside the client window span, ``f``
    # (binding point "e"=enclosing) inside each serve span. Binding is
    # by (pid, tid, ts-inside-slice) per the trace-event format.
    links = 0
    for key, cl in clients.items():
        srvs = servers.get(key)
        if not srvs:
            continue
        fid = int.from_bytes(hashlib.blake2b(
            repr(key).encode(), digest_size=4).digest(), "big")
        events.append({
            "ph": "s", "id": fid, "name": "dcn", "cat": "dcn",
            "pid": cl["pid"], "tid": cl["tid"],
            "ts": round(cl["ts"] + min(1.0, cl.get("dur", 0) / 2), 1),
        })
        for sv in srvs:
            events.append({
                "ph": "f", "bp": "e", "id": fid, "name": "dcn",
                "cat": "dcn", "pid": sv["pid"], "tid": sv["tid"],
                "ts": round(sv["ts"] + min(1.0, sv.get("dur", 0) / 2), 1),
            })
            links += 1

    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "zest-tpu",
            "merged_hosts": [str(k) for k in keys],
            "reference_host": str(reference),
            "epoch_base_s": round(base, 6),
            "flow_links": links,
            "clock_normalization": clock_meta,
        },
    }
    if trace_ids:
        doc["otherData"]["trace_ids"] = sorted(trace_ids)
    return doc


def host_coverage_s(doc: dict, host, root_name: str | None = None):
    """(union coverage seconds, root span seconds) of one host's track
    in a merged doc — the per-host acceptance check (coverage ≥90% of
    the host's root pull/round span). ``root_name`` defaults to the
    host's longest span."""
    evs = [e for e in doc.get("traceEvents", [])
           if e.get("ph") == "X"
           and str(e.get("args", {}).get("host")) == str(host)]
    if not evs:
        return 0.0, 0.0
    if root_name is None:
        root = max(evs, key=lambda e: e.get("dur", 0.0))
    else:
        cands = [e for e in evs if e["name"] == root_name]
        if not cands:
            return 0.0, 0.0
        root = max(cands, key=lambda e: e.get("dur", 0.0))
    ivs = sorted((e["ts"], e["ts"] + e.get("dur", 0.0)) for e in evs)
    total, end = 0.0, float("-inf")
    for s, e in ivs:
        if s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total / 1e6, root.get("dur", 0.0) / 1e6


_SCRAPE_POOL = None
_SCRAPE_POOL_LOCK = threading.Lock()


def scrape_pool(workers: int | None = None):
    """The process-shared bounded executor behind every pod-scope
    scrape fan-out (``/v1/metrics?scope=pod``, ``/v1/timeline?scope=
    pod``, ``gather_traces``). One pool for the whole process — at
    hundreds of peers, concurrent pod-scope requests queue on these
    workers instead of bursting a fresh thread per peer per request
    (ISSUE 16 satellite). Sized on first use: an explicit ``workers``
    (Config.pod_scrape_workers) wins, else ZEST_POD_SCRAPE_WORKERS,
    else 8; later calls reuse the existing pool regardless."""
    from concurrent.futures import ThreadPoolExecutor

    global _SCRAPE_POOL
    with _SCRAPE_POOL_LOCK:
        if _SCRAPE_POOL is None:
            if workers is None:
                raw = os.environ.get("ZEST_POD_SCRAPE_WORKERS", "")
                workers = int(raw) if raw.strip() else 8
            _SCRAPE_POOL = ThreadPoolExecutor(
                max_workers=max(1, int(workers)),
                thread_name_prefix="zest-podscrape")
        return _SCRAPE_POOL


def gather_traces(api_addrs: dict, timeout_s: float = 5.0):
    """Snapshot every host's live tracer over ``GET /v1/trace``.

    ``api_addrs`` maps host key → (host, http_port). Returns
    ``(docs, errors)`` — hosts that fail to answer (daemon down, no
    tracer armed) land in ``errors`` instead of failing the gather;
    a merged trace of the hosts that DID answer is still the operator's
    best artifact. Scrapes run concurrently on the shared bounded
    :func:`scrape_pool`: N dead peers must cost one timeout, not N."""

    def scrape(item):
        key, (host, port) = item
        url = f"http://{host}:{port}/v1/trace"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                doc = json.loads(r.read().decode())
        except Exception as exc:  # noqa: BLE001 - per-host, reported
            return key, None, str(exc)
        if not doc.get("traceEvents"):
            return key, None, "empty trace (tracer not armed?)"
        return key, doc, None

    docs: dict = {}
    errors: dict = {}
    items = sorted(api_addrs.items(), key=lambda i: str(i))
    if not items:
        return docs, errors
    for key, doc, err in scrape_pool().map(scrape, items):
        if doc is not None:
            docs[key] = doc
        else:
            errors[key] = err
    return docs, errors


# ── Pod metrics aggregation ──

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? '
    r'(-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def parse_prometheus(text: str) -> dict:
    """Parse exposition text → ``{name: {"kind", "help", "samples":
    {labeltuple: value}}}``. Histogram/summary series parse under their
    sample names (``x_bucket``/``x_sum``/``x_count``) with the base
    name's TYPE recorded, which is exactly what additive re-summing
    needs. Unparseable lines raise — aggregating a half-read host would
    silently under-count the pod."""
    out: dict = {}
    kinds: dict = {}
    helps: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            helps[name] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            kinds[name] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"malformed sample line: {line!r}")
        name, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            leftover = _LABEL_RE.sub("", labelstr).strip(", ")
            if leftover:
                raise ValueError(f"malformed labels: {labelstr!r}")
            for lm in _LABEL_RE.finditer(labelstr):
                labels[lm.group(1)] = _unescape(lm.group(2))
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in kinds:
                base = name[:-len(suffix)]
                break
        v = {"+Inf": math.inf, "-Inf": -math.inf}.get(value)
        if v is None:
            v = float("nan") if value == "NaN" else float(value)
        entry = out.setdefault(name, {
            "kind": kinds.get(base, "untyped"),
            "help": helps.get(base, ""),
            "samples": {},
        })
        entry["samples"][tuple(sorted(labels.items()))] = v
    return out


_ADDITIVE_KINDS = frozenset({"counter", "histogram"})


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _line(name: str, labels: dict, value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape(v)}"'
                         for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def aggregate_prometheus(host_texts: dict, errors: dict | None = None) -> str:
    """N hosts' Prometheus texts → one pod-scope exposition.

    ``host_texts`` maps host label → that host's ``/v1/metrics`` body.
    Counters and histogram series (additive by Prometheus semantics)
    are summed across hosts per labelset; gauges and untyped samples
    keep one sample per host with a ``host`` label (summing a gauge —
    an occupancy, a ratio — would be meaningless). Adds the derived pod
    gauges (see :func:`_derived_pod_samples`) and the scrape health
    gauges (``zest_pod_hosts``, ``zest_pod_scrape_errors{host}``).

    A host whose body does not PARSE (a proxy's HTML error page with a
    200, a truncated stream) is demoted to a scrape error like a host
    that never answered — one flapping peer must not 500 the whole
    pod surface."""
    errors = dict(errors or {})
    parsed = {}
    for label, text in host_texts.items():
        try:
            parsed[label] = parse_prometheus(text)
        except ValueError as exc:
            errors[label] = f"unparseable metrics: {exc}"

    merged: dict = {}  # name → {"kind","help","samples":{labels: value}}
    for label in sorted(parsed, key=str):
        for name, entry in parsed[label].items():
            slot = merged.setdefault(name, {
                "kind": entry["kind"], "help": entry["help"],
                "samples": {},
            })
            if not slot["help"]:
                slot["help"] = entry["help"]
            additive = entry["kind"] in _ADDITIVE_KINDS
            for labelkey, value in entry["samples"].items():
                if additive:
                    slot["samples"][labelkey] = (
                        slot["samples"].get(labelkey, 0.0) + value)
                else:
                    key = tuple(sorted(
                        dict(labelkey, host=str(label)).items()))
                    slot["samples"][key] = value

    for name, help_text, kind, samples in _derived_pod_samples(parsed):
        merged[name] = {"kind": kind, "help": help_text,
                        "samples": samples}

    merged["zest_pod_hosts"] = {
        "kind": "gauge",
        "help": "Hosts aggregated into this pod-scope scrape",
        "samples": {(): float(len(parsed))},
    }
    if errors:
        merged["zest_pod_scrape_errors"] = {
            "kind": "gauge",
            "help": "Pod peers that failed the metrics scrape (1=down)",
            "samples": {
                (("host", str(h)),): 1.0 for h in sorted(errors, key=str)
            },
        }

    out: list[str] = []
    headered: set[str] = set()

    def _header(base: str, help_text: str, kind: str) -> None:
        if base in headered:
            return
        headered.add(base)
        out.append(f"# HELP {base} "
                   + help_text.replace("\\", "\\\\").replace("\n", "\\n"))
        out.append(f"# TYPE {base} {kind}")

    for name in sorted(merged):
        entry = merged[name]
        base = name
        if entry["kind"] == "histogram":
            # TYPE/HELP belong to the base series name, declared once
            # before its first _bucket/_sum/_count sample group.
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    base = name[:-len(suffix)]
                    break
        _header(base, entry["help"], entry["kind"])
        for labelkey in sorted(entry["samples"]):
            out.append(_line(name, dict(labelkey),
                             entry["samples"][labelkey]))
    return "\n".join(out) + "\n"


def _derived_pod_samples(parsed: dict):
    """The pod-level gauges no single host can compute (ISSUE 7):

    - ``zest_coop_straggler_seconds``: slowest-minus-median host
      exchange wall (per-host ``zest_coop_exchange_wall_seconds``);
    - ``zest_coop_fetch_share_skew``: max/mean of per-host coop fetch
      bytes (``zest_coop_fetch_bytes``) — the ownership plan promises
      ≤1.15, so drift here means quarantine re-shards or fallbacks;
    - ``zest_pod_peer_served_ratio``: swarm-wide peer-vs-CDN byte
      ratio over every host's summed ``zest_coop_bytes_total`` tiers
      (fallback bytes count as non-peer: conservative).
    """
    walls, fetch_bytes = [], []
    tiers: dict[str, float] = {}
    for host_doc in parsed.values():
        w = host_doc.get("zest_coop_exchange_wall_seconds")
        if w and w["samples"]:
            walls.append(max(w["samples"].values()))
        fb = host_doc.get("zest_coop_fetch_bytes")
        if fb and fb["samples"]:
            fetch_bytes.append(max(fb["samples"].values()))
        cb = host_doc.get("zest_coop_bytes_total")
        if cb:
            for labelkey, v in cb["samples"].items():
                tier = dict(labelkey).get("tier", "")
                tiers[tier] = tiers.get(tier, 0.0) + v

    out = []
    if walls:
        straggler = max(walls) - statistics.median(walls)
        out.append((
            "zest_coop_straggler_seconds",
            "Slowest-minus-median host cooperative exchange wall",
            "gauge", {(): round(straggler, 6)},
        ))
    if fetch_bytes:
        mean = sum(fetch_bytes) / len(fetch_bytes)
        skew = (max(fetch_bytes) / mean) if mean else 1.0
        out.append((
            "zest_coop_fetch_share_skew",
            "Max-over-mean of per-host cooperative fetch bytes",
            "gauge", {(): round(skew, 6)},
        ))
    if tiers:
        peer = tiers.get("peer", 0.0) + tiers.get("dcn", 0.0)
        total = peer + tiers.get("cdn", 0.0) + tiers.get("fallback", 0.0)
        if total:
            out.append((
                "zest_pod_peer_served_ratio",
                "Swarm-wide fraction of cooperative network bytes "
                "served by peers (fallback counted as non-peer)",
                "gauge", {(): round(peer / total, 6)},
            ))
    return out
