"""zest_tpu.telemetry — process-wide observability for the pull path.

Five pieces, zero dependencies, all thread-safe:

- **Spans** (:mod:`.trace`): ``with telemetry.span("swarm.fetch",
  xorb=h) as sp: ... sp.add_bytes(n)`` — nested wall-clock spans that
  serialize to Chrome/Perfetto ``trace_event`` JSON. Armed by
  ``ZEST_TRACE=path`` (written at exit) or ``zest trace``.
- **Metrics** (:mod:`.metrics`): counters/gauges/histograms with label
  sets in one process registry; the per-session stats objects
  (``FetchStats``, ``SwarmStats``, fault counters, cache hit/miss ints)
  mirror into it, and live state registers scrape-time collectors.
  Exported as Prometheus text on the daemon's ``GET /v1/metrics`` and
  summarized in ``/v1/status`` / ``zest stats``.
- **Fleet correlation** (:mod:`.fleet`): cross-host trace identity
  (``mint_trace_id``), merged multi-track Perfetto traces
  (``merge_traces``) with flow links and clock-offset normalization,
  and the pod-scope Prometheus aggregation behind
  ``GET /v1/metrics?scope=pod``.
- **The flight recorder** (:mod:`.recorder`): a bounded ring of the
  last N notable events (strikes, quarantines, fallbacks, faults,
  verify rejections, budget declines), served at ``GET /v1/debug``
  and dumped as a JSON crash report on pull failure / SIGTERM.
- **Pull sessions** (:mod:`.session`): every pull as a first-class
  observable — a bounded table of live + recent sessions (id,
  repo@sha, tenant, phase, byte progress, ETA, terminal stats) behind
  ``GET /v1/pulls``, its SSE progress stream, and ``zest ps``.
- **Critical-path attribution** (:mod:`.critpath`): the automated
  analyzer over completed trace docs — blame-attributed longest path,
  per-stage/per-tier exclusive seconds, ``stats["critical_path"]``,
  and ``zest analyze``.
- **The switch** (:mod:`.state`): ``ZEST_TELEMETRY=0`` turns the whole
  layer into flag checks; tracing additionally requires ``ZEST_TRACE``.

Import discipline: this package imports nothing from the rest of
``zest_tpu``, so every hot-path module can use it without cycles.
"""

from zest_tpu.telemetry.metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    render_prometheus,
    sum_allowlisted,
)
from zest_tpu.telemetry.state import enabled, set_enabled  # noqa: F401
from zest_tpu.telemetry.trace import (  # noqa: F401
    NULL_SPAN,
    Span,
    Tracer,
    span,
)
from zest_tpu.telemetry import state as _state
from zest_tpu.telemetry import trace as trace  # noqa: PLC0414
from zest_tpu.telemetry import recorder as recorder  # noqa: PLC0414
from zest_tpu.telemetry.recorder import record  # noqa: F401
from zest_tpu.telemetry import session as session  # noqa: PLC0414
from zest_tpu.telemetry import critpath as critpath  # noqa: PLC0414
from zest_tpu.telemetry import timeline as timeline  # noqa: PLC0414
from zest_tpu.telemetry import remediate as remediate  # noqa: PLC0414

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricError",
    "MetricsRegistry",
    "NULL_SPAN",
    "Span",
    "Tracer",
    "counter",
    "critpath",
    "enabled",
    "gauge",
    "histogram",
    "record",
    "recorder",
    "remediate",
    "render_prometheus",
    "reset_all",
    "session",
    "set_enabled",
    "span",
    "status_snapshot",
    "sum_allowlisted",
    "timeline",
    "trace",
]


def status_snapshot() -> dict:
    """The ``telemetry`` block for ``/v1/status``: is the layer on, is a
    trace armed, and how much has been recorded."""
    tracer = trace.active()
    doc: dict = {
        "enabled": enabled(),
        "trace_active": tracer is not None,
        "metrics": len(REGISTRY.metrics()),
    }
    if tracer is not None:
        doc["trace_path"] = trace.trace_path()
        doc["spans"] = len(tracer)
    return doc


def reset_all() -> None:
    """Tests: unresolve the enable flag, drop the tracer + contexts,
    clear metrics, empty the flight recorder."""
    _state.reset()
    trace.reset()
    trace.clear_context()
    REGISTRY.reset()
    recorder.reset()
    session.reset()
    timeline.reset()
    remediate.reset()
