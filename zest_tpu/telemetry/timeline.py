"""Live telemetry timelines: an in-process time-series store with
streaming anomaly detection (ISSUE 15).

Everything the observability stack built so far is either a *point in
time* (gauges, ``/v1/status``, the session snapshot) or an *aggregate
over all time* (registry counters, terminal stats dicts). Neither can
answer the questions a gradually-failing shared pool actually raises —
"was throughput collapsing before the error?", "has the admission
queue been growing without a single admit?", "which collective phase
kept stalling on its barrier?" — because nothing keeps a metric's
*history*. This module is that history:

- **The store** (:class:`TimelineStore`): a bounded set of named
  series, each a fixed-capacity ring of ``(t, value)`` samples taken
  at ``ZEST_TIMELINE_HZ`` (default 1 Hz) by one process-wide sampler
  thread. Memory is bounded by construction: per-series ring capacity
  × a hard series-count cap, oldest-touched series evicted first.
- **Rates from existing counters**: the sampler derives per-tier
  fetch B/s, per-lane file B/s, dcn / collective wire B/s, and seed
  upload B/s from the registry counters the subsystems already bump —
  zero new hot-path work; the instrumented code paths don't change.
  Rate samples are exact by construction: each sample is
  ``delta / dt`` over the tick interval, so integrating a rate series
  (:func:`integrate`) reproduces the counter's total delta.
- **Structural gauges**: subsystems register live *probes*
  (``register_probe(name, fn)`` — called at tick time: tenancy queue
  depth, admitted sessions, singleflight in-flight count, HostRing
  occupancy/stalls) or *post* cells (``post(name, value)`` — for
  transient state like the collective exchange's current phase index
  and cumulative barrier wait). Per-session byte progress is sampled
  straight off the session table.
- **The anomaly detector** (:class:`AnomalyDetector`): streaming rules
  evaluated every tick — sustained throughput collapse (session rate
  < 25% of its own EWMA for ≥ ``ZEST_ANOMALY_WINDOW_S`` while bytes
  remain), zero-progress stall, tenant-queue growth without a single
  admission, and per-phase collective straggler attribution. Each
  firing records a flight-recorder event (kind ``anomaly``), bumps
  ``zest_anomalies_total{kind}``, and annotates the live session so
  ``/v1/pulls`` / ``zest top`` show the anomaly next to the pull it
  belongs to.

Surfaces: ``GET /v1/timeline?since=<cursor>`` (cursor-paged JSON),
``?scope=pod`` (the coordinator merges every peer's timeline onto its
own clock via the PR-7 hello offsets — :func:`merge_timelines`),
dashboard sparklines, and ``zest top``.

Knob-off contract: ``ZEST_TIMELINE=0`` is hard-off — no sampler
thread, an empty store, every ``register_probe``/``post`` call one
flag check — and the pull is bit-for-bit the timeline-less pull
(pinned by test). ``ZEST_TELEMETRY=0`` implies it.

Import discipline: same as the rest of the package — nothing from
``zest_tpu`` outside ``telemetry`` is imported, so every subsystem can
register probes without cycles.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import OrderedDict, deque

from zest_tpu.telemetry import metrics, recorder, state
from zest_tpu.telemetry import session as session_mod

ENV_TIMELINE = "ZEST_TIMELINE"
ENV_HZ = "ZEST_TIMELINE_HZ"
ENV_WINDOW = "ZEST_ANOMALY_WINDOW_S"
ENV_SAMPLES = "ZEST_TIMELINE_SAMPLES"

DEFAULT_HZ = 1.0
DEFAULT_WINDOW_S = 5.0
DEFAULT_SAMPLES = 512      # ring capacity per series
MAX_SERIES = 256           # hard cap on concurrent series
_ANOMALY_RING = 64         # recent-anomalies ring on the store

# Push fan-out series (ISSUE 19) — posted via ``post()``, charted by
# the dashboard/`zest top` like any other series. The publisher daemon
# posts the first two on every ``/v1/push`` notification; each watch
# subscriber posts the third after its hot-swap completes, making
# trainer-to-fleet propagation a live line, not a post-hoc number:
#   ``push.new_xorb_bytes``  bytes minted by the last push
#   ``push.dedup_ratio``     its CDC dedup ratio vs the base revision
#   ``push.propagation_s``   trainer pushed_at -> swap-complete latency
SERIES_PUSH_PREFIX = "push."

# Throughput-collapse rule constants: the session's rate must fall
# below COLLAPSE_FRACTION of its own EWMA — and the EWMA itself must be
# above a noise floor, or an idle trickle would "collapse" constantly.
COLLAPSE_FRACTION = 0.25
_COLLAPSE_FLOOR_BPS = 64 * 1024
# EWMA time constant, in anomaly windows: long enough that one slow
# tick doesn't drag the baseline down to meet the collapsed rate.
_EWMA_WINDOWS = 3.0

_M_ANOMALIES = metrics.counter(
    "zest_anomalies_total",
    "Streaming anomalies detected on live timelines, by kind",
    ("kind",))
_M_SAMPLES = metrics.counter(
    "zest_timeline_samples_total",
    "Samples appended to the in-process timeline store")

# Counter → rate derivations: (series prefix, registry metric, label
# key). One series per observed label value (``<prefix>.<label>_bps``),
# or ``<prefix>.bps`` for unlabeled/summed metrics. All are byte
# counters, so every derived series is in bytes/second.
RATE_SOURCES = (
    ("fetch", "zest_fetch_bytes_total", "source"),
    ("files", "zest_files_bytes_total", "lane"),
    ("coop", "zest_coop_bytes_total", "tier"),
    ("collective", "zest_coop_collective_bytes_total", "link"),
    ("dcn", "zest_dcn_bytes_served_total", None),
    ("seed", "zest_seed_bytes_total", None),
)

ANOMALY_COLLAPSE = "throughput_collapse"
ANOMALY_STALL = "stall"
ANOMALY_QUEUE = "queue_stuck"
ANOMALY_STRAGGLER = "collective_straggler"


# ── On/off switch (lazy env resolution, same shape as state.enabled) ──

_OFF_VALUES = frozenset({"0", "false", "off", "no"})

_flag_lock = threading.Lock()
_enabled: bool | None = None


def enabled() -> bool:
    """The hot-path gate: ``ZEST_TELEMETRY`` off implies timeline off;
    ``ZEST_TIMELINE=0`` turns just this layer off."""
    if not state.enabled():
        return False
    global _enabled
    on = _enabled
    if on is not None:
        return on
    with _flag_lock:
        if _enabled is None:
            raw = os.environ.get(ENV_TIMELINE, "").strip().lower()
            _enabled = raw not in _OFF_VALUES
        return _enabled


def set_enabled(on: bool | None) -> None:
    """Test/CLI override; ``None`` returns to env resolution."""
    global _enabled
    with _flag_lock:
        _enabled = on


def _env_float(name: str, default: float, floor: float) -> float:
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    if not math.isfinite(v) or v < floor:
        return default
    return v


def _env_int(name: str, default: int, floor: int) -> int:
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= floor else default


# ── Series + store ──


class Series:
    """One named timeline: a fixed-capacity ring of
    ``(seq, t, value)`` samples. ``seq`` is the store-global sample
    counter — the paging cursor ``GET /v1/timeline?since=`` resumes
    from."""

    __slots__ = ("name", "kind", "ring", "last_touch")

    def __init__(self, name: str, kind: str, capacity: int):
        self.name = name
        self.kind = kind            # "rate" | "gauge"
        self.ring: deque = deque(maxlen=capacity)
        self.last_touch = 0.0

    def samples_since(self, since: int) -> list[list[float]]:
        return [[t, v] for seq, t, v in self.ring if seq > since]


class AnomalyDetector:
    """Streaming anomaly rules over the sampler's per-tick views.

    All state is per-episode: a rule arms when its condition first
    holds, fires once the condition has held for ``window_s``, and
    re-arms only after the condition clears — so a wedged pull
    produces ONE stall anomaly, not one per tick."""

    def __init__(self, store: "TimelineStore", window_s: float):
        self.store = store
        self.window_s = window_s
        # sid → {last_bytes, last_t, ewma, collapse_since, stall_since,
        #        fired: set[str]}
        self._sessions: dict[str, dict] = {}
        self._queue: dict = {}       # queue-growth episode state
        self._collective: dict = {}  # per-phase barrier baseline

    # — firing —

    def _fire(self, kind: str, session=None, **fields) -> None:
        sid = getattr(session, "id", None)
        _M_ANOMALIES.inc(kind=kind)
        ev = {"anomaly": kind, **fields}
        if sid is not None:
            ev["session"] = sid
        recorder.record("anomaly", **ev)
        if session is not None:
            note = getattr(session, "note_anomaly", None)
            if note is not None:
                try:
                    note(kind, fields)
                except Exception:  # noqa: BLE001 - annotation is advisory
                    pass
        self.store._note_anomaly(kind, sid, fields)
        # The anomaly STREAM (ISSUE 17): subscribers — the remediation
        # engine — see every firing with the live session attached.
        for cb in list(_anomaly_listeners):
            try:
                cb(kind, session, dict(fields))
            except Exception:  # noqa: BLE001 - a subscriber must never
                pass           # take the detector down

    # — per-session rules —

    def observe_session(self, sess, now: float) -> None:
        sid = sess.id
        row = self._sessions.get(sid)
        f = sess._fetch
        done = None
        if f is not None:
            done = (f.bytes_from_cache + f.bytes_from_peer
                    + f.bytes_from_cdn)
        if row is None:
            self._sessions[sid] = {
                "last_bytes": done, "last_t": now, "ewma": 0.0,
                "collapse_since": None, "stall_since": None,
                "fired": set(),
            }
            return
        dt = now - row["last_t"]
        if dt <= 0 or done is None:
            return
        last = row["last_bytes"]
        row["last_t"] = now
        row["last_bytes"] = done
        if last is None:
            return
        rate = max(0.0, (done - last) / dt)
        total = sess.total_bytes
        # Progress-bar semantics, like the session ETA: the tiers count
        # blob bytes against the payload total — "bytes remain" is
        # approximate, which is fine for an anomaly gate.
        bytes_remain = total is not None and done < total
        # "Byte-moving" is judged on the OPEN stage multiset, not the
        # display phase: during a direct landing the display phase is
        # hbm_commit (it outranks fetch in the session's rank table)
        # while fetch workers are still pulling bytes inside it — a
        # mid-landing fetch stall must still fire. When the fetch/files
        # stages have genuinely closed, a slow commit is not a stall.
        try:
            open_stages = tuple(sess._open)
        except RuntimeError:  # dict mutated under us — next tick reads
            open_stages = ()
        moving_phase = ("fetch" in open_stages or "files" in open_stages
                        or sess.phase in ("fetch", "files"))

        # Zero-progress stall: no byte movement for a whole window
        # while the pull sits in a byte-moving phase with work left.
        if rate == 0.0 and moving_phase and (bytes_remain or done == 0):
            if row["stall_since"] is None:
                row["stall_since"] = now
            elif (now - row["stall_since"] >= self.window_s
                    and ANOMALY_STALL not in row["fired"]):
                row["fired"].add(ANOMALY_STALL)
                self._fire(ANOMALY_STALL, session=sess,
                           phase=sess.phase, bytes_done=done,
                           stalled_s=round(now - row["stall_since"], 2))
        else:
            row["stall_since"] = None
            if rate > 0.0:
                row["fired"].discard(ANOMALY_STALL)

        # Sustained throughput collapse vs the session's OWN history:
        # the EWMA is the baseline, so a pull that was always slow
        # doesn't alarm — only one that *fell off* its own rate.
        ewma = row["ewma"]
        collapsed = (ewma > _COLLAPSE_FLOOR_BPS
                     and rate < COLLAPSE_FRACTION * ewma
                     and bytes_remain)
        if collapsed:
            if row["collapse_since"] is None:
                row["collapse_since"] = now
            elif (now - row["collapse_since"] >= self.window_s
                    and ANOMALY_COLLAPSE not in row["fired"]):
                row["fired"].add(ANOMALY_COLLAPSE)
                self._fire(ANOMALY_COLLAPSE, session=sess,
                           rate_bps=int(rate), ewma_bps=int(ewma),
                           bytes_done=done)
        else:
            row["collapse_since"] = None
            if ewma > 0 and rate >= COLLAPSE_FRACTION * ewma:
                row["fired"].discard(ANOMALY_COLLAPSE)
        # Update the EWMA AFTER judging: the collapsed ticks must not
        # drag the baseline down to meet the collapsed rate instantly.
        tau = max(self.window_s * _EWMA_WINDOWS, 1e-6)
        alpha = 1.0 - math.exp(-dt / tau)
        row["ewma"] = ewma + alpha * (rate - ewma)

    def drop_session(self, sid: str) -> None:
        self._sessions.pop(sid, None)

    # — queue rule —

    def observe_queue(self, depth, admitted_total, now: float) -> None:
        """Tenant queue growth without admission: the queue holds (or
        grows) for a whole window while ``admitted_total`` doesn't
        move — the signature of a wedged/undersized admission stage."""
        if depth is None or admitted_total is None:
            return
        q = self._queue
        stuck = (depth > 0 and bool(q)
                 and admitted_total == q.get("admitted")
                 and depth >= q.get("depth", 0))
        if not stuck:
            # Idle, drained below the episode's start depth, or an
            # admission happened: start a fresh episode.
            self._queue = {"since": now, "depth": depth,
                           "admitted": admitted_total, "fired": False}
            return
        q["depth"] = depth
        if now - q["since"] >= self.window_s and not q.get("fired"):
            q["fired"] = True
            self._fire(ANOMALY_QUEUE, depth=int(depth),
                       waited_s=round(now - q["since"], 2))

    # — collective rule —

    def observe_collective(self, cells: dict, now: float) -> None:
        """Per-phase straggler attribution: barrier wait accumulated
        *within one phase* exceeding the window means this phase's
        partner is the straggler — fired once per phase, carrying the
        phase index and partner host."""
        phase = cells.get("collective.phase")
        barrier = cells.get("collective.barrier_s")
        if phase is None or barrier is None:
            self._collective = {}
            return
        c = self._collective
        if c.get("phase") != phase:
            self._collective = {"phase": phase, "barrier0": barrier,
                                "fired": False}
            return
        waited = barrier - c.get("barrier0", 0.0)
        if waited >= self.window_s and not c.get("fired"):
            c["fired"] = True
            fields = {"phase": int(phase),
                      "barrier_wait_s": round(waited, 2)}
            partner = cells.get("collective.partner")
            if partner is not None:
                fields["partner"] = int(partner)
            self._fire(ANOMALY_STRAGGLER, **fields)


class TimelineStore:
    """The process timeline: bounded series rings, probe/cell
    registries, the counter-rate state, and the anomaly ring."""

    def __init__(self, capacity: int | None = None,
                 max_series: int = MAX_SERIES,
                 window_s: float | None = None):
        if capacity is None:
            capacity = _env_int(ENV_SAMPLES, DEFAULT_SAMPLES, 2)
        if window_s is None:
            window_s = _env_float(ENV_WINDOW, DEFAULT_WINDOW_S, 0.05)
        self.capacity = capacity
        self.max_series = max(1, max_series)
        self.window_s = window_s
        self.hz = _env_float(ENV_HZ, DEFAULT_HZ, 0.01)
        self._lock = threading.Lock()
        self._series: OrderedDict[str, Series] = OrderedDict()
        self._seq = 0
        self._probes: dict[str, object] = {}
        self._cells: dict[str, float] = {}
        # (metric, label value) → (counter value, t) rate baselines.
        self._rate_state: dict[tuple[str, str], tuple[float, float]] = {}
        # When the previous tick ran (monotonic): a labelset FIRST seen
        # mid-run credits its whole counter value over this interval —
        # the bytes moved since the last look, there was just no
        # labelset row yet to watch them through.
        self._last_tick_t: float | None = None
        self._anomalies: deque = deque(maxlen=_ANOMALY_RING)
        self._clock_offsets: dict = {}
        self.detector = AnomalyDetector(self, window_s)
        self.ticks = 0

    # — write side —

    def _append(self, name: str, value: float, kind: str,
                t: float) -> None:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                while len(self._series) >= self.max_series:
                    # Oldest-touched series evicts first (move-to-end
                    # on every append keeps the OrderedDict in touch
                    # order).
                    self._series.popitem(last=False)
                s = self._series[name] = Series(name, kind,
                                                self.capacity)
            self._seq += 1
            # Microsecond timestamps: rate samples are integrated back
            # to byte totals (×dt), so millisecond rounding would leak
            # ~1% per tick into the smoke gate's 5% budget.
            s.ring.append((self._seq, round(t, 6), value))
            s.last_touch = t
            self._series.move_to_end(name)
        _M_SAMPLES.inc()

    def _note_anomaly(self, kind: str, sid, fields: dict) -> None:
        ev = {"t": round(time.time(), 3), "kind": kind}
        if sid is not None:
            ev["session"] = sid
        ev.update({k: v for k, v in fields.items()
                   if isinstance(v, (str, int, float, bool))})
        with self._lock:
            self._anomalies.append(ev)

    def set_clock_offsets(self, offsets: dict) -> None:
        """Record the pod clock offsets the last coop round measured
        (host index → {offset_s, rtt_s}) — what ``?scope=pod`` hands
        :func:`merge_timelines` for normalization."""
        with self._lock:
            self._clock_offsets.update(
                {str(k): dict(v) for k, v in offsets.items()})

    # — the sampling pass —

    def tick(self, now: float | None = None, wall: float | None = None,
             registry=None) -> None:
        """One sampling pass. ``now`` is the monotonic rate clock,
        ``wall`` the sample timestamp (tests inject both); production
        calls leave them None."""
        if now is None:
            now = time.monotonic()
        if wall is None:
            wall = time.time()
        if registry is None:
            registry = metrics.REGISTRY
        self.ticks += 1

        # 1. Rates derived from the existing registry counters.
        last_tick = self._last_tick_t
        self._last_tick_t = now
        by_name = {m.name: m for m in registry.metrics()}
        for prefix, metric_name, label_key in RATE_SOURCES:
            m = by_name.get(metric_name)
            if m is None:
                continue
            sums: dict[str, float] = {}
            for labels, value in m.samples():
                key = labels.get(label_key, "") if label_key else ""
                sums[key] = sums.get(key, 0.0) + value
            for label_value, total in sums.items():
                rk = (metric_name, label_value)
                prev = self._rate_state.get(rk)
                self._rate_state[rk] = (total, now)
                name = (f"{prefix}.{label_value}_bps" if label_value
                        else f"{prefix}.bps")
                if prev is None:
                    if last_tick is None or now <= last_tick:
                        # The store's very first look: no prior instant
                        # to rate against — a zero baseline anchors the
                        # series for integration.
                        self._append(name, 0.0, "rate", wall)
                        continue
                    # First seen mid-run: the whole counter value moved
                    # since the previous tick (the labelset just didn't
                    # exist to watch). A leading zero anchor at the
                    # previous tick keeps integrate() exact.
                    dt = now - last_tick
                    self._append(name, 0.0, "rate", wall - dt)
                    self._append(name, round(total / dt, 1), "rate",
                                 wall)
                    continue
                pv, pt = prev
                dt = now - pt
                if dt <= 0:
                    continue
                self._append(name, round(max(0.0, total - pv) / dt, 1),
                             "rate", wall)

        # 2. Registered probes (live structural gauges).
        with self._lock:
            probes = list(self._probes.items())
            cells = dict(self._cells)
        probe_vals: dict[str, float] = {}
        for name, fn in probes:
            try:
                v = fn()
            except Exception:  # noqa: BLE001 - a dying probe drops out
                continue
            if v is None:
                continue
            probe_vals[name] = float(v)
            self._append(name, float(v), "gauge", wall)

        # 3. Posted cells (transient subsystem state).
        for name, v in cells.items():
            self._append(name, float(v), "gauge", wall)

        # 4. Per-session byte progress + the session anomaly rules.
        active = session_mod.SESSIONS.active()
        live_ids = set()
        for sess in active:
            live_ids.add(sess.id)
            f = sess._fetch
            if f is not None:
                done = (f.bytes_from_cache + f.bytes_from_peer
                        + f.bytes_from_cdn)
                self._append(f"session.{sess.id}.bytes", float(done),
                             "gauge", wall)
            self.detector.observe_session(sess, now)
        for sid in list(self.detector._sessions):
            if sid not in live_ids:
                self.detector.drop_session(sid)

        # 5. Queue + collective anomaly rules (probe/cell views).
        self.detector.observe_queue(
            probe_vals.get("tenancy.queue_depth"),
            probe_vals.get("tenancy.admitted_total"), now)
        self.detector.observe_collective(cells, now)

        # 6. Tick subscribers (ISSUE 17): the remediation engine's
        # periodic rules (seeder scan, shed recovery, knob tuner) ride
        # the sampler cadence instead of owning a thread.
        for cb in list(_tick_listeners):
            try:
                cb(self, now)
            except Exception:  # noqa: BLE001 - sampling must never crash
                pass

    # — read side —

    def payload(self, since: int = 0, prefix: str | None = None) -> dict:
        """The ``GET /v1/timeline`` document: every series' samples
        with cursor > ``since`` (cursor-paged — pass the returned
        ``cursor`` back as ``since`` to stream increments), the recent
        anomaly ring, and the sampling config."""
        with self._lock:
            series = {
                name: {"kind": s.kind,
                       "samples": s.samples_since(since)}
                for name, s in self._series.items()
                if prefix is None or name.startswith(prefix)
            }
            doc = {
                "enabled": True,
                "hz": self.hz,
                "window_s": self.window_s,
                "cursor": self._seq,
                "series": {n: d for n, d in series.items()
                           if d["samples"]},
                "anomalies": list(self._anomalies),
            }
            if self._clock_offsets:
                doc["clock_offsets"] = dict(self._clock_offsets)
        return doc


# ── The sampler thread ──


class _Sampler:
    def __init__(self, store: TimelineStore):
        self.store = store
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._run, daemon=True, name="zest-timeline")

    def _run(self) -> None:
        interval = 1.0 / max(self.store.hz, 0.01)
        # Immediate baseline tick: pins "the previous look" to the
        # sampler's start, so bytes that move before the first interval
        # elapses are credited to it instead of vanishing into a
        # first-sight baseline.
        try:
            self.store.tick()
        except Exception:  # noqa: BLE001 - sampling must never crash
            pass
        while not self._stop.wait(interval):
            try:
                self.store.tick()
            except Exception:  # noqa: BLE001 - sampling must never crash
                pass

    def stop(self) -> None:
        self._stop.set()


# ── Process-wide instance + module-level hooks ──

STORE = TimelineStore()

_sampler_lock = threading.Lock()
_sampler: _Sampler | None = None

# Anomaly/tick subscribers (ISSUE 17). Module-level, not store-level:
# subscribers outlive a test's store swap the same way probes don't —
# they re-attach to whatever STORE currently is via the forwarding
# call sites above.
_anomaly_listeners: list = []
_tick_listeners: list = []


def add_anomaly_listener(cb) -> None:
    """``cb(kind, session, fields)`` on every detector firing.
    Idempotent: re-adding the same callable is a no-op."""
    if cb not in _anomaly_listeners:
        _anomaly_listeners.append(cb)


def add_tick_listener(cb) -> None:
    """``cb(store, now)`` after every sampling pass. Idempotent."""
    if cb not in _tick_listeners:
        _tick_listeners.append(cb)


def remove_anomaly_listener(cb) -> None:
    try:
        _anomaly_listeners.remove(cb)
    except ValueError:
        pass


def remove_tick_listener(cb) -> None:
    try:
        _tick_listeners.remove(cb)
    except ValueError:
        pass


def _session_evicted(sid: str) -> None:
    """Session-table eviction → detector episode teardown (ISSUE 17
    satellite): a session that terminates mid-episode between ticks
    used to leave its armed-off episode row behind, suppressing the
    first firing of a new session reusing the id slot. Finish-time
    eviction clears it regardless of sampler timing."""
    try:
        STORE.detector.drop_session(sid)
    except Exception:  # noqa: BLE001 - teardown is advisory
        pass


session_mod.add_evict_listener(_session_evicted)


def ensure_started() -> bool:
    """Start the process sampler (idempotent). Called from pull entry
    and the daemon's serve path; a no-op (False) when the layer is
    knob-off."""
    if not enabled():
        return False
    global _sampler
    with _sampler_lock:
        if _sampler is None:
            _sampler = _Sampler(STORE)
            _sampler.thread.start()
    return True


def register_probe(name: str, fn) -> None:
    """Register a live gauge sampled every tick (``fn() -> float or
    None``). Replace semantics: re-registering a name swaps the
    callable — subsystems that rebuild (tenancy state, landing rings)
    just re-register."""
    if not enabled():
        return
    with STORE._lock:
        STORE._probes[name] = fn


def unregister_probe(name: str, fn=None) -> None:
    """Remove a probe. With ``fn`` given, remove only if that callable
    is still the registered one — an old owner's teardown must not
    drop the probe its replacement just registered (the landing-ring
    close-after-replace case)."""
    with STORE._lock:
        if fn is None or STORE._probes.get(name) is fn:
            STORE._probes.pop(name, None)


def post(name: str, value: float) -> None:
    """Set a transient cell the sampler records each tick (the
    collective exchange's phase index / barrier seconds)."""
    if not enabled():
        return
    with STORE._lock:
        STORE._cells[name] = float(value)


def clear(prefix: str) -> None:
    """Drop every posted cell under ``prefix`` (phase over)."""
    with STORE._lock:
        for name in [n for n in STORE._cells if n.startswith(prefix)]:
            STORE._cells.pop(name, None)


def set_clock_offsets(offsets: dict) -> None:
    if not enabled() or not offsets:
        return
    STORE.set_clock_offsets(offsets)


def payload(since: int = 0, prefix: str | None = None) -> dict:
    """The ``/v1/timeline`` document (an explicit ``enabled: false``
    stub when knob-off, so pollers see the state instead of a 404)."""
    if not enabled():
        return {"enabled": False, "series": {}, "anomalies": [],
                "cursor": 0}
    return STORE.payload(since=since, prefix=prefix)


def status_block() -> dict:
    """The ``timeline`` block for ``/v1/status``."""
    if not enabled():
        return {"enabled": False}
    with STORE._lock:
        return {"enabled": True, "hz": STORE.hz,
                "series": len(STORE._series), "cursor": STORE._seq,
                "anomalies": len(STORE._anomalies),
                "ticks": STORE.ticks}


def reset() -> None:
    """Tests: stop the sampler, drop the store + subscribers,
    unresolve the flag."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None
    global STORE
    STORE = TimelineStore()
    del _anomaly_listeners[:]
    del _tick_listeners[:]
    set_enabled(None)


# ── Pure helpers (integration + pod merge) ──


def integrate(samples: list[list[float]]) -> float:
    """∫ rate·dt over a rate series' samples — left-Riemann over the
    sample intervals, which is *exact* for series this store derived
    (each sample IS delta/dt for the interval ending at its
    timestamp). The smoke gate checks this against ``FetchStats``."""
    total = 0.0
    for (t0, _v0), (t1, v1) in zip(samples, samples[1:]):
        total += v1 * (t1 - t0)
    return total


def merge_timelines(host_docs: dict, reference=None) -> dict:
    """Merge per-host ``/v1/timeline`` docs into one pod-scope doc:
    series renamed ``h<host>.<name>``, timestamps normalized onto the
    reference host's clock via each doc's recorded hello clock offsets
    (PR 7; a host without an offset estimate merges on raw wall
    clocks — recorded as ``applied_offset_s: null``, same honesty rule
    as ``fleet.merge_traces``). Anomalies merge into one time-ordered
    list stamped with their host."""
    if not host_docs:
        raise ValueError("no timelines to merge")
    keys = sorted(host_docs, key=str)
    if reference is None:
        reference = keys[0]
    ref_offsets = (host_docs[reference].get("clock_offsets") or {})

    merged_series: dict = {}
    anomalies: list[dict] = []
    norm_meta: dict = {}
    for host in keys:
        doc = host_docs[host]
        offset = 0.0 if host == reference else None
        est = ref_offsets.get(str(host))
        if isinstance(est, dict) and "offset_s" in est:
            offset = float(est["offset_s"])
        else:
            own = (doc.get("clock_offsets") or {}).get(str(reference))
            if isinstance(own, dict) and "offset_s" in own:
                offset = -float(own["offset_s"])
        norm_meta[str(host)] = {
            "applied_offset_s": (None if offset is None
                                 else round(offset, 6))}
        shift = -(offset or 0.0)
        for name, s in (doc.get("series") or {}).items():
            merged_series[f"h{host}.{name}"] = {
                "kind": s.get("kind", "gauge"),
                # µs rounding like the store's own samples: ms-rounded
                # timestamps would leak ~1%/tick back into integrate()
                # on a pod-merged rate series.
                "samples": [[round(t + shift, 6), v]
                            for t, v in s.get("samples", [])],
            }
        for ev in doc.get("anomalies") or []:
            out = dict(ev)
            out["host"] = host
            if "t" in out:
                out["t"] = round(out["t"] + shift, 6)
            anomalies.append(out)
    anomalies.sort(key=lambda e: e.get("t", 0))
    return {
        "scope": "pod",
        "reference": reference,
        "hosts": [str(k) for k in keys],
        "clock_normalization": norm_meta,
        "series": merged_series,
        "anomalies": anomalies,
    }
