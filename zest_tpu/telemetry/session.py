"""Pull sessions: every pull as a first-class observable (ISSUE 11).

Before this module, a pull was observable only in aggregate: the
process metrics registry answers "what has this host done across every
pull", and the ``zest_last_pull_*`` gauges answer "how did the LAST
pull do" — which clobber each other the moment a daemon runs two pulls
concurrently (the multi-tenant refactor's baseline scenario, ROADMAP
item 1). The session table is the per-pull layer in between: a
process-global, bounded registry of live and recently-finished pulls,
each carrying its identity (id, ``repo@sha``, tenant), live phase and
byte progress, an ETA, and — once terminal — the pull's full stats
dict (including ``stats["critical_path"]`` when the pull ran traced).

Zero new hot-path work, by construction: a session holds *references*
to the pull's existing instrumentation objects (the
:class:`~zest_tpu.transfer.pull.StageClock` and the bridge's
``FetchStats``) and computes every snapshot lazily at read time — the
instrumented code paths don't change shape. The only push-style hook
is the StageClock's coarse per-stage-entry observer (a handful of
calls per pull, never per chunk), which is what drives the live
``phase`` field and wakes SSE streams.

Surfaces built on the table:

- ``GET /v1/pulls`` (active + recent ring), ``GET /v1/pulls/<id>``,
  and the SSE progress stream ``GET /v1/pulls/<id>/events``;
- ``zest ps [--watch]`` and the dashboard's active-pulls panel;
- the ``/v1/debug`` landing block (per-session values, immune to the
  gauge clobber);
- flight-recorder session attribution: :func:`current_id` is the
  resolver the recorder stamps events with.

Same zero-cost discipline as the rest of the package: with
``ZEST_TELEMETRY=0`` :func:`begin` returns ``None`` and the table
stays empty — the knob-off pull is bit-for-bit the pre-session pull.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from zest_tpu.telemetry import recorder, state

ENV_RECENT = "ZEST_SESSIONS_RECENT"
DEFAULT_RECENT = 32

# Display rank of a concurrently-open stage set: the landing outranks
# the background file lane, payload movement outranks metadata. An
# unknown stage ranks lowest but still displays when it's all there is.
_PHASE_RANK = {
    "files": 1,
    "resolve": 2,
    "cas_metadata": 3,
    "fetch": 4,
    "decode": 5,
    "hbm_commit": 6,
}


class PullSession:
    """One pull's live identity + progress. Snapshots are computed at
    read time from the attached clock/stats objects; mutation is
    limited to the coarse lifecycle hooks (phase, revision, totals,
    terminal state), each of which bumps ``version`` and notifies the
    condition SSE streams wait on."""

    def __init__(self, sid: str, repo: str, revision: str,
                 tenant: str | None, device: str | None):
        self.id = sid
        self.repo = repo
        self.revision = revision  # ref at begin; resolved sha once known
        self.tenant = tenant
        self.device = device
        self.started_at = round(time.time(), 6)
        self._t0 = time.monotonic()
        # running | ok | error | cancelled | rejected (admission 429)
        self.status = "running"
        self.error: str | None = None
        self.phase = "starting"
        # The pull's cancellation token (transfer.tenancy.CancelToken),
        # attached by pull_model so DELETE /v1/pulls/<id> and the SSE
        # disconnect path can abort the session; None for sessions that
        # predate the token (or were registered outside pull_model).
        self.cancel_token = None
        self.total_bytes: int | None = None  # pending payload, when known
        self.stats: dict | None = None       # terminal stats dict ref
        self.slo: dict = {}                  # slo -> breach info
        self.anomalies: dict = {}            # anomaly kind -> info
        self.ended_at: float | None = None
        self._ended_t: float | None = None
        self._clock = None
        self._fetch = None
        self._open: dict[str, int] = {}
        self._cv = threading.Condition()
        self.version = 0

    # ── Hooks (called from the pull, coarse-grained) ──

    def attach(self, clock=None, fetch_stats=None) -> None:
        """Wire the pull's existing instrumentation in: the StageClock
        (its observer drives ``phase``) and the bridge's FetchStats
        (read lazily for byte progress). No code path changes shape —
        the session only *watches* objects the pull already updates."""
        if clock is not None:
            self._clock = clock
            clock.observer = self._on_stage
        if fetch_stats is not None:
            self._fetch = fetch_stats

    def set_revision(self, sha: str) -> None:
        with self._cv:
            self.revision = sha
            self.version += 1
            self._cv.notify_all()

    def set_total_bytes(self, n: int) -> None:
        with self._cv:
            self.total_bytes = max(0, int(n))
            self.version += 1
            self._cv.notify_all()

    def set_phase(self, phase: str) -> None:
        """Direct phase override for lifecycle states outside the
        StageClock's view — ``queued`` while parked in the admission
        queue (ISSUE 13), back to ``starting`` on admit. Stage-observer
        updates keep flowing through :meth:`_on_stage` unchanged."""
        with self._cv:
            if phase != self.phase:
                self.phase = phase
                self.version += 1
                self._cv.notify_all()

    def cancel(self, reason: str = "cancelled") -> bool:
        """Fire the session's cancel token (``DELETE /v1/pulls/<id>``).
        False when the session has no token or is already terminal."""
        token = self.cancel_token
        if token is None or self.status != "running":
            return False
        token.cancel(reason)
        return True

    def note_slo(self, slo: str, info: dict) -> None:
        with self._cv:
            self.slo[slo] = dict(info)
            self.version += 1
            self._cv.notify_all()

    def note_anomaly(self, kind: str, info: dict | None = None) -> None:
        """Streaming-anomaly annotation (ISSUE 15): the timeline
        detector stamps the live session so ``/v1/pulls`` and ``zest
        top`` show the anomaly next to the pull it belongs to. Keyed
        by kind — a re-fired episode updates in place (bounded by the
        handful of anomaly kinds, never per-tick growth)."""
        with self._cv:
            row = dict(info or {})
            row["t"] = round(time.time(), 3)
            self.anomalies[kind] = row
            self.version += 1
            self._cv.notify_all()

    def _on_stage(self, stage: str, entered: bool) -> None:
        """StageClock observer: maintain the open-stage multiset and
        derive the display phase (highest-ranked open stage; the last
        exited stage when nothing is open)."""
        with self._cv:
            n = self._open.get(stage, 0) + (1 if entered else -1)
            if n <= 0:
                self._open.pop(stage, None)
            else:
                self._open[stage] = n
            if self._open:
                phase = max(self._open, key=lambda s: _PHASE_RANK.get(s, 0))
            else:
                phase = stage
            if phase != self.phase:
                self.phase = phase
                self.version += 1
                self._cv.notify_all()

    def finish(self, status: str, error: str | None = None,
               stats: dict | None = None) -> None:
        with self._cv:
            self.status = status
            self.error = error
            self.stats = stats
            self._ended_t = time.monotonic()
            self.ended_at = round(time.time(), 6)
            if status == "ok":
                self.phase = "done"
            elif status in ("cancelled", "rejected"):
                self.phase = status
            self.version += 1
            self._cv.notify_all()

    # ── Read side ──

    def wait(self, version: int, timeout: float = 1.0) -> int:
        """Block until the session's version moves past ``version`` (or
        the timeout lapses — the SSE heartbeat); returns the current
        version either way."""
        with self._cv:
            if self.version == version and self.status == "running":
                self._cv.wait(timeout)
            return self.version

    def _bytes_block(self) -> dict | None:
        f = self._fetch
        if f is None:
            return None
        block = {
            "cache": f.bytes_from_cache,
            "peer": f.bytes_from_peer,
            "cdn": f.bytes_from_cdn,
        }
        if self.total_bytes is not None:
            block["total"] = self.total_bytes
        return block

    def snapshot(self, detail: bool = False) -> dict:
        """JSON-friendly view. The list view (``detail=False``) is the
        ``/v1/pulls`` row; ``detail=True`` adds the live stage walls
        and, once terminal, the pull's full stats dict."""
        with self._cv:
            status, error, phase = self.status, self.error, self.phase
            version, slo = self.version, dict(self.slo)
            anomalies = dict(self.anomalies)
            ended_t, ended_at = self._ended_t, self.ended_at
            stats = self.stats
        end = ended_t if ended_t is not None else time.monotonic()
        elapsed = max(0.0, end - self._t0)
        doc: dict = {
            "id": self.id,
            "repo": self.repo,
            "revision": self.revision,
            "status": status,
            "phase": phase,
            "started_at": self.started_at,
            "elapsed_s": round(elapsed, 3),
            "version": version,
        }
        if self.tenant:
            doc["tenant"] = self.tenant
        if self.device:
            doc["device"] = self.device
        b = self._bytes_block()
        if b is not None:
            doc["bytes"] = b
            done = b["cache"] + b["peer"] + b["cdn"]
            total = b.get("total")
            if status == "ok":
                doc["progress"] = 1.0
            elif total:
                # Approximate by design: the tiers count wire/cache blob
                # bytes (compressed) against the uncompressed payload
                # total — good enough for a progress bar, never for
                # accounting (stats are the accounting).
                doc["progress"] = round(min(done / total, 0.99), 4)
                # ETA only while RUNNING: an errored session's frozen
                # partial progress is honest, an ETA for a pull that
                # will never finish is not.
                if status == "running" and 0 < done < total \
                        and elapsed > 0.05:
                    rate = done / elapsed
                    doc["eta_s"] = round((total - done) / rate, 1)
        if ended_at is not None:
            doc["ended_at"] = ended_at
        if error:
            doc["error"] = error
        if slo:
            doc["slo"] = slo
        if anomalies:
            doc["anomalies"] = anomalies
        if stats is not None:
            for k in ("time_to_hbm_s", "time_to_first_layer_s",
                      "time_to_swap_s", "peer_served_ratio"):
                if stats.get(k) is not None:
                    doc[k] = stats[k]
        if detail:
            clock = self._clock
            if clock is not None:
                doc["stages"] = clock.summary()
            if stats is not None:
                doc["stats"] = stats
        return doc

    def landing_block(self) -> dict | None:
        """This session's landing values in the ``/v1/debug`` block's
        shape — the per-session replacement for the clobber-prone
        ``zest_last_pull_*`` process gauges. None until the session is
        terminal with a --device landing."""
        stats = self.stats
        if not stats or stats.get("time_to_hbm_s") is None:
            return None
        landing: dict = {"session": self.id,
                         "time_to_hbm_s": stats["time_to_hbm_s"]}
        fl = stats.get("time_to_first_layer_s")
        if fl is not None:
            landing["first_layer_s"] = fl
            landing["first_layer_ratio"] = round(
                fl / stats["time_to_hbm_s"], 4) \
                if stats["time_to_hbm_s"] else None
            stalls = ((stats.get("hbm") or {}).get("ring") or {}).get(
                "stalls", 0)
            if stalls:
                landing["ring_stalls"] = int(stalls)
        delta = stats.get("delta")
        if delta is not None:
            ratio = delta.get("fetched_ratio",
                              delta.get("delta_bytes_ratio"))
            if ratio is not None:
                landing["delta_ratio"] = ratio
        swap = stats.get("time_to_swap_s")
        if swap is not None:
            landing["swap_s"] = swap
        return landing


class SessionTable:
    """Process-global bounded registry: live sessions plus a ring of
    the most recent terminal ones (``ZEST_SESSIONS_RECENT``, default
    32) — bounded cardinality by construction, so every surface built
    on it (endpoints, recorder stamps, the debug landing block) is
    safe in a long-lived daemon."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(ENV_RECENT, DEFAULT_RECENT))
            except ValueError:
                capacity = DEFAULT_RECENT
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._active: dict[str, PullSession] = {}
        self._recent: deque[PullSession] = deque(maxlen=self.capacity)
        self._seq = 0
        # SLO burn accounting: slo -> [evaluated pulls, breaches].
        self._slo_counts: dict[str, list[int]] = {}

    def begin(self, repo: str, revision: str = "main",
              tenant: str | None = None,
              device: str | None = None) -> PullSession:
        # Tenant resolution lives with the caller (pull_model: explicit
        # arg, else Config.tenant, which Config.load reads from
        # ZEST_TENANT) — a second env read here would let the env
        # override an embedder's explicit Config.
        with self._lock:
            self._seq += 1
            sid = f"p{self._seq:04d}-{os.urandom(3).hex()}"
            sess = PullSession(sid, repo, revision, tenant, device)
            self._active[sid] = sess
        return sess

    def finish(self, sess: PullSession, status: str,
               error: str | None = None,
               stats: dict | None = None) -> None:
        # Terminal transition AND the active→recent move under ONE
        # table-lock hold: marking terminal first would let a
        # concurrent payload() list a finished session under "active";
        # moving first would make it vanish from both lists. Lock
        # order is table → session everywhere (payload() snapshots the
        # same way); no session method reaches back into the table.
        with self._lock:
            sess.finish(status, error=error, stats=stats)
            self._active.pop(sess.id, None)
            self._recent.append(sess)
        # Eviction listeners run OUTSIDE the lock (they reach into
        # other modules — lock order is table → session only). The
        # timeline's anomaly detector rides this to clear per-session
        # episode state at finish time instead of the next sampler
        # tick (ISSUE 17 satellite: a session terminating mid-episode
        # during a sampler gap must not leave the detector armed-off
        # for a reused id slot).
        for cb in list(_evict_listeners):
            try:
                cb(sess.id)
            except Exception:  # noqa: BLE001 - observers must not
                pass           # break the terminal transition

    def note_slo(self, slo: str, breached: bool) -> None:
        with self._lock:
            row = self._slo_counts.setdefault(slo, [0, 0])
            row[0] += 1
            if breached:
                row[1] += 1

    def get(self, sid: str) -> PullSession | None:
        with self._lock:
            sess = self._active.get(sid)
            if sess is not None:
                return sess
            for s in self._recent:
                if s.id == sid:
                    return s
        return None

    def active(self) -> list[PullSession]:
        with self._lock:
            return list(self._active.values())

    def active_ids(self) -> list[str]:
        with self._lock:
            return list(self._active)

    def recent(self) -> list[PullSession]:
        """Newest first."""
        with self._lock:
            return list(self._recent)[::-1]

    def slo_burn(self) -> dict:
        """Process-lifetime burn per armed SLO: evaluated pulls,
        breaches, and the burn ratio (the error-budget spend rate a
        fleet scrape divides against its budget window)."""
        with self._lock:
            counts = {k: list(v) for k, v in self._slo_counts.items()}
        return {
            slo: {"pulls": pulls, "breaches": breaches,
                  "burn": round(breaches / pulls, 4) if pulls else 0.0}
            for slo, (pulls, breaches) in sorted(counts.items())
        }

    def payload(self) -> dict:
        """The ``GET /v1/pulls`` document. Both lists are captured
        under ONE lock acquisition (a pull finishing between two
        separate reads would appear in `active` AND `recent` — a
        duplicated row in `zest ps`/the dashboard), and the active
        rows are re-filtered to still-running after snapshotting: a
        session that went terminal between the capture and its
        snapshot drops out for one tick (the next read shows it under
        `recent`) instead of rendering a finished pull as active."""
        with self._lock:
            active = list(self._active.values())
            recent = list(self._recent)[::-1]
        active_rows = [s.snapshot() for s in active]
        doc = {
            "active": [r for r in active_rows
                       if r["status"] == "running"],
            "recent": [s.snapshot() for s in recent],
            "capacity": self.capacity,
        }
        burn = self.slo_burn()
        if burn:
            doc["slo"] = burn
        return doc

    def last_landing(self) -> dict | None:
        """The most recent terminal session's landing block — what the
        ``/v1/debug`` landing panel renders. Session-scoped, so two
        concurrent pulls can never cross-contaminate it the way the
        process-global ``zest_last_pull_*`` gauges do."""
        for sess in self.recent():
            block = sess.landing_block()
            if block is not None:
                return block
        return None


# ── Process-wide instance + module-level hooks ──

SESSIONS = SessionTable()

_tls = threading.local()

# Module-wired like the recorder's session resolver below: survives
# SessionTable swaps AND reset() — the timeline registers once at
# import and must keep hearing evictions from every future table.
_evict_listeners: list = []


def add_evict_listener(cb) -> None:
    """``cb(sid)`` after a session's terminal transition (the id left
    the active table). Idempotent."""
    if cb not in _evict_listeners:
        _evict_listeners.append(cb)


def begin(repo: str, revision: str = "main", tenant: str | None = None,
          device: str | None = None) -> PullSession | None:
    """Register a session, or ``None`` with ``ZEST_TELEMETRY=0`` (the
    knob-off contract: an empty table, zero bookkeeping)."""
    if not state.enabled():
        return None
    return SESSIONS.begin(repo, revision, tenant=tenant, device=device)


def finish(sess: PullSession | None, status: str,
           error: str | None = None, stats: dict | None = None) -> None:
    if sess is None:
        return
    SESSIONS.finish(sess, status, error=error, stats=stats)


def get(sid: str) -> PullSession | None:
    return SESSIONS.get(sid)


def payload() -> dict:
    return SESSIONS.payload()


def last_landing() -> dict | None:
    return SESSIONS.last_landing()


def use(sid: str | None) -> None:
    """Bind this thread to a session id (worker-thread inheritance —
    pools capture the id at construction and re-bind per task)."""
    _tls.sid = sid


class bind:
    """Context manager binding the calling thread to a session id for
    the block (``None`` is a no-op bind — the knob-off path)."""

    def __init__(self, sid: str | None):
        self._sid = sid
        self._prev: str | None = None

    def __enter__(self) -> "bind":
        self._prev = getattr(_tls, "sid", None)
        _tls.sid = self._sid
        return self

    def __exit__(self, *exc) -> None:
        _tls.sid = self._prev


def current_id() -> str | None:
    """The session this thread's work belongs to: the thread binding
    when set, else — the common daemon case — the sole active session.
    With several concurrent pulls an unbound thread resolves to None
    (no stamp) rather than guessing wrong."""
    sid = getattr(_tls, "sid", None)
    if sid:
        return sid
    active = SESSIONS.active_ids()
    if len(active) == 1:
        return active[0]
    return None


def reset() -> None:
    """Tests: fresh table at the env-configured capacity."""
    global SESSIONS
    SESSIONS = SessionTable()
    _tls.sid = None


# Flight-recorder attribution (ISSUE 11 satellite): every recorded
# event — and the crash-report envelope — carries the session id of the
# pull it belongs to, so a `/v1/debug` tail from a busy daemon reads
# per-pull instead of interleaved soup.
recorder.set_session_resolver(current_id)
