"""Anomaly → action: the self-healing control plane (ISSUE 17).

PR 14 made the system *see* its failures — the timeline's streaming
anomaly detector fires ``stall`` / ``throughput_collapse`` /
``queue_stuck`` / ``collective_straggler`` — but every remediation was
still a human reading ``zest top``. This module closes the loop: a
policy engine subscribed to the anomaly stream
(:func:`timeline.add_anomaly_listener`) plus the sampler tick
(:func:`timeline.add_tick_listener`) that maps each firing to a
**bounded, rate-limited, reversible** action through recovery paths
that already exist:

========================  ========  ==============================
anomaly / evidence        action    recovery path it drives
========================  ========  ==============================
stall / collapse on a     hedge     ``XetBridge.arm_hedge`` — the
fetch-bound session                 existing hedge pool races the
                                    next waterfall tier mid-flight
                                    (no deadline required anymore)
collective_straggler      strike    ``health.record_failure`` on
                                    the blamed partner → the
                                    quarantine re-shard path; past
                                    a patience budget, a mid-round
                                    abort down the PR-13 ladder
collapsing seeder         demote    ``health.demote`` + swarm
(served-bytes EWMA +                re-announce — proactive, BEFORE
strike kinds)                       the strike budget exhausts
queue_stuck + SLO burn    shed      ``AdmissionController.shed`` —
projecting a breach                 lowest-deficit queued tenants
                                    get 429/Retry-After; re-admit
                                    when burn recovers
ring-stall growth         tune      ``ZEST_LAND_RING_BYTES``-class
                                    knob nudges within hard rails
========================  ========  ==============================

Safety rails, all pinned by test:

- **Per-action token buckets** (``ZEST_REMEDIATE_BURST`` capacity,
  one token per ``ZEST_REMEDIATE_RATE_S``): a flapping detector can
  never drive an action storm.
- **Enable mask** ``ZEST_REMEDIATE_ACTIONS`` (comma list; default
  all): a masked action records the decision as ``disabled`` and
  touches nothing.
- **Dry-run** (``ZEST_REMEDIATE_DRY=1`` or ``zest heal --dry-run``):
  every decision recorded, no action executed.
- **Oscillation damping**: a knob nudged one way must not nudge back
  within ``ZEST_REMEDIATE_OBSERVE_S`` of the last nudge.
- **Never strike the healthy**: a remediation may drive an action
  against a peer only on anomaly/strike evidence already attributed
  to it; the proactive path (``demote``) explicitly does NOT add a
  strike — see ``HealthRegistry.demote``.
- **Reversible**: hedges race (never cancel the primary), demotion
  expires into the existing probation path, shed tenants re-admit on
  burn recovery, and knob nudges never leave [configured base,
  hard cap].

Every decision — executed or not — is a flight-recorder event (kind
``remediation``) carrying before/after timeline snapshots, a
``zest_remediations_total{action,outcome}`` sample, and a row on
``GET /v1/remediations`` / ``zest heal``. ``ZEST_REMEDIATE=0``
(default **on**) restores pure-observer behavior bit-for-bit: no
listener state, no registered targets, no events, no metric.

Import discipline: telemetry imports nothing from the rest of
``zest_tpu``, so action *targets* (the bridge's hedge armer, the
admission shedder, the swarm's demoter, the collective's abort hook)
are injected by their owners via :func:`register_target` — the same
direction as ``timeline.register_probe``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from zest_tpu.telemetry import metrics, recorder
from zest_tpu.telemetry import session as session_mod
from zest_tpu.telemetry import timeline

ENV_REMEDIATE = "ZEST_REMEDIATE"
ENV_ACTIONS = "ZEST_REMEDIATE_ACTIONS"
ENV_DRY = "ZEST_REMEDIATE_DRY"
ENV_RATE_S = "ZEST_REMEDIATE_RATE_S"
ENV_BURST = "ZEST_REMEDIATE_BURST"
ENV_PATIENCE = "ZEST_REMEDIATE_PATIENCE"
ENV_BURN_MAX = "ZEST_REMEDIATE_BURN_MAX"
ENV_OBSERVE_S = "ZEST_REMEDIATE_OBSERVE_S"

ACTIONS = ("hedge", "strike", "demote", "shed", "tune")

DEFAULT_RATE_S = 10.0     # seconds per replenished token, per action
DEFAULT_BURST = 3         # token-bucket capacity, per action
DEFAULT_PATIENCE = 2      # straggler firings before a mid-round abort
DEFAULT_BURN_MAX = 0.1    # SLO burn ratio that projects a breach
DEFAULT_OBSERVE_S = 30.0  # oscillation-damping / demote-cooldown window
_LOG_CAP = 256            # decision ring behind /v1/remediations
_SNAP_SAMPLES = 8         # samples per series in a before/after snapshot

# Hard rails for the ring auto-tuner: never below the configured base
# (a test's 1 MiB ring must stay 1 MiB-scale), never above base×8 or
# the absolute cap, whichever is smaller.
RING_KNOB = "land_ring_bytes"
RING_GROWTH_CAP = 8
RING_ABS_CAP_BYTES = 4 * 1024 * 1024 * 1024

# Strike kinds that count as "this seeder is going bad" evidence for
# the proactive demote rule (all recorded by OTHER subsystems on real
# failures — the engine itself never invents one).
_DEMOTE_EVIDENCE_KINDS = ("corrupt", "seed_stall", "stalled_reader",
                          "io_timeout", "error")
_DEMOTE_EVIDENCE_STRIKES = 2
# Served-bytes EWMA collapse: recent < this fraction of the peer's own
# peak (and the peak above a noise floor) reads as a collapsing seeder.
_DEMOTE_COLLAPSE_FRACTION = 0.25
_DEMOTE_COLLAPSE_FLOOR = 1 * 1024 * 1024

_KIND_TO_ACTION = {
    timeline.ANOMALY_STALL: "hedge",
    timeline.ANOMALY_COLLAPSE: "hedge",
    timeline.ANOMALY_STRAGGLER: "strike",
    timeline.ANOMALY_QUEUE: "shed",
}

_OFF_VALUES = frozenset({"0", "false", "off", "no"})
_ON_VALUES = frozenset({"1", "true", "on", "yes"})

_M_REMEDIATIONS = metrics.counter(
    "zest_remediations_total",
    "Self-healing control-plane decisions, by action and outcome",
    ("action", "outcome"))


# ── On/off switch (lazy env resolution, same shape as timeline's) ──

_flag_lock = threading.Lock()
_forced: bool | None = None


def enabled() -> bool:
    """Default ON; ``ZEST_REMEDIATE=0`` is the pure-observer rollback.
    Timeline off implies remediate off — there is no anomaly stream to
    subscribe to."""
    if not timeline.enabled():
        return False
    forced = _forced
    if forced is not None:
        return forced
    raw = os.environ.get(ENV_REMEDIATE, "").strip().lower()
    return raw not in _OFF_VALUES


def set_enabled(on: bool | None) -> None:
    """Test/CLI override; ``None`` returns to env resolution."""
    global _forced
    with _flag_lock:
        _forced = on


def parse_actions(raw: str | None) -> frozenset[str]:
    """The ``ZEST_REMEDIATE_ACTIONS`` mask: comma-separated action
    names; empty or ``all`` means every action. Unknown names are
    ignored here (the engine must not crash a pull on a typo) —
    ``Config.load`` is the strict front door that rejects them."""
    raw = (raw or "").strip().lower()
    if not raw or raw == "all":
        return frozenset(ACTIONS)
    return frozenset(p.strip() for p in raw.split(",")
                     if p.strip() in ACTIONS)


def _enabled_actions() -> frozenset[str]:
    return parse_actions(os.environ.get(ENV_ACTIONS))


class _TokenBucket:
    """Per-action rate limit: ``capacity`` tokens, one replenished
    every ``refill_s`` — a flapping detector drains the bucket and the
    engine goes quiet instead of storming the recovery paths."""

    __slots__ = ("capacity", "refill_s", "tokens", "last_t")

    def __init__(self, capacity: int, refill_s: float):
        self.capacity = max(1, capacity)
        self.refill_s = max(refill_s, 1e-9)
        self.tokens = float(self.capacity)
        self.last_t = time.monotonic()

    def take(self, now: float) -> bool:
        self.tokens = min(float(self.capacity),
                          self.tokens + (now - self.last_t) / self.refill_s)
        self.last_t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class RemediationEngine:
    """The policy engine: anomaly/tick subscriber, injected-target
    registry, decision log, and the safety rails."""

    def __init__(self):
        self._lock = threading.RLock()
        self._log: deque = deque(maxlen=_LOG_CAP)
        self._targets: dict[str, object] = {}
        self._buckets: dict[str, _TokenBucket] = {}
        self.counts: dict[tuple[str, str], int] = {}
        # Straggler patience: firings observed since the current
        # collective target registered (one exchange = one budget).
        self._straggler_fired = 0
        # Per-peer demote state: served-bytes peak + last demote time.
        self._peers: dict[str, dict] = {}
        self._shedding = False
        # Knob state: base (configured), value (current), rails, and
        # the last nudge (t, dir) the damping rule checks.
        self._knobs: dict[str, dict] = {}
        self._ring_last: float | None = None
        # HBM-pool tick state (ISSUE 18): last-seen gate-stall seconds
        # and eviction count — growth between ticks is the evidence.
        self._pool_stall_last: float | None = None
        self._pool_evict_last: float | None = None
        # Decisions whose "after" snapshot settles on the next tick
        # (the /v1/remediations view; the flight event carries the
        # immediate post-action snapshot).
        self._pending_after: list[tuple[dict, tuple[str, ...]]] = []

        self.rate_s = _env_float(ENV_RATE_S, DEFAULT_RATE_S, 0.01)
        self.burst = _env_int(ENV_BURST, DEFAULT_BURST, 1)
        self.patience = _env_int(ENV_PATIENCE, DEFAULT_PATIENCE, 1)
        self.burn_max = _env_float(ENV_BURN_MAX, DEFAULT_BURN_MAX, 1e-6)
        self.observe_s = _env_float(ENV_OBSERVE_S, DEFAULT_OBSERVE_S,
                                    0.01)
        raw = os.environ.get(ENV_DRY, "").strip().lower()
        self.dry_run = raw in _ON_VALUES

    # ── Injected targets ──

    def register_target(self, name: str, fn) -> None:
        """Replace semantics, like ``timeline.register_probe``: the
        latest owner of a name wins (benches rebuild swarms)."""
        with self._lock:
            self._targets[name] = fn
            if name == "collective":
                # A fresh exchange gets a fresh patience budget.
                self._straggler_fired = 0

    def unregister_target(self, name: str, fn=None) -> None:
        """With ``fn`` given, remove only if that callable is still the
        registered one — an old owner's teardown must not drop its
        replacement's registration."""
        with self._lock:
            if fn is None or self._targets.get(name) is fn:
                self._targets.pop(name, None)

    # ── Snapshots ──

    def _snapshot(self, names: tuple[str, ...]) -> dict:
        """Tail samples of the named timeline series — the evidence a
        decision was taken on (``before``) or left behind (``after``).
        Pre-serialized structure (lists), so the flight recorder's
        scalar coercion keeps it machine-readable as JSON."""
        store = timeline.STORE
        out: dict = {}
        with store._lock:
            for name in names:
                s = store._series.get(name)
                if s is None:
                    continue
                tail = list(s.ring)[-_SNAP_SAMPLES:]
                out[name] = [[t, v] for _seq, t, v in tail]
        return out

    # ── The decision spine ──

    def _decide(self, action: str, *, kind: str | None = None,
                sid: str | None = None, reason: str = "",
                series: tuple[str, ...] = (), execute=None,
                detail: dict | None = None, gated: bool = True) -> dict:
        """One policy decision end-to-end: mask → token bucket →
        dry-run → execute, with the decision recorded whatever the
        outcome. ``gated=False`` skips mask+bucket — used only for
        *reversal* legs (shed recovery), which must never be the thing
        the rate limit blocks."""
        now = time.monotonic()
        detail = dict(detail or {})
        before = self._snapshot(series)
        outcome = "success"
        with self._lock:
            if gated and action not in _enabled_actions():
                outcome = "disabled"
            elif gated and not self._bucket(action).take(now):
                outcome = "rate_limited"
            elif execute is None:
                outcome = "no_target"
            elif self.dry_run:
                outcome = "dry_run"
        if outcome == "success":
            try:
                result = execute()
                if isinstance(result, dict):
                    detail.update(result)
            except Exception as exc:  # noqa: BLE001 - the control plane
                outcome = "failed"    # must never take the pull down
                detail["error"] = str(exc)
        after = self._snapshot(series)
        entry = {
            "t": round(time.time(), 3),
            "action": action,
            "outcome": outcome,
            "anomaly": kind,
            "session": sid,
            "reason": reason,
            "dry_run": self.dry_run,
            "detail": detail,
            "before": before,
            "after": after,
        }
        with self._lock:
            self._log.append(entry)
            key = (action, outcome)
            self.counts[key] = self.counts.get(key, 0) + 1
            if series:
                self._pending_after.append((entry, series))
        _M_REMEDIATIONS.inc(action=action, outcome=outcome)
        recorder.record(
            "remediation", action=action, outcome=outcome,
            anomaly=kind, session=sid, reason=reason,
            detail=detail, before=before, after=after)
        return entry

    def _bucket(self, action: str) -> _TokenBucket:
        b = self._buckets.get(action)
        if b is None:
            b = self._buckets[action] = _TokenBucket(self.burst,
                                                     self.rate_s)
        return b

    # ── Anomaly-driven actions ──

    def on_anomaly(self, kind: str, session, fields: dict) -> None:
        action = _KIND_TO_ACTION.get(kind)
        if action == "hedge":
            self._act_hedge(kind, session, fields)
        elif action == "strike":
            self._act_straggler(kind, fields)
        elif action == "shed":
            self._act_shed(kind, fields)

    def _act_hedge(self, kind: str, session, fields: dict) -> None:
        """(a) stall / throughput_collapse on a fetch-bound session →
        arm the bridge's mid-flight hedge to the next waterfall tier.
        Evidence replaces the deadline the hedge path used to
        require."""
        sid = getattr(session, "id", None)
        if sid is None:
            return
        phase = (fields or {}).get("phase") or getattr(session, "phase",
                                                       "")
        with self._lock:
            fn = self._targets.get(f"hedge:{sid}")
        if fn is None:
            # No bridge registered for this session — not fetch-bound
            # (or remediation was off when the pull started). Nothing
            # to drive; stay silent rather than log a no_target per
            # stall of an unrelated phase.
            return
        self._decide(
            "hedge", kind=kind, sid=sid,
            reason=f"{kind} in phase {phase or '?'}",
            series=(f"session.{sid}.bytes", "fetch.cdn_bps",
                    "fetch.peer_bps"),
            execute=lambda: fn(f"anomaly:{kind}"),
            detail={"phase": phase})

    def _act_straggler(self, kind: str, fields: dict) -> None:
        """(b) collective_straggler → strike the blamed partner so the
        existing quarantine re-shard path re-plans its ownership on the
        next phase; past the patience budget, request a mid-round abort
        down the PR-13 ladder."""
        partner = (fields or {}).get("partner")
        with self._lock:
            fn = self._targets.get("collective")
            self._straggler_fired += 1
            fired = self._straggler_fired
        if fn is None or partner is None:
            return
        cmd = "abort" if fired >= self.patience else "strike"
        self._decide(
            "strike", kind=kind,
            reason=(f"barrier straggler partner={partner} "
                    f"(firing {fired}/{self.patience})"),
            series=("collective.barrier_s", "collective.phase"),
            execute=lambda: fn(cmd, int(partner)),
            detail={"cmd": cmd, "partner": int(partner),
                    "barrier_wait_s": (fields or {}).get(
                        "barrier_wait_s")})

    def _act_shed(self, kind: str, fields: dict) -> None:
        """(d) queue_stuck + SLO burn projecting a breach → shed the
        lowest-deficit queued tenants with 429/Retry-After."""
        with self._lock:
            fn = self._targets.get("shed")
        if fn is None:
            return
        burn = _worst_burn()
        if burn < self.burn_max:
            self._decide(
                "shed", kind=kind,
                reason=(f"queue stuck but burn {burn:.3f} < "
                        f"{self.burn_max:.3f} — no breach projected"),
                series=("tenancy.queue_depth",),
                execute=lambda: {"skipped": True},
                detail={"burn": round(burn, 4), "cmd": "none"})
            return
        def _shed():
            out = fn("shed")
            with self._lock:
                self._shedding = True
            return out
        self._decide(
            "shed", kind=kind,
            reason=(f"queue stuck with SLO burn {burn:.3f} ≥ "
                    f"{self.burn_max:.3f}"),
            series=("tenancy.queue_depth", "tenancy.active_pulls"),
            execute=_shed,
            detail={"burn": round(burn, 4), "cmd": "shed",
                    "depth": (fields or {}).get("depth")})

    # ── Tick-driven actions ──

    def on_tick(self, store, now: float) -> None:
        self._settle_after()
        self._scan_seeders(now)
        self._maybe_recover_shed()
        self._tune_ring(store, now)
        self._pool_rules(store, now)

    def _settle_after(self) -> None:
        """Fill each recent decision's settled after-snapshot one tick
        later — the /v1/remediations view shows the series AFTER the
        action had a sampling interval to take effect."""
        with self._lock:
            pending, self._pending_after = self._pending_after, []
        for entry, series in pending:
            entry["after"] = self._snapshot(series)

    def _scan_seeders(self, now: float) -> None:
        """(c) collapsing seeder → proactive demote/re-announce BEFORE
        the strike budget exhausts. Evidence only: near-budget strikes,
        repeated bad-kind strikes, or a served-bytes EWMA that fell off
        its own peak — and the demotion itself never adds a strike."""
        with self._lock:
            monitor = self._targets.get("peer_health")
            demote = self._targets.get("demote")
        if monitor is None or demote is None:
            return
        try:
            view = monitor() or {}
        except Exception:  # noqa: BLE001 - a dying monitor drops out
            return
        budget = int(view.get("strike_budget", 3))
        for row in view.get("rows", ()):
            addr = row.get("peer")
            if not addr or row.get("quarantined_for_s"):
                continue
            served = float(row.get("served_bytes_recent") or 0.0)
            st = self._peers.setdefault(addr, {"peak": 0.0,
                                               "demoted_t": None})
            st["peak"] = max(st["peak"], served)
            if (st["demoted_t"] is not None
                    and now - st["demoted_t"] < self.observe_s):
                continue
            strikes = int(row.get("strikes") or 0)
            kinds = row.get("strike_kinds") or {}
            bad = sum(int(kinds.get(k, 0))
                      for k in _DEMOTE_EVIDENCE_KINDS)
            collapsing = (st["peak"] > _DEMOTE_COLLAPSE_FLOOR
                          and served < (_DEMOTE_COLLAPSE_FRACTION
                                        * st["peak"]))
            if strikes >= max(1, budget - 1):
                reason = (f"strikes {strikes} one short of "
                          f"budget {budget}")
            elif bad >= _DEMOTE_EVIDENCE_STRIKES:
                reason = f"{bad} bad-kind strikes ({dict(kinds)})"
            elif collapsing and strikes >= 1:
                reason = (f"served-bytes collapse "
                          f"{int(served)} < 25% of peak "
                          f"{int(st['peak'])} with a strike")
            else:
                continue
            st["demoted_t"] = now
            host, _, port = addr.rpartition(":")
            self._decide(
                "demote", reason=reason,
                series=("seed.bps", "fetch.peer_bps"),
                execute=lambda h=host, p=port: demote((h, int(p))),
                detail={"peer": addr, "strikes": strikes,
                        "served_recent": int(served)})

    def _maybe_recover_shed(self) -> None:
        """The reversal leg of (d): when burn falls back under half the
        trigger, lift shedding so parked tenants re-admit. Ungated —
        recovery must never be what the rate limit blocks."""
        with self._lock:
            if not self._shedding:
                return
            fn = self._targets.get("shed")
        if fn is None:
            with self._lock:
                self._shedding = False
            return
        burn = _worst_burn()
        if burn >= self.burn_max / 2.0:
            return
        def _recover():
            out = fn("recover")
            with self._lock:
                self._shedding = False
            return out
        self._decide(
            "shed",
            reason=(f"burn recovered to {burn:.3f} < "
                    f"{self.burn_max / 2.0:.3f} — re-admitting"),
            series=("tenancy.queue_depth",),
            execute=_recover,
            detail={"burn": round(burn, 4), "cmd": "recover"},
            gated=False)

    # ── The knob auto-tuner ──

    def set_knob_base(self, knob: str, base: int) -> None:
        """Pin a knob's configured base + hard rails. Called by the
        pull path with the value Config resolved — the tuner may only
        move within [base, min(base×8, absolute cap)]."""
        if knob != RING_KNOB:
            return
        with self._lock:
            k = self._knobs.get(knob)
            if k is not None and k["base"] == base:
                return
            self._knobs[knob] = {
                "base": int(base),
                "value": int(base),
                "min": int(base),
                "max": max(int(base),
                           min(int(base) * RING_GROWTH_CAP,
                               RING_ABS_CAP_BYTES)),
                "last_t": None,
                "last_dir": 0,
            }

    def knob_override(self, knob: str) -> int | None:
        """The tuner's current override (None = configured base)."""
        with self._lock:
            k = self._knobs.get(knob)
            if k is None or k["value"] == k["base"]:
                return None
            return int(k["value"])

    def _tune_ring(self, store, now: float) -> None:
        """(e) nudge ``ZEST_LAND_RING_BYTES`` from the observed
        ``ring.stalls`` series: stall growth while a ring is live →
        double within rails; a full quiet observation window → halve
        back toward base. One direction per observation window (the
        damping rail)."""
        with store._lock:
            s = store._series.get("ring.stalls")
            stalls = s.ring[-1][2] if s is not None and s.ring else None
        with self._lock:
            k = self._knobs.get(RING_KNOB)
            if k is None:
                self._ring_last = stalls
                return
            last = self._ring_last
            self._ring_last = stalls
            grew = (stalls is not None and last is not None
                    and stalls > last)
            in_window = (k["last_t"] is not None
                         and now - k["last_t"] < self.observe_s)
            cur = k["value"]
            if grew and cur < k["max"]:
                # Damping: an up-nudge within the window of a DOWN
                # nudge would oscillate; same-direction repeats are
                # also one-per-window (each doubling deserves its own
                # observation).
                if in_window:
                    return
                new, direction = min(k["max"], cur * 2), 1
            elif (not grew and stalls is not None and cur > k["min"]
                    and not in_window and k["last_t"] is not None):
                new, direction = max(k["min"], cur // 2), -1
            else:
                return
            if new == cur:
                return
        self._decide(
            "tune",
            reason=("ring stalls growing" if direction > 0
                    else f"quiet for {self.observe_s:.0f}s — easing "
                         "back toward base"),
            series=("ring.stalls", "ring.in_use_bytes"),
            execute=lambda: self._apply_knob(RING_KNOB, new, direction,
                                            now),
            detail={"knob": RING_KNOB, "from": cur, "to": new,
                    "dir": "up" if direction > 0 else "down"})

    def _pool_rules(self, store, now: float) -> None:
        """(f) HBM serving-pool rules (ISSUE 18), both tick-driven:

        * **cold-land stall → hedge**: ``hbm_pool.gate_stall_s``
          growing between ticks while a land is in flight means a
          decode is blocked on its layer gates — arm the pool's rush
          mode (``pool_land`` target), which flushes every layer
          boundary immediately instead of batching commits. (A cold
          re-land that needs a *network* pull rides the existing
          per-session hedge machinery; this rule covers the local
          landing tail the pool owns.)
        * **pool thrash → shed**: evictions growing between ticks
          means admissions are fighting over the watermark — shed the
          coldest unpinned tree (``pool_shed`` target) so the hot set
          stops churning.
        """

        def _last(name: str) -> float | None:
            with store._lock:
                s = store._series.get(name)
                return (s.ring[-1][2]
                        if s is not None and s.ring else None)

        stall = _last("hbm_pool.gate_stall_s")
        evictions = _last("hbm_pool.evictions")
        landing = _last("hbm_pool.landing")
        with self._lock:
            stall_last, self._pool_stall_last = \
                self._pool_stall_last, stall
            evict_last, self._pool_evict_last = \
                self._pool_evict_last, evictions
            land_fn = self._targets.get("pool_land")
            shed_fn = self._targets.get("pool_shed")
        stall_grew = (stall is not None and stall_last is not None
                      and stall > stall_last + 1e-9)
        if stall_grew and landing and land_fn is not None:
            self._decide(
                "hedge",
                reason=(f"pool gate stall grew to {stall:.2f}s with a "
                        "land in flight — rushing layer flushes"),
                series=("hbm_pool.gate_stall_s", "hbm_pool.landing",
                        "hbm_pool.resident_bytes"),
                execute=lambda: land_fn("rush"),
                detail={"cmd": "rush", "gate_stall_s": round(stall, 3)})
        evict_grew = (evictions is not None and evict_last is not None
                      and evictions > evict_last)
        if evict_grew and shed_fn is not None:
            self._decide(
                "shed",
                reason=(f"pool thrash: evictions grew to "
                        f"{int(evictions)} — shedding the coldest "
                        "model"),
                series=("hbm_pool.evictions", "hbm_pool.resident_bytes",
                        "hbm_pool.pinned_bytes"),
                execute=lambda: shed_fn("shed_coldest"),
                detail={"cmd": "shed_coldest",
                        "evictions": int(evictions)})

    def _apply_knob(self, knob: str, new: int, direction: int,
                    now: float) -> dict:
        with self._lock:
            k = self._knobs[knob]
            k["value"] = int(new)
            k["last_t"] = now
            k["last_dir"] = direction
        return {"applied": int(new)}

    # ── Read side ──

    def payload(self, limit: int = 50) -> dict:
        with self._lock:
            recent = [dict(e) for e in list(self._log)[-limit:]]
            counts: dict[str, dict[str, int]] = {}
            for (action, outcome), n in sorted(self.counts.items()):
                counts.setdefault(action, {})[outcome] = n
            knobs = {name: {kk: vv for kk, vv in k.items()
                            if kk != "last_t"}
                     for name, k in self._knobs.items()}
            return {
                "enabled": True,
                "dry_run": self.dry_run,
                "actions": sorted(_enabled_actions()),
                "rate_s": self.rate_s,
                "burst": self.burst,
                "patience": self.patience,
                "burn_max": self.burn_max,
                "observe_s": self.observe_s,
                "shedding": self._shedding,
                "knobs": knobs,
                "counts": counts,
                "targets": sorted(self._targets),
                "recent": recent,
            }

    def status_block(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "dry_run": self.dry_run,
                "decisions": sum(self.counts.values()),
                "shedding": self._shedding,
            }


def _worst_burn() -> float:
    """The worst SLO burn ratio across armed SLOs (PR-10 burn math:
    breaches/pulls per SLO from the session table) — the breach
    projection behind (d)."""
    try:
        burns = session_mod.SESSIONS.slo_burn()
    except Exception:  # noqa: BLE001 - advisory
        return 0.0
    worst = 0.0
    for row in burns.values():
        b = row.get("burn")
        if isinstance(b, (int, float)):
            worst = max(worst, float(b))
    return worst


def _env_float(name: str, default: float, floor: float) -> float:
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v >= floor else default


def _env_int(name: str, default: int, floor: int) -> int:
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= floor else default


# ── Process-wide instance + module-level hooks ──

ENGINE: RemediationEngine | None = None
_engine_lock = threading.Lock()
_subscribed = False


def ensure_started() -> bool:
    """Build the engine and subscribe it to the anomaly/tick stream
    (idempotent). Called from the pull entry next to
    ``timeline.ensure_started``; a no-op (False) when knob-off — the
    pure-observer contract starts here."""
    if not enabled():
        return False
    global ENGINE, _subscribed
    with _engine_lock:
        if ENGINE is None:
            ENGINE = RemediationEngine()
        if not _subscribed:
            timeline.add_anomaly_listener(_on_anomaly)
            timeline.add_tick_listener(_on_tick)
            _subscribed = True
    return True


def _on_anomaly(kind: str, session, fields: dict) -> None:
    eng = ENGINE
    if eng is not None and enabled():
        eng.on_anomaly(kind, session, fields)


def _on_tick(store, now: float) -> None:
    eng = ENGINE
    if eng is not None and enabled():
        eng.on_tick(store, now)


def register_target(name: str, fn) -> bool:
    """Inject an action target (``hedge:<sid>``, ``collective``,
    ``shed``, ``demote``, ``peer_health``). No-op (False) when the
    engine is off — with ``ZEST_REMEDIATE=0`` no owner leaves a trace
    here."""
    if not ensure_started():
        return False
    ENGINE.register_target(name, fn)
    return True


def unregister_target(name: str, fn=None) -> None:
    eng = ENGINE
    if eng is not None:
        eng.unregister_target(name, fn)


def set_knob_base(knob: str, base: int) -> None:
    if ensure_started():
        ENGINE.set_knob_base(knob, base)


def knob_override(knob: str) -> int | None:
    eng = ENGINE
    if eng is None or not enabled():
        return None
    return eng.knob_override(knob)


def set_dry_run(on: bool) -> bool:
    """The ``zest heal --dry-run`` toggle (POST /v1/remediations).
    Returns the dry-run state now in effect."""
    if not ensure_started():
        return False
    ENGINE.dry_run = bool(on)
    return ENGINE.dry_run


def payload(limit: int = 50) -> dict:
    """The ``GET /v1/remediations`` document (an explicit
    ``enabled: false`` stub when knob-off, mirroring timeline)."""
    eng = ENGINE
    if not enabled() or eng is None:
        return {"enabled": enabled(), "counts": {}, "recent": []}
    return eng.payload(limit=limit)


def status_block() -> dict:
    """The ``remediate`` block for ``/v1/status``."""
    eng = ENGINE
    if not enabled() or eng is None:
        return {"enabled": enabled()}
    return eng.status_block()


def reset() -> None:
    """Tests: drop the engine, unsubscribe, unresolve the flag."""
    global ENGINE, _subscribed
    with _engine_lock:
        ENGINE = None
        _subscribed = False
    set_enabled(None)
