"""Flight recorder: a bounded ring of the last N *notable* events.

Post-hoc triage of a chaos-matrix run (or a real pod incident) used to
be log archaeology: the interesting facts — which peer struck out, when
the circuit breaker tripped, which exchange units fell back to CDN,
what fault the injector fired right before the landing went sideways —
are scattered across per-module counters that say *how many* but never
*when* or *in what order*. The recorder is the ordering: every notable
event lands in one process-wide ring with a wall-clock timestamp, the
thread's open-span stack (so an event anchors into the Perfetto trace),
and the fleet trace context (``trace_id``/``host``), and the ring is

- served live at ``GET /v1/debug`` (the dashboard tails it),
- dumped to a JSON crash report on pull failure / SIGTERM / an
  operator's ``zest debug --out report.json``.

Event kinds recorded by the instrumented sites (ISSUE 7):

==================  ====================================================
``fault_fired``     the chaos injector fired (zest_tpu.faults)
``peer_strike``     a health strike (p2p.health; kind= the failure)
``peer_quarantined``the strike tripped the circuit breaker
``cdn_fallback``    an exchange/federated unit degraded to the waterfall
``verify_rejected`` a peer/owner blob failed verification at the trust
                    boundary
``budget_decline``  a byte-budget handoff declined to the slow lane
``pull_failed``     pull_model is about to re-raise (dumps the report)
==================  ====================================================

Same zero-cost discipline as every other telemetry surface: with
``ZEST_TELEMETRY=0`` ``record()`` is one flag check; the ring itself is
a deque append under a lock otherwise (the sites are failure paths and
coarse-grained events, never per-chunk hot loops).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from zest_tpu.telemetry import state, trace

ENV_EVENTS = "ZEST_RECORDER_EVENTS"
DEFAULT_EVENTS = 512

# Session attribution (ISSUE 11): an injected ``fn() -> session id or
# None``
# the session table registers at import — the recorder must not import
# the session module (it would invert the package's dependency order),
# but every event a busy daemon records should say WHICH pull it
# belongs to.
_session_resolver = None


def set_session_resolver(fn) -> None:
    global _session_resolver
    _session_resolver = fn


def _current_session() -> str | None:
    if _session_resolver is None:
        return None
    try:
        return _session_resolver()
    except Exception:  # noqa: BLE001 - attribution must never break recording
        return None


class FlightRecorder:
    """Thread-safe bounded event ring for one process."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(ENV_EVENTS, DEFAULT_EVENTS))
            except ValueError:
                capacity = DEFAULT_EVENTS
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self.recorded = 0  # lifetime count (ring length caps at capacity)

    def record(self, kind: str, /, **fields) -> None:
        ev: dict = {"t": round(time.time(), 6), "kind": kind}
        spans = trace.open_spans()
        if spans:
            ev["span"] = spans[-1]
        ctx = trace.current_context()
        if ctx:
            ev.update({k: v for k, v in ctx.items() if k not in ev})
        sid = _current_session()
        if sid is not None and "session" not in ev:
            ev["session"] = sid
        for k, v in fields.items():
            if v is None:
                continue
            if k in ("t", "kind"):  # field names the envelope owns
                k = f"{k}_"
            if isinstance(v, (str, int, float, bool)):
                ev[k] = v
            elif isinstance(v, (dict, list, tuple)):
                # Structured payloads (the remediation events' before/
                # after timeline snapshots, ISSUE 17) stay machine-
                # readable when JSON-clean; anything dirtier falls back
                # to the scalar coercion below.
                try:
                    json.dumps(v)
                except (TypeError, ValueError):
                    ev[k] = str(v)
                else:
                    ev[k] = list(v) if isinstance(v, tuple) else v
            else:
                ev[k] = str(v)
        with self._lock:
            self._ring.append(ev)
            self.recorded += 1

    def tail(self, n: int | None = None) -> list[dict]:
        with self._lock:
            events = list(self._ring)
        if n is None:
            return events
        return events[-n:] if n > 0 else []  # [-0:] would be ALL

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.recorded = 0

    # ── Crash report ──

    def report(self, reason: str = "") -> dict:
        ctx = trace.current_context()
        doc = {
            "tool": "zest-tpu",
            "kind": "flight-recorder",
            "reason": reason,
            "dumped_at": round(time.time(), 6),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "recorded_total": self.recorded,
            "events": self.tail(),
        }
        if ctx:
            doc["context"] = ctx
        sid = _current_session()
        if sid is not None:
            doc["session"] = sid
        return doc

    def dump(self, path: str | os.PathLike, reason: str = "") -> str:
        """Write the crash-report JSON (atomic tmp+rename, same
        discipline as the trace export); returns the path written."""
        path = os.fspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.report(reason), f, indent=1)
        os.replace(tmp, path)
        return path


# ── Process-wide instance + module-level hooks ──

RECORDER = FlightRecorder()


def record(kind: str, /, **fields) -> None:
    """The hot-path hook: one flag check when telemetry is off."""
    if not state.enabled():
        return
    RECORDER.record(kind, **fields)


def tail(n: int | None = None) -> list[dict]:
    return RECORDER.tail(n)


def dump_crash_report(cache_dir, reason: str) -> str | None:
    """Dump under ``{cache_dir}/crash/`` with a timestamped name; None
    when telemetry is off or the ring is empty (an empty report would
    only bury the real one). Best-effort: a failing dump must never
    mask the exception that triggered it."""
    if not state.enabled() or not RECORDER.tail(1):
        return None
    try:
        name = f"zest-crash-{int(time.time())}-{os.getpid()}.json"
        return RECORDER.dump(os.path.join(os.fspath(cache_dir),
                                          "crash", name), reason)
    except OSError:
        return None


def reset() -> None:
    """Tests: fresh ring at the env-configured capacity."""
    global RECORDER
    RECORDER = FlightRecorder()
