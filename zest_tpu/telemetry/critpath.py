"""Automated critical-path attribution over completed trace docs.

The PR-7 Perfetto traces answer "what happened during this pull" only
when a human eyeballs them. This module is the machine: given a trace
— a live :class:`~zest_tpu.telemetry.trace.Tracer`, a solo exported
Chrome doc, or a ``fleet.merge_traces`` multi-host doc — it computes
the **blame-attributed critical path** through the span set and
reports where the wall time actually went: per-stage and per-tier
exclusive seconds, the top blocking spans, and the
fetch/decode/verify/commit split. It powers ``stats["critical_path"]``
on traced pulls, the ``zest analyze <trace.json>`` CLI, the SLO
breach events' ``blamed_stage``, and the ``critpath_smoke.py`` CI
gate.

Attribution model
-----------------
Spans carry no explicit dependency edges, so the path is derived from
the wall timeline the way trace-profilers conventionally do it: walk
the root ``pull`` span's wall from start to end; at every instant,
blame the **most specific active span** — the one with the latest
start time, which for properly nested spans is exactly the deepest
one, and across threads is the most recently dispatched work. Each
span's *blamed* time is therefore its exclusive time minus any window
where deeper/more-recent work ran — summing the blames tiles the root
wall exactly (minus ``idle_s``: wall covered by no span but the root,
i.e. untraced time). The stage split sums to the path length by
construction, which is what the CI smoke asserts.

The sweep is O(n log n) in the span count: one sorted boundary pass
with a lazy max-heap of active spans.
"""

from __future__ import annotations

_SKIP_NAMES = frozenset({
    # A stat interval, not work: anchored at the pull's t0 and covering
    # everything up to the first-layer commit — blaming it would hide
    # the real stages beneath it.
    "stage.first_layer",
})

# Ordered (prefix, category) rules; first match wins. "verify" anywhere
# in the name beats the prefix table (pod/coop verification spans are
# nested under fetch-ish parents).
_CATEGORY_PREFIXES = (
    # Tenancy admission wait (ISSUE 15): time parked in the fair queue
    # is its own stage — "queued" — not fetch work and not untraced
    # idle. A pull that spent 40 s queued and 5 s fetching must blame
    # the queue, or the analyzer would tell the operator to tune the
    # CDN.
    ("tenancy.queued", "queued"),
    ("stage.resolve", "metadata"),
    ("stage.cas_metadata", "metadata"),
    ("cas.reconstruction", "metadata"),
    ("stage.fetch", "fetch"),
    ("fetch.", "fetch"),
    ("cdn.", "fetch"),
    ("swarm.", "fetch"),
    ("peer.", "fetch"),
    ("dcn.", "fetch"),
    # Collective exchange (ISSUE 14/15): phase spans are byte movement
    # — they blame as "fetch" with the wire link class as the tier
    # (``link`` attr: dcn cross-slice, ici within a slice), so the
    # per-tier fetch split stays one comparable ledger whether the
    # bytes came over the waterfall or the collective. Barrier spans
    # blame as "barrier" (a lagging partner's idle, which is neither
    # fetch nor exchange work — the straggler signal).
    ("coop.collective.barrier", "barrier"),
    ("coop.collective.", "fetch"),
    ("coop.exchange", "exchange"),
    ("coop.", "fetch"),
    # Transport-split spans (ISSUE 20): the pluggable exchange backends
    # emit bare ``collective.*`` names (lane packing, loopback serve)
    # that are NOT nested under a ``coop.collective.`` phase prefix —
    # they are exchange work and must blame as such, not vanish into
    # "other". Checkpoint fan-out spans (``push`` and any ``push.*``
    # child) get their own stage for the same reason: a publisher
    # process's wall is push work, and "other" at 90% tells the
    # operator nothing.
    ("collective.", "exchange"),
    ("push", "push"),
    ("federated.", "fetch"),
    ("pod.", "fetch"),
    ("warm.", "fetch"),
    ("cas.", "fetch"),
    ("land.", "decode"),  # land.decode + land.slice (the run lane)
    ("stage.decode", "decode"),
    ("hbm.commit", "commit"),
    ("stage.hbm_commit", "commit"),
    ("delta.swap", "commit"),
    ("stage.files", "files"),
    ("files.", "files"),
)


class AnalyzeError(ValueError):
    """The doc cannot be analyzed (no root span, malformed events)."""


def categorize(name: str) -> str:
    if "verify" in name:
        return "verify"
    for prefix, cat in _CATEGORY_PREFIXES:
        if name.startswith(prefix):
            return cat
    return "other"


def _tier_of(name: str, attrs: dict) -> str | None:
    """Serving tier of a fetch-category span, for the per-tier split."""
    t = attrs.get("tier") or attrs.get("source")
    if t:
        return str(t)
    if name.startswith("coop.collective."):
        # Phase spans carry the link class (ici intra-slice, dcn
        # cross-slice); an attr-less one is wire movement all the same.
        return str(attrs.get("link") or "dcn")
    if name.startswith("cdn."):
        return "cdn"
    if name.startswith(("swarm.", "peer.")):
        return "peer"
    if name.startswith("dcn."):
        return "dcn"
    return None


class _Iv:
    __slots__ = ("name", "t0", "t1", "attrs")

    def __init__(self, name: str, t0: float, t1: float, attrs: dict):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs


def _pick_root(ivs: list[_Iv], root_name: str, newest: bool) -> _Iv:
    roots = [s for s in ivs if s.name == root_name]
    if not roots:
        raise AnalyzeError(f"no root {root_name!r} span in the trace")
    if newest:
        # The LAST pull that finished — what stats["critical_path"]
        # wants from a long-lived daemon's accumulated tracer.
        return max(roots, key=lambda s: s.t1)
    # The dominant pull — what an exported doc analysis wants.
    return max(roots, key=lambda s: s.t1 - s.t0)


def _analyze(ivs: list[_Iv], root_name: str = "pull", top_k: int = 8,
             newest_root: bool = False, root: _Iv | None = None) -> dict:
    import heapq

    if root is None:
        root = _pick_root(ivs, root_name, newest_root)
    r0, r1 = root.t0, root.t1
    if r1 <= r0:
        raise AnalyzeError("root span has no duration")
    spans = [s for s in ivs
             if s is not root and s.name not in _SKIP_NAMES
             and s.name != root_name
             and s.t1 > r0 and s.t0 < r1]
    for s in spans:  # clip to the root window
        s.t0 = max(s.t0, r0)
        s.t1 = min(s.t1, r1)

    boundaries = sorted({r0, r1, *(s.t0 for s in spans),
                         *(s.t1 for s in spans)})
    spans.sort(key=lambda s: s.t0)
    # Heap entries: (-t0, t1, idx) — the top is the latest-started
    # active span (ties go to the shorter span: for same-start nesting
    # the deepest span is the shortest). Lazy deletion: an entry whose
    # span ended at or before the segment start is dead for every later
    # segment too (time only advances), so it pops permanently.
    heap: list[tuple[float, float, int]] = []
    next_span = 0
    blamed_s: dict[int, float] = {}
    idle_s = 0.0
    path: list[tuple[int | None, float, float]] = []  # merged segments
    for a, b in zip(boundaries, boundaries[1:]):
        while next_span < len(spans) and spans[next_span].t0 <= a:
            heapq.heappush(heap, (-spans[next_span].t0,
                                  spans[next_span].t1, next_span))
            next_span += 1
        while heap and spans[heap[0][2]].t1 <= a:
            heapq.heappop(heap)
        if heap:
            idx = heap[0][2]
            blamed_s[idx] = blamed_s.get(idx, 0.0) + (b - a)
        else:
            idx = None
            idle_s += b - a
        if path and path[-1][0] == idx and abs(path[-1][2] - a) < 1e-12:
            path[-1] = (idx, path[-1][1], b)
        else:
            path.append((idx, a, b))

    stages: dict[str, float] = {}
    tiers: dict[str, float] = {}
    by_name: dict[str, float] = {}
    for idx, sec in blamed_s.items():
        s = spans[idx]
        cat = categorize(s.name)
        stages[cat] = stages.get(cat, 0.0) + sec
        by_name[s.name] = by_name.get(s.name, 0.0) + sec
        if cat == "fetch":
            tier = _tier_of(s.name, s.attrs)
            if tier:
                tiers[tier] = tiers.get(tier, 0.0) + sec
    path_s = sum(blamed_s.values())
    wall = r1 - r0

    top = sorted(blamed_s.items(), key=lambda kv: kv[1], reverse=True)
    top_spans = []
    for idx, sec in top[:max(0, top_k)]:
        s = spans[idx]
        top_spans.append({
            "name": s.name,
            "category": categorize(s.name),
            "start_s": round(s.t0 - r0, 4),
            "dur_s": round(s.t1 - s.t0, 4),
            "blamed_s": round(sec, 4),
        })

    doc = {
        "root": {"name": root.name, "wall_s": round(wall, 4)},
        "path_s": round(path_s, 4),
        "idle_s": round(idle_s, 4),
        "coverage": round(path_s / wall, 4),
        "steps": len(path),
        "stages": {k: round(v, 4) for k, v in
                   sorted(stages.items(), key=lambda kv: -kv[1])},
        "top_spans": top_spans,
    }
    if tiers:
        doc["tiers"] = {k: round(v, 4) for k, v in
                        sorted(tiers.items(), key=lambda kv: -kv[1])}
    by = sorted(by_name.items(), key=lambda kv: -kv[1])[:12]
    doc["by_name"] = {k: round(v, 4) for k, v in by}
    for key in ("repo", "revision", "host"):
        if key in root.attrs:
            doc["root"][key] = root.attrs[key]
    return doc


def analyze_tracer(tracer, root_name: str = "pull", top_k: int = 8,
                   root_span=None) -> dict | None:
    """Analyze a live tracer's recorded spans. ``root_span`` — the
    caller's own just-closed root :class:`~zest_tpu.telemetry.trace.
    Span` — pins the analysis window exactly (pull_model passes its
    root, so a daemon's accumulated tracer can never hand pull A
    another pull's root). Without it, the *newest* finished root is
    picked. Returns None when no root exists yet (tracer armed
    mid-pull).

    Caveat, inherent to a process-global tracer: spans from an
    overlapping concurrent pull that fall inside the window are not
    distinguishable (spans carry no per-pull identity) and share the
    blame; the per-session surfaces (``/v1/pulls``) stay correct —
    only the trace-level attribution blurs, exactly as the shared
    ``ZEST_TRACE`` file itself does."""
    ivs = [_Iv(s.name, s.t0, s.t1, s.attrs) for s in tracer.spans()]
    root = None
    if root_span is not None and getattr(root_span, "t1", 0):
        root = _Iv(root_span.name, root_span.t0, root_span.t1,
                   dict(root_span.attrs))
    try:
        return _analyze(ivs, root_name=root_name, top_k=top_k,
                        newest_root=True, root=root)
    except AnalyzeError:
        return None


def analyze_doc(doc: dict, host=None, root_name: str = "pull",
                top_k: int = 8) -> dict:
    """Analyze an exported Chrome trace doc (solo export or a
    ``fleet.merge_traces`` multi-host doc). For merged docs the
    analysis is confined to ONE host's spans — ``host`` selects it,
    defaulting to the host of the dominant root span (mixing hosts
    would blame one host's clock against another's). Raises
    :class:`AnalyzeError` when no root span is found. Accepts both
    Chrome trace forms: the object form (``{"traceEvents": [...]}``)
    our exporter writes and the bare-array variant other tools emit."""
    if isinstance(doc, list):
        raw = doc
    elif isinstance(doc, dict):
        raw = doc.get("traceEvents", [])
    else:
        raise AnalyzeError("not a Chrome trace document")
    events = [e for e in raw
              if isinstance(e, dict) and e.get("ph") == "X"]
    ivs = []
    for e in events:
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) \
                or not isinstance(dur, (int, float)):
            continue
        ivs.append(_Iv(str(e.get("name", "")), ts / 1e6,
                       (ts + dur) / 1e6, e.get("args") or {}))
    if host is None:
        root = _pick_root(ivs, root_name, newest=False)
        host = root.attrs.get("host")
    if host is not None:
        ivs = [s for s in ivs
               if str(s.attrs.get("host", host)) == str(host)]
    return _analyze(ivs, root_name=root_name, top_k=top_k)


def render_text(report: dict) -> list[str]:
    """Human-readable summary lines for ``zest analyze``."""
    root = report["root"]
    head = f"critical path: {root.get('name', 'pull')}"
    if root.get("repo"):
        head += f" {root['repo']}"
        if root.get("revision"):
            head += f"@{str(root['revision'])[:12]}"
    if root.get("host") is not None:
        head += f" (host {root['host']})"
    lines = [
        head,
        f"  wall {root['wall_s']}s — path {report['path_s']}s "
        f"({report['coverage']:.0%} attributed), "
        f"idle {report['idle_s']}s, {report['steps']} steps",
        "  stage split:",
    ]
    path_s = report["path_s"] or 1.0
    for stage, sec in report["stages"].items():
        lines.append(f"    {stage:<9} {sec:>9.3f}s  {sec / path_s:>5.1%}")
    if report.get("tiers"):
        lines.append("  fetch tiers: " + "  ".join(
            f"{t}={sec:.3f}s" for t, sec in report["tiers"].items()))
    lines.append("  top blocking spans:")
    for s in report["top_spans"]:
        lines.append(
            f"    {s['blamed_s']:>8.3f}s  {s['name']:<22} "
            f"[{s['category']}]  @+{s['start_s']:.3f}s "
            f"(span {s['dur_s']:.3f}s)")
    return lines
