// Native LZ4 block codec (independent implementation from the public spec).
//
// Same stream format as the pure-Python codec in zest_tpu/cas/compression.py;
// the two are cross-checked in tests/test_compression.py (python-compress ->
// native-decompress and vice versa).
//
// C ABI:
//   zest_lz4_compress(src, n, dst, dst_cap) -> compressed size, or 0 on
//     insufficient dst_cap (callers size dst with zest_lz4_bound).
//   zest_lz4_decompress(src, n, dst, expected) -> expected on success,
//     0 on malformed input.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr size_t MIN_MATCH = 4;
constexpr size_t HASH_LOG = 16;
constexpr size_t MAX_OFFSET = 0xFFFF;
// Incompressible-run acceleration (the reference LZ4 "skip trigger"):
// after every 2^SKIP_TRIGGER consecutive match misses the scan step
// grows by one, so random data degenerates to a fast skip + one big
// literal copy instead of a per-byte probe. The Python encoder
// (_lz4_compress_py) applies the same schedule.
constexpr size_t SKIP_TRIGGER = 6;

inline uint32_t hash4(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return (v * 2654435761u) >> (32 - HASH_LOG);
}

inline uint8_t* emit_varlen(uint8_t* op, size_t v) {
  while (v >= 255) {
    *op++ = 255;
    v -= 255;
  }
  *op++ = (uint8_t)v;
  return op;
}

}  // namespace

extern "C" {

size_t zest_lz4_bound(size_t n) { return n + n / 255 + 16; }

size_t zest_lz4_compress(const uint8_t* src, size_t n, uint8_t* dst,
                         size_t dst_cap) {
  if (dst_cap < zest_lz4_bound(n)) return 0;
  uint8_t* op = dst;
  if (n == 0) {
    *op++ = 0;
    return (size_t)(op - dst);
  }

  int32_t table[1u << HASH_LOG];
  std::memset(table, -1, sizeof(table));

  size_t anchor = 0;
  size_t pos = 0;
  // Spec end conditions: last 5 bytes literals, last match starts >= 12
  // bytes before the end.
  size_t match_limit = n >= 12 ? n - 12 : 0;

  size_t search = 1u << SKIP_TRIGGER;
  while (pos < match_limit) {
    uint32_t h = hash4(src + pos);
    int32_t cand = table[h];
    table[h] = (int32_t)pos;
    if (cand < 0 || pos - (size_t)cand > MAX_OFFSET ||
        std::memcmp(src + cand, src + pos, 4) != 0) {
      pos += search++ >> SKIP_TRIGGER;
      continue;
    }
    search = 1u << SKIP_TRIGGER;
    size_t mlen = 4;
    size_t limit = n - 5;
    while (pos + mlen < limit && src[cand + mlen] == src[pos + mlen]) mlen++;

    size_t lit_len = pos - anchor;
    size_t ml = mlen - MIN_MATCH;
    *op++ = (uint8_t)((lit_len < 15 ? lit_len : 15) << 4 |
                      (ml < 15 ? ml : 15));
    if (lit_len >= 15) op = emit_varlen(op, lit_len - 15);
    std::memcpy(op, src + anchor, lit_len);
    op += lit_len;
    uint16_t offset = (uint16_t)(pos - (size_t)cand);
    *op++ = (uint8_t)offset;
    *op++ = (uint8_t)(offset >> 8);
    if (ml >= 15) op = emit_varlen(op, ml - 15);

    pos += mlen;
    anchor = pos;
  }

  size_t lit_len = n - anchor;
  *op++ = (uint8_t)((lit_len < 15 ? lit_len : 15) << 4);
  if (lit_len >= 15) op = emit_varlen(op, lit_len - 15);
  std::memcpy(op, src + anchor, lit_len);
  op += lit_len;
  return (size_t)(op - dst);
}

size_t zest_lz4_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                           size_t expected) {
  size_t ip = 0;
  size_t out = 0;
  while (ip < n) {
    uint8_t token = src[ip++];
    size_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= n) return 0;
        b = src[ip++];
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > n || out + lit_len > expected) return 0;
    std::memcpy(dst + out, src + ip, lit_len);
    ip += lit_len;
    out += lit_len;
    if (ip == n) break;  // final literals-only sequence
    if (ip + 2 > n) return 0;
    size_t offset = (size_t)src[ip] | ((size_t)src[ip + 1] << 8);
    ip += 2;
    if (offset == 0 || offset > out) return 0;
    size_t mlen = (token & 0xF) + MIN_MATCH;
    if ((token & 0xF) == 15) {
      uint8_t b;
      do {
        if (ip >= n) return 0;
        b = src[ip++];
        mlen += b;
      } while (b == 255);
    }
    if (out + mlen > expected) return 0;
    // Byte-sequential copy: overlapping matches replicate correctly.
    const uint8_t* match = dst + out - offset;
    for (size_t i = 0; i < mlen; i++) dst[out + i] = match[i];
    out += mlen;
  }
  return out == expected ? expected : 0;
}

}  // extern "C"
