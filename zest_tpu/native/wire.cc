// Native DCN wire framing — the host-to-host transport hot path.
//
// The reference's serving loop frames every CHUNK_RESPONSE through
// src/bt_wire.zig (bt_wire_frame bench: 11,943 MB/s, BASELINE.md); the
// Python codecs in zest_tpu/p2p/wire.py are byte-identical but copy the
// chunk data three times (sub-payload + extended + frame concats). These
// entry points build the complete framed message in one pass into a
// caller-provided buffer, so a 64 KiB chunk is copied exactly once.
//
// Frame layout (BEP 3 + BEP 10 + BEP XET, src/bt_wire.zig:89-146 and
// src/bep_xet.zig:66-124):
//   [4 len BE][1 msg_id=20][1 ext_id][1 kind][...kind-specific...]
//
// Exposed C ABI (consumed via ctypes in zest_tpu/native/__init__.py):
//   zest_wire_response_size(data_len)            -> total framed bytes
//   zest_wire_frame_chunk_response(...)          -> bytes written
//   zest_wire_frame_chunk_request(...)           -> bytes written (51)
//   zest_wire_frame_chunk_not_found(...)         -> bytes written (43)

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr uint8_t MSG_EXTENDED = 20;
constexpr uint8_t XET_CHUNK_REQUEST = 0x01;
constexpr uint8_t XET_CHUNK_RESPONSE = 0x02;
constexpr uint8_t XET_CHUNK_NOT_FOUND = 0x03;

inline uint8_t* put32be(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)(v >> 24);
  p[1] = (uint8_t)(v >> 16);
  p[2] = (uint8_t)(v >> 8);
  p[3] = (uint8_t)v;
  return p + 4;
}

// Common prefix: [4 len BE][20][ext_id][kind]; returns cursor past kind.
inline uint8_t* put_prefix(uint8_t* p, uint32_t body_len, uint8_t ext_id,
                           uint8_t kind) {
  p = put32be(p, body_len);
  *p++ = MSG_EXTENDED;
  *p++ = ext_id;
  *p++ = kind;
  return p;
}

}  // namespace

extern "C" {

// Total framed size of a CHUNK_RESPONSE carrying data_len payload bytes.
size_t zest_wire_response_size(size_t data_len) {
  return 4 + 2 + 13 + data_len;  // len + [20, ext] + xet hdr + data
}

// [4 len][20][ext][0x02][4 req][4 offset][4 dlen][data]; one memcpy.
size_t zest_wire_frame_chunk_response(uint8_t ext_id, uint32_t req_id,
                                      uint32_t chunk_offset,
                                      const uint8_t* data, size_t data_len,
                                      uint8_t* out) {
  uint8_t* p = put_prefix(out, (uint32_t)(2 + 13 + data_len), ext_id,
                          XET_CHUNK_RESPONSE);
  p = put32be(p, req_id);
  p = put32be(p, chunk_offset);
  p = put32be(p, (uint32_t)data_len);
  if (data_len) std::memcpy(p, data, data_len);
  return (size_t)(p - out) + data_len;
}

// [4 len][20][ext][0x01][4 req][32 hash][4 start][4 end] = 51 bytes.
size_t zest_wire_frame_chunk_request(uint8_t ext_id, uint32_t req_id,
                                     const uint8_t* hash32,
                                     uint32_t range_start, uint32_t range_end,
                                     uint8_t* out) {
  uint8_t* p = put_prefix(out, 2 + 45, ext_id, XET_CHUNK_REQUEST);
  p = put32be(p, req_id);
  std::memcpy(p, hash32, 32);
  p += 32;
  p = put32be(p, range_start);
  p = put32be(p, range_end);
  return (size_t)(p - out);
}

// [4 len][20][ext][0x03][4 req][32 hash] = 43 bytes.
size_t zest_wire_frame_chunk_not_found(uint8_t ext_id, uint32_t req_id,
                                       const uint8_t* hash32, uint8_t* out) {
  uint8_t* p = put_prefix(out, 2 + 37, ext_id, XET_CHUNK_NOT_FOUND);
  p = put32be(p, req_id);
  std::memcpy(p, hash32, 32);
  p += 32;
  return (size_t)(p - out);
}

}  // extern "C"
