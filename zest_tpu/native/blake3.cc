// Native BLAKE3 for the host hot path (chunk verification, CDC dedup).
//
// Independent implementation from the BLAKE3 spec; validated against the
// pure-Python anchor (zest_tpu/cas/blake3.py) and the official test
// vectors in tests/test_blake3.py. The reference gets this from zig-xet's
// `hashing` module (SURVEY.md section 2.2); its headline microbenchmark is
// blake3_64kb at 3517 MB/s (BASELINE.md) — beat it here.
//
// Exposed C ABI (consumed via ctypes in zest_tpu/native/__init__.py):
//   zest_blake3(data, len, out32)
//   zest_blake3_keyed(key32, data, len, out32)
//   zest_blake3_batch(data, count, item_len, out32xN)   — many equal-size items
//
// Layout notes: the hot path is an 8-wide AVX2 core that hashes eight
// complete 1 KiB BLAKE3 chunks at once in transposed (SoA) form — one
// chunk per 32-bit lane of a ymm register, the same lanes-carry-chunks
// layout as the Pallas TPU kernel (zest_tpu/ops/blake3_pallas.py). The
// scalar core (compiled -O3 -march=native) handles tails, parent folds,
// and non-AVX2 builds, and is the bit-exactness anchor the wide path is
// tested against.

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr size_t BLOCK_LEN = 64;
constexpr size_t CHUNK_LEN = 1024;
constexpr size_t KEY_WORDS = 8;

constexpr uint32_t CHUNK_START = 1 << 0;
constexpr uint32_t CHUNK_END = 1 << 1;
constexpr uint32_t PARENT = 1 << 2;
constexpr uint32_t ROOT = 1 << 3;
constexpr uint32_t KEYED_HASH = 1 << 4;

constexpr uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};

inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

inline uint32_t load32le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline void store32le(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

#define G(a, b, c, d, mx, my)          \
  do {                                 \
    a = a + b + (mx);                  \
    d = rotr32(d ^ a, 16);             \
    c = c + d;                         \
    b = rotr32(b ^ c, 12);             \
    a = a + b + (my);                  \
    d = rotr32(d ^ a, 8);              \
    c = c + d;                         \
    b = rotr32(b ^ c, 7);              \
  } while (0)

// Per-round message-word schedules (the standard permutation advanced r
// times): rounds index the message statically instead of materializing
// a permuted copy per round — shared by the scalar core and (via the
// same table under the SIMD section) the wide cores. Dropping the
// per-round 16-word permute+copy measurably speeds the scalar core,
// which also runs every parent fold in the tree.
constexpr int SCHED[7][16] = {
    { 0,  1,  2,  3,  4,  5,  6,  7,  8,  9, 10, 11, 12, 13, 14, 15},
    { 2,  6,  3, 10,  7,  0,  4, 13,  1, 11, 12,  5,  9, 14, 15,  8},
    { 3,  4, 10, 12, 13,  2,  7, 14,  6,  5,  9,  0, 11, 15,  8,  1},
    {10,  7, 12,  9, 14,  3, 13, 15,  4,  0, 11,  2,  5,  8,  1,  6},
    {12, 13,  9, 11, 15, 10, 14,  8,  7,  2,  5,  3,  0,  1,  6,  4},
    { 9, 14, 11,  5,  8, 12, 15,  1, 13,  3,  0, 10,  2,  6,  4,  7},
    {11, 15,  5,  0,  1,  9,  8,  6, 14, 10,  2, 12,  3,  4,  7, 13},
};

// One full compression. `out16` receives the 16-word extended output.
void compress(const uint32_t cv[8], const uint32_t m[16], uint64_t counter,
              uint32_t block_len, uint32_t flags, uint32_t out16[16]) {
  uint32_t v0 = cv[0], v1 = cv[1], v2 = cv[2], v3 = cv[3];
  uint32_t v4 = cv[4], v5 = cv[5], v6 = cv[6], v7 = cv[7];
  uint32_t v8 = IV[0], v9 = IV[1], v10 = IV[2], v11 = IV[3];
  uint32_t v12 = (uint32_t)counter, v13 = (uint32_t)(counter >> 32);
  uint32_t v14 = block_len, v15 = flags;

  for (int r = 0; r < 7; r++) {
    const int* s = SCHED[r];
    G(v0, v4, v8, v12, m[s[0]], m[s[1]]);
    G(v1, v5, v9, v13, m[s[2]], m[s[3]]);
    G(v2, v6, v10, v14, m[s[4]], m[s[5]]);
    G(v3, v7, v11, v15, m[s[6]], m[s[7]]);
    G(v0, v5, v10, v15, m[s[8]], m[s[9]]);
    G(v1, v6, v11, v12, m[s[10]], m[s[11]]);
    G(v2, v7, v8, v13, m[s[12]], m[s[13]]);
    G(v3, v4, v9, v14, m[s[14]], m[s[15]]);
  }

  out16[0] = v0 ^ v8;
  out16[1] = v1 ^ v9;
  out16[2] = v2 ^ v10;
  out16[3] = v3 ^ v11;
  out16[4] = v4 ^ v12;
  out16[5] = v5 ^ v13;
  out16[6] = v6 ^ v14;
  out16[7] = v7 ^ v15;
  out16[8] = v8 ^ cv[0];
  out16[9] = v9 ^ cv[1];
  out16[10] = v10 ^ cv[2];
  out16[11] = v11 ^ cv[3];
  out16[12] = v12 ^ cv[4];
  out16[13] = v13 ^ cv[5];
  out16[14] = v14 ^ cv[6];
  out16[15] = v15 ^ cv[7];
}

#if defined(__AVX2__)

// ── 8-wide core: eight complete 1 KiB chunks per call, SoA in ymm ──

#if defined(__AVX512VL__)
// AVX-512VL gives a native 32-bit rotate on 256-bit registers: 1 uop
// for every rotate distance.
inline __m256i rotr16v(__m256i x) { return _mm256_ror_epi32(x, 16); }
inline __m256i rotr8v(__m256i x) { return _mm256_ror_epi32(x, 8); }
inline __m256i rotr12v(__m256i x) { return _mm256_ror_epi32(x, 12); }
inline __m256i rotr7v(__m256i x) { return _mm256_ror_epi32(x, 7); }
#else
// Byte-granularity rotates go through vpshufb (1 uop); 12/7 need shifts.
inline __m256i rotr16v(__m256i x) {
  const __m256i tbl = _mm256_setr_epi8(
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13,
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13);
  return _mm256_shuffle_epi8(x, tbl);
}
inline __m256i rotr8v(__m256i x) {
  const __m256i tbl = _mm256_setr_epi8(
      1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12,
      1, 2, 3, 0, 5, 6, 7, 4, 9, 10, 11, 8, 13, 14, 15, 12);
  return _mm256_shuffle_epi8(x, tbl);
}
inline __m256i rotr12v(__m256i x) {
  return _mm256_or_si256(_mm256_srli_epi32(x, 12),
                         _mm256_slli_epi32(x, 20));
}
inline __m256i rotr7v(__m256i x) {
  return _mm256_or_si256(_mm256_srli_epi32(x, 7),
                         _mm256_slli_epi32(x, 25));
}
#endif

inline void g8(__m256i& a, __m256i& b, __m256i& c, __m256i& d,
               __m256i mx, __m256i my) {
  a = _mm256_add_epi32(_mm256_add_epi32(a, b), mx);
  d = rotr16v(_mm256_xor_si256(d, a));
  c = _mm256_add_epi32(c, d);
  b = rotr12v(_mm256_xor_si256(b, c));
  a = _mm256_add_epi32(_mm256_add_epi32(a, b), my);
  d = rotr8v(_mm256_xor_si256(d, a));
  c = _mm256_add_epi32(c, d);
  b = rotr7v(_mm256_xor_si256(b, c));
}

// In-register 8x8 u32 transpose: rows of 8 words -> word-major vectors.
inline void transpose8(__m256i r[8]) {
  __m256i t0 = _mm256_unpacklo_epi32(r[0], r[1]);
  __m256i t1 = _mm256_unpackhi_epi32(r[0], r[1]);
  __m256i t2 = _mm256_unpacklo_epi32(r[2], r[3]);
  __m256i t3 = _mm256_unpackhi_epi32(r[2], r[3]);
  __m256i t4 = _mm256_unpacklo_epi32(r[4], r[5]);
  __m256i t5 = _mm256_unpackhi_epi32(r[4], r[5]);
  __m256i t6 = _mm256_unpacklo_epi32(r[6], r[7]);
  __m256i t7 = _mm256_unpackhi_epi32(r[6], r[7]);
  __m256i s0 = _mm256_unpacklo_epi64(t0, t2);
  __m256i s1 = _mm256_unpackhi_epi64(t0, t2);
  __m256i s2 = _mm256_unpacklo_epi64(t1, t3);
  __m256i s3 = _mm256_unpackhi_epi64(t1, t3);
  __m256i s4 = _mm256_unpacklo_epi64(t4, t6);
  __m256i s5 = _mm256_unpackhi_epi64(t4, t6);
  __m256i s6 = _mm256_unpacklo_epi64(t5, t7);
  __m256i s7 = _mm256_unpackhi_epi64(t5, t7);
  r[0] = _mm256_permute2x128_si256(s0, s4, 0x20);
  r[1] = _mm256_permute2x128_si256(s1, s5, 0x20);
  r[2] = _mm256_permute2x128_si256(s2, s6, 0x20);
  r[3] = _mm256_permute2x128_si256(s3, s7, 0x20);
  r[4] = _mm256_permute2x128_si256(s0, s4, 0x31);
  r[5] = _mm256_permute2x128_si256(s1, s5, 0x31);
  r[6] = _mm256_permute2x128_si256(s2, s6, 0x31);
  r[7] = _mm256_permute2x128_si256(s3, s7, 0x31);
}

// Compress one 64-byte block of 8 chunks at once. cv: word-major state,
// cv[w] lane L = word w of chunk L. m: 16 word-major message vectors.
inline void compress8(__m256i cv[8], const __m256i m[16],
                      __m256i counter_lo, __m256i counter_hi,
                      uint32_t block_len, uint32_t flags) {
  __m256i v0 = cv[0], v1 = cv[1], v2 = cv[2], v3 = cv[3];
  __m256i v4 = cv[4], v5 = cv[5], v6 = cv[6], v7 = cv[7];
  __m256i v8 = _mm256_set1_epi32((int)IV[0]);
  __m256i v9 = _mm256_set1_epi32((int)IV[1]);
  __m256i v10 = _mm256_set1_epi32((int)IV[2]);
  __m256i v11 = _mm256_set1_epi32((int)IV[3]);
  __m256i v12 = counter_lo;
  __m256i v13 = counter_hi;
  __m256i v14 = _mm256_set1_epi32((int)block_len);
  __m256i v15 = _mm256_set1_epi32((int)flags);

  // Fully unrolled so every SCHED index is a compile-time constant and
  // the message words stay addressable without indirection.
#define B3_ROUND(R)                                                     \
  do {                                                                  \
    g8(v0, v4, v8, v12, m[SCHED[R][0]], m[SCHED[R][1]]);                \
    g8(v1, v5, v9, v13, m[SCHED[R][2]], m[SCHED[R][3]]);                \
    g8(v2, v6, v10, v14, m[SCHED[R][4]], m[SCHED[R][5]]);               \
    g8(v3, v7, v11, v15, m[SCHED[R][6]], m[SCHED[R][7]]);               \
    g8(v0, v5, v10, v15, m[SCHED[R][8]], m[SCHED[R][9]]);               \
    g8(v1, v6, v11, v12, m[SCHED[R][10]], m[SCHED[R][11]]);             \
    g8(v2, v7, v8, v13, m[SCHED[R][12]], m[SCHED[R][13]]);              \
    g8(v3, v4, v9, v14, m[SCHED[R][14]], m[SCHED[R][15]]);              \
  } while (0)
  B3_ROUND(0); B3_ROUND(1); B3_ROUND(2); B3_ROUND(3);
  B3_ROUND(4); B3_ROUND(5); B3_ROUND(6);
#undef B3_ROUND

  cv[0] = _mm256_xor_si256(v0, v8);
  cv[1] = _mm256_xor_si256(v1, v9);
  cv[2] = _mm256_xor_si256(v2, v10);
  cv[3] = _mm256_xor_si256(v3, v11);
  cv[4] = _mm256_xor_si256(v4, v12);
  cv[5] = _mm256_xor_si256(v5, v13);
  cv[6] = _mm256_xor_si256(v6, v14);
  cv[7] = _mm256_xor_si256(v7, v15);
}

// Hash 8 complete, non-final 1 KiB chunks starting at `data` (contiguous,
// counters chunk_counter..+7); writes the 8 chunk CVs row-major.
void hash8_chunks(const uint32_t key[8], uint32_t base_flags,
                  const uint8_t* data, uint64_t chunk_counter,
                  uint32_t out_cvs[8][8]) {
  __m256i cv[8];
  for (int w = 0; w < 8; w++) cv[w] = _mm256_set1_epi32((int)key[w]);

  alignas(32) uint32_t ctr_lo[8], ctr_hi[8];
  for (int i = 0; i < 8; i++) {
    ctr_lo[i] = (uint32_t)(chunk_counter + i);
    ctr_hi[i] = (uint32_t)((chunk_counter + i) >> 32);
  }
  __m256i vlo = _mm256_load_si256((const __m256i*)ctr_lo);
  __m256i vhi = _mm256_load_si256((const __m256i*)ctr_hi);

  constexpr int NBLOCKS = CHUNK_LEN / BLOCK_LEN;  // 16
  for (int b = 0; b < NBLOCKS; b++) {
    __m256i lo[8], hi[8];
    for (int i = 0; i < 8; i++) {
      const uint8_t* p = data + (size_t)i * CHUNK_LEN + (size_t)b * BLOCK_LEN;
      lo[i] = _mm256_loadu_si256((const __m256i*)p);
      hi[i] = _mm256_loadu_si256((const __m256i*)(p + 32));
    }
    transpose8(lo);  // lo[w] = word w (0-7) of each chunk's block
    transpose8(hi);  // hi[w] = word 8+w
    __m256i m[16];
    for (int w = 0; w < 8; w++) { m[w] = lo[w]; m[8 + w] = hi[w]; }

    uint32_t flags = base_flags;
    if (b == 0) flags |= CHUNK_START;
    if (b == NBLOCKS - 1) flags |= CHUNK_END;
    compress8(cv, m, vlo, vhi, BLOCK_LEN, flags);
  }

  transpose8(cv);  // back to chunk-major rows
  for (int i = 0; i < 8; i++)
    _mm256_storeu_si256((__m256i*)out_cvs[i], cv[i]);
}

// Fold 8 parent pairs at once. A parent's 64-byte message is exactly
// its two children's CVs back-to-back, and `cvs_in` is a flat [2*8][8]
// CV array — so pair i IS the 64 contiguous bytes at cvs_in + 16*i,
// loaded lo/hi like one hash8 block. All inputs are read into
// registers before any store, so out_cvs may alias cvs_in (the
// level-order fold writes in place).
void fold8_parents(const uint32_t key[8], uint32_t flags,
                   const uint32_t (*cvs_in)[8], uint32_t (*out_cvs)[8]) {
  __m256i cv[8];
  for (int w = 0; w < 8; w++) cv[w] = _mm256_set1_epi32((int)key[w]);
  __m256i lo[8], hi[8];
  for (int i = 0; i < 8; i++) {
    const uint8_t* p = (const uint8_t*)cvs_in[2 * i];
    lo[i] = _mm256_loadu_si256((const __m256i*)p);
    hi[i] = _mm256_loadu_si256((const __m256i*)(p + 32));
  }
  transpose8(lo);
  transpose8(hi);
  __m256i m[16];
  for (int w = 0; w < 8; w++) { m[w] = lo[w]; m[8 + w] = hi[w]; }
  __m256i zero = _mm256_setzero_si256();
  compress8(cv, m, zero, zero, BLOCK_LEN, flags | PARENT);
  transpose8(cv);
  for (int i = 0; i < 8; i++)
    _mm256_storeu_si256((__m256i*)out_cvs[i], cv[i]);
}

#endif  // __AVX2__

#if defined(__AVX512F__)

// ── 16-wide core: sixteen complete 1 KiB chunks per call ──
// One 64-byte block row per chunk is exactly one zmm load; a 16x16 u32
// transpose turns 16 row loads into the 16 word-major message vectors.

inline void g16(__m512i& a, __m512i& b, __m512i& c, __m512i& d,
                __m512i mx, __m512i my) {
  a = _mm512_add_epi32(_mm512_add_epi32(a, b), mx);
  d = _mm512_ror_epi32(_mm512_xor_si512(d, a), 16);
  c = _mm512_add_epi32(c, d);
  b = _mm512_ror_epi32(_mm512_xor_si512(b, c), 12);
  a = _mm512_add_epi32(_mm512_add_epi32(a, b), my);
  d = _mm512_ror_epi32(_mm512_xor_si512(d, a), 8);
  c = _mm512_add_epi32(c, d);
  b = _mm512_ror_epi32(_mm512_xor_si512(b, c), 7);
}

// Transpose r[i] = 16 words of row i  ->  r[w] = word w of 16 rows.
// Four stages: epi32 unpacks (row pairs), epi64 unpacks (row quads),
// then two rounds of 128-bit-lane shuffles. Derivation: after stage 2,
// s[4g+m] lane k holds word 4k+m of rows 4g..4g+3; the lane shuffles
// regroup lanes by word index.
inline void transpose16(__m512i r[16]) {
  __m512i t[16], s[16];
  for (int i = 0; i < 8; i++) {
    t[2 * i] = _mm512_unpacklo_epi32(r[2 * i], r[2 * i + 1]);
    t[2 * i + 1] = _mm512_unpackhi_epi32(r[2 * i], r[2 * i + 1]);
  }
  for (int g = 0; g < 4; g++) {
    s[4 * g + 0] = _mm512_unpacklo_epi64(t[4 * g + 0], t[4 * g + 2]);
    s[4 * g + 1] = _mm512_unpackhi_epi64(t[4 * g + 0], t[4 * g + 2]);
    s[4 * g + 2] = _mm512_unpacklo_epi64(t[4 * g + 1], t[4 * g + 3]);
    s[4 * g + 3] = _mm512_unpackhi_epi64(t[4 * g + 1], t[4 * g + 3]);
  }
  for (int m = 0; m < 4; m++) {
    __m512i p1 = _mm512_shuffle_i32x4(s[m], s[4 + m], 0x88);
    __m512i p2 = _mm512_shuffle_i32x4(s[m], s[4 + m], 0xdd);
    __m512i p3 = _mm512_shuffle_i32x4(s[8 + m], s[12 + m], 0x88);
    __m512i p4 = _mm512_shuffle_i32x4(s[8 + m], s[12 + m], 0xdd);
    r[m] = _mm512_shuffle_i32x4(p1, p3, 0x88);
    r[8 + m] = _mm512_shuffle_i32x4(p1, p3, 0xdd);
    r[4 + m] = _mm512_shuffle_i32x4(p2, p4, 0x88);
    r[12 + m] = _mm512_shuffle_i32x4(p2, p4, 0xdd);
  }
}

inline void compress16(__m512i cv[8], const __m512i m[16],
                       __m512i counter_lo, __m512i counter_hi,
                       uint32_t block_len, uint32_t flags) {
  __m512i v0 = cv[0], v1 = cv[1], v2 = cv[2], v3 = cv[3];
  __m512i v4 = cv[4], v5 = cv[5], v6 = cv[6], v7 = cv[7];
  __m512i v8 = _mm512_set1_epi32((int)IV[0]);
  __m512i v9 = _mm512_set1_epi32((int)IV[1]);
  __m512i v10 = _mm512_set1_epi32((int)IV[2]);
  __m512i v11 = _mm512_set1_epi32((int)IV[3]);
  __m512i v12 = counter_lo;
  __m512i v13 = counter_hi;
  __m512i v14 = _mm512_set1_epi32((int)block_len);
  __m512i v15 = _mm512_set1_epi32((int)flags);

#define B3_ROUND16(R)                                                   \
  do {                                                                  \
    g16(v0, v4, v8, v12, m[SCHED[R][0]], m[SCHED[R][1]]);               \
    g16(v1, v5, v9, v13, m[SCHED[R][2]], m[SCHED[R][3]]);               \
    g16(v2, v6, v10, v14, m[SCHED[R][4]], m[SCHED[R][5]]);              \
    g16(v3, v7, v11, v15, m[SCHED[R][6]], m[SCHED[R][7]]);              \
    g16(v0, v5, v10, v15, m[SCHED[R][8]], m[SCHED[R][9]]);              \
    g16(v1, v6, v11, v12, m[SCHED[R][10]], m[SCHED[R][11]]);            \
    g16(v2, v7, v8, v13, m[SCHED[R][12]], m[SCHED[R][13]]);             \
    g16(v3, v4, v9, v14, m[SCHED[R][14]], m[SCHED[R][15]]);             \
  } while (0)
  B3_ROUND16(0); B3_ROUND16(1); B3_ROUND16(2); B3_ROUND16(3);
  B3_ROUND16(4); B3_ROUND16(5); B3_ROUND16(6);
#undef B3_ROUND16

  cv[0] = _mm512_xor_si512(v0, v8);
  cv[1] = _mm512_xor_si512(v1, v9);
  cv[2] = _mm512_xor_si512(v2, v10);
  cv[3] = _mm512_xor_si512(v3, v11);
  cv[4] = _mm512_xor_si512(v4, v12);
  cv[5] = _mm512_xor_si512(v5, v13);
  cv[6] = _mm512_xor_si512(v6, v14);
  cv[7] = _mm512_xor_si512(v7, v15);
}

// Hash 16 complete, non-final 1 KiB chunks starting at `data`
// (contiguous, counters chunk_counter..+15); CVs row-major.
void hash16_chunks(const uint32_t key[8], uint32_t base_flags,
                   const uint8_t* data, uint64_t chunk_counter,
                   uint32_t out_cvs[16][8]) {
  __m512i cv[8];
  for (int w = 0; w < 8; w++) cv[w] = _mm512_set1_epi32((int)key[w]);

  alignas(64) uint32_t ctr_lo[16], ctr_hi[16];
  for (int i = 0; i < 16; i++) {
    ctr_lo[i] = (uint32_t)(chunk_counter + i);
    ctr_hi[i] = (uint32_t)((chunk_counter + i) >> 32);
  }
  __m512i vlo = _mm512_load_si512((const void*)ctr_lo);
  __m512i vhi = _mm512_load_si512((const void*)ctr_hi);

  constexpr int NBLOCKS = CHUNK_LEN / BLOCK_LEN;  // 16
  for (int b = 0; b < NBLOCKS; b++) {
    __m512i m[16];
    for (int i = 0; i < 16; i++) {
      m[i] = _mm512_loadu_si512(
          (const void*)(data + (size_t)i * CHUNK_LEN + (size_t)b * BLOCK_LEN));
    }
    transpose16(m);

    uint32_t flags = base_flags;
    if (b == 0) flags |= CHUNK_START;
    if (b == NBLOCKS - 1) flags |= CHUNK_END;
    compress16(cv, m, vlo, vhi, BLOCK_LEN, flags);
  }

  // cv[w] holds word w of 16 chunks; widen to 16 rows for the store.
  __m512i rows[16];
  for (int w = 0; w < 8; w++) rows[w] = cv[w];
  for (int w = 8; w < 16; w++) rows[w] = _mm512_setzero_si512();
  transpose16(rows);
  for (int i = 0; i < 16; i++) {
    alignas(64) uint32_t tmp[16];
    _mm512_store_si512((void*)tmp, rows[i]);
    std::memcpy(out_cvs[i], tmp, 8 * sizeof(uint32_t));
  }
}

// Fold 16 parent pairs at once (see fold8_parents: pair i is the 64
// contiguous bytes at cvs_in + 16*i; in-place safe).
void fold16_parents(const uint32_t key[8], uint32_t flags,
                    const uint32_t (*cvs_in)[8], uint32_t (*out_cvs)[8]) {
  __m512i cv[8];
  for (int w = 0; w < 8; w++) cv[w] = _mm512_set1_epi32((int)key[w]);
  __m512i m[16];
  for (int i = 0; i < 16; i++)
    m[i] = _mm512_loadu_si512((const void*)cvs_in[2 * i]);
  transpose16(m);
  __m512i zero = _mm512_setzero_si512();
  compress16(cv, m, zero, zero, BLOCK_LEN, flags | PARENT);
  __m512i rows[16];
  for (int w = 0; w < 8; w++) rows[w] = cv[w];
  for (int w = 8; w < 16; w++) rows[w] = _mm512_setzero_si512();
  transpose16(rows);
  for (int i = 0; i < 16; i++) {
    alignas(64) uint32_t tmp[16];
    _mm512_store_si512((void*)tmp, rows[i]);
    std::memcpy(out_cvs[i], tmp, 8 * sizeof(uint32_t));
  }
}

#endif  // __AVX512F__

void load_block(const uint8_t* data, size_t len, uint32_t m[16]) {
  uint8_t padded[BLOCK_LEN];
  const uint8_t* src = data;
  if (len < BLOCK_LEN) {
    std::memset(padded, 0, sizeof(padded));
    std::memcpy(padded, data, len);
    src = padded;
  }
  for (int i = 0; i < 16; i++) m[i] = load32le(src + 4 * i);
}

// Hash one complete-or-final chunk; writes the chunk CV. If `root_out` is
// non-null the chunk is the whole tree and the final block carries ROOT.
void hash_chunk(const uint32_t key[8], const uint8_t* data, size_t len,
                uint64_t chunk_counter, uint32_t base_flags, uint32_t cv_out[8],
                uint8_t* root_out) {
  uint32_t cv[8];
  std::memcpy(cv, key, sizeof(cv));
  size_t nblocks = len <= BLOCK_LEN ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
  uint32_t out16[16];
  for (size_t i = 0; i < nblocks; i++) {
    size_t off = i * BLOCK_LEN;
    size_t blen = (i + 1 == nblocks) ? len - off : BLOCK_LEN;
    uint32_t m[16];
    load_block(data + off, blen, m);
    uint32_t flags = base_flags;
    if (i == 0) flags |= CHUNK_START;
    if (i + 1 == nblocks) {
      flags |= CHUNK_END;
      if (root_out != nullptr) flags |= ROOT;
    }
    compress(cv, m, chunk_counter, (uint32_t)blen, flags, out16);
    std::memcpy(cv, out16, 8 * sizeof(uint32_t));
  }
  std::memcpy(cv_out, cv, 8 * sizeof(uint32_t));
  if (root_out != nullptr) {
    for (int i = 0; i < 8; i++) store32le(root_out + 4 * i, cv[i]);
  }
}

// Full-tree hash. Iterative chunk walk with a CV stack (max depth 54).
void blake3_full(const uint32_t key[8], uint32_t base_flags,
                 const uint8_t* data, size_t len, uint8_t out[32]) {
  if (len <= CHUNK_LEN) {
    uint32_t cv[8];
    hash_chunk(key, data, len, 0, base_flags, cv, out);
    return;
  }

  // Two phases, both SIMD-wide: (1) hash every leaf chunk 16/8 at a
  // time, (2) fold the tree LEVEL-ORDER, pairing adjacent CVs and
  // promoting a trailing odd CV unchanged — which builds exactly the
  // canonical left-full BLAKE3 tree (the standard wide-fold identity;
  // the previous incremental stack built the same tree but ran every
  // parent compression through the scalar core, capping large-input
  // throughput at the scalar rate).
  size_t n_chunks = (len + CHUNK_LEN - 1) / CHUNK_LEN;
  // CV workspace: a stack buffer covers every input up to 256 KiB —
  // all CDC chunks (<= 128 KiB) and the 64 KiB headline shape — so the
  // hot verification path never allocates; larger inputs (multi-MB
  // xorb blobs) amortize one heap allocation over megabytes of hashing.
  uint32_t stack_cvs[256][8];
  uint32_t(*cvs)[8] =
      n_chunks <= 256 ? stack_cvs : new uint32_t[n_chunks][8];

  // Leaves: every COMPLETE chunk rides the widest available path (an
  // exact-multiple input has no partial tail, so even its last chunk
  // does); only a partial final chunk needs the block-wise scalar
  // hash_chunk.
  size_t full = len / CHUNK_LEN;
  size_t rem = len - full * CHUNK_LEN;
  size_t i = 0;
#if defined(__AVX512F__)
  for (; full - i >= 16; i += 16)
    hash16_chunks(key, base_flags, data + i * CHUNK_LEN, i, &cvs[i]);
#endif
#if defined(__AVX2__)
  for (; full - i >= 8; i += 8)
    hash8_chunks(key, base_flags, data + i * CHUNK_LEN, i, &cvs[i]);
#endif
  for (; i < full; i++)
    hash_chunk(key, data + i * CHUNK_LEN, CHUNK_LEN, i, base_flags,
               cvs[i], nullptr);
  if (rem)
    hash_chunk(key, data + full * CHUNK_LEN, rem, full, base_flags,
               cvs[full], nullptr);

  // Level-order fold down to 2 CVs (the root fold is special-cased for
  // the ROOT flag). The wide folds read a full register set before
  // storing, so writing cvs[o] while reading cvs[2p] is safe (o <= 2p).
  uint32_t out16[16];
  size_t n = n_chunks;
  while (n > 2) {
    size_t pairs = n / 2;
    size_t p = 0, o = 0;
#if defined(__AVX512F__)
    for (; pairs - p >= 16; p += 16, o += 16)
      fold16_parents(key, base_flags, &cvs[2 * p], &cvs[o]);
#endif
#if defined(__AVX2__)
    for (; pairs - p >= 8; p += 8, o += 8)
      fold8_parents(key, base_flags, &cvs[2 * p], &cvs[o]);
#endif
    for (; p < pairs; p++, o++) {
      compress(key, cvs[2 * p], 0, BLOCK_LEN, base_flags | PARENT, out16);
      std::memcpy(cvs[o], out16, 8 * sizeof(uint32_t));
    }
    if (n & 1) {  // odd tail: promote unchanged
      std::memcpy(cvs[o], cvs[n - 1], 8 * sizeof(uint32_t));
      o++;
    }
    n = o;
  }

  compress(key, cvs[0], 0, BLOCK_LEN, base_flags | PARENT | ROOT, out16);
  if (cvs != stack_cvs) delete[] cvs;
  for (int k = 0; k < 8; k++) store32le(out + 4 * k, out16[k]);
}

}  // namespace

extern "C" {

void zest_blake3(const uint8_t* data, size_t len, uint8_t out[32]) {
  blake3_full(IV, 0, data, len, out);
}

void zest_blake3_keyed(const uint8_t key[32], const uint8_t* data, size_t len,
                       uint8_t out[32]) {
  uint32_t kw[KEY_WORDS];
  for (size_t i = 0; i < KEY_WORDS; i++) kw[i] = load32le(key + 4 * i);
  blake3_full(kw, KEYED_HASH, data, len, out);
}

// Hash `count` equal-length items laid out contiguously; out = count * 32.
// Independent items — this is the chunk-verification hot loop.
void zest_blake3_batch(const uint8_t* data, size_t count, size_t item_len,
                       uint8_t* out) {
  for (size_t i = 0; i < count; i++) {
    blake3_full(IV, 0, data + i * item_len, item_len, out + i * 32);
  }
}

void zest_blake3_keyed_batch(const uint8_t key[32], const uint8_t* data,
                             size_t count, size_t item_len, uint8_t* out) {
  uint32_t kw[KEY_WORDS];
  for (size_t i = 0; i < KEY_WORDS; i++) kw[i] = load32le(key + 4 * i);
  for (size_t i = 0; i < count; i++) {
    blake3_full(kw, KEYED_HASH, data + i * item_len, item_len, out + i * 32);
  }
}

}  // extern "C"
