// Native BLAKE3 for the host hot path (chunk verification, CDC dedup).
//
// Independent implementation from the BLAKE3 spec; validated against the
// pure-Python anchor (zest_tpu/cas/blake3.py) and the official test
// vectors in tests/test_blake3.py. The reference gets this from zig-xet's
// `hashing` module (SURVEY.md section 2.2); its headline microbenchmark is
// blake3_64kb at 3517 MB/s (BASELINE.md) — beat it here.
//
// Exposed C ABI (consumed via ctypes in zest_tpu/native/__init__.py):
//   zest_blake3(data, len, out32)
//   zest_blake3_keyed(key32, data, len, out32)
//   zest_blake3_batch(data, count, item_len, out32xN)   — many equal-size items
//
// Layout notes: scalar core with aggressively unrolled rounds; compiled
// -O3 -march=native so GCC vectorizes the 4-lane column/diagonal steps.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr size_t BLOCK_LEN = 64;
constexpr size_t CHUNK_LEN = 1024;
constexpr size_t KEY_WORDS = 8;

constexpr uint32_t CHUNK_START = 1 << 0;
constexpr uint32_t CHUNK_END = 1 << 1;
constexpr uint32_t PARENT = 1 << 2;
constexpr uint32_t ROOT = 1 << 3;
constexpr uint32_t KEYED_HASH = 1 << 4;

constexpr uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};

inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

inline uint32_t load32le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline void store32le(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

#define G(a, b, c, d, mx, my)          \
  do {                                 \
    a = a + b + (mx);                  \
    d = rotr32(d ^ a, 16);             \
    c = c + d;                         \
    b = rotr32(b ^ c, 12);             \
    a = a + b + (my);                  \
    d = rotr32(d ^ a, 8);              \
    c = c + d;                         \
    b = rotr32(b ^ c, 7);              \
  } while (0)

// One full compression. `out16` receives the 16-word extended output.
void compress(const uint32_t cv[8], const uint32_t m_in[16], uint64_t counter,
              uint32_t block_len, uint32_t flags, uint32_t out16[16]) {
  static constexpr int P[16] = {2, 6, 3, 10, 7, 0, 4, 13,
                                1, 11, 12, 5, 9, 14, 15, 8};
  uint32_t v0 = cv[0], v1 = cv[1], v2 = cv[2], v3 = cv[3];
  uint32_t v4 = cv[4], v5 = cv[5], v6 = cv[6], v7 = cv[7];
  uint32_t v8 = IV[0], v9 = IV[1], v10 = IV[2], v11 = IV[3];
  uint32_t v12 = (uint32_t)counter, v13 = (uint32_t)(counter >> 32);
  uint32_t v14 = block_len, v15 = flags;

  uint32_t m[16];
  std::memcpy(m, m_in, sizeof(m));

  for (int r = 0; r < 7; r++) {
    G(v0, v4, v8, v12, m[0], m[1]);
    G(v1, v5, v9, v13, m[2], m[3]);
    G(v2, v6, v10, v14, m[4], m[5]);
    G(v3, v7, v11, v15, m[6], m[7]);
    G(v0, v5, v10, v15, m[8], m[9]);
    G(v1, v6, v11, v12, m[10], m[11]);
    G(v2, v7, v8, v13, m[12], m[13]);
    G(v3, v4, v9, v14, m[14], m[15]);
    if (r < 6) {
      uint32_t t[16];
      for (int i = 0; i < 16; i++) t[i] = m[P[i]];
      std::memcpy(m, t, sizeof(m));
    }
  }

  out16[0] = v0 ^ v8;
  out16[1] = v1 ^ v9;
  out16[2] = v2 ^ v10;
  out16[3] = v3 ^ v11;
  out16[4] = v4 ^ v12;
  out16[5] = v5 ^ v13;
  out16[6] = v6 ^ v14;
  out16[7] = v7 ^ v15;
  out16[8] = v8 ^ cv[0];
  out16[9] = v9 ^ cv[1];
  out16[10] = v10 ^ cv[2];
  out16[11] = v11 ^ cv[3];
  out16[12] = v12 ^ cv[4];
  out16[13] = v13 ^ cv[5];
  out16[14] = v14 ^ cv[6];
  out16[15] = v15 ^ cv[7];
}

void load_block(const uint8_t* data, size_t len, uint32_t m[16]) {
  uint8_t padded[BLOCK_LEN];
  const uint8_t* src = data;
  if (len < BLOCK_LEN) {
    std::memset(padded, 0, sizeof(padded));
    std::memcpy(padded, data, len);
    src = padded;
  }
  for (int i = 0; i < 16; i++) m[i] = load32le(src + 4 * i);
}

// Hash one complete-or-final chunk; writes the chunk CV. If `root_out` is
// non-null the chunk is the whole tree and the final block carries ROOT.
void hash_chunk(const uint32_t key[8], const uint8_t* data, size_t len,
                uint64_t chunk_counter, uint32_t base_flags, uint32_t cv_out[8],
                uint8_t* root_out) {
  uint32_t cv[8];
  std::memcpy(cv, key, sizeof(cv));
  size_t nblocks = len <= BLOCK_LEN ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
  uint32_t out16[16];
  for (size_t i = 0; i < nblocks; i++) {
    size_t off = i * BLOCK_LEN;
    size_t blen = (i + 1 == nblocks) ? len - off : BLOCK_LEN;
    uint32_t m[16];
    load_block(data + off, blen, m);
    uint32_t flags = base_flags;
    if (i == 0) flags |= CHUNK_START;
    if (i + 1 == nblocks) {
      flags |= CHUNK_END;
      if (root_out != nullptr) flags |= ROOT;
    }
    compress(cv, m, chunk_counter, (uint32_t)blen, flags, out16);
    std::memcpy(cv, out16, 8 * sizeof(uint32_t));
  }
  std::memcpy(cv_out, cv, 8 * sizeof(uint32_t));
  if (root_out != nullptr) {
    for (int i = 0; i < 8; i++) store32le(root_out + 4 * i, cv[i]);
  }
}

// Full-tree hash. Iterative chunk walk with a CV stack (max depth 54).
void blake3_full(const uint32_t key[8], uint32_t base_flags,
                 const uint8_t* data, size_t len, uint8_t out[32]) {
  if (len <= CHUNK_LEN) {
    uint32_t cv[8];
    hash_chunk(key, data, len, 0, base_flags, cv, out);
    return;
  }

  uint32_t cv_stack[54][8];
  size_t stack_len = 0;
  uint64_t chunk_counter = 0;
  size_t pos = 0;
  uint32_t out16[16];

  // All chunks except the last are complete; the last is handled below so
  // the root flag can be applied at the right node.
  while (len - pos > CHUNK_LEN) {
    uint32_t cv[8];
    hash_chunk(key, data + pos, CHUNK_LEN, chunk_counter, base_flags, cv,
               nullptr);
    pos += CHUNK_LEN;
    chunk_counter++;
    uint64_t total = chunk_counter;
    while ((total & 1) == 0) {
      uint32_t m[16];
      std::memcpy(m, cv_stack[--stack_len], 8 * sizeof(uint32_t));
      std::memcpy(m + 8, cv, 8 * sizeof(uint32_t));
      compress(key, m, 0, BLOCK_LEN, base_flags | PARENT, out16);
      std::memcpy(cv, out16, 8 * sizeof(uint32_t));
      total >>= 1;
    }
    std::memcpy(cv_stack[stack_len++], cv, 8 * sizeof(uint32_t));
  }

  // Final (partial or full) chunk.
  uint32_t cv[8];
  hash_chunk(key, data + pos, len - pos, chunk_counter, base_flags, cv,
             nullptr);

  // Fold the stack; the topmost fold is the root.
  while (stack_len > 0) {
    uint32_t m[16];
    std::memcpy(m, cv_stack[--stack_len], 8 * sizeof(uint32_t));
    std::memcpy(m + 8, cv, 8 * sizeof(uint32_t));
    uint32_t flags = base_flags | PARENT;
    if (stack_len == 0) flags |= ROOT;
    compress(key, m, 0, BLOCK_LEN, flags, out16);
    std::memcpy(cv, out16, 8 * sizeof(uint32_t));
  }
  for (int i = 0; i < 8; i++) store32le(out + 4 * i, cv[i]);
}

}  // namespace

extern "C" {

void zest_blake3(const uint8_t* data, size_t len, uint8_t out[32]) {
  blake3_full(IV, 0, data, len, out);
}

void zest_blake3_keyed(const uint8_t key[32], const uint8_t* data, size_t len,
                       uint8_t out[32]) {
  uint32_t kw[KEY_WORDS];
  for (size_t i = 0; i < KEY_WORDS; i++) kw[i] = load32le(key + 4 * i);
  blake3_full(kw, KEYED_HASH, data, len, out);
}

// Hash `count` equal-length items laid out contiguously; out = count * 32.
// Independent items — this is the chunk-verification hot loop.
void zest_blake3_batch(const uint8_t* data, size_t count, size_t item_len,
                       uint8_t* out) {
  for (size_t i = 0; i < count; i++) {
    blake3_full(IV, 0, data + i * item_len, item_len, out + i * 32);
  }
}

void zest_blake3_keyed_batch(const uint8_t key[32], const uint8_t* data,
                             size_t count, size_t item_len, uint8_t* out) {
  uint32_t kw[KEY_WORDS];
  for (size_t i = 0; i < KEY_WORDS; i++) kw[i] = load32le(key + 4 * i);
  for (size_t i = 0; i < count; i++) {
    blake3_full(kw, KEYED_HASH, data + i * item_len, item_len, out + i * 32);
  }
}

}  // extern "C"
