"""Native (C++) acceleration library: build + ctypes bindings.

The reference's byte-level hot paths are native Zig (SURVEY.md §2.1); ours
are C++ compiled on demand into ``libzest.so`` and bound via ctypes (pybind11
is not in this image). Everything here has a pure-Python fallback — the
native lib is a performance tier, never a functional requirement.

Build is lazy and cached: first use compiles with g++ -O3 -march=native into
``zest_tpu/native/build/``; set ``ZEST_NATIVE=0`` to disable entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_BUILD_DIR = _HERE / "build"
_SOURCES = ["blake3.cc", "decode.cc", "gearhash.cc", "lz4.cc", "wire.cc"]

_lock = threading.Lock()
_dll: ctypes.CDLL | None = None
_tried = False


def _compile() -> Path | None:
    sources = [_HERE / s for s in _SOURCES if (_HERE / s).exists()]
    if not sources:
        return None
    _BUILD_DIR.mkdir(exist_ok=True)
    so_path = _BUILD_DIR / "libzest.so"
    stamp = _BUILD_DIR / "libzest.stamp"
    fingerprint = "|".join(
        f"{s.name}:{s.stat().st_mtime_ns}" for s in sorted(sources)
    )
    if so_path.exists() and stamp.exists() and stamp.read_text() == fingerprint:
        return so_path
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", str(so_path), *[str(s) for s in sources],
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (subprocess.SubprocessError, FileNotFoundError):
        # A stale .so from a previous build must not mask the failure — the
        # pure-Python fallback is always correct, old native code may not be.
        return None
    stamp.write_text(fingerprint)
    return so_path


def _load() -> ctypes.CDLL | None:
    global _dll, _tried
    with _lock:
        if _tried:
            return _dll
        _tried = True
        if os.environ.get("ZEST_NATIVE") == "0":
            return None
        so = _compile()
        if so is None:
            return None
        try:
            dll = ctypes.CDLL(str(so))
        except OSError:
            return None
        try:
            _bind(dll)
        except AttributeError:
            # A stale .so missing newer symbols must degrade to the pure
            # path, not crash every native caller through available().
            return None
        _dll = dll
        return _dll


def _bind(dll: ctypes.CDLL) -> None:
        dll.zest_blake3.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_blake3_keyed.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_blake3_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_blake3_keyed_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_gear_cut_points.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
        ]
        dll.zest_gear_cut_points.restype = ctypes.c_size_t
        dll.zest_lz4_bound.argtypes = [ctypes.c_size_t]
        dll.zest_lz4_bound.restype = ctypes.c_size_t
        dll.zest_lz4_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t
        ]
        dll.zest_lz4_compress.restype = ctypes.c_size_t
        dll.zest_lz4_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t
        ]
        dll.zest_lz4_decompress.restype = ctypes.c_size_t
        dll.zest_wire_response_size.argtypes = [ctypes.c_size_t]
        dll.zest_wire_response_size.restype = ctypes.c_size_t
        dll.zest_wire_frame_chunk_response.argtypes = [
            ctypes.c_uint8, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        dll.zest_wire_frame_chunk_response.restype = ctypes.c_size_t
        dll.zest_wire_frame_chunk_request.argtypes = [
            ctypes.c_uint8, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_char_p,
        ]
        dll.zest_wire_frame_chunk_request.restype = ctypes.c_size_t
        dll.zest_wire_frame_chunk_not_found.argtypes = [
            ctypes.c_uint8, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_char_p,
        ]
        dll.zest_wire_frame_chunk_not_found.restype = ctypes.c_size_t
        dll.zest_decode_batch.argtypes = [
            ctypes.c_void_p,  # const uint8_t* const* srcs
            ctypes.c_void_p,  # const uint64_t* src_lens
            ctypes.c_void_p,  # const uint8_t* schemes
            ctypes.c_void_p,  # const uint64_t* dst_offs
            ctypes.c_void_p,  # const uint64_t* dst_lens
            ctypes.c_uint64,  # n
            ctypes.c_void_p,  # uint8_t* dst
            ctypes.c_uint64,  # dst_cap
            ctypes.c_uint64,  # workers
        ]
        dll.zest_decode_batch.restype = ctypes.c_size_t
        dll.zest_parse_frames.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        dll.zest_parse_frames.restype = ctypes.c_size_t


_gear_array = None


def _gear_as_array():
    global _gear_array
    if _gear_array is None:
        from zest_tpu.cas.chunking import GEAR

        _gear_array = (ctypes.c_uint64 * 256)(*GEAR)
    return _gear_array


_scratch_tls = threading.local()


def _scratch(n: int) -> ctypes.Array:
    """Reusable per-thread output buffer of >= n bytes.

    ``ctypes.create_string_buffer`` zero-fills on every call — for 64 KiB
    chunk codecs that memset (plus the allocation) costs as much as the
    native codec itself. One geometrically-grown buffer per thread makes
    the marshalling cost O(copy-out) only; per-thread keeps concurrent
    fetch workers from sharing (and corrupting) one buffer."""
    buf = getattr(_scratch_tls, "buf", None)
    if buf is None or len(buf) < n:
        size = max(128 * 1024, 1 << (n - 1).bit_length())
        buf = _scratch_tls.buf = ctypes.create_string_buffer(size)
    return buf


class lib:
    """Namespace of native entry points with ctypes marshalling."""

    @staticmethod
    def available() -> bool:
        return _load() is not None

    @staticmethod
    def blake3(data: bytes) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(32)
        dll.zest_blake3(data, len(data), out)
        return out.raw

    @staticmethod
    def blake3_keyed(key: bytes, data: bytes) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(32)
        dll.zest_blake3_keyed(key, data, len(data), out)
        return out.raw

    @staticmethod
    def blake3_batch(data: bytes, count: int, item_len: int) -> bytes:
        """Hash ``count`` contiguous equal-size items; returns count*32 bytes."""
        dll = _load()
        out = ctypes.create_string_buffer(32 * count)
        dll.zest_blake3_batch(data, count, item_len, out)
        return out.raw

    @staticmethod
    def blake3_keyed_batch(key: bytes, data: bytes, count: int,
                           item_len: int) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(32 * count)
        dll.zest_blake3_keyed_batch(key, data, count, item_len, out)
        return out.raw

    @staticmethod
    def gear_cut_points(data: bytes, min_chunk: int, max_chunk: int,
                        mask: int) -> list[int]:
        dll = _load()
        cap = len(data) // min_chunk + 2 if min_chunk else len(data) + 2
        out = (ctypes.c_uint64 * cap)()
        n = dll.zest_gear_cut_points(
            data, len(data), _gear_as_array(), min_chunk, max_chunk,
            mask, out, cap,
        )
        return list(out[:n])

    @staticmethod
    def lz4_compress(data: bytes) -> bytes:
        dll = _load()
        cap = dll.zest_lz4_bound(len(data))
        out = _scratch(cap)
        n = dll.zest_lz4_compress(data, len(data), out, cap)
        if n == 0 and len(data) > 0:
            raise RuntimeError("native lz4 compress failed")
        return ctypes.string_at(out, n)

    @staticmethod
    def frame_chunk_response(ext_id: int, req_id: int, chunk_offset: int,
                             data: bytes) -> bytes:
        """Complete framed BEP10+XET CHUNK_RESPONSE in one pass."""
        dll = _load()
        out = _scratch(dll.zest_wire_response_size(len(data)))
        n = dll.zest_wire_frame_chunk_response(
            ext_id, req_id, chunk_offset, data, len(data), out
        )
        return ctypes.string_at(out, n)

    @staticmethod
    def frame_chunk_request(ext_id: int, req_id: int, chunk_hash: bytes,
                            range_start: int, range_end: int) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(51)
        n = dll.zest_wire_frame_chunk_request(
            ext_id, req_id, chunk_hash, range_start, range_end, out
        )
        return out.raw[:n]

    @staticmethod
    def frame_chunk_not_found(ext_id: int, req_id: int,
                              chunk_hash: bytes) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(43)
        n = dll.zest_wire_frame_chunk_not_found(
            ext_id, req_id, chunk_hash, out
        )
        return out.raw[:n]

    @staticmethod
    def decode_batch(src_ptrs, src_lens, schemes, dst_offs, dst_lens,
                     dst_ptr: int, dst_cap: int, workers: int) -> int:
        """Decode N chunk payloads into a caller-owned buffer in ONE
        GIL-released call (native/decode.cc): ``src_ptrs``/``src_lens``/
        ``schemes``/``dst_offs``/``dst_lens`` are C-contiguous numpy
        arrays (u64/u64/u8/u64/u64) of equal length, ``dst_ptr`` the
        destination base address. Returns 0 on success, or ``i + 1`` for
        the first failing descriptor (dst contents are then unspecified
        — callers fall back to the pure path, which also produces the
        precise error). Callers own every buffer's lifetime for the
        duration of the call; validation (range bounds, overlap) lives
        in cas.compression.decode_batch_into, the one entry point."""
        dll = _load()
        n = len(schemes)
        if n == 0:
            return 0
        return dll.zest_decode_batch(
            src_ptrs.ctypes.data, src_lens.ctypes.data, schemes.ctypes.data,
            dst_offs.ctypes.data, dst_lens.ctypes.data, n,
            dst_ptr, dst_cap, max(1, int(workers)),
        )

    @staticmethod
    def parse_frames(buf, frames_end: int, max_chunks: int):
        """Columnar frame-table parse of a xorb frame stream (one native
        pass — no per-chunk Python): returns ``(frame_offs u64,
        comp_lens u32, unc_lens u32, schemes u8)`` numpy arrays of the
        chunk count, or None for a malformed stream (the caller's
        pure-Python walk then produces the precise error)."""
        import numpy as np

        dll = _load()
        src = np.frombuffer(buf, dtype=np.uint8)
        cap = max(1, min(max_chunks, frames_end // 8 + 1))
        frame_offs = np.empty(cap, dtype=np.uint64)
        comp_lens = np.empty(cap, dtype=np.uint32)
        unc_lens = np.empty(cap, dtype=np.uint32)
        schemes = np.empty(cap, dtype=np.uint8)
        n = dll.zest_parse_frames(
            src.ctypes.data, frames_end, cap,
            frame_offs.ctypes.data, comp_lens.ctypes.data,
            unc_lens.ctypes.data, schemes.ctypes.data,
        )
        if n == ctypes.c_size_t(-1).value:
            return None
        return (frame_offs[:n], comp_lens[:n], unc_lens[:n], schemes[:n])

    @staticmethod
    def lz4_decompress(data: bytes, expected_len: int) -> bytes:
        from zest_tpu.cas.compression import CompressionError, _lz4_decompress_py

        if expected_len == 0:
            # The native return code can't distinguish "decoded 0 bytes"
            # from "malformed"; the pure path validates properly.
            return _lz4_decompress_py(data, 0)
        dll = _load()
        out = _scratch(expected_len)
        n = dll.zest_lz4_decompress(data, len(data), out, expected_len)
        if n != expected_len:
            raise CompressionError("native lz4: malformed input")
        return ctypes.string_at(out, expected_len)
