"""Native (C++) acceleration library: build + ctypes bindings.

The reference's byte-level hot paths are native Zig (SURVEY.md §2.1); ours
are C++ compiled on demand into ``libzest.so`` and bound via ctypes (pybind11
is not in this image). Everything here has a pure-Python fallback — the
native lib is a performance tier, never a functional requirement.

Build is lazy and cached: first use compiles with g++ -O3 -march=native into
``zest_tpu/native/build/``; set ``ZEST_NATIVE=0`` to disable entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_BUILD_DIR = _HERE / "build"
_SOURCES = ["blake3.cc", "gearhash.cc", "lz4.cc"]

_lock = threading.Lock()
_dll: ctypes.CDLL | None = None
_tried = False


def _compile() -> Path | None:
    sources = [_HERE / s for s in _SOURCES if (_HERE / s).exists()]
    if not sources:
        return None
    _BUILD_DIR.mkdir(exist_ok=True)
    so_path = _BUILD_DIR / "libzest.so"
    stamp = _BUILD_DIR / "libzest.stamp"
    fingerprint = "|".join(
        f"{s.name}:{s.stat().st_mtime_ns}" for s in sorted(sources)
    )
    if so_path.exists() and stamp.exists() and stamp.read_text() == fingerprint:
        return so_path
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", str(so_path), *[str(s) for s in sources],
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (subprocess.SubprocessError, FileNotFoundError):
        # A stale .so from a previous build must not mask the failure — the
        # pure-Python fallback is always correct, old native code may not be.
        return None
    stamp.write_text(fingerprint)
    return so_path


def _load() -> ctypes.CDLL | None:
    global _dll, _tried
    with _lock:
        if _tried:
            return _dll
        _tried = True
        if os.environ.get("ZEST_NATIVE") == "0":
            return None
        so = _compile()
        if so is None:
            return None
        try:
            dll = ctypes.CDLL(str(so))
        except OSError:
            return None
        dll.zest_blake3.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_blake3_keyed.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_blake3_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p
        ]
        dll.zest_blake3_keyed_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_size_t, ctypes.c_char_p
        ]
        _dll = dll
        return _dll


class lib:
    """Namespace of native entry points with ctypes marshalling."""

    @staticmethod
    def available() -> bool:
        return _load() is not None

    @staticmethod
    def blake3(data: bytes) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(32)
        dll.zest_blake3(data, len(data), out)
        return out.raw

    @staticmethod
    def blake3_keyed(key: bytes, data: bytes) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(32)
        dll.zest_blake3_keyed(key, data, len(data), out)
        return out.raw

    @staticmethod
    def blake3_batch(data: bytes, count: int, item_len: int) -> bytes:
        """Hash ``count`` contiguous equal-size items; returns count*32 bytes."""
        dll = _load()
        out = ctypes.create_string_buffer(32 * count)
        dll.zest_blake3_batch(data, count, item_len, out)
        return out.raw

    @staticmethod
    def blake3_keyed_batch(key: bytes, data: bytes, count: int,
                           item_len: int) -> bytes:
        dll = _load()
        out = ctypes.create_string_buffer(32 * count)
        dll.zest_blake3_keyed_batch(key, data, count, item_len, out)
        return out.raw
