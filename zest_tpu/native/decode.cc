// Native batch decode engine: N chunk frames -> one destination buffer.
//
// The GB-scale landing used to touch every pulled byte with a scalar
// Python core: per-chunk frame parsing, per-chunk LZ4-frame decode, and
// a Python-level copy into the tensor buffer. This engine takes a whole
// batch of decode descriptors in ONE ctypes call (the GIL is released
// for the call's duration) and decodes them across a std::thread pool
// straight into the caller-owned destination — no per-chunk Python
// round-trips, no intermediate bytes objects.
//
// Descriptor i:
//   srcs[i]/src_lens[i]  — the chunk's compressed payload (NOT the frame
//                          header; the Python side strips it)
//   schemes[i]           — cas.compression.Scheme (0 NONE, 1 LZ4,
//                          2 BG4_LZ4, 3 BITSLICE_LZ4)
//   dst_offs[i]/dst_lens[i] — destination range within dst (the chunk's
//                          uncompressed bytes land at dst + dst_offs[i])
//
// The LZ4 payloads are LZ4 *frames* (magic 0x184D2204), exactly what the
// xorb container stores — the frame walk here mirrors the pure-Python
// lz4_frame_decompress in cas/compression.py and the two are
// cross-checked in tests/test_decode_engine.py.
//
// C ABI:
//   zest_decode_batch(...) -> 0 on success, i+1 for the first (lowest-
//     index) failing descriptor. Callers re-run the failing descriptor
//     through the pure-Python path for a precise error.

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" size_t zest_lz4_decompress(const uint8_t* src, size_t n,
                                      uint8_t* dst, size_t expected);

namespace {

constexpr uint8_t SCHEME_NONE = 0;
constexpr uint8_t SCHEME_LZ4 = 1;
constexpr uint8_t SCHEME_BG4 = 2;
constexpr uint8_t SCHEME_BITSLICE = 3;

// LZ4 frame walk (spec: magic, FLG/BD, optional content-size/dict-id,
// header-checksum byte, then u32-length blocks; bit 31 = stored).
bool frame_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                      size_t expected) {
  static const uint8_t kMagic[4] = {0x04, 0x22, 0x4d, 0x18};
  if (n < 7 || std::memcmp(src, kMagic, 4) != 0) return false;
  uint8_t flg = src[4], bd = src[5];
  if ((flg >> 6) != 1) return false;
  size_t block_max = (size_t)1 << (8 + 2 * ((bd >> 4) & 0x7));
  size_t pos = 6;
  if (flg & 0x08) pos += 8;  // content size (chunk header is authoritative)
  if (flg & 0x01) pos += 4;  // dictionary id
  pos += 1;                  // header checksum byte
  size_t out = 0;
  for (;;) {
    if (pos + 4 > n) return false;
    uint32_t bsz;
    std::memcpy(&bsz, src + pos, 4);
    pos += 4;
    if (bsz == 0) break;
    bool stored = (bsz & 0x80000000u) != 0;
    bsz &= 0x7FFFFFFFu;
    if (pos + bsz > n) return false;
    const uint8_t* block = src + pos;
    pos += bsz;
    if (flg & 0x10) pos += 4;  // block checksum; ignored
    if (stored) {
      if (out + bsz > expected) return false;
      std::memcpy(dst + out, block, bsz);
      out += bsz;
    } else {
      size_t remaining = expected - out;
      size_t want = remaining < block_max ? remaining : block_max;
      if (zest_lz4_decompress(block, bsz, dst + out, want) != want)
        return false;
      out += want;
    }
  }
  return out == expected;
}

// ByteGrouping4 inverse: planar [plane0 | plane1 | plane2 | plane3]
// (sizes (n-k+3)/4) -> interleaved bytes, dst[4i+k] = plane_k[i].
void bg4_inverse(const uint8_t* src, uint8_t* dst, size_t n) {
  size_t off = 0;
  for (size_t k = 0; k < 4; k++) {
    size_t size_k = (n - k + 3) / 4;
    const uint8_t* plane = src + off;
    for (size_t i = 0; i < size_k; i++) dst[4 * i + k] = plane[i];
    off += size_k;
  }
}

// Bitslice inverse: 8 MSB-first bit planes of (n+7)/8 bytes each
// (numpy packbits order) -> original bytes.
void bitslice_inverse(const uint8_t* src, uint8_t* dst, size_t n) {
  size_t plane_len = (n + 7) / 8;
  std::memset(dst, 0, n);
  for (size_t b = 0; b < 8; b++) {
    const uint8_t* plane = src + b * plane_len;
    for (size_t i = 0; i < n; i++) {
      uint8_t bit = (plane[i >> 3] >> (7 - (i & 7))) & 1;
      dst[i] |= (uint8_t)(bit << b);
    }
  }
}

bool decode_one(const uint8_t* src, size_t src_len, uint8_t scheme,
                uint8_t* dst, size_t dst_len,
                std::vector<uint8_t>& scratch) {
  switch (scheme) {
    case SCHEME_NONE:
      if (src_len != dst_len) return false;
      std::memcpy(dst, src, dst_len);
      return true;
    case SCHEME_LZ4:
      return frame_decompress(src, src_len, dst, dst_len);
    case SCHEME_BG4:
      if (scratch.size() < dst_len) scratch.resize(dst_len);
      if (!frame_decompress(src, src_len, scratch.data(), dst_len))
        return false;
      bg4_inverse(scratch.data(), dst, dst_len);
      return true;
    case SCHEME_BITSLICE: {
      size_t plane_bytes = ((dst_len + 7) / 8) * 8;
      if (scratch.size() < plane_bytes) scratch.resize(plane_bytes);
      if (!frame_decompress(src, src_len, scratch.data(), plane_bytes))
        return false;
      bitslice_inverse(scratch.data(), dst, dst_len);
      return true;
    }
    default:
      return false;
  }
}

}  // namespace

extern "C" {

size_t zest_parse_frames(const uint8_t* data, uint64_t n, uint64_t max_chunks,
                         uint64_t* frame_offs, uint32_t* comp_lens,
                         uint32_t* unc_lens, uint8_t* schemes) {
  // One pass over a xorb frame stream: fills the columnar chunk table
  // (frame offset, compressed len, uncompressed len, scheme) that
  // XorbReader used to build with a per-chunk Python loop. Returns the
  // chunk count, or (size_t)-1 on a malformed stream (truncated header,
  // nonzero frame version, payload past the end, > max_chunks).
  uint64_t pos = 0;
  uint64_t count = 0;
  while (pos < n) {
    if (pos + 8 > n) return (size_t)-1;
    if (data[pos] != 0) return (size_t)-1;  // unknown frame version
    uint32_t comp = (uint32_t)data[pos + 1] | ((uint32_t)data[pos + 2] << 8) |
                    ((uint32_t)data[pos + 3] << 16);
    uint32_t unc = (uint32_t)data[pos + 5] | ((uint32_t)data[pos + 6] << 8) |
                   ((uint32_t)data[pos + 7] << 16);
    uint64_t end = pos + 8 + comp;
    if (end > n) return (size_t)-1;
    if (count >= max_chunks) return (size_t)-1;
    frame_offs[count] = pos;
    comp_lens[count] = comp;
    unc_lens[count] = unc;
    schemes[count] = data[pos + 4];
    count++;
    pos = end;
  }
  return (size_t)count;
}

size_t zest_decode_batch(const uint8_t* const* srcs, const uint64_t* src_lens,
                         const uint8_t* schemes, const uint64_t* dst_offs,
                         const uint64_t* dst_lens, uint64_t n, uint8_t* dst,
                         uint64_t dst_cap, uint64_t workers) {
  if (n == 0) return 0;
  // Bounds are re-checked here so a buggy caller can never make a worker
  // scribble outside dst (the Python layer also validates, with ranges).
  for (uint64_t i = 0; i < n; i++) {
    if (dst_offs[i] + dst_lens[i] > dst_cap ||
        dst_offs[i] + dst_lens[i] < dst_offs[i])
      return (size_t)(i + 1);
  }
  // First (lowest-index) failure wins, so error reporting is
  // deterministic regardless of worker interleaving.
  std::atomic<uint64_t> first_error{n + 1};

  auto run = [&](uint64_t lo, uint64_t hi) {
    std::vector<uint8_t> scratch;
    for (uint64_t i = lo; i < hi; i++) {
      if (first_error.load(std::memory_order_relaxed) <= i) return;
      if (!decode_one(srcs[i], (size_t)src_lens[i], schemes[i],
                      dst + dst_offs[i], (size_t)dst_lens[i], scratch)) {
        uint64_t cur = first_error.load(std::memory_order_relaxed);
        while (i + 1 < cur && !first_error.compare_exchange_weak(
                                  cur, i + 1, std::memory_order_relaxed)) {
        }
      }
    }
  };

  uint64_t nw = workers;
  if (nw > n) nw = n;
  if (nw <= 1) {
    run(0, n);
  } else {
    // Contiguous stripes (not an atomic work queue): descriptors are
    // typically source-ordered, so stripes keep each worker streaming
    // through adjacent payload bytes.
    std::vector<std::thread> threads;
    threads.reserve((size_t)nw);
    uint64_t per = (n + nw - 1) / nw;
    for (uint64_t w = 0; w < nw; w++) {
      uint64_t lo = w * per;
      uint64_t hi = lo + per < n ? lo + per : n;
      if (lo >= hi) break;
      threads.emplace_back(run, lo, hi);
    }
    for (auto& t : threads) t.join();
  }
  uint64_t err = first_error.load();
  return err <= n ? (size_t)err : 0;
}

}  // extern "C"
