// Native GearHash CDC boundary scanner.
//
// Same algorithm and gear table as zest_tpu/cas/chunking.py (the table is
// passed in from Python so there is exactly one source of truth).
//
// C ABI:
//   zest_gear_cut_points(data, len, gear256, min, max, mask, out, out_cap)
//     -> number of cut points written (chunk end offsets, exclusive).

#include <cstdint>
#include <cstddef>

extern "C" {

size_t zest_gear_cut_points(const uint8_t* data, size_t len,
                            const uint64_t* gear, size_t min_chunk,
                            size_t max_chunk, uint64_t mask, uint64_t* out,
                            size_t out_cap) {
  size_t n_out = 0;
  size_t start = 0;
  uint64_t h = 0;
  for (size_t i = 0; i < len;) {
    h = (h << 1) + gear[data[i]];
    i++;
    size_t length = i - start;
    if (((length >= min_chunk) && ((h & mask) == 0)) || length >= max_chunk) {
      if (n_out < out_cap) out[n_out++] = i;
      start = i;
      h = 0;
    }
  }
  if (start < len && n_out < out_cap) out[n_out++] = len;
  return n_out;
}

}  // extern "C"
