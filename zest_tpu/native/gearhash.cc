// Native GearHash CDC boundary scanner.
//
// Same algorithm and gear table as zest_tpu/cas/chunking.py (the table is
// passed in from Python so there is exactly one source of truth).
//
// C ABI:
//   zest_gear_cut_points(data, len, gear256, min, max, mask, out, out_cap)
//     -> number of cut points written (chunk end offsets, exclusive).

#include <cstdint>
#include <cstddef>

extern "C" {

size_t zest_gear_cut_points(const uint8_t* data, size_t len,
                            const uint64_t* gear, size_t min_chunk,
                            size_t max_chunk, uint64_t mask, uint64_t* out,
                            size_t out_cap) {
  // h = sum of gear[b_j] << age: contributions older than 64 bytes have
  // shifted out of the u64 entirely, so h at any position depends only
  // on the last 64 bytes. After a cut we therefore skip straight to
  // (min_chunk - 64) and warm the hash over just that window — the
  // sub-min region (usually 8 KiB) costs 64 table lookups, not 8192.
  constexpr size_t WINDOW = 64;
  if (min_chunk < 1) min_chunk = 1;  // a zero-length chunk can never cut
  size_t n_out = 0;
  size_t start = 0;
  while (start < len && n_out < out_cap) {
    size_t end_cap = (len - start > max_chunk) ? start + max_chunk : len;
    size_t check_from = start + min_chunk;  // first admissible cut end
    if (check_from >= end_cap) {
      // No mask cut can fire: either the max cap lands first (only when
      // max <= min, degenerate) or the data ends inside the min region.
      out[n_out++] = end_cap;
      start = end_cap;
      continue;
    }
    uint64_t h = 0;
    size_t warm = check_from > start + WINDOW ? check_from - WINDOW : start;
    for (size_t j = warm; j < check_from; j++) h = (h << 1) + gear[data[j]];

    // Scan: at i the candidate chunk is [start, i); h covers ..i-1.
    // Unrolled 8x so the end-of-range test runs once per 8 bytes; the
    // mask test itself must stay per-byte (cuts land at any offset).
    size_t i = check_from;
    bool cut = false;
#define GEAR_STEP                                                       \
    if ((h & mask) == 0) { cut = true; goto scan_done; }                \
    h = (h << 1) + gear[data[i]];                                       \
    i++
    while (i + 8 <= end_cap) {
      GEAR_STEP; GEAR_STEP; GEAR_STEP; GEAR_STEP;
      GEAR_STEP; GEAR_STEP; GEAR_STEP; GEAR_STEP;
    }
    for (;;) {
      if ((h & mask) == 0) { cut = true; break; }
      if (i == end_cap) break;
      h = (h << 1) + gear[data[i]];
      i++;
    }
#undef GEAR_STEP
  scan_done:
    if (cut) {
      out[n_out++] = i;
      start = i;
    } else {
      // max-size cut, or the final (possibly short) chunk at data end.
      out[n_out++] = end_cap;
      start = end_cap;
    }
  }
  return n_out;
}

}  // extern "C"
